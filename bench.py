"""Headline benchmark: allreduce bus-bandwidth at 256 MiB float32.

Mirrors BASELINE.json config #2 (OSU-style MPI_Allreduce sweep; the
north-star size is 256 MiB f32). With n >= 2 devices this times the
framework's psum allreduce over a 1-D mesh and reports ring bus
bandwidth 2(n-1)/n * bytes / t. On a single chip (the driver's bench
environment) it times the on-device SUM op hot loop (out = acc*c + a,
the ``ompi/op`` kernel of BASELINE's north star, read acc + read a +
write = 3x bytes through HBM per iteration) using the Pallas streaming
kernel from ``ompi_release_tpu/ops/pallas_op.py``.

Both the measured kernel and the ceiling are Pallas calls on purpose:
a pallas_call is opaque to XLA, so the timing loop cannot be
algebraically folded across iterations (an XLA-level axpy loop CAN be:
acc*c+a twice = acc*c^2 + (ac+a) — which silently inflates the
number). Round-1's 0.707 ratio came from exactly that instability in
the ceiling kernel plus short-loop noise.

Timing method: the tunneled single-chip backend has ~100 ms fixed
per-call round-trip latency, so each measurement jits a fori_loop of K
kernel iterations and takes the slope between K_lo and K_hi — pure
device time, latency cancelled. K_hi = 258 keeps the slope well above
the tunnel's ms-scale jitter (sub-ms kernels at K_hi = 66 measured an
impossible > HBM-peak ceiling). Completion is forced by fetching an
8-byte checksum (block_until_ready alone can return early through the
tunnel).

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so
the baseline is the measured HBM copy ceiling of the same chip (the
2-stream Pallas scale kernel, ~818 GB/s on v5e = its spec sheet) — the
ratio is "fraction of achievable memory bandwidth", target >= 0.8 per
the north star.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time
from functools import partial

import numpy as np

K_LO, K_HI = 2, 258


def _sync(r):
    np.asarray(r)  # tiny checksum fetch forces remote completion


def _timed(fn, *args):
    t0 = time.perf_counter()
    _sync(fn(*args))
    return time.perf_counter() - t0


def _per_iter_times(measurements, iters=5):
    """Interleaved slope timing for several loops at once.

    measurements: list of (loop_fn, args). Interleaving the K_lo/K_hi
    samples of all loops round-robin cancels slow clock/thermal drift
    between measurement phases (a sequential A-then-B measurement puts
    all of B's samples minutes after A's and skews any A/B ratio).
    """
    for fn, args in measurements:  # compile + warm both K values
        _sync(fn(*args, K_LO))
        _sync(fn(*args, K_HI))
    lo = [[] for _ in measurements]
    hi = [[] for _ in measurements]
    for _ in range(iters):
        for i, (fn, args) in enumerate(measurements):
            lo[i].append(_timed(fn, *args, K_LO))
            hi[i].append(_timed(fn, *args, K_HI))
    out = []
    for i in range(len(measurements)):
        slope = (np.median(hi[i]) - np.median(lo[i])) / (K_HI - K_LO)
        out.append(max(float(slope), 1e-12))
    return out


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ompi_release_tpu.ops import pallas_op

    devices = jax.devices()
    n = len(devices)
    size_bytes = 256 * 1024 * 1024
    elems = size_bytes // 4

    if n >= 2:
        mesh = Mesh(np.array(devices), ("rank",))
        sh = NamedSharding(mesh, P("rank"))
        x = jax.device_put(
            jnp.ones((n * elems,), jnp.float32), sh
        )
        inv_n = np.float32(1.0 / n)

        @partial(jax.jit, static_argnums=1)
        def allreduce_loop(x, k):
            def spmd(b):
                def body(i, acc):
                    return lax.psum(acc, "rank") * inv_n

                acc = lax.fori_loop(0, k, body, b)
                return (acc[0] + acc[-1])[None]

            s = jax.shard_map(spmd, mesh=mesh, in_specs=P("rank"),
                              out_specs=P("rank"))(x)
            return s[0]

        metric_loop, metric_args = allreduce_loop, (x,)
        streams = None  # bus-bandwidth formula below
        metric = f"allreduce_256MiB_f32_busbw_{n}dev"
    else:
        cols = pallas_op.AXPY_BLOCK[1]
        rows = elems // cols
        a = jax.device_put(
            jnp.ones((rows, cols), jnp.float32), devices[0]
        )
        metric_loop = pallas_op.make_axpy_loop(rows, cols)
        metric_args = (a,)
        streams = 3
        metric = "op_sum_256MiB_f32_hbm_bw"

    # HBM copy ceiling on device 0: read + write = 2x bytes per iter
    c_cols = pallas_op.SCALE_BLOCK[1]
    c_rows = elems // c_cols
    c = jax.device_put(
        jnp.ones((c_rows, c_cols), jnp.float32), devices[0]
    )
    copy_loop = pallas_op.make_scale_loop(c_rows, c_cols)

    per, per_copy = _per_iter_times(
        [(metric_loop, metric_args), (copy_loop, (c,))]
    )
    if streams is None:
        value = (2 * (n - 1) / n) * size_bytes / per / 1e9
    else:
        value = streams * size_bytes / per / 1e9
    ceiling = 2 * size_bytes / per_copy / 1e9

    print(json.dumps({
        "metric": metric,
        "value": round(value, 3),
        "unit": "GB/s",
        "vs_baseline": round(value / ceiling, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
