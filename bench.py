"""BASELINE bench suite: all 5 configs, one JSON line each.

BASELINE.json's five configs, each emitting one JSON metric line, the
headline (op_sum_256MiB_f32_hbm_bw, comparable across rounds) LAST:

  1. ring        — examples/ring_c.c 4-rank token ring
  2. allreduce   — OSU-style f32 SUM sweep, 8 B..256 MiB
  3. bcast       — contiguous f32 (+ allgather bf16, config 3's pair)
  4. reduce_scatter_block — f32 SUM (ZeRO-style 64 MiB gradient shard)
  5. alltoall    — int32 all-pairs shuffle (2-D torus)

With n >= 2 devices the configs run the framework's own SPMD
collectives (coll/spmd.py kernels under shard_map). On ONE chip — the
driver's bench environment — each config runs its single-chip
op-kernel analogue from ompi_release_tpu/ops/pallas_op.py: the
HBM-bound data movement the collective would perform locally
(allreduce/reduce_scatter -> the 3-stream SUM/axpy hot loop,
bcast/allgather -> the 2-stream copy, alltoall -> the blocked
transpose shuffle, ring -> chained dependent kernel dispatches).
Pallas kernels on purpose: a pallas_call is opaque to XLA, so the
timing loop cannot be algebraically folded across iterations.

Timing: the tunneled single-chip backend has ~100 ms fixed per-call
latency, so each measurement jits a fori_loop of K iterations and
takes the (K_hi - K_lo) slope — pure device time, latency cancelled.
Completion is forced by fetching an 8-byte checksum.

The ceiling (the "baseline" in vs_baseline): measured single-run HBM
bandwidth on this chip wobbles +-20% (tunnel contention/thermal) —
round 2's vs_baseline of 1.054 was exactly a ceiling measured in a
slow moment. So: (a) every round interleaves ALL loops, metric and
ceiling alike; (b) the ceiling is the per-round MAX bandwidth any
2-stream copy candidate OR the metric itself achieved — vs_baseline
<= 1.0 by construction, because a chip that demonstrably moved X GB/s
has a ceiling of at least X; (c) each line carries the ceiling and its
cross-round coefficient of variation so the denominator's stability is
in the output, not assumed; (d) sweep points whose working set fits in
on-chip memory run at VMEM bandwidth (5-20x HBM; iterations verified
by checksum) — those report tier "on-chip" with vs_baseline null
rather than a fake HBM ratio. The HBM-bound lines (256 MiB headline,
bcast/allgather, 128 MiB reduce_scatter, transpose) carry real
ratios.

Prints one JSON object per line; the LAST line is the headline
{"metric", "value", "unit", "vs_baseline", ...} the driver parses.
"""

import json
import sys
import time
from functools import partial

import numpy as np

MiB = 1024 * 1024
SWEEP_BYTES = [8, 64 * 1024, MiB, 16 * MiB, 256 * MiB]
# largest working set eligible for the "on-chip" tier label (v5e VMEM
# is 128 MiB; leave headroom for double-buffering scratch)
ONCHIP_WS = 112 * MiB


def _human(nbytes):
    for unit, div in (("MiB", 1024 * 1024), ("KiB", 1024)):
        if nbytes >= div:
            return f"{nbytes // div}{unit}"
    return f"{nbytes}B"


def _sync(r):
    np.asarray(r)  # tiny checksum fetch forces remote completion


def _timed(fn, args, k):
    t0 = time.perf_counter()
    _sync(fn(*args, k))
    return time.perf_counter() - t0


def _ks(traffic_bytes_per_iter, on_tpu):
    """Static initial (K_lo, K_hi) guess from HBM traffic at
    ~700 GB/s with a 3 us dispatch floor. Only a STARTING POINT:
    sub-VMEM working sets run 5-20x faster than the HBM estimate
    (on-chip residency), so the real K is set by :func:`_calibrate_k`
    from a measured per-iteration time."""
    if not on_tpu:
        return (2, 18)
    est = max(traffic_bytes_per_iter / 700e9, 3e-6)
    k_hi = max(258, int(0.75 / est))
    return (max(2, k_hi // 32), k_hi)


K_CAP = 4_000_000
TARGET_S = 0.75


def _calibrate_k(loop, args, static_hi):
    """Measure the loop's actual per-iteration time and size K_hi for
    ~TARGET_S seconds of device time. The tunnel's per-call latency
    jitter is tens of ms, so (a) the calibration probe grows K
    geometrically until the K-call exceeds the base call by >250 ms
    (jitter then contributes <16% error), and (b) the final K_hi-K_lo
    delta towers over jitter by construction. Without this, a K sized
    from the HBM estimate left VMEM-resident loops with ~10 ms deltas
    inside ~40 ms jitter — slopes came out near zero and bandwidths
    absurd."""
    # min-of-N: tunnel latency spikes are one-sided (they only ADD
    # time), so minima approach the true floor — a single probe can
    # jitter past the threshold and size K from pure noise
    base = min(_timed(loop, args, 2) for _ in range(3))
    k = max(64, static_hi // 8)
    while True:
        dt = min(_timed(loop, args, k) for _ in range(2)) - base
        if dt > 0.25 or k >= K_CAP:
            per = max(dt / k, 2e-8)
            break
        k *= 4
    k_hi = min(max(int(TARGET_S / per), 258), K_CAP)
    return max(2, k_hi // 32), k_hi


def _run_rounds(specs, rounds, progress=None):
    """Interleaved slope timing: every round times every loop's K_lo
    and K_hi back to back, so cross-loop ratios (metric/ceiling) are
    taken between samples milliseconds apart, not minutes.

    ``progress`` (a dict, if given) is refreshed after every completed
    round with copies of the per-spec timings, so an abort path — the
    global watchdog's hard-exit, a mid-sweep backend crash — can
    salvage metric lines from the rounds already measured instead of
    losing the whole sweep."""
    for s in specs:  # compile + warm both K values
        _sync(s["loop"](*s["args"], s["k_lo"]))
        _sync(s["loop"](*s["args"], s["k_hi"]))
    slopes = [[] for _ in specs]
    lo_t = [[] for _ in specs]
    hi_t = [[] for _ in specs]
    for r in range(rounds):
        for i, s in enumerate(specs):
            tlo = _timed(s["loop"], s["args"], s["k_lo"])
            thi = _timed(s["loop"], s["args"], s["k_hi"])
            lo_t[i].append(tlo)
            hi_t[i].append(thi)
            slopes[i].append(
                max((thi - tlo) / (s["k_hi"] - s["k_lo"]), 1e-12)
            )
        if progress is not None:
            # fresh copies + whole-reference assignment: the reader is
            # the watchdog thread, which must never see a row
            # mid-append
            progress["slopes"] = [list(row) for row in slopes]
            progress["lo_t"] = [list(row) for row in lo_t]
            progress["hi_t"] = [list(row) for row in hi_t]
            progress["rounds_done"] = r + 1
    _flag_unstable(specs, lo_t, hi_t)
    return np.asarray(slopes)  # (n_specs, rounds)


def _flag_unstable(specs, lo_t, hi_t):
    for i, s in enumerate(specs):
        # a median K-delta inside the tunnel's jitter band means the
        # slope is noise, not signal — flag rather than report garbage
        s["unstable"] = (
            np.median(hi_t[i]) - np.median(lo_t[i])
        ) < 0.05 and jnp_on_tpu()


def jnp_on_tpu():
    import jax

    return jax.default_backend() == "tpu"


def _sweep_geom(elems):
    """(rows, cols, blk_rows) for an axpy sweep point: full tuned
    blocks for large sizes, one minimal (8, 128)-multiple tile padded
    up for tiny ones."""
    cols = 2048 if elems >= 8 * 2048 else 128
    rows = max(8, -(-elems // cols))
    blk = min(256, -(-rows // 8) * 8)
    rows = -(-rows // blk) * blk
    return rows, cols, blk


def _single_chip_specs(jax, jnp, dev, on_tpu):
    """The 5 configs as single-chip op-kernel analogues + ceiling
    candidates. Returns (specs, ceiling_names)."""
    from ompi_release_tpu.ops import pallas_op

    put = lambda a: jax.device_put(a, dev)
    specs = []

    # config 1: ring — 4 chained dependent kernel dispatches per iter
    ring_loop = pallas_op.make_chain_loop(hops=4)
    k_lo, k_hi = _ks(0, on_tpu)  # dispatch-latency bound
    specs.append(dict(
        name="ring_4hop", loop=ring_loop,
        args=(put(jnp.zeros((8, 128), jnp.float32)),),
        k_lo=k_lo, k_hi=k_hi, nbytes=None, hops=4,
    ))

    # config 2: allreduce sweep — the SUM op hot loop (3 HBM streams)
    sweep = SWEEP_BYTES if on_tpu else SWEEP_BYTES[:3]
    for size in sweep:
        elems = max(1, size // 4)
        rows, cols, blk = _sweep_geom(elems)
        loop = pallas_op.make_axpy_loop(rows, cols, blk_rows=blk)
        k_lo, k_hi = _ks(3 * size, on_tpu)
        specs.append(dict(
            name=f"allreduce_{_human(size)}", loop=loop,
            args=(put(jnp.ones((rows, cols), jnp.float32)),),
            k_lo=k_lo, k_hi=k_hi, nbytes=3 * size, size=size,
            ws=2 * size,
        ))

    big = 256 * MiB if on_tpu else 4 * MiB

    # config 3: bcast f32 + allgather bf16 — 2-stream copy traffic
    for nm, dtype, isz in (("bcast_f32", jnp.float32, 4),
                           ("allgather_bf16", jnp.bfloat16, 2)):
        elems = big // isz
        cols = 2048
        rows = elems // cols
        loop = pallas_op.make_scale_loop(rows, cols, dtype=dtype)
        k_lo, k_hi = _ks(2 * big, on_tpu)
        specs.append(dict(
            name=nm, loop=loop, args=(put(jnp.ones((rows, cols), dtype)),),
            k_lo=k_lo, k_hi=k_hi, nbytes=2 * big, ws=2 * big,
        ))

    # config 4: reduce_scatter_block — the same reduction kernel at a
    # ZeRO-ish 128 MiB gradient-shard size (3 x 128 MiB working set
    # cannot be on-chip-resident: this line must be an HBM number)
    rs_size = 128 * MiB if on_tpu else 2 * MiB
    elems = rs_size // 4
    rows, cols, blk = _sweep_geom(elems)
    loop = pallas_op.make_axpy_loop(rows, cols, blk_rows=blk)
    k_lo, k_hi = _ks(3 * rs_size, on_tpu)
    specs.append(dict(
        name="reduce_scatter_block_f32", loop=loop,
        args=(put(jnp.ones((rows, cols), jnp.float32)),),
        k_lo=k_lo, k_hi=k_hi, nbytes=3 * rs_size, ws=2 * rs_size,
    ))

    # config 5: alltoall i32 — blocked transpose (all-pairs shuffle),
    # applied twice per loop iteration = 4 streams counted (see
    # make_transpose_loop: a single non-aliased call per iteration
    # makes XLA copy the fori_loop carry back every iteration — 2N
    # uncounted bytes that capped three rounds of this line at ~0.49
    # of ceiling; the r04 probes 5-7 nailed it to aliasing alone).
    # 1024 sits exactly at the 16 MB scoped-VMEM limit (2 x 4 MB
    # buffers double-buffered), so fall back if the compiler tightens
    # it.
    tn = 8192 if on_tpu else 1024
    x = put(jnp.arange(tn * tn, dtype=jnp.int32).reshape(tn, tn))
    small = None
    last_err = None
    for t_block in (1024, 512, 256):
        if tn % t_block:
            continue
        try:
            t_loop, t_call = pallas_op.make_transpose_loop(
                tn, block=t_block
            )
            small = np.asarray(t_call(x)[:4, :4])  # compiles/executes
            break
        except Exception as e:  # scoped-VMEM tightened: smaller tile
            last_err = e
    if small is None:
        raise RuntimeError(
            f"no transpose block size compiled for n={tn}: {last_err}"
        )
    np.testing.assert_array_equal(small, np.asarray(x[:4, :4]).T)
    k_lo, k_hi = _ks(4 * tn * tn * 4, on_tpu)
    specs.append(dict(
        name="alltoall_i32_torus", loop=t_loop, args=(x,),
        k_lo=k_lo, k_hi=k_hi, nbytes=4 * tn * tn * 4,
        ws=2 * tn * tn * 4,
    ))

    # ceiling candidates: alternate copy block shapes (the primary
    # candidate is bcast_f32 above — same kernel, tuned SCALE_BLOCK).
    # Which shape wins varies session to session (+-20% wobble), so
    # the ceiling takes the per-round max over all of them.
    elems = big // 4
    for cand_name, (ar, ac) in (
        ("ceiling_copy_alt", pallas_op.SCALE_BLOCK_ALT),
        ("ceiling_copy_alt2", pallas_op.SCALE_BLOCK_ALT2),
    ):
        rows = elems // ac
        loop = pallas_op.make_scale_loop(rows, ac, blk_rows=ar)
        k_lo, k_hi = _ks(2 * big, on_tpu)
        specs.append(dict(
            name=cand_name, loop=loop,
            args=(put(jnp.ones((rows, ac), jnp.float32)),),
            k_lo=k_lo, k_hi=k_hi, nbytes=2 * big,
        ))

    # parity spot-check (BASELINE metric demands result parity): the
    # op component's axpy against numpy
    a = np.random.default_rng(0).standard_normal((64, 256)).astype(np.float32)
    b = np.random.default_rng(1).standard_normal((64, 256)).astype(np.float32)
    got = np.asarray(pallas_op.axpy(jnp.asarray(a), jnp.asarray(b), 0.5))
    np.testing.assert_allclose(got, b * 0.5 + a, rtol=1e-6)

    return specs, ("bcast_f32", "ceiling_copy_alt", "ceiling_copy_alt2")


#: bf16 matmul peak by device kind substring (published chip specs);
#: unknown kinds report achieved FLOP/s with mfu null rather than a
#: made-up ratio
PEAK_FLOPS = (
    ("v5 lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v4", 275e12), ("v6", 918e12),
)


def _mfu_metric(jax, jnp, dev, on_tpu, rounds):
    """Compute-bound line: the flagship transformer's fwd+bwd step on
    one chip (tiny-but-MXU-shaped dims), slope-timed like every other
    loop, FLOPs taken from XLA's own cost analysis. Every other bench
    config is memory-bound, so without this a regression in the
    compute path (e.g. ops/pallas_attention.py) would be invisible to
    the round record."""
    from jax import lax

    from ompi_release_tpu.models import transformer as tfm
    from ompi_release_tpu.parallel.mesh_axes import build_parallel_mesh

    if on_tpu:
        cfg = tfm.ModelConfig(
            vocab=2048, d_model=512, n_layers=4, n_heads=8, head_dim=64,
            d_ff=2048, max_seq=256, dtype=jnp.bfloat16,
        )
        b, s = 8, 256
    else:  # CI-sized
        cfg = tfm.ModelConfig(
            vocab=128, d_model=64, n_layers=2, n_heads=4, head_dim=16,
            d_ff=128, max_seq=32, dtype=jnp.float32,
        )
        b, s = 2, 32
    mesh = build_parallel_mesh(devices=[dev])
    params = tfm.shard_params(
        tfm.init_params(jax.random.PRNGKey(0), cfg), cfg, mesh
    )
    fwd = tfm.make_forward(cfg, mesh)
    rng = np.random.RandomState(0)
    tok = jax.device_put(
        jnp.asarray(rng.randint(0, cfg.vocab, size=(b, s), dtype=np.int32)),
        dev,
    )
    tgt = jnp.roll(tok, -1, axis=1)
    grad_fn = jax.value_and_grad(lambda p: fwd(p, tok, tgt))

    def loop(params, k):
        def body(_, p):
            _, g = grad_fn(p)
            # inline SGD keeps every iteration's bwd live (no folding)
            return jax.tree.map(
                lambda a, d: a - jnp.asarray(1e-6, a.dtype)
                * d.astype(a.dtype), p, g)
        p = lax.fori_loop(0, k, body, params)
        return jnp.sum(jax.tree.leaves(p)[0].astype(jnp.float32))

    loop = jax.jit(loop)

    # FLOPs per fwd+bwd step from the compiler, not a hand formula
    flops_per_step = None
    try:
        ca = jax.jit(grad_fn).lower(params).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops_per_step = float(ca.get("flops", 0.0)) or None
    except Exception:
        pass

    k_lo, k_hi = _calibrate_k(loop, (params,), 258) if on_tpu else (2, 10)
    # warm both K programs, then slope-time like the bandwidth lines
    _sync(loop(params, k_lo))
    _sync(loop(params, k_hi))
    slopes, lo_t, hi_t = [], [], []
    for _ in range(rounds):
        tlo = _timed(loop, (params,), k_lo)
        thi = _timed(loop, (params,), k_hi)
        lo_t.append(tlo)
        hi_t.append(thi)
        slopes.append(max((thi - tlo) / (k_hi - k_lo), 1e-12))
    sec_per_step = float(np.median(slopes))

    entry = {
        "metric": "transformer_fwdbwd_step", "unit": "TFLOP/s",
        "sec_per_step": round(sec_per_step, 6),
        "vs_baseline": None,
    }
    # same jitter gate as _run_rounds: a K-delta inside the tunnel's
    # latency band is noise — flag it rather than report a confident
    # garbage MFU
    if on_tpu and (np.median(hi_t) - np.median(lo_t)) < 0.05:
        entry.update(value=None, mfu=None, unstable=True,
                     note="K-delta inside tunnel jitter; unreliable")
        return entry
    if flops_per_step is None:
        entry["value"] = None
        entry["note"] = "XLA cost analysis unavailable on this backend"
        return entry
    achieved = flops_per_step / sec_per_step
    entry["value"] = round(achieved / 1e12, 3)
    entry["flops_per_step"] = flops_per_step
    kind = dev.device_kind.lower()
    peak = next((p for sub, p in PEAK_FLOPS if sub in kind), None)
    if peak is not None and on_tpu:
        entry["mfu"] = round(achieved / peak, 4)
        entry["peak_tflops"] = peak / 1e12
        entry["device_kind"] = dev.device_kind
    else:
        entry["mfu"] = None
    return entry


def _mesh_specs(jax, jnp, devices, on_tpu):
    """The 5 configs as real SPMD collectives over the device mesh,
    using the framework's coll/spmd kernels.

    No spec here carries a ``ws`` key ON PURPOSE: the on-chip tier
    label exists for single-chip op loops whose whole working set can
    sit in VMEM; a collective always crosses the interconnect, so
    every mesh line is ineligible (the gate's missing-ws default) and
    reports a real ratio."""
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ompi_release_tpu.coll import spmd
    from ompi_release_tpu.ops import op as ops_mod
    from ompi_release_tpu.ops import pallas_op

    n = len(devices)
    mesh = Mesh(np.array(devices), ("rank",))
    sh = NamedSharding(mesh, P("rank"))
    specs = []

    def coll_loop(body_fn):
        @partial(jax.jit, static_argnums=1)
        def loop(x, k):
            def spmd_body(b):
                # pvary: psum-style outputs are rank-INvariant in
                # shard_map's varying-axes type system; the loop carry
                # must stay varying to match its input type (ppermute
                # outputs are already varying — leave those alone)
                def body(i, a):
                    out = body_fn(a)
                    if "rank" not in getattr(jax.typeof(out), "vma",
                                             frozenset()):
                        out = lax.pvary(out, ("rank",))
                    return out

                acc = lax.fori_loop(0, k, body, b)
                flat = acc.reshape(-1)
                return (flat[0] + flat[-1])[None]

            s = jax.shard_map(spmd_body, mesh=mesh, in_specs=P("rank"),
                              out_specs=P("rank"))(x)
            return s[0]

        return loop

    inv_n = np.float32(1.0 / n)

    # config 1: ring — one ppermute hop per iteration (token ring)
    perm = [(i, (i + 1) % n) for i in range(n)]
    ring = coll_loop(lambda a: lax.ppermute(a, "rank", perm))
    tok = jax.device_put(jnp.zeros((n, 128), jnp.float32), sh)
    k_lo, k_hi = _ks(0, on_tpu) if on_tpu else (2, 34)
    specs.append(dict(name="ring_4hop", loop=ring, args=(tok,),
                      k_lo=k_lo, k_hi=k_hi, nbytes=None, hops=1))

    # config 2: allreduce sweep (psum = coll/xla's lowering)
    sweep = SWEEP_BYTES if on_tpu else SWEEP_BYTES[:3]
    for size in sweep:
        elems = max(n, size // 4)
        x = jax.device_put(jnp.ones((elems,), jnp.float32), sh)
        loop = coll_loop(
            lambda a: spmd.allreduce_lax(a, ops_mod.SUM, "rank") * inv_n
        )
        k_lo, k_hi = _ks(2 * size, on_tpu)
        specs.append(dict(
            name=f"allreduce_{_human(size)}", loop=loop, args=(x,),
            k_lo=k_lo, k_hi=k_hi, size=size,
            nbytes=int(2 * (n - 1) / n * size),  # ring bus traffic
        ))

    big = 256 * MiB if on_tpu else 2 * MiB
    belems = max(n, big // 4)

    # config 3: bcast f32 + allgather bf16
    xb = jax.device_put(jnp.ones((belems,), jnp.float32), sh)
    bcast = coll_loop(
        lambda a: spmd.bcast_masked_psum(a, a.dtype, "rank", 0)
    )
    k_lo, k_hi = _ks(2 * big, on_tpu)
    specs.append(dict(name="bcast_f32", loop=bcast, args=(xb,),
                      k_lo=k_lo, k_hi=k_hi, nbytes=big))
    xg = jax.device_put(jnp.ones((belems,), jnp.bfloat16), sh)
    gather = coll_loop(
        lambda a: lax.all_gather(a, "rank")[lax.axis_index("rank")]
    )
    specs.append(dict(name="allgather_bf16", loop=gather, args=(xg,),
                      k_lo=k_lo, k_hi=k_hi,
                      nbytes=int((n - 1) / n * big * 2 // 2)))

    # config 4: reduce_scatter_block (psum_scatter lowering; the tile
    # rebuilding the loop carry adds local HBM traffic — reported bw
    # is collective bytes only, see docstring)
    seg = belems // n
    xr = jax.device_put(jnp.ones((n * seg,), jnp.float32), sh)
    rs = coll_loop(
        lambda a: jnp.tile(
            spmd.reduce_scatter_lax(a, ops_mod.SUM, "rank", n) * inv_n, n
        )
    )
    specs.append(dict(name="reduce_scatter_block_f32", loop=rs,
                      args=(xr,), k_lo=k_lo, k_hi=k_hi,
                      nbytes=int((n - 1) / n * 4 * n * seg)))

    # config 5: alltoall int32 on a 2-D torus (two-phase x then y),
    # falling back to 1-D when n has no 2-D factorization
    a_ax = 2 if n % 2 == 0 and n > 2 else 1
    if a_ax > 1:
        mesh2 = Mesh(np.array(devices).reshape(a_ax, n // a_ax),
                     ("x", "y"))

        @partial(jax.jit, static_argnums=1)
        def a2a(x, k):
            def spmd_body(b):
                def body(i, acc):
                    acc = lax.all_to_all(acc, "x", 0, 0, tiled=True)
                    return lax.all_to_all(acc, "y", 0, 0, tiled=True)

                acc = lax.fori_loop(0, k, body, b)
                flat = acc.reshape(-1)
                return (flat[0] + flat[-1])[None]

            from jax.sharding import PartitionSpec as P2
            s = jax.shard_map(spmd_body, mesh=mesh2,
                              in_specs=P2(("x", "y")),
                              out_specs=P2(("x", "y")))(x)
            return s[0]

        xa = jax.device_put(
            jnp.ones((belems,), jnp.int32),
            NamedSharding(mesh2, jax.sharding.PartitionSpec(("x", "y"))),
        )
        specs.append(dict(name="alltoall_i32_torus", loop=a2a,
                          args=(xa,), k_lo=k_lo, k_hi=k_hi,
                          nbytes=int(2 * (n - 1) / n * big)))
    else:
        xa = jax.device_put(jnp.ones((belems,), jnp.int32), sh)
        a2a = coll_loop(lambda a: spmd.alltoall_lax(
            a.reshape(n, -1), "rank", n).reshape(-1))
        specs.append(dict(name="alltoall_i32_torus", loop=a2a,
                          args=(xa,), k_lo=k_lo, k_hi=k_hi,
                          nbytes=int((n - 1) / n * big)))

    # ceiling: single-device HBM copy (placeholder for an ICI-bandwidth
    # ceiling until multi-chip hardware is available — documented, not
    # hidden: collective busbw vs one chip's copy bw)
    csize = 16 * MiB if on_tpu else MiB
    elems = csize // 4
    cols = 2048
    loop = pallas_op.make_scale_loop(elems // cols, cols)
    k_lo, k_hi = _ks(2 * csize, on_tpu)
    specs.append(dict(
        name="ceiling_copy", loop=loop,
        args=(jax.device_put(jnp.ones((elems // cols, cols),
                                      jnp.float32), devices[0]),),
        k_lo=k_lo, k_hi=k_hi, nbytes=2 * csize,
    ))

    # parity: psum of ones over the mesh == n on every shard
    ones = jax.device_put(jnp.ones((n,), jnp.float32), sh)
    got = jax.shard_map(
        lambda b: spmd.allreduce_lax(b, ops_mod.SUM, "rank"),
        mesh=mesh, in_specs=jax.sharding.PartitionSpec("rank"),
        out_specs=jax.sharding.PartitionSpec("rank"))(ones)
    np.testing.assert_allclose(np.asarray(got), np.full(n, n), rtol=0)

    return specs, ("ceiling_copy",)


def _init_backend(jax, attempts=3, first_delay=5.0,
                  attempt_timeout_s=180.0):
    """jax.devices() with bounded retry-with-backoff AND a watchdog.

    Round 4's BENCH record was lost to a transient axon outage
    (UNAVAILABLE at backend setup); the same outage class can also make
    ``jax.devices()`` HANG inside the tunnel rather than raise, which
    no try/except can bound — so each attempt runs on a daemon thread
    with a deadline. On final failure the caller gets None and main()
    emits a parseable tpu_unavailable marker; a hung attempt exits via
    ``os._exit`` after printing it (the stuck C call would otherwise
    block interpreter teardown past the driver's timeout)."""
    import os
    import threading

    delay = first_delay
    last = "unknown"
    for i in range(attempts):
        box = {}

        def probe():
            try:
                box["devices"] = jax.devices()
            except Exception as e:  # jaxlib raises RuntimeError subtypes
                box["error"] = e

        t = threading.Thread(target=probe, daemon=True)
        t.start()
        t.join(timeout=attempt_timeout_s)
        if "devices" in box:
            return box["devices"]
        if t.is_alive():
            # stuck inside the backend client: no recovery is possible
            # in-process — record the marker and hard-exit parseably
            print(json.dumps({
                "metric": "bench_error", "value": None, "unit": None,
                "vs_baseline": None, "error": "tpu_unavailable",
                "detail": f"backend init hung > {attempt_timeout_s:.0f}s "
                          f"(attempt {i + 1})",
            }), flush=True)
            os._exit(0)
        last = str(box.get("error", "unknown"))
        print(json.dumps({
            "event": "backend_init_retry", "attempt": i + 1,
            "error": last[:200],
        }), file=sys.stderr)
        if i + 1 < attempts:
            time.sleep(delay)
            delay *= 2
            try:
                import jax._src.api as _api
                _api.clear_backends()
            except Exception:
                pass
    # retries exhausted: the caller falls back to the CPU backend and
    # labels its lines, instead of a bare bench_error (the trajectory
    # stays non-empty); the marker below is informational only
    print(json.dumps({
        "event": "tpu_unavailable", "detail": last[:300],
    }), file=sys.stderr)
    return None


#: callables the watchdog runs (best-effort) before its hard-exit, so
#: partially-measured phases can flush what they have — see
#: ``salvage_sweep`` in main()
_SALVAGE_HOOKS = []


def _arm_global_watchdog(budget_s=1500.0):
    """If the whole run exceeds ``budget_s`` (a healthy TPU run takes
    ~2-4 min; only a mid-sweep tunnel hang gets near this), print the
    parseable marker and hard-exit so the driver records evidence
    instead of a timeout."""
    import os
    import threading

    def fire():
        for hook in list(_SALVAGE_HOOKS):
            try:
                hook()
            except Exception:
                pass  # salvage must never block the exit marker
        print(json.dumps({
            "metric": "bench_error", "value": None, "unit": None,
            "vs_baseline": None, "error": "tpu_unavailable",
            "detail": f"bench exceeded {budget_s:.0f}s wall budget "
                      "(backend hang mid-sweep?)",
        }), flush=True)
        os._exit(0)

    t = threading.Timer(budget_s, fire)
    t.daemon = True
    t.start()
    return t


def _backend_alive(jax, timeout_s=20.0):
    """Cached-backend probe: ``jax.devices()`` after a successful init
    is a client-cache read (fast), but a tunnel that died mid-run can
    HANG it — so the probe runs on a daemon thread with a deadline.
    Returns False on hang or error; the caller skips/labels the suite
    instead of losing the whole round to a 180 s init stall."""
    import threading

    box = {}

    def probe():
        try:
            box["ok"] = bool(jax.devices())
        except Exception:
            box["ok"] = False

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    return box.get("ok", False)


def _run_suite(name, fn, emit, jax, attempts=2, first_delay=5.0,
               needs_backend=True):
    """Run one micro-suite behind a cached-backend probe with bounded
    retry-with-backoff on ``tpu_unavailable``-class failures. Every
    outcome emits parseable lines: the suite's own on success, one
    labelled error line on final failure — never a silent hole in the
    round record (the BENCH r04/r05 failure mode).
    ``needs_backend=False`` skips the probe entirely: a device-free
    suite (the fleet simulator) must emit its lines precisely on the
    rounds where the backend is down and they are the only evidence."""
    delay = first_delay
    last = None
    for i in range(attempts):
        if needs_backend and not _backend_alive(jax):
            last = ("backend unavailable: cached jax.devices() probe "
                    "hung or errored before the suite")
            if i + 1 < attempts:
                time.sleep(delay)
                delay *= 2
                continue
            break
        try:
            for ln in fn():
                emit(ln)
            return
        except Exception as e:
            last = f"{type(e).__name__}: {e}"[:300]
            retriable = "unavailable" in str(e).lower()
            if retriable and i + 1 < attempts:
                time.sleep(delay)
                delay *= 2
                continue
            break
    emit({"metric": name, "value": None, "unit": None,
          "vs_baseline": None, "error": "tpu_unavailable"
          if last and "unavailable" in last.lower() else "suite_failed",
          "detail": last})


def _pvar_snapshot():
    """Current pvar values, JSON-ready (per-config observability)."""
    try:
        import ompi_release_tpu.obs  # noqa: F401  journal pvars exist
        from ompi_release_tpu.mca import pvar as _pvar_mod

        return _pvar_mod.PVARS.read_all()
    except Exception:
        return {}


#: pvars the coll micro-suite labels its lines with (segment counts,
#: fusion savings, plan-cache behaviour — the PR-goal observables)
_MICRO_PVARS = (
    "coll_pipeline_segments", "coll_fusion_batched",
    "coll_fusion_flushes", "coll_fusion_bytes_saved",
    "coll_programs_compiled", "coll_invocations",
    "coll_plan_cache_hits", "coll_compiled_cache_hits",
    "coll_orchestration_seconds",
    "obs_sample_overhead_seconds", "obs_series_points",
    "obs_sample_ticks",
)


def _micro_pvars():
    from ompi_release_tpu.mca import pvar as _pvar_mod

    out = {}
    for name in _MICRO_PVARS:
        pv = _pvar_mod.PVARS.lookup(name)
        if pv is not None:
            out[name] = pv.read()
    return out


def _coll_micro_suite():
    """coll_pipeline / coll_fusion micro-suite through the framework's
    own driver (not raw meshes): a ≥1 MiB pipelined allreduce + bcast
    and a 64-small-tensors fusion burst, one JSON line each, every
    line labelled with the cumulative pvar snapshot so BENCH_* files
    capture segment counts and fusion savings. The fusion line's
    device_collectives < tensors_fused check is pvar-based, so it
    holds on the CPU backend too."""
    import ompi_release_tpu as mpi
    from ompi_release_tpu.mca import var as mca_var

    lines = []
    world = mpi.init()

    # -- pipeline case: 1 MiB/rank allreduce + bcast, 256 KiB segments
    mca_var.set_value("coll", "tuned")
    try:
        tuned = world.dup(name="bench_pipe")
    finally:
        mca_var.VARS.unset("coll")
    elems = MiB // 4
    x = np.ones((world.size, elems), np.float32)
    try:
        mca_var.set_value("coll_tuned_allreduce_algorithm", "ring")
        mca_var.set_value("coll_tuned_bcast_algorithm", "binomial")
        mca_var.set_value("coll_pipeline_segsize", 256 * 1024)
        for name, call in (
            ("coll_pipeline_allreduce_1MiB",
             lambda: tuned.allreduce(x)),
            ("coll_pipeline_bcast_1MiB",
             lambda: tuned.bcast(x, root=0)),
        ):
            _sync(call())  # compile + prime the plan cache
            t0 = time.perf_counter()
            reps = 3
            for _ in range(reps):
                _sync(call())
            dt = (time.perf_counter() - t0) / reps
            lines.append({
                "metric": name, "value": round(MiB / dt / 1e9, 4),
                "unit": "GB/s", "vs_baseline": None,
                "suite": "coll_pipeline", "seconds": round(dt, 6),
                "pvars": _micro_pvars(), "cumulative": True,
            })
        # -- sampled-overhead case: the SAME 1 MiB allreduce with the
        # continuous metrics plane armed (obs + sampler at a busy
        # 50 ms interval). The ratio line is the <2%-overhead claim
        # measured in situ, with the obs_sample_overhead_seconds pvar
        # delta as the sampler's own accounting of where time went.
        import ompi_release_tpu.obs as _obs_pkg
        from ompi_release_tpu.obs import sampler as _sampler
        from ompi_release_tpu.runtime.runtime import Runtime as _Rt

        from ompi_release_tpu.mca import pvar as _pvar_mod

        def _ov():
            pv = _pvar_mod.PVARS.lookup("obs_sample_overhead_seconds")
            return float(pv.read()) if pv is not None else 0.0

        call = lambda: tuned.allreduce(x)
        reps = 5
        _sync(call())
        t0 = time.perf_counter()
        for _ in range(reps):
            _sync(call())
        base_dt = (time.perf_counter() - t0) / reps
        was_enabled = _obs_pkg.enabled
        ov0 = _ov()
        _obs_pkg.enable()
        mca_var.set_value("obs_sample_interval", 0.05)
        _sampler.SAMPLER.start(0.05, runtime=_Rt._instance)
        try:
            _sync(call())
            t0 = time.perf_counter()
            for _ in range(reps):
                _sync(call())
            samp_dt = (time.perf_counter() - t0) / reps
        finally:
            _sampler.stop(final_push=False)
            if not was_enabled:
                _obs_pkg.disable()
            mca_var.VARS.unset("obs_sample_interval")
        lines.append({
            "metric": "coll_pipeline_allreduce_1MiB_sampled",
            "value": round(base_dt / max(samp_dt, 1e-9), 4),
            "unit": "x_vs_sampled_run", "vs_baseline": None,
            "suite": "coll_pipeline",
            "seconds": round(samp_dt, 6),
            "unsampled_seconds": round(base_dt, 6),
            "sampler_overhead_s": round(_ov() - ov0, 6),
            "pvars": _micro_pvars(), "cumulative": True,
        })
    finally:
        mca_var.VARS.unset("coll_tuned_allreduce_algorithm")
        mca_var.VARS.unset("coll_tuned_bcast_algorithm")
        mca_var.VARS.unset("coll_pipeline_segsize")
        tuned.free()

    # -- fusion case: 64 small tensors through the fusion buffer
    from ompi_release_tpu.mca import pvar as _pvar_mod

    def _counter(name):
        pv = _pvar_mod.PVARS.lookup(name)
        return float(pv.read()) if pv is not None else 0.0

    b0, f0 = _counter("coll_fusion_batched"), _counter("coll_fusion_flushes")
    fb = world.fusion_buffer()
    tensors = 64
    small = [np.full((world.size, 256), i, np.float32)
             for i in range(tensors)]
    t0 = time.perf_counter()
    handles = [fb.allreduce(s) for s in small]
    fb.flush()
    vals = [h.result() for h in handles]
    dt = time.perf_counter() - t0
    np.testing.assert_allclose(
        np.asarray(vals[3][0]), np.full(256, 3.0 * world.size), rtol=0
    )
    fused = int(_counter("coll_fusion_batched") - b0)
    issued = int(_counter("coll_fusion_flushes") - f0)
    lines.append({
        "metric": "coll_fusion_64x1KiB", "value": issued, "unit":
        "device_collectives", "vs_baseline": None,
        "suite": "coll_fusion", "tensors_fused": fused,
        "fewer_collectives_than_tensors": issued < fused,
        "seconds": round(dt, 6),
        "pvars": _micro_pvars(), "cumulative": True,
    })
    return lines  # main()'s emit() stamps the backend label


def _steady_state_micro_suite():
    """Interpreted-vs-compiled steady state (the compiled whole-
    schedule plan layer, coll/plan): the SAME collective at 4 KiB–
    1 MiB run through the fully interpreted per-call dispatch
    (``coll_compiled=0``) and through frozen compiled plans, one-shot
    blocking AND MPI-4 persistent. Python-orchestration time is
    separated from device/wire time two ways that must agree: the
    ``coll_orchestration_seconds`` pvar delta (the dispatch path's own
    accounting, the acceptance witness) and wall − (wall − orch).
    Every compiled leg asserts BITWISE parity against its interpreted
    twin in-app before a single line is emitted — the plans fire the
    very programs the interpreted path compiled, so this is a
    structural identity being spot-checked, not a tolerance."""
    import ompi_release_tpu as mpi
    from ompi_release_tpu.mca import pvar as _pvar_mod
    from ompi_release_tpu.mca import var as mca_var

    world = mpi.init()
    lines = []
    KiB = 1024
    # the tuned component's pipelined/segmented schedules are the
    # documented per-call Python overhead (ring segments, binomial
    # segment trees, per-dispatch decision rules) — the comparison the
    # compiled plans exist to win. Force them for both legs.
    mca_var.set_value("coll", "tuned")
    try:
        tuned_i = world.dup(name="steady_interp")
        tuned_c = world.dup(name="steady_comp")
    finally:
        mca_var.VARS.unset("coll")
    mca_var.set_value("coll_tuned_allreduce_algorithm", "ring")
    mca_var.set_value("coll_tuned_bcast_algorithm", "binomial")
    mca_var.set_value("coll_pipeline_segsize", 64 * KiB)

    def _orch():
        pv = _pvar_mod.PVARS.lookup("coll_orchestration_seconds")
        return float(pv.read()) if pv is not None else 0.0

    def _hits():
        pv = _pvar_mod.PVARS.lookup("coll_compiled_cache_hits")
        return pv.read() if pv is not None else {"sum": 0, "count": 0}

    reps = 30
    cases = [("allreduce", 4 * KiB), ("allreduce", 256 * KiB),
             ("allreduce", MiB), ("bcast", 256 * KiB),
             ("allgather", 256 * KiB)]
    try:
        _steady_cases(cases, reps, world, tuned_i, tuned_c, lines,
                      _orch, _hits, mca_var)
    finally:
        mca_var.VARS.unset("coll_tuned_allreduce_algorithm")
        mca_var.VARS.unset("coll_tuned_bcast_algorithm")
        mca_var.VARS.unset("coll_pipeline_segsize")
        tuned_i.free()
        tuned_c.free()

    # spanning leg: a real 3-process loopback job fires the SAME
    # 256 KiB allreduce interpreted vs through frozen wire plans
    # (precomposed round structure + frame headers) vs through frozen
    # plans WITH the obs plane on (the flight-recorder leg — the
    # "tracing never de-optimizes the hot path" acceptance factor);
    # orchestration is the posting+dispatch pvar delta, parity and
    # plan-replay (cache-hit deltas) asserted in-app. The obs leg
    # leaves ledger-p*.json dumps behind which tpu-doctor must expand
    # into cross-process flow arrows — checked host-side below.
    import os
    import tempfile

    from ompi_release_tpu.tools.tpurun import run_loopback_app

    dump_dir = tempfile.mkdtemp(prefix="steady_obs_")
    doc = run_loopback_app(
        3, _STEADY_SPAN_APP % {"repo": os.path.dirname(
            os.path.abspath(__file__)), "dump": dump_dir}, {},
        "steady_span.json", timeout_s=280)
    if doc is None:
        lines.append({
            "metric": "steady_spanning_suite", "value": None,
            "unit": None, "vs_baseline": None,
            "error": "loopback job failed"})
    else:
        for ln in doc["lines"]:
            ln.setdefault("suite", "steady_state")
            ln.setdefault("vs_baseline", None)
            lines.append(ln)
        lines.append(_steady_obs_trace_line(dump_dir))
    return lines


def _steady_obs_trace_line(dump_dir):
    """Host-side check of the obs leg's flight-recorder dumps: doctor
    must expand the per-rank binary rings against the frozen plan
    metadata into synthetic spans whose flow ids PAIR across ranks
    (the merged-trace arrows). Informational metric (no gate prefix);
    the hard signal is paired_flows > 0."""
    from ompi_release_tpu.obs import doctor as _doctor

    line = {"metric": "obs_ledger_trace_spanning_allreduce_256KiB",
            "unit": None, "vs_baseline": None, "suite": "steady_state"}
    try:
        dumps = _doctor.load_dir(dump_dir)
        ledger_spans = [s for d in dumps for s in d["spans"]
                        if s.get("ledger")]
        pairs = [p for p in _doctor.flow_pairs(dumps)
                 if p["src"].get("ledger") and p["cross_process"]]
        line.update({
            "value": len(pairs), "ledger_spans": len(ledger_spans),
            "paired_flows": len(pairs),
            "arrows_reconstructed": bool(pairs),
        })
        assert ledger_spans, "obs leg left no ledger dumps to expand"
        assert pairs, ("ledger-reconstructed sends/recvs did not pair "
                       "into cross-process flow arrows")
    except AssertionError as e:
        line.update({"value": None, "error": str(e)})
    return line


def _steady_cases(cases, reps, world, tuned_i, tuned_c, lines,
                  _orch, _hits, mca_var):
    for coll, nbytes in cases:
        elems = max(1, nbytes // 4)
        x = (np.arange(world.size * elems, dtype=np.float32)
             .reshape(world.size, elems) * 0.5)
        label = f"{coll}_{_human(nbytes)}"

        def call(comm, _c=coll, _x=x):
            if _c == "allreduce":
                return comm.allreduce(_x)
            if _c == "bcast":
                return comm.bcast(_x, root=0)
            return comm.allgather(_x)

        def timed_leg(comm):
            _sync(call(comm))  # warm: compile / freeze the plan
            o0 = _orch()
            t0 = time.perf_counter()
            for _ in range(reps):
                _sync(call(comm))
            wall = (time.perf_counter() - t0) / reps
            orch = (_orch() - o0) / reps
            return wall, orch, np.asarray(call(comm))

        mca_var.set_value("coll_compiled", 0)
        try:
            wall_i, orch_i, want = timed_leg(tuned_i)
        finally:
            mca_var.VARS.unset("coll_compiled")

        h0 = _hits()
        wall_c, orch_c, got = timed_leg(tuned_c)
        h1 = _hits()
        np.testing.assert_array_equal(got, want)  # BITWISE in-app
        assert h1["sum"] - h0["sum"] >= reps, (
            "compiled leg did not fire frozen plans")
        wall_p = orch_p = None
        if coll == "allreduce":
            # MPI-4 persistent: start() re-fires the same frozen
            # plan the blocking calls froze (signature memoized at
            # *_init — start() builds nothing)
            req = tuned_c.allreduce_init(x)
            req.start(); req.wait()
            o0 = _orch()
            t0 = time.perf_counter()
            for _ in range(reps):
                req.start()
                req.wait()
            wall_p = (time.perf_counter() - t0) / reps
            orch_p = (_orch() - o0) / reps
            np.testing.assert_array_equal(np.asarray(req.value), want)

        common = {
            "suite": "steady_state", "vs_baseline": None,
            "reps": reps, "bytes": nbytes,
        }
        lines.append({
            "metric": f"steady_orch_{label}_interpreted",
            "value": round(orch_i, 9), "unit": "s",
            "wall_seconds": round(wall_i, 9),
            "comm_alone_seconds": round(wall_i - orch_i, 9), **common,
        })
        lines.append({
            "metric": f"steady_orch_{label}_compiled",
            "value": round(orch_c, 9), "unit": "s",
            "wall_seconds": round(wall_c, 9),
            "comm_alone_seconds": round(wall_c - orch_c, 9), **common,
        })
        lines.append({
            "metric": f"compiled_{label}_orch_speedup",
            "value": round(orch_i / max(orch_c, 1e-12), 3),
            "unit": "x_orchestration",
            "interpreted_orch_s": round(orch_i, 9),
            "compiled_orch_s": round(orch_c, 9),
            "wall_speedup": round(wall_i / max(wall_c, 1e-12), 3),
            **common,
        })
        if wall_p is not None:
            lines.append({
                "metric": f"steady_orch_{label}_persistent",
                "value": round(orch_p, 9), "unit": "s",
                "wall_seconds": round(wall_p, 9), **common,
            })


def _rma_steady_micro_suite():
    """Interpreted-vs-planned steady state for the one-sided plane
    (the RMA analogue of the coll steady-state suite, osc/plan): the
    SAME fence epoch — put + accumulate + get on a driver window — run
    through the fully interpreted per-epoch dispatch
    (``osc_compiled=0``) and through frozen access plans whose single
    fused XLA program replays per epoch. Python-orchestration time is
    the ``osc_orchestration_seconds`` pvar delta (both paths feed it);
    the planned leg asserts BITWISE parity against its interpreted
    twin in-app (same branch lambdas, so structural identity) and that
    ``osc_plan_cache_hits`` recorded >= reps replays. A second block
    does the same for the planned symmetric-heap bulk path
    (``shmem_bulk``): batched puts/AMOs drained as one window epoch
    per quiet vs the per-call epochs, wall-time compared with parity
    on every PE's final heap contents."""
    import jax.numpy as jnp

    import ompi_release_tpu as mpi
    from ompi_release_tpu import ops
    # eager: osc/plan is lazily imported by the window close path, and
    # its pvars only exist after module import — baseline reads below
    # need them registered NOW
    import ompi_release_tpu.osc.plan  # noqa: F401
    from ompi_release_tpu.mca import pvar as _pvar_mod
    from ompi_release_tpu.mca import var as mca_var
    from ompi_release_tpu.osc import win_allocate
    from ompi_release_tpu.oshmem import shmem as _shmem_mod

    world = mpi.init()
    lines = []
    KiB = 1024
    reps = 30

    def _orch():
        pv = _pvar_mod.PVARS.lookup("osc_orchestration_seconds")
        return float(pv.read()) if pv is not None else 0.0

    def _hits():
        pv = _pvar_mod.PVARS.lookup("osc_plan_cache_hits")
        return pv.read() if pv is not None else {"sum": 0, "count": 0}

    for nbytes in (4 * KiB, 64 * KiB, 256 * KiB):
        elems = max(1, nbytes // 4)
        label = f"rma_fence_{_human(nbytes)}"
        pay = np.arange(elems, dtype=np.float32) * 0.5
        acc = np.full(elems, 0.25, np.float32)

        def epoch(win, _pay=pay, _acc=acc):
            win.fence()
            win.put(_pay, target=1)
            win.accumulate(_acc, target=1, op=ops.SUM)
            g = win.get(target=1)
            win.fence_end()
            return np.asarray(g.value)

        def leg(win):
            epoch(win)  # warm: freeze the plan / compile branches
            o0 = _orch()
            t0 = time.perf_counter()
            for _ in range(reps):
                out = epoch(win)
            wall = (time.perf_counter() - t0) / reps
            orch = (_orch() - o0) / reps
            return wall, orch, out, np.asarray(win.read())

        win_i = win_allocate(world, (elems,), jnp.float32)
        win_c = win_allocate(world, (elems,), jnp.float32)
        try:
            mca_var.set_value("osc_compiled", 0)
            try:
                wall_i, orch_i, got_i, data_i = leg(win_i)
            finally:
                mca_var.VARS.unset("osc_compiled")
            h0 = _hits()
            wall_c, orch_c, got_c, data_c = leg(win_c)
            h1 = _hits()
            np.testing.assert_array_equal(got_c, got_i)  # BITWISE
            np.testing.assert_array_equal(data_c, data_i)
            assert h1["sum"] - h0["sum"] >= reps, (
                "planned leg did not replay frozen epoch plans")
        finally:
            win_i.free()
            win_c.free()

        common = {"suite": "steady_state", "vs_baseline": None,
                  "reps": reps, "bytes": nbytes}
        lines.append({
            "metric": f"steady_{label}_interpreted",
            "value": round(orch_i, 9), "unit": "s",
            "wall_seconds": round(wall_i, 9),
            "comm_alone_seconds": round(wall_i - orch_i, 9), **common,
        })
        lines.append({
            "metric": f"steady_{label}_planned",
            "value": round(orch_c, 9), "unit": "s",
            "wall_seconds": round(wall_c, 9),
            "comm_alone_seconds": round(wall_c - orch_c, 9), **common,
        })
        lines.append({
            "metric": f"compiled_{label}_orch_speedup",
            "value": round(orch_i / max(orch_c, 1e-12), 3),
            "unit": "x_orchestration",
            "interpreted_orch_s": round(orch_i, 9),
            "planned_orch_s": round(orch_c, 9),
            "wall_speedup": round(wall_i / max(wall_c, 1e-12), 3),
            **common,
        })

    # planned symmetric-heap bulk path: per-call epochs vs one drained
    # window epoch per quiet, same op stream, parity on every PE
    shmem = _shmem_mod.shmem_init()

    def _bulk_ops():
        pv = _pvar_mod.PVARS.lookup("shmem_bulk_ops")
        return float(pv.read()) if pv is not None else 0.0

    for nbytes in (4 * KiB, 64 * KiB):
        elems = max(1, nbytes // 4)
        label = f"shmem_put_{_human(nbytes)}"
        vals = [np.full(elems, float(pe + 1), np.float32)
                for pe in range(shmem.n_pes)]
        bump = np.full(elems, 0.5, np.float32)

        def leg():
            sym = shmem.malloc((elems,), jnp.float32)
            try:
                for pe in range(shmem.n_pes):  # warm
                    shmem.put(sym, vals[pe], pe=pe)
                shmem.quiet()
                t0 = time.perf_counter()
                for _ in range(reps):
                    for pe in range(shmem.n_pes):
                        shmem.put(sym, vals[pe], pe=pe)
                        shmem.atomic_add(sym, bump, pe=pe)
                    shmem.quiet()
                wall = (time.perf_counter() - t0) / reps
                out = np.stack([np.asarray(shmem.get(sym, pe=pe))
                                for pe in range(shmem.n_pes)])
            finally:
                sym.free()
            return wall, out

        mca_var.set_value("shmem_bulk", 0)
        try:
            wall_p, want = leg()
        finally:
            mca_var.VARS.unset("shmem_bulk")
        b0 = _bulk_ops()
        wall_b, got = leg()
        assert _bulk_ops() - b0 >= reps, (
            "bulk leg did not route through the planned heap path")
        np.testing.assert_array_equal(got, want)  # BITWISE in-app

        common = {"suite": "steady_state", "vs_baseline": None,
                  "reps": reps, "bytes": nbytes}
        lines.append({
            "metric": f"steady_{label}_percall",
            "value": round(wall_p, 9), "unit": "s", **common,
        })
        lines.append({
            "metric": f"steady_{label}_bulk",
            "value": round(wall_b, 9), "unit": "s", **common,
        })
        lines.append({
            "metric": f"compiled_{label}_bulk_speedup",
            "value": round(wall_p / max(wall_b, 1e-12), 3),
            "unit": "x_wall", **common,
        })
    return lines


_STEADY_SPAN_APP = r"""
import json, os, sys, time
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import ompi_release_tpu as mpi
from ompi_release_tpu.mca import pvar, var as mca_var
from ompi_release_tpu.runtime.runtime import Runtime

def _pv(name):
    p = pvar.PVARS.lookup(name)
    return float(p.read()) if p is not None else 0.0

world = mpi.init()
elems = (256 * 1024) // 4
x = np.stack([np.arange(elems, dtype=np.float32) * 0.25
              for _ in range(len(world.local_comm_ranks))])
reps = 10

def leg():
    np.asarray(world.allreduce(x))  # warm: record/freeze or compile
    o0 = _pv("coll_orchestration_seconds")
    t0 = time.perf_counter()
    for _ in range(reps):
        out = np.asarray(world.allreduce(x))
    wall = (time.perf_counter() - t0) / reps
    orch = (_pv("coll_orchestration_seconds") - o0) / reps
    return wall, orch, out

def _hits():
    p = pvar.PVARS.lookup("coll_compiled_cache_hits")
    return p.read() if p is not None else {"sum": 0, "count": 0}

mca_var.set_value("coll_compiled", 0)
wall_i, orch_i, want = leg()
mca_var.VARS.unset("coll_compiled")
wall_c, orch_c, got = leg()
np.testing.assert_array_equal(got, want)  # BITWISE in-app

# obs-ON compiled leg: the flight recorder rides the SAME frozen
# plans — hit counter keeps advancing, results stay bitwise, and
# every fire appends one fixed-size record to the binary ledger ring
import ompi_release_tpu.obs as _obs_pkg
from ompi_release_tpu.obs import ledger as _ledger
mca_var.set_value("obs_dump_dir", %(dump)r)
_obs_pkg.enable()
h0 = _hits()
wall_o, orch_o, got_o = leg()
h1 = _hits()
np.testing.assert_array_equal(got_o, want)  # observed: still BITWISE
assert h1["sum"] - h0["sum"] >= reps, "obs-ON leg fell off the frozen plan"
recs = _ledger.records()
assert recs, "observed compiled fires must land in the ledger"
rec = recs[-1]
rec_bytes = _ledger.snapshot()["record_bytes"] + 8 * len(rec["round_ts"])

pidx = int(Runtime.current().bootstrap["process_index"])
if pidx == 0:
    with open(os.environ["OMPITPU_LOOPBACK_OUT"], "w") as f:
        json.dump({"lines": [
            {"metric": "steady_orch_spanning_allreduce_256KiB_interpreted",
             "value": round(orch_i, 9), "unit": "s",
             "wall_seconds": round(wall_i, 9), "reps": reps},
            {"metric": "steady_orch_spanning_allreduce_256KiB_compiled",
             "value": round(orch_c, 9), "unit": "s",
             "wall_seconds": round(wall_c, 9), "reps": reps},
            {"metric": "compiled_spanning_allreduce_orch_speedup",
             "value": round(orch_i / max(orch_c, 1e-12), 3),
             "unit": "x_orchestration",
             "wall_speedup": round(wall_i / max(wall_c, 1e-12), 3)},
            {"metric": "steady_obs_orch_spanning_allreduce_256KiB_compiled",
             "value": round(orch_o, 9), "unit": "s",
             "wall_seconds": round(wall_o, 9), "reps": reps},
            # THE acceptance factor: obs-ON compiled leg within 1.15x
            # of the obs-OFF compiled leg (lower-better gated via the
            # steady_ prefix so the budget holds across rounds)
            {"metric": "steady_obs_overhead_spanning_allreduce_256KiB",
             "value": round(wall_o / max(wall_c, 1e-12), 3),
             "unit": "ratio", "budget": 1.15,
             "orch_ratio": round(orch_o / max(orch_c, 1e-12), 3)},
            {"metric": "ledger_record_bytes_spanning_allreduce_256KiB",
             "value": rec_bytes, "unit": "bytes",
             "wire_rounds": len(rec["round_ts"])},
        ]}, f)
mpi.finalize()
"""


def _native_rounds_micro_suite():
    """Three-way orchestration split for spanning collectives over a
    REAL 3-process loopback job: the SAME allreduce/bcast/allgather at
    4 KiB–1 MiB fired (a) fully interpreted (``coll_compiled=0``, the
    per-call dispatch), (b) through frozen wire plans replayed by the
    Python PlannedXchg loop (``coll_plan_native=0``), and (c) through
    the native C plan executor (one ctypes slice loop walks every
    round). Orchestration is the ``coll_orchestration_seconds`` pvar
    delta; every leg asserts BITWISE parity against its interpreted
    twin in-app, the native leg asserts it actually fired C-side
    (``plan_native_fires`` advanced, zero ``plan_native_fallbacks``),
    and the app asserts ``wire_native_fallback_copies`` stayed zero —
    the contiguous path never staged through a bounce buffer. THE
    acceptance factor rides ``compiled_native_allreduce_*_orch_speedup``
    (planned-replay orchestration / native orchestration, >= 2x at
    <= 256 KiB); gate directions come for free from the ``steady_``
    (lower-better) and ``compiled_`` (higher-better) prefixes."""
    import os

    from ompi_release_tpu.tools.tpurun import run_loopback_app

    doc = run_loopback_app(
        3, _NATIVE_ROUNDS_APP % {"repo": os.path.dirname(
            os.path.abspath(__file__))}, {},
        "native_rounds.json", timeout_s=420)
    if doc is None:
        return [{"metric": "native_rounds_suite", "value": None,
                 "unit": None, "vs_baseline": None,
                 "error": "loopback job failed"}]
    lines = []
    for ln in doc["lines"]:
        ln.setdefault("suite", "native_rounds")
        ln.setdefault("vs_baseline", None)
        lines.append(ln)
    return lines


_NATIVE_ROUNDS_APP = r"""
import json, os, sys, time
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import ompi_release_tpu as mpi
from ompi_release_tpu.mca import pvar, var as mca_var
from ompi_release_tpu.runtime.runtime import Runtime

def _pv(name):
    p = pvar.PVARS.lookup(name)
    return float(p.read()) if p is not None else 0.0

world = mpi.init()
L = len(world.local_comm_ranks)
# recursive doubling freezes to a byte-provable plan (the ring
# algorithm's mid-round partial mutations withdraw to PlannedXchg --
# that selection is the fallback contract, not a failure)
mca_var.set_value("hier_inter_algorithm", "recursive_doubling")
reps = 8
KiB = 1024
cases = [("allreduce", 4 * KiB), ("allreduce", 256 * KiB),
         ("allreduce", 1024 * KiB), ("bcast", 4 * KiB),
         ("bcast", 256 * KiB), ("allgather", 64 * KiB)]
lines = []

def call(coll, x):
    if coll == "allreduce":
        return np.asarray(world.allreduce(x))
    if coll == "bcast":
        return np.asarray(world.bcast(x, root=0))
    return np.asarray(world.allgather(x))

def leg(coll, x):
    call(coll, x)  # warm: record + freeze (+ native lowering)
    o0 = _pv("coll_orchestration_seconds")
    t0 = time.perf_counter()
    for _ in range(reps):
        out = call(coll, x)
    wall = (time.perf_counter() - t0) / reps
    orch = (_pv("coll_orchestration_seconds") - o0) / reps
    return wall, orch, out

for coll, nbytes in cases:
    elems = max(1, nbytes // 4)
    x = np.stack([np.arange(elems, dtype=np.float32) * 0.25 + i
                  for i in range(L)])
    hum = ("1MiB" if nbytes >= 1024 * KiB
           else "%%dKiB" %% (nbytes // KiB))
    label = coll + "_" + hum

    mca_var.set_value("coll_compiled", 0)
    wall_i, orch_i, want = leg(coll, x)
    mca_var.VARS.unset("coll_compiled")

    mca_var.set_value("coll_plan_native", 0)
    wall_p, orch_p, got_p = leg(coll, x)
    mca_var.VARS.unset("coll_plan_native")

    f0, fb0 = _pv("plan_native_fires"), _pv("plan_native_fallbacks")
    wall_n, orch_n, got_n = leg(coll, x)
    f1, fb1 = _pv("plan_native_fires"), _pv("plan_native_fallbacks")

    np.testing.assert_array_equal(got_p, want)  # BITWISE in-app
    np.testing.assert_array_equal(got_n, want)  # BITWISE in-app
    assert f1 - f0 >= reps, (
        "native leg fell back to interpreted replay: %%s" %% label)
    assert fb1 - fb0 == 0, (
        "native leg took per-fire safety fallbacks: %%s" %% label)
    speed = orch_p / max(orch_n, 1e-12)
    if coll == "allreduce" and nbytes <= 256 * KiB:
        # THE acceptance factor: the C slice loop beats the Python
        # round replay by >= 2x on orchestration at small payloads
        assert speed >= 2.0, (
            "native orchestration speedup %%.2fx < 2x at %%s"
            %% (speed, label))

    common = {"reps": reps, "bytes": nbytes}
    lines.append({"metric": "steady_native_orch_%%s_interpreted" %% label,
                  "value": round(orch_i, 9), "unit": "s",
                  "wall_seconds": round(wall_i, 9),
                  "comm_alone_seconds": round(wall_i - orch_i, 9),
                  **common})
    lines.append({"metric": "steady_native_orch_%%s_planned" %% label,
                  "value": round(orch_p, 9), "unit": "s",
                  "wall_seconds": round(wall_p, 9),
                  "comm_alone_seconds": round(wall_p - orch_p, 9),
                  **common})
    lines.append({"metric": "steady_native_orch_%%s_native" %% label,
                  "value": round(orch_n, 9), "unit": "s",
                  "wall_seconds": round(wall_n, 9),
                  "comm_alone_seconds": round(wall_n - orch_n, 9),
                  **common})
    lines.append({"metric": "compiled_native_%%s_orch_speedup" %% label,
                  "value": round(speed, 3), "unit": "x_orchestration",
                  "planned_orch_s": round(orch_p, 9),
                  "native_orch_s": round(orch_n, 9),
                  "vs_interpreted": round(orch_i / max(orch_n, 1e-12), 3),
                  "wall_speedup": round(wall_p / max(wall_n, 1e-12), 3),
                  **common})

assert _pv("wire_native_fallback_copies") == 0, (
    "contiguous native fires must not stage through bounce buffers")
lines.append({"metric": "native_rounds_pool",
              "value": _pv("plan_pool_hits"), "unit": None,
              "pool_bytes": _pv("plan_pool_bytes"),
              "native_fires": _pv("plan_native_fires"),
              "native_fallbacks": _pv("plan_native_fallbacks")})

pidx = int(Runtime.current().bootstrap["process_index"])
if pidx == 0:
    with open(os.environ["OMPITPU_LOOPBACK_OUT"], "w") as f:
        json.dump({"lines": lines}, f)
mpi.finalize()
"""


def _sentinel_micro_suite():
    """sentinel lines: the SAME 1 MiB allreduce with the collective
    contract sentinel off (obs_sentinel=0 — one attribute check per
    collective) and on in post-hoc mode (obs_sentinel=1 — signature
    hash + journal event per collective), with the
    ``sentinel_ops_hashed`` pvar delta as the witness that the
    enabled leg really hashed every call. The obs plane is ON for
    BOTH legs so the overhead_frac isolates the sentinel's own cost
    — only the obs_sentinel cvar varies between legs. All three
    metrics gate lower-better (tpu_bench_gate: ``s`` unit /
    ``sentinel_`` prefix), so the near-zero-overhead claim is
    enforced across rounds, not asserted once."""
    import ompi_release_tpu as mpi
    import ompi_release_tpu.obs as _obs_pkg
    from ompi_release_tpu.mca import pvar as _pvar_mod
    from ompi_release_tpu.mca import var as mca_var
    from ompi_release_tpu.obs import sentinel as _sentinel

    world = mpi.init()
    elems = MiB // 4
    x = np.ones((world.size, elems), np.float32)
    call = lambda: world.allreduce(x)  # noqa: E731
    reps = 5

    def timed():
        _sync(call())  # warm the plan cache outside the timing
        t0 = time.perf_counter()
        for _ in range(reps):
            _sync(call())
        return (time.perf_counter() - t0) / reps

    def _hashed():
        pv = _pvar_mod.PVARS.lookup("sentinel_ops_hashed")
        return float(pv.read()) if pv is not None else 0.0

    # the disabled leg must really BE disabled, whatever the operator
    # passed on the command line — and teardown must hand their
    # setting back, not strip it for the rest of the round
    prior = int(mca_var.get("obs_sentinel", 0) or 0)
    was_enabled = _obs_pkg.enabled
    try:
        _obs_pkg.enable()  # same obs state on BOTH legs
        mca_var.set_value("obs_sentinel", 0)
        _sentinel.refresh(True)
        base_dt = timed()  # obs_sentinel=0: the provably-free leg
        mca_var.set_value("obs_sentinel", 1)
        _sentinel.refresh(True)
        h0 = _hashed()
        sent_dt = timed()
    finally:
        if prior:
            mca_var.set_value("obs_sentinel", prior)
        else:
            mca_var.VARS.unset("obs_sentinel")
        if not was_enabled:
            _obs_pkg.disable()
        else:
            _sentinel.refresh(True)
    hashed = int(_hashed() - h0)
    assert hashed >= reps, (
        f"sentinel witness: expected >= {reps} hashed ops, got {hashed}")
    return [{
        "metric": "sentinel_allreduce_1MiB_disabled",
        "value": round(base_dt, 6), "unit": "s", "vs_baseline": None,
        "suite": "sentinel",
    }, {
        "metric": "sentinel_allreduce_1MiB_posthoc",
        "value": round(sent_dt, 6), "unit": "s", "vs_baseline": None,
        "suite": "sentinel", "ops_hashed": hashed,
    }, {
        "metric": "sentinel_allreduce_overhead_frac",
        "value": round(sent_dt / max(base_dt, 1e-9) - 1.0, 4),
        "unit": "frac_overhead", "vs_baseline": None,
        "suite": "sentinel", "ops_hashed": hashed,
        "disabled_seconds": round(base_dt, 6),
        "enabled_seconds": round(sent_dt, 6),
    }]


#: worker app for the wire micro-suite: a REAL 3-process tpurun job on
#: the CPU mesh (the wire is host-side regardless of accelerator), so
#: the emitted numbers exercise the exact envelope/fragment/lane code
#: a multi-controller job runs. Process 0 writes its JSON lines to
#: OMPITPU_WIRE_BENCH_OUT; the parent re-emits them as bench lines.
_WIRE_BENCH_APP = r'''
import json, os, sys, threading, time
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# distinct shm identity per worker: every byte rides the DCN staged
# path — the fragment pipeline under measurement (shm handoffs are a
# single segment memcpy and would hide it)
os.environ["OMPITPU_HOST_ID"] = (
    "wirebench-" + os.environ["OMPITPU_NODE_ID"])
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import ompi_release_tpu as mpi
from ompi_release_tpu.mca import pvar, var as mca_var
from ompi_release_tpu.runtime.runtime import Runtime

SIZES = json.loads(os.environ["OMPITPU_WIRE_BENCH_SIZES"])
HOL_MIB = int(os.environ.get("OMPITPU_WIRE_BENCH_HOL_MIB", "8"))
AGV_MIB = int(os.environ.get("OMPITPU_WIRE_BENCH_AGV_MIB", "1"))
world = mpi.init()
rt = Runtime.current()
me = rt.bootstrap["process_index"]
lines = []

def _hol():
    pv = pvar.PVARS.lookup("wire_hol_wait_seconds")
    return float(pv.read()) if pv is not None else 0.0

# -- p2p ping-pong bandwidth (rank 1 in p0 <-> rank 3 in p1) ---------------
for size in SIZES:
    x = np.ones(max(1, size // 4), np.float32)
    best = None
    for _ in range(3):
        world.barrier()
        if me == 0:
            t0 = time.perf_counter()
            world.send(x, 3, tag=11, rank=1)
            v, _st = world.recv(source=3, tag=12, rank=1)
            dt = time.perf_counter() - t0
            assert np.asarray(v).shape == x.shape
            best = dt if best is None else min(best, dt)
        elif me == 1:
            v, _st = world.recv(source=1, tag=11, rank=3)
            world.send(np.asarray(v), 1, tag=12, rank=3)
    if me == 0:
        lines.append({
            "metric": "wire_p2p_%%dMiB" %% (size >> 20),
            "value": round(2 * size / best / 1e9, 4), "unit": "GB/s",
            "vs_baseline": None, "suite": "wire", "rtt_s": round(best, 5),
        })

# -- two concurrent large transfers, distinct tags: lanes 4 vs 1 -----------
hol_size = HOL_MIB << 20
xh = np.ones(hol_size // 4, np.float32)
for lanes in (4, 1):
    mca_var.set_value("wire_p2p_lanes", lanes)
    world.barrier()
    h0 = _hol()
    world.barrier()
    if me == 0:
        t0 = time.perf_counter()
        ts = [threading.Thread(target=lambda: world.send(xh, 3, tag=1,
                                                         rank=0)),
              threading.Thread(target=lambda: world.send(xh, 3, tag=2,
                                                         rank=1))]
        for t in ts: t.start()
        for t in ts: t.join()
        wall = time.perf_counter() - t0
    elif me == 1:
        world.recv(source=1, tag=2, rank=3)
        world.recv(source=0, tag=1, rank=3)
    world.barrier()
    if me == 0:
        lines.append({
            "metric": "wire_hol_2x%%dMiB_lanes%%d" %% (HOL_MIB, lanes),
            "value": round(_hol() - h0, 4), "unit": "hol_wait_s",
            "vs_baseline": None, "suite": "wire",
            "wall_s": round(wall, 4),
        })
mca_var.VARS.unset("wire_p2p_lanes")

# -- spanning-comm allgatherv round: three wire configurations -------------
#   pipelined     zero-copy fragments + overlapped reap (the PR path)
#   legacy_frames wire_pipeline_segsize=0 (tobytes + ordered join)
#   sequential    pipelined frames, fixed process-order reap
agv = np.arange((AGV_MIB << 20) // 4, dtype=np.float32)
bufs = [agv + r for r in world.local_comm_ranks]
configs = (("pipelined", 1 << 20, True),
           ("legacy_frames", 0, True),
           ("sequential", 1 << 20, False))
times = {}
for key, seg, overlap in configs:
    mca_var.set_value("wire_pipeline_segsize", seg)
    mca_var.set_value("wire_overlap_exchange", overlap)
    world.barrier()
    best = None
    for _ in range(3):
        world.barrier()
        t0 = time.perf_counter()
        out = world.allgatherv(bufs)
        dt = time.perf_counter() - t0
        assert np.asarray(out).shape[0] == world.size * agv.shape[0]
        best = dt if best is None else min(best, dt)
    times[key] = best
mca_var.VARS.unset("wire_pipeline_segsize")
mca_var.VARS.unset("wire_overlap_exchange")

# -- skewed exchange: time-to-first-data, arrival order vs process order ---
# Process 1 (FIRST in reap order) enters its round late; the overlap
# reap returns process 2's payload almost immediately while the
# sequential baseline parks on the slow peer — the latency a pipelined
# consumer of early rows actually feels.
SKEW_S = 0.4
first = {}
rt_router = rt.wire
for key, overlap in (("overlap", True), ("sequential", False)):
    world.barrier()
    if me == 0:
        t0 = time.perf_counter()
        if overlap:
            pending = {1: 1, 2: 1}
            src, _arr = rt_router.coll_recv_any(world, pending)
            first[key] = time.perf_counter() - t0
            pending[src] -= 1
            while sum(pending.values()):
                s2, _ = rt_router.coll_recv_any(world, pending)
                pending[s2] -= 1
        else:
            _ = rt_router.coll_recv(world, 1)   # parks on the slow peer
            first[key] = time.perf_counter() - t0
            _ = rt_router.coll_recv(world, 2)
    elif me == 1:
        time.sleep(SKEW_S)
        rt_router.coll_send(world, 0, agv)
    else:
        rt_router.coll_send(world, 0, agv)
    world.barrier()

if me == 0:
    for key, _seg, _ov in configs:
        lines.append({
            "metric": "wire_allgatherv_%%dMiB_%%s" %% (AGV_MIB, key),
            "value": round(times[key], 4), "unit": "s",
            "vs_baseline": None, "suite": "wire",
        })
    lines.append({
        "metric": "wire_allgatherv_pipeline_speedup",
        "value": round(times["legacy_frames"]
                       / max(times["pipelined"], 1e-9), 4),
        "unit": "x_vs_legacy_framing", "vs_baseline": None,
        "suite": "wire",
    })
    lines.append({
        "metric": "wire_allgatherv_overlap_speedup",
        "value": round(times["sequential"]
                       / max(times["pipelined"], 1e-9), 4),
        "unit": "x_vs_sequential", "vs_baseline": None, "suite": "wire",
    })
    lines.append({
        "metric": "wire_skewed_first_data_overlap",
        "value": round(first["overlap"], 4), "unit": "s",
        "vs_baseline": None, "suite": "wire",
        "sequential_s": round(first["sequential"], 4),
        "first_data_speedup": round(
            first["sequential"] / max(first["overlap"], 1e-9), 2),
        "skew_s": SKEW_S,
        "pvars": {k: v for k, v in pvar.PVARS.read_all().items()
                  if k.startswith(("wire_", "btl_dcn_"))},
        "cumulative": True,
    })
    with open(os.environ["OMPITPU_WIRE_BENCH_OUT"], "w") as f:
        json.dump(lines, f)
world.barrier()
mpi.finalize()
'''


#: worker app for the hier_scaling micro-suite: a REAL 4-process
#: tpurun job (one device per process) timing the spanning-collective
#: INTER schedules against each other and reading the per-process
#: hier_inter_bytes / hier_inter_msgs_sent deltas that prove the
#: O(P^2) -> O(log P) / ~2n claims. Process 0 writes the JSON lines to
#: OMPITPU_HIER_BENCH_OUT.
_HIER_BENCH_APP = r'''
import json, math, os, sys, time
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import ompi_release_tpu as mpi
from ompi_release_tpu.mca import pvar, var as mca_var

SIZE = int(os.environ.get("OMPITPU_HIER_BENCH_BYTES", str(256 << 10)))
world = mpi.init()
from ompi_release_tpu.runtime.runtime import Runtime
rt = Runtime.current()
me = rt.bootstrap["process_index"]
P = n_procs = 4
assert world.size == 4, world.size

def _pv(name):
    p = pvar.PVARS.lookup(name)
    return float(p.read()) if p is not None else 0.0

x = np.ones((1, SIZE // 4), np.float32) * (me + 1)
want = float(sum(r + 1 for r in range(world.size)))
ALGS = ("linear", "recursive_doubling", "ring", "rabenseifner")
deltas, times = [], []
for alg in ALGS:
    mca_var.set_value("hier_inter_algorithm", alg)
    world.barrier()
    world.allreduce(x)          # warm the schedule + shadow programs
    world.barrier()
    b0 = _pv("hier_inter_bytes")
    t0 = time.perf_counter()
    got = np.asarray(world.allreduce(x))
    dt = time.perf_counter() - t0
    deltas.append(_pv("hier_inter_bytes") - b0)
    times.append(dt)
    assert abs(float(got[0][0]) - want) < 1e-3, got[0][0]
    mca_var.VARS.unset("hier_inter_algorithm")

# bcast: root send count, linear P-1 vs binomial ceil(log2 P)
bd = {}
for alg in ("linear", "binomial"):
    mca_var.set_value("hier_inter_algorithm", alg)
    world.barrier()
    s0 = _pv("hier_inter_msgs_sent")
    world.bcast(x, root=0)
    bd[alg] = _pv("hier_inter_msgs_sent") - s0
    mca_var.VARS.unset("hier_inter_algorithm")
world.barrier()

# every process's byte deltas to process 0 (AFTER the measurements)
rows = world.gatherv([np.asarray(deltas, np.float32)], root=0)
if me == 0:
    per_proc = np.asarray(rows).reshape(world.size, len(ALGS))
    lines = []
    for i, alg in enumerate(ALGS):
        worst = float(per_proc[:, i].max())
        lines.append({
            "metric": "hier_allreduce_%%dKiB_inter_bytes_%%s"
                      %% (SIZE >> 10, alg),
            "value": round(worst / SIZE, 4),
            "unit": "xN_bytes_per_proc_max", "vs_baseline": None,
            "suite": "hier_scaling", "procs": world.size,
            "per_proc_xN": [round(float(v) / SIZE, 4)
                            for v in per_proc[:, i]],
            "seconds": round(times[i], 5),
        })
    lines.append({
        "metric": "hier_bcast_root_msgs",
        "value": bd["binomial"], "unit": "sends_at_root",
        "vs_baseline": None, "suite": "hier_scaling",
        "linear_sends": bd["linear"],
        "binomial_depth_bound": math.ceil(math.log2(world.size)),
        "pvars": {k: v for k, v in pvar.PVARS.read_all().items()
                  if k.startswith("hier_")},
        "cumulative": True,
    })
    with open(os.environ["OMPITPU_LOOPBACK_OUT"], "w") as f:
        json.dump(lines, f)
world.barrier()
mpi.finalize()
'''


def _hier_micro_suite(backend_label):
    """hier_scaling lines: per-process inter BYTES of a 4-process
    spanning allreduce under every schedule (linear's (P-1)n = 3n vs
    ring/Rabenseifner's <= 2n + padding), and the bcast root's send
    count dropping from P-1 to the binomial ceil(log2 P) — measured
    through a real 4-process tpurun job on the CPU mesh (the inter
    step rides host wire transports either way)."""
    import os

    from ompi_release_tpu.tools.tpurun import run_loopback_app

    lines = run_loopback_app(
        4, _HIER_BENCH_APP % {"repo": os.path.dirname(
            os.path.abspath(__file__))},
        {"OMPITPU_HIER_BENCH_BYTES": str(
            (1 << 20) if backend_label is None else (256 << 10))},
        "hier_bench.json", timeout_s=300)
    if lines is None:
        return [{"metric": "hier_scaling_suite", "value": None,
                 "unit": None, "vs_baseline": None,
                 "error": "hier bench job failed"}]
    return lines  # main()'s emit() stamps the backend label


def _wire_micro_suite(backend_label):
    """Cross-process wire lines: p2p ping-pong bandwidth (1 MiB up to
    256 MiB on full machines), two concurrent distinct-tag transfers
    under 4 lanes vs 1 (the head-of-line pvar is the metric), and a
    spanning-comm allgatherv with overlapped vs sequential reaping —
    all through a REAL 3-process tpurun job, CPU mesh (the wire rides
    host sockets/shm either way). Same labelled CPU fallback contract
    as every other line: ``backend`` marks tpu_unavailable rounds."""
    import os
    import sys as _sys
    import tempfile

    from ompi_release_tpu.tools.tpurun import Job

    full = backend_label is None
    sizes = [1 << 20, 16 << 20, 64 << 20, 256 << 20] if full else \
        [1 << 20, 4 << 20, 16 << 20]
    with tempfile.TemporaryDirectory() as td:
        app = os.path.join(td, "wire_bench_app.py")
        out_path = os.path.join(td, "wire_bench.json")
        with open(app, "w") as f:
            f.write(_WIRE_BENCH_APP % {"repo": os.path.dirname(
                os.path.abspath(__file__))})
        env_keep = dict(os.environ)
        os.environ["OMPITPU_WIRE_BENCH_SIZES"] = json.dumps(sizes)
        os.environ["OMPITPU_WIRE_BENCH_OUT"] = out_path
        os.environ["OMPITPU_WIRE_BENCH_HOL_MIB"] = "32" if full else "8"
        os.environ["OMPITPU_WIRE_BENCH_AGV_MIB"] = "4" if full else "1"
        try:
            job = Job(3, [_sys.executable, app], [], heartbeat_s=0.5,
                      miss_limit=8)
            rc = job.run(timeout_s=420 if full else 240)
        finally:
            os.environ.clear()
            os.environ.update(env_keep)
        if rc != 0 or not os.path.exists(out_path):
            return [{"metric": "wire_micro_suite", "value": None,
                     "unit": None, "vs_baseline": None,
                     "error": f"wire bench job rc={rc}"}]
        with open(out_path) as f:
            lines = json.load(f)
    return lines  # main()'s emit() stamps the backend label


#: worker app for the native_wire micro-suite: 2-process tpurun jobs
#: on the CPU mesh driving the SAME p2p ping-pong through three byte
#: paths — the shm ring (co-hosted, the headline numbers), the
#: vectored socket (forced cross-host via OMPITPU_HOST_ID), and the
#: portable staged frames (capability cards stripped LIVE mid-job,
#: proving the per-peer fallback reassembles the byte-identical
#: framing) — plus HOL-lane and QoS legs over the native BTL and the
#: wire_native_copies_per_mib zero-copy witness. Process 0 writes its
#: JSON lines to OMPITPU_LOOPBACK_OUT.
_NATIVE_WIRE_BENCH_APP = r'''
import json, os, sys, threading, time
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
MODE = os.environ["OMPITPU_NW_BENCH_MODE"]  # shm | tcp | qos
if MODE == "tcp":
    # distinct shm identity: fragments ride the vectored socket path
    os.environ["OMPITPU_HOST_ID"] = (
        "nwbench-" + os.environ["OMPITPU_NODE_ID"])
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import ompi_release_tpu as mpi
from ompi_release_tpu.mca import pvar, var as mca_var
from ompi_release_tpu.runtime.runtime import Runtime

if MODE == "qos":
    # QoS lane partitioning must exist before the router comes up
    mca_var.set_value("wire_qos_classes", "latency:3,bulk:1")
    mca_var.set_value("wire_qos_class", "latency")
SIZES = json.loads(os.environ.get("OMPITPU_NW_BENCH_SIZES", "[]"))
HOL_MIB = int(os.environ.get("OMPITPU_NW_BENCH_HOL_MIB", "8"))
world = mpi.init()
rt = Runtime.current()
me = rt.bootstrap["process_index"]
peer = 1 - me
assert rt.wire._nw is not None, "native datapath did not come up"
assert rt.wire._btl_for(peer).NAME == "nativewire"
lines = []

def _pv(name):
    p = pvar.PVARS.lookup(name)
    return float(p.read()) if p is not None else 0.0

def pingpong_rtt(size, tag):
    """Best-of-3 round trip of `size` bytes each way; seconds."""
    x = np.ones(max(1, size // 4), np.float32)
    best = None
    for _ in range(3):
        world.barrier()
        if me == 0:
            t0 = time.perf_counter()
            world.send(x, 2, tag=tag, rank=0)
            v, _st = world.recv(source=2, tag=tag + 1, rank=0)
            dt = time.perf_counter() - t0
            assert np.asarray(v).shape == x.shape
            best = dt if best is None else min(best, dt)
        else:
            v, _st = world.recv(source=0, tag=tag, rank=2)
            world.send(np.asarray(v), 0, tag=tag + 1, rank=2)
    return best

if MODE in ("shm", "tcp"):
    suffix = "" if MODE == "shm" else "tcp_"
    for size in SIZES:
        rtt = pingpong_rtt(size, 11)
        if me == 0:
            lines.append({
                "metric": "wire_native_p2p_%%s%%dMiB" %% (suffix,
                                                          size >> 20),
                "value": round(2 * size / rtt / 1e9, 4), "unit": "GB/s",
                "vs_baseline": None, "suite": "native_wire",
                "rtt_s": round(rtt, 5)})

if MODE == "tcp":
    # live per-peer fallback: strip the capability cards and the SAME
    # transfers ride the portable staged frames — receivers that race
    # the strip still reassemble (the framing is byte-identical)
    for c in rt.bootstrap["peer_cards"]:
        if isinstance(c, dict):
            c.pop("nativewire", None)
    world.barrier()
    assert rt.wire._btl_for(peer).NAME == "dcn"
    for size in SIZES:
        rtt = pingpong_rtt(size, 31)
        if me == 0:
            lines.append({
                "metric": "wire_staged_p2p_%%dMiB" %% (size >> 20),
                "value": round(2 * size / rtt / 1e9, 4), "unit": "GB/s",
                "vs_baseline": None, "suite": "native_wire",
                "rtt_s": round(rtt, 5)})

if MODE == "shm":
    # HOL leg: two concurrent distinct-tag transfers over the native
    # rings, 4 lanes vs 1 — the head-of-line pvar is the metric,
    # mirroring the portable wire suite's leg on the native BTL
    xh = np.ones((HOL_MIB << 20) // 4, np.float32)
    for lanes in (4, 1):
        mca_var.set_value("wire_p2p_lanes", lanes)
        world.barrier()
        h0 = _pv("wire_hol_wait_seconds")
        if me == 0:
            ts = [threading.Thread(target=lambda t=t: world.send(
                      xh, 2, tag=t, rank=0)) for t in (51, 52)]
            for t in ts: t.start()
            for t in ts: t.join()
        else:
            world.recv(source=0, tag=52, rank=2)
            world.recv(source=0, tag=51, rank=2)
        world.barrier()
        if me == 0:
            lines.append({
                "metric": "wire_native_hol_2x%%dMiB_lanes%%d"
                          %% (HOL_MIB, lanes),
                "value": round(_pv("wire_hol_wait_seconds") - h0, 4),
                "unit": "hol_wait_s", "vs_baseline": None,
                "suite": "native_wire"})
    mca_var.VARS.unset("wire_p2p_lanes")
    if me == 0:
        lines.append({
            "metric": "wire_native_copies_per_mib",
            "value": round(_pv("wire_native_copies_per_mib"), 5),
            "unit": "copies/MiB", "vs_baseline": None,
            "suite": "native_wire",
            "native_bytes": _pv("wire_native_bytes"),
            "native_frames": _pv("wire_native_frames"),
            "fallback_copies": _pv("wire_native_fallback_copies")})

if MODE == "qos":
    # QoS leg on the native BTL: with the lane space partitioned by
    # class, a small latency-probe pingpong is timed solo and then
    # under a concurrent 6 x 16 MiB bulk stream on its own tag
    def lat_round(tag, reps):
        xs = np.ones((64 << 10) // 4, np.float32)
        ts = []
        for _i in range(reps):
            if me == 0:
                t0 = time.perf_counter()
                world.send(xs, 2, tag=tag, rank=0)
                world.recv(source=2, tag=tag + 1, rank=0)
                ts.append(time.perf_counter() - t0)
            else:
                world.recv(source=0, tag=tag, rank=2)
                world.send(xs, 0, tag=tag + 1, rank=2)
        return ts

    world.barrier()
    solo = lat_round(81, 10)
    world.barrier()
    xb = np.ones((16 << 20) // 4, np.float32)

    def _bulk():
        # its own rank pair (1 -> 3): the latency probe's 0 <-> 2
        # envelopes never share a queue with the bulk stream
        for _k in range(6):
            if me == 0:
                world.send(xb, 3, tag=71, rank=1)
            else:
                world.recv(source=1, tag=71, rank=3)

    th = threading.Thread(target=_bulk)
    th.start()
    under = lat_round(91, 10)
    th.join(timeout=180)
    assert not th.is_alive(), "bulk stream wedged"
    world.barrier()
    if me == 0:
        lines.append({
            "metric": "wire_native_qos_latency_solo_s",
            "value": round(sum(solo) / len(solo), 6), "unit": "s",
            "vs_baseline": None, "suite": "native_wire",
            "qos_classes": "latency:3,bulk:1"})
        lines.append({
            "metric": "wire_native_qos_latency_under_bulk_s",
            "value": round(sum(under) / len(under), 6), "unit": "s",
            "vs_baseline": None, "suite": "native_wire",
            "qos_classes": "latency:3,bulk:1"})

if me == 0:
    with open(os.environ["OMPITPU_LOOPBACK_OUT"], "w") as f:
        json.dump(lines, f)
world.barrier()
mpi.finalize()
'''


def _native_wire_micro_suite(backend_label):
    """native_wire lines: the zero-copy datapath's p2p ping-pong
    through all three byte paths (native shm ring / native vectored
    socket / portable staged frames via a LIVE per-peer capability
    strip), the headline ``wire_native_p2p_256MiB`` GB/s line on full
    machines, the ``wire_native_copies_per_mib`` zero-copy witness,
    HOL-lane and QoS legs over the native BTL, and the derived
    ``wire_native_shm_speedup_vs_staged`` acceptance factor. Withdraws
    with an informational line when the native symbols are absent —
    the portable-only build is a supported configuration, not a bench
    failure."""
    import os

    from ompi_release_tpu.tools.tpurun import run_loopback_app

    try:
        from ompi_release_tpu.native import wire_symbols_available
        have = bool(wire_symbols_available())
    except Exception:
        have = False
    if not have:
        return [{"metric": "native_wire_suite", "value": None,
                 "unit": None, "vs_baseline": None,
                 "error": "native wire symbols unavailable "
                          "(portable staged path in force)"}]
    full = backend_label is None
    sizes = [1 << 20, 16 << 20, 64 << 20, 256 << 20] if full else \
        [1 << 20, 4 << 20, 16 << 20]
    repo = os.path.dirname(os.path.abspath(__file__))
    app = _NATIVE_WIRE_BENCH_APP % {"repo": repo}
    lines = []
    for mode, timeout in (("shm", 420 if full else 240),
                          ("tcp", 420 if full else 240),
                          ("qos", 240)):
        got = run_loopback_app(
            2, app,
            {"OMPITPU_NW_BENCH_MODE": mode,
             "OMPITPU_NW_BENCH_SIZES": json.dumps(sizes),
             "OMPITPU_NW_BENCH_HOL_MIB": "32" if full else "8"},
            "native_wire_%s.json" % mode, timeout_s=timeout)
        if got is None:
            lines.append({"metric": "native_wire_%s_leg" % mode,
                          "value": None, "unit": None,
                          "vs_baseline": None,
                          "error": "native wire bench job failed"})
            continue
        lines.extend(got)
    by = {ln["metric"]: ln for ln in lines
          if ln.get("value") is not None}
    top = sizes[-1] >> 20
    nat = by.get("wire_native_p2p_%dMiB" % top)
    stg = by.get("wire_staged_p2p_%dMiB" % top)
    if nat and stg and stg["value"]:
        lines.append({
            "metric": "wire_native_shm_speedup_vs_staged",
            "value": round(nat["value"] / stg["value"], 4),
            "unit": "x_vs_staged", "vs_baseline": None,
            "suite": "native_wire", "size_mib": top})
    return lines


#: worker app for the native_obs micro-suite: the SAME shm-ring p2p
#: loop with the always-on C counter blocks (every build has them),
#: once with the optional native event ring OFF (the baseline wall)
#: and once ON (one 32-byte C-side record per fragment) — the wall
#: ratio is the observability plane's cost on the zero-copy byte
#: path. A third 3-proc mode sends a ring of transfers with the event
#: ring AND obs dumps on, so the parent can doctor-merge the
#: nativeev-p*.json dumps and count reconstructed cross-process
#: fragment flows. Process 0 writes JSON lines to
#: OMPITPU_LOOPBACK_OUT.
_NATIVE_OBS_BENCH_APP = r'''
import json, os, sys, time
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
MODE = os.environ["OMPITPU_NOBS_MODE"]  # counters | events | doctor
SIZE = int(os.environ.get("OMPITPU_NOBS_SIZE", str(2 << 20)))
REPS = int(os.environ.get("OMPITPU_NOBS_REPS", "10"))
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import ompi_release_tpu as mpi
from ompi_release_tpu.mca import pvar
from ompi_release_tpu.obs import nativeev as obs_nativeev
from ompi_release_tpu.runtime.runtime import Runtime

world = mpi.init()
rt = Runtime.current()
me = rt.bootstrap["process_index"]
assert rt.wire._nw is not None, "native datapath did not come up"
# the event ring must track its cvar: ON only in the events/doctor legs
assert (obs_nativeev.get_ring() is not None) == (MODE != "counters"), (
    "event-ring lifecycle does not match btl_nativewire_events")
lines = []

def _pv(name):
    p = pvar.PVARS.lookup(name)
    return float(p.read()) if p is not None else 0.0

if MODE in ("counters", "events"):
    x = np.ones(max(1, SIZE // 4), np.float32)

    def _round(reps):
        t0 = time.perf_counter()
        for _i in range(reps):
            if me == 0:
                world.send(x, 2, tag=13, rank=0)
                v, _st = world.recv(source=2, tag=14, rank=0)
            else:
                v, _st = world.recv(source=0, tag=13, rank=2)
                world.send(np.asarray(v), 0, tag=14, rank=2)
        return time.perf_counter() - t0

    world.barrier()
    _round(1)  # warmup: ring attach + first-touch stay out of walls
    wall = None
    for _b in range(3):
        world.barrier()
        dt = _round(REPS)
        wall = dt if wall is None else min(wall, dt)
    world.barrier()
    if me == 0:
        lines.append({
            "metric": "native_obs_%%s_wall_s" %% MODE,
            "value": round(wall, 5), "unit": "s",
            "vs_baseline": None, "suite": "native_obs",
            "reps": REPS, "size_mib": SIZE >> 20,
            "native_bytes": _pv("wire_native_bytes")})
        if MODE == "counters":
            # the C counter blocks themselves, as gate-tracked lines
            lines.append({
                "metric": "wire_native_stall_count",
                "value": _pv("wire_native_ring_stalls"),
                "unit": "stalls", "vs_baseline": None,
                "suite": "native_obs"})
            lines.append({
                "metric": "wire_native_stall_seconds",
                "value": round(_pv("wire_native_stall_seconds"), 5),
                "unit": "s", "vs_baseline": None,
                "suite": "native_obs"})
            lines.append({
                "metric": "wire_native_ring_hwm_frac",
                "value": round(_pv("wire_native_ring_hwm_frac"), 5),
                "unit": "frac", "vs_baseline": None,
                "suite": "native_obs"})
        else:
            lines.append({
                "metric": "native_obs_event_records",
                "value": float(obs_nativeev.get_ring().count()),
                "unit": None, "vs_baseline": None,
                "suite": "native_obs"})

if MODE == "doctor":
    # ring of staged transfers: proc i's rank 2i -> proc (i+1)%%3's
    # rank (2i+2)%%6, sequential with barriers (no deadlock to manage)
    x = np.ones(max(1, SIZE // 4), np.float32)
    hops = ((0, 1, 0, 2), (1, 2, 2, 4), (2, 0, 4, 0))
    for tag_off, (src, dst, srank, drank) in enumerate(hops):
        world.barrier()
        if me == src:
            world.send(x, drank, tag=41 + tag_off, rank=srank)
        elif me == dst:
            v, _st = world.recv(source=srank, tag=41 + tag_off,
                                rank=drank)
            assert np.asarray(v).shape == x.shape
    world.barrier()
    if me == 0:
        lines.append({"metric": "native_obs_doctor_leg_ok",
                      "value": 1.0, "unit": None,
                      "vs_baseline": None, "suite": "native_obs"})

if me == 0:
    with open(os.environ["OMPITPU_LOOPBACK_OUT"], "w") as f:
        json.dump(lines, f)
world.barrier()
mpi.finalize()
'''


def _native_obs_micro_suite(backend_label):
    """native_obs lines: the native-wire observability plane's cost
    and fidelity. ``native_obs_counters_wall_s`` is the p2p wall with
    ONLY the always-on C counter blocks (every build pays this — the
    gate trends it across rounds); ``native_obs_events_wall_s`` adds
    the optional event ring (one 32-byte C record per fragment), and
    ``native_obs_overhead_ratio`` is events/counters with the 1.05
    acceptance budget. The doctor leg runs a 3-process job with the
    event ring and obs dumps on, doctor-merges the ``nativeev-p*``
    dumps, and reports how many cross-process native fragment flows
    reconstructed with paired ids. Withdraws with an informational
    line when the native telemetry symbols are absent."""
    import os
    import tempfile

    from ompi_release_tpu.tools.tpurun import run_loopback_app

    try:
        from ompi_release_tpu.native import (
            telemetry_symbols_available, wire_symbols_available)
        have = bool(wire_symbols_available()
                    and telemetry_symbols_available())
    except Exception:
        have = False
    if not have:
        return [{"metric": "native_obs_suite", "value": None,
                 "unit": None, "vs_baseline": None,
                 "error": "native telemetry symbols unavailable "
                          "(stale .so or portable-only build)"}]
    full = backend_label is None
    size = (8 << 20) if full else (2 << 20)
    reps = 40 if full else 12
    repo = os.path.dirname(os.path.abspath(__file__))
    app = _NATIVE_OBS_BENCH_APP % {"repo": repo}
    lines = []
    walls = {}
    for mode in ("counters", "events"):
        mca = ([("btl_nativewire_events", "1")]
               if mode == "events" else [])
        got = run_loopback_app(
            2, app,
            {"OMPITPU_NOBS_MODE": mode,
             "OMPITPU_NOBS_SIZE": str(size),
             "OMPITPU_NOBS_REPS": str(reps)},
            "native_obs_%s.json" % mode, timeout_s=300, mca=mca)
        if got is None:
            lines.append({"metric": "native_obs_%s_leg" % mode,
                          "value": None, "unit": None,
                          "vs_baseline": None,
                          "error": "native obs bench job failed"})
            continue
        lines.extend(got)
        for ln in got:
            if ln.get("metric") == "native_obs_%s_wall_s" % mode:
                walls[mode] = ln.get("value")
    if walls.get("counters") and walls.get("events"):
        lines.append({
            "metric": "native_obs_overhead_ratio",
            "value": round(walls["events"] / walls["counters"], 4),
            "unit": "ratio", "vs_baseline": None,
            "suite": "native_obs", "budget": 1.05})
    # doctor-merge fidelity: 3 processes, event ring + obs dumps on
    with tempfile.TemporaryDirectory() as dump_dir:
        got = run_loopback_app(
            3, app,
            {"OMPITPU_NOBS_MODE": "doctor",
             "OMPITPU_NOBS_SIZE": str(1 << 20),
             "OMPITPU_NOBS_REPS": "1"},
            "native_obs_doctor.json", timeout_s=300,
            mca=[("btl_nativewire_events", "1"),
                 ("obs_enable", "1"),
                 ("obs_dump_dir", dump_dir)])
        if got is None:
            lines.append({"metric": "native_obs_doctor_leg",
                          "value": None, "unit": None,
                          "vs_baseline": None,
                          "error": "native obs doctor job failed"})
        else:
            from ompi_release_tpu.obs import doctor as _doctor

            dumps = _doctor.load_dir(dump_dir)
            nw = [s for d in dumps for s in d.get("spans", ())
                  if s.get("nativeev")]
            pairs = [p for p in _doctor.flow_pairs(dumps)
                     if p["cross_process"]
                     and p["src"].get("nativeev")]
            lines.append({
                "metric": "native_obs_doctor_nativeev_spans",
                "value": float(len(nw)), "unit": None,
                "vs_baseline": None, "suite": "native_obs",
                "procs": len(dumps)})
            lines.append({
                "metric": "native_obs_doctor_flow_pairs",
                "value": float(len(pairs)), "unit": None,
                "vs_baseline": None, "suite": "native_obs"})
    return lines


#: worker app for the overlap micro-suite: a REAL 3-process tpurun job
#: measuring exposed vs hidden comm time — blocking allreduce-per-
#: bucket followed by compute, vs overlapped iallreduce buckets
#: (parallel/dp.GradientSync) issued UNDER the compute loop — once
#: with the async progress engine's thread enabled and once in the
#: polling fallback. Process 0 writes its JSON lines to
#: OMPITPU_LOOPBACK_OUT.
_OVERLAP_BENCH_APP = r'''
import json, os, sys, time
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# distinct shm identity per worker: comm rides the DCN staged path so
# the hidden/exposed split measures real wire time, not a memcpy
os.environ["OMPITPU_HOST_ID"] = (
    "ovlbench-" + os.environ["OMPITPU_NODE_ID"])
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import ompi_release_tpu as mpi
from ompi_release_tpu.mca import pvar, var as mca_var
from ompi_release_tpu.parallel.dp import GradientSync
from ompi_release_tpu.runtime.runtime import Runtime

LEAF = int(os.environ.get("OMPITPU_OVERLAP_LEAF", "48000"))
world = mpi.init()
rt = Runtime.current()
me = rt.bootstrap["process_index"]
ln = len(world.local_comm_ranks)
grads = {"w%%d" %% k: np.ones((ln, LEAF), np.float32) * (me + k + 1)
         for k in range(6)}
sync = GradientSync(world, mean=False, bucket_bytes=1 << 20)

def _pv(name):
    p = pvar.PVARS.lookup(name)
    v = p.read() if p is not None else 0.0
    return float(v) if not isinstance(v, dict) else 0.0

def blocking_step():
    for k in sorted(grads):
        world.allreduce(grads[k])

def compute(seconds):
    a = np.ones((96, 96), np.float32)
    t_end = time.perf_counter() + seconds
    while time.perf_counter() < t_end:
        a = a @ a * 1e-4

# warm every compiled program / wire channel once
blocking_step()
sync.issue(grads).wait()

# comm time alone: the blocking allreduce-per-bucket cost per step
world.barrier()
best = None
for _ in range(3):
    world.barrier()
    t0 = time.perf_counter()
    blocking_step()
    dt = time.perf_counter() - t0
    best = dt if best is None else min(best, dt)
t_comm = best
t_compute = max(t_comm, 0.02)

results = {}
for mode in ("engine", "polling"):
    if mode == "engine":
        mca_var.set_value("progress_thread", True)
    else:
        mca_var.VARS.unset("progress_thread")
    world.barrier()
    t_block = t_ovl = None
    for _ in range(3):
        world.barrier()
        t0 = time.perf_counter()
        blocking_step()
        compute(t_compute)
        dt = time.perf_counter() - t0
        t_block = dt if t_block is None else min(t_block, dt)
        world.barrier()
        h0 = _pv("nbc_hidden_seconds")
        t0 = time.perf_counter()
        pending = sync.issue(grads)
        compute(t_compute)
        out = pending.wait()
        dt = time.perf_counter() - t0
        t_ovl = dt if t_ovl is None else min(t_ovl, dt)
    # parity witness: the overlapped result equals the blocking one
    ref = np.asarray(world.allreduce(grads["w0"]))
    np.testing.assert_allclose(np.asarray(out["w0"]), ref, rtol=1e-6)
    hidden_s = _pv("nbc_hidden_seconds") - h0
    results[mode] = {
        "t_block": t_block, "t_ovl": t_ovl,
        # the gated value is the ENGINE'S OWN accounting of comm time
        # that ran while the caller computed (the nbc_hidden_seconds
        # pvar over the last overlapped step, against the measured
        # comm-alone time): engine leg ~1, polling leg exactly 0. The
        # wall-clock fraction rides along as a label — it also absorbs
        # cross-process skew, so it is noisier than the pvar witness.
        "hidden_frac": max(0.0, min(1.0, hidden_s / max(t_comm, 1e-9))),
        "wall_hidden_frac": max(0.0, min(1.0, (t_block - t_ovl)
                                         / max(t_comm, 1e-9))),
        "hidden_pvar_s": hidden_s,
    }
mca_var.VARS.unset("progress_thread")

if me == 0:
    lines = []
    for mode, r in results.items():
        suffix = "" if mode == "engine" else "_polling"
        lines.append({
            "metric": "overlap_allreduce_hidden_frac" + suffix,
            "value": round(r["hidden_frac"], 4), "unit": "frac_hidden",
            "vs_baseline": None, "suite": "overlap",
            "t_block_s": round(r["t_block"], 5),
            "t_overlap_s": round(r["t_ovl"], 5),
            "t_comm_s": round(t_comm, 5),
            "wall_hidden_frac": round(r["wall_hidden_frac"], 4),
            "nbc_hidden_delta_s": round(r["hidden_pvar_s"], 5),
        })
    lines.append({
        "metric": "overlap_allreduce_speedup",
        "value": round(results["engine"]["t_block"]
                       / max(results["engine"]["t_ovl"], 1e-9), 4),
        "unit": "x_vs_blocking", "vs_baseline": None,
        "suite": "overlap",
        "pvars": {k: v for k, v in pvar.PVARS.read_all().items()
                  if k.startswith(("nbc_", "progress_",
                                   "wire_coll_pumped"))},
        "cumulative": True,
    })
    with open(os.environ["OMPITPU_LOOPBACK_OUT"], "w") as f:
        json.dump(lines, f)
world.barrier()
mpi.finalize()
'''


def _overlap_micro_suite(backend_label):
    """overlap lines: exposed vs hidden comm time for gradient-bucket
    allreduce through a REAL 3-process tpurun job, CPU mesh (the wire
    and the progress engine are host-side either way). The engine leg
    runs with the dedicated progress thread (hidden fraction > 0 —
    comm rode under the compute loop); the polling leg is the
    deterministic fallback where schedules drain at wait() (hidden
    fraction ~0). Gate direction: frac_hidden / overlap_* are
    higher-better."""
    import os

    from ompi_release_tpu.tools.tpurun import run_loopback_app

    lines = run_loopback_app(
        3, _OVERLAP_BENCH_APP % {"repo": os.path.dirname(
            os.path.abspath(__file__))},
        {"OMPITPU_OVERLAP_LEAF": str(
            96000 if backend_label is None else 48000)},
        "overlap_bench.json", timeout_s=300)
    if lines is None:
        return [{"metric": "overlap_suite", "value": None,
                 "unit": None, "vs_baseline": None,
                 "error": "overlap bench job failed"}]
    return lines  # main()'s emit() stamps the backend label


#: worker app for the tree_overlap micro-suite: a REAL 3-process
#: tpurun job training a tiny models/transformer.TpuLM locally per
#: process (the data-parallel trainer shape) and syncing the WHOLE
#: gradient pytree through parallel/tree.TreeSync — per-leaf blocking
#: allreduces vs one planned fused pass overlapped under the next
#: step's real fwd/bwd, engine vs polling legs; plus a HostPipeline
#: microbatch leg with blocking vs nonblocking stage-boundary
#: transfers. Process 0 writes the JSON lines to OMPITPU_LOOPBACK_OUT.
_TREE_BENCH_APP = r'''
import json, os, sys, time
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# distinct shm identity per worker: comm rides the DCN staged path so
# hidden/exposed splits measure real wire time, not a memcpy
os.environ["OMPITPU_HOST_ID"] = (
    "treebench-" + os.environ["OMPITPU_NODE_ID"])
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import ompi_release_tpu as mpi
from jax.sharding import Mesh
from ompi_release_tpu.mca import pvar, var as mca_var
from ompi_release_tpu.models import transformer as tfm
from ompi_release_tpu.parallel import pp as pp_mod, tree as tree_mod
from ompi_release_tpu.runtime.runtime import Runtime

world = mpi.init()
rt = Runtime.current()
me = rt.bootstrap["process_index"]

def _pv(name):
    p = pvar.PVARS.lookup(name)
    v = p.read() if p is not None else 0.0
    return float(v) if not isinstance(v, dict) else 0.0

# ---- the trainer: a tiny TpuLM on this process's 1-device mesh ------
cfg = tfm.ModelConfig(vocab=128, d_model=64, n_layers=2, n_heads=2,
                      head_dim=16, d_ff=192, max_seq=32,
                      microbatches=1, dtype=jnp.float32)
mesh = Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1, 1, 1),
            ("dp", "pp", "sp", "ep", "tp"))
params = tfm.init_params(jax.random.PRNGKey(0), cfg)
loss_fn = tfm.make_forward(cfg, mesh)
grad_fn = jax.jit(jax.value_and_grad(loss_fn))
rng = np.random.RandomState(me)
toks = rng.randint(0, cfg.vocab, (4, 32)).astype(np.int32)
tgts = rng.randint(0, cfg.vocab, (4, 32)).astype(np.int32)

def grad_step():
    _, g = grad_fn(params, toks, tgts)
    return jax.block_until_ready(g)

grads = grad_step()  # compile + first real backward
t0 = time.perf_counter()
grad_step()
t_grad = time.perf_counter() - t0
# driver-mode tree: leading member-slice axis on every leaf
gtree = jax.tree.map(lambda g: np.asarray(g)[None], grads)
leaves = jax.tree.leaves(gtree)
tree_bytes = sum(l.nbytes for l in leaves)

def blocking_perleaf():
    for l in leaves:
        world.allreduce(l)

sync = tree_mod.TreeSync(world, mean=False, bucket_bytes=1 << 20)
blocking_perleaf()          # warm per-leaf programs/channels
sync.issue(gtree).wait()    # warm the planned pass + plan cache

# comm time alone, both shapes
world.barrier()
t_perleaf = t_planned = None
for _ in range(3):
    world.barrier()
    t0 = time.perf_counter()
    blocking_perleaf()
    dt = time.perf_counter() - t0
    t_perleaf = dt if t_perleaf is None else min(t_perleaf, dt)
    world.barrier()
    t0 = time.perf_counter()
    sync.issue(gtree).wait()
    dt = time.perf_counter() - t0
    t_planned = dt if t_planned is None else min(t_planned, dt)

def compute(seconds):
    # REAL trainer compute: fwd/bwd steps until the budget elapses
    t_end = time.perf_counter() + seconds
    while time.perf_counter() < t_end:
        grad_step()

t_compute = max(t_planned, t_grad, 0.02)
results = {}
for mode in ("engine", "polling"):
    if mode == "engine":
        mca_var.set_value("progress_thread", True)
    else:
        mca_var.VARS.unset("progress_thread")
    world.barrier()
    t_block = t_ovl = None
    for _ in range(3):
        world.barrier()
        t0 = time.perf_counter()
        blocking_perleaf()
        compute(t_compute)
        dt = time.perf_counter() - t0
        t_block = dt if t_block is None else min(t_block, dt)
        world.barrier()
        h0 = _pv("nbc_hidden_seconds")
        th0 = _pv("tree_hidden_seconds")
        t0 = time.perf_counter()
        pending = sync.issue(gtree)
        compute(t_compute)
        out = pending.wait()
        dt = time.perf_counter() - t0
        t_ovl = dt if t_ovl is None else min(t_ovl, dt)
    # parity witness: planned overlapped pass == per-leaf blocking
    ref = np.asarray(world.allreduce(
        np.asarray(grads["embed"])[None]))
    np.testing.assert_array_equal(np.asarray(out["embed"]), ref)
    hidden_s = _pv("nbc_hidden_seconds") - h0
    results[mode] = {
        "t_block": t_block, "t_ovl": t_ovl,
        # the gated witness: the ENGINE'S own accounting of comm time
        # that ran under the trainer's fwd/bwd (nbc_hidden_seconds
        # delta over the last overlapped pass vs the measured planned
        # comm-alone time); engine ~1, polling exactly 0
        "hidden_frac": max(0.0, min(1.0,
                                    hidden_s / max(t_planned, 1e-9))),
        "tree_hidden_s": _pv("tree_hidden_seconds") - th0,
        "nbc_hidden_s": hidden_s,
    }
mca_var.VARS.unset("progress_thread")

# ---- HostPipeline: microbatch schedule, boundary comm nb vs blocking
# 512 KiB boundary activations (the trainer-scale shape where the
# transfer is worth hiding) under the progress thread, so posted-early
# irecvs/isends complete off the caller while the stage computes
S = world.size
m = 6
W = rng.randn(512, 512).astype(np.float32) * 0.05
mbs = [np.ones((256, 512), np.float32) * (k + 1) for k in range(m)]

def stage_fn(x):
    y = np.asarray(x)
    for _ in range(3):  # one stage's compute per microbatch
        y = np.tanh(y @ W)
    return y

mca_var.set_value("progress_thread", True)
pp_res = {}
for leg, nb in (("nonblocking", True), ("blocking", False)):
    pipe = pp_mod.HostPipeline(world, stage_fn, stage=me,
                               nonblocking=nb)
    world.barrier()
    pipe.run(mbs)  # warm channels
    best = None
    w0 = _pv("pp_boundary_wait_seconds")
    for _ in range(3):
        world.barrier()
        t0 = time.perf_counter()
        outs = pipe.run(mbs)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    # fleet-summed EXPOSED boundary wait (stage 0 never receives, so
    # rank 0's own pvar alone would read 0)
    mine = _pv("pp_boundary_wait_seconds") - w0
    total = float(np.asarray(world.allreduce(
        np.array([[mine]], np.float32)))[0, 0])
    pp_res[leg] = {"t": best, "exposed_s": total, "out": outs}
mca_var.VARS.unset("progress_thread")
# parity witness: both schedules produce identical last-stage outputs
if me == S - 1:
    for a, b in zip(pp_res["nonblocking"]["out"],
                    pp_res["blocking"]["out"]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

if me == 0:
    lines = [{
        "metric": "tree_planned_pass_speedup",
        "value": round(t_perleaf / max(t_planned, 1e-9), 4),
        "unit": "x_vs_blocking", "vs_baseline": None,
        "suite": "tree_overlap",
        "t_perleaf_s": round(t_perleaf, 5),
        "t_planned_s": round(t_planned, 5),
        "tree_bytes": int(tree_bytes),
        "leaves": len(leaves),
        "t_grad_s": round(t_grad, 5),
    }]
    for mode, r in results.items():
        suffix = "" if mode == "engine" else "_polling"
        lines.append({
            "metric": "tree_allreduce_hidden_frac" + suffix,
            "value": round(r["hidden_frac"], 4), "unit": "frac_hidden",
            "vs_baseline": None, "suite": "tree_overlap",
            "t_block_s": round(r["t_block"], 5),
            "t_overlap_s": round(r["t_ovl"], 5),
            "t_comm_s": round(t_planned, 5),
            "nbc_hidden_delta_s": round(r["nbc_hidden_s"], 5),
            "tree_hidden_delta_s": round(r["tree_hidden_s"], 5),
        })
    lines.append({
        "metric": "tree_overlap_speedup",
        "value": round(results["engine"]["t_block"]
                       / max(results["engine"]["t_ovl"], 1e-9), 4),
        "unit": "x_vs_blocking", "vs_baseline": None,
        "suite": "tree_overlap",
    })
    lines.append({
        "metric": "tree_pp_overlap_speedup",
        "value": round(pp_res["blocking"]["t"]
                       / max(pp_res["nonblocking"]["t"], 1e-9), 4),
        "unit": "x_vs_blocking", "vs_baseline": None,
        "suite": "tree_overlap",
        "t_blocking_s": round(pp_res["blocking"]["t"], 5),
        "t_nonblocking_s": round(pp_res["nonblocking"]["t"], 5),
        "exposed_blocking_s": round(pp_res["blocking"]["exposed_s"], 5),
        "exposed_nonblocking_s": round(
            pp_res["nonblocking"]["exposed_s"], 5),
        "microbatches": m, "stages": S,
    })
    lines.append({
        "metric": "tree_overlap_pvars", "value": None, "unit": None,
        "vs_baseline": None, "suite": "tree_overlap",
        "pvars": {k: v for k, v in pvar.PVARS.read_all().items()
                  if k.startswith(("tree_", "pp_boundary",
                                   "nbc_hidden"))},
        "cumulative": True,
    })
    with open(os.environ["OMPITPU_LOOPBACK_OUT"], "w") as f:
        json.dump(lines, f, default=str)
world.barrier()
mpi.finalize()
'''


def _tree_micro_suite(backend_label):
    """tree_overlap lines: the planned whole-tree gradient pass vs the
    per-leaf loop at trainer scale — a REAL 3-process tpurun job
    computing actual models/transformer fwd/bwd gradients per step,
    syncing the full pytree through parallel/tree.TreeSync. Reports
    planned-vs-per-leaf comm speedup, exposed-vs-hidden comm fraction
    (engine vs polling; nbc_hidden_seconds/tree_hidden_seconds deltas
    are the witnesses), and the HostPipeline microbatch leg with
    nonblocking vs blocking stage boundaries. Gate direction: tree_*
    and frac_hidden are higher-better."""
    import os

    from ompi_release_tpu.tools.tpurun import run_loopback_app

    lines = run_loopback_app(
        3, _TREE_BENCH_APP % {"repo": os.path.dirname(
            os.path.abspath(__file__))},
        {}, "tree_bench.json", timeout_s=420)
    if lines is None:
        return [{"metric": "tree_overlap_suite", "value": None,
                 "unit": None, "vs_baseline": None,
                 "error": "tree_overlap bench job failed"}]
    return lines  # main()'s emit() stamps the backend label


#: worker app for the ft_recovery micro-suite: a REAL 3-process tpurun
#: job under the --ft-continue policy driving an ElasticStep training
#: loop; the sensor SIGKILLs rank 2 mid-run (kill cvars scoped by
#: rank), the survivors detect via the job-epoch bump, revoke+shrink,
#: roll back to the last committed checkpoint, and finish — process 0
#: writes the recovery-time/steps-lost lines plus the pvar witnesses.
_FT_BENCH_APP = r'''
import json, os, sys, time
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import ompi_release_tpu as mpi
from ompi_release_tpu.mca import pvar
from ompi_release_tpu.ft.checkpoint import Checkpointer
from ompi_release_tpu.ft.sensor import FtTester
from ompi_release_tpu.parallel.elastic import ElasticStep

STEPS = int(os.environ.get("OMPITPU_FT_BENCH_STEPS", "8"))

world = mpi.init()
from ompi_release_tpu.runtime.runtime import Runtime
rt = Runtime.current()
me = rt.bootstrap["process_index"]

def _pv(name):
    p = pvar.PVARS.lookup(name)
    return float(p.read()) if p is not None else 0.0

ckpt = Checkpointer(os.path.join(
    os.path.dirname(os.environ["OMPITPU_LOOPBACK_OUT"]),
    "ft_ckpt", "rank%%d" %% me))

def step_fn(step, state, comm):
    contrib = np.full((len(comm.local_comm_ranks), 4),
                      float(step + 1), np.float32)
    got = np.asarray(comm.allreduce(contrib))
    return np.asarray(state) + got[:1]

es = ElasticStep(world, step_fn, ckpt, policy="shrink",
                 checkpoint_every=1,
                 tester=FtTester.from_cvars(me))
t0 = time.perf_counter()
state, stats = es.run(np.zeros((1, 4), np.float32), STEPS)
wall = time.perf_counter() - t0

if me == 0:
    lines = [{
        "metric": "ft_recovery_seconds", "value": round(
            _pv("ft_recovery_seconds"), 4),
        "unit": "s", "vs_baseline": None, "suite": "ft_recovery",
        "procs": 3, "steps": STEPS, "wall_s": round(wall, 4),
        "failures_detected": _pv("ft_failures_detected"),
        "recoveries": _pv("ft_recoveries"),
        "revokes": _pv("ft_revokes"),
    }, {
        "metric": "ft_steps_lost", "value": stats["steps_lost"],
        "unit": "steps", "vs_baseline": None, "suite": "ft_recovery",
        "checkpoint_every": 1,
    }]
    assert _pv("ft_failures_detected") == 1.0, "expected ONE failure"
    assert _pv("ft_recoveries") == 1.0, "expected ONE recovery"
    with open(os.environ["OMPITPU_LOOPBACK_OUT"], "w") as f:
        json.dump(lines, f)
mpi.finalize()
'''


def _ft_micro_suite(backend_label):
    """ft_recovery lines: wall time of one detect->revoke->shrink->
    rollback cycle and the steps recomputed after rollback, measured
    through a real 3-process tpurun job (--ft-continue policy) whose
    rank 2 is SIGKILLed by the armed sensor mid-run. Lower-better on
    both metrics — a recovery-time regression gates exactly like a
    latency regression (tpu_bench_gate METRIC_LOWER_BETTER_PREFIXES).
    Loopback-CPU either way: detection, wire reaps, and the shrink
    agreement are host-side paths."""
    import os

    from ompi_release_tpu.tools.tpurun import run_loopback_app

    lines = run_loopback_app(
        3, _FT_BENCH_APP % {"repo": os.path.dirname(
            os.path.abspath(__file__))},
        {"OMPITPU_FT_BENCH_STEPS": "8",
         "OMPITPU_MCA_sensor_ft_kill_step": "3",
         "OMPITPU_MCA_sensor_ft_kill_rank": "2"},
        "ft_bench.json", timeout_s=300,
        job_kw={"on_failure": "continue", "heartbeat_s": 0.3,
                "miss_limit": 4})
    if lines is None:
        return [{"metric": "ft_recovery_suite", "value": None,
                 "unit": None, "vs_baseline": None,
                 "error": "ft recovery bench job failed"}]
    return lines  # main()'s emit() stamps the backend label


def _fleet_micro_suite(sizes=(256, 1024)):
    """fleet_scaling lines: the simulated-fleet harness
    (ompi_release_tpu/testing/fleet_sim.py) runs the REAL
    hier_schedules round code at P simulated ranks over the virtual
    wire and emits the scaling observables the O(log P) claims rest
    on — bcast root sends, recursive-doubling rounds, Rabenseifner
    per-rank inter bytes, and the fabric-model makespan. Every line
    carries tier_label "sim": the numbers are deterministic functions
    of (schedule, fabric model), so the gate's per-(metric, tier) fit
    must never mix them with loopback-cpu/tpu wall-clock history —
    and within the sim tier a tripped bound IS a schedule regression
    (more rounds / more bytes), not noise. sim_* metrics are
    lower-better, topo_* (torus/multiring speedups over the flat
    ring) higher-better (tpu_bench_gate registers both prefixes).
    Device-free: no backend involved, jax never imported."""
    import math

    from ompi_release_tpu.coll import hier_schedules as hs
    from ompi_release_tpu.testing import fleet_sim as fs

    lines = []
    for P in sizes:
        fleet = fs.FleetSim(P, hosts_per=8, seed=1)
        procs = fleet.procs
        logp = fs.log2_rounds(P)

        def line(metric, value, unit, **kv):
            lines.append(dict(
                {"metric": f"{metric}_p{P}", "value": value,
                 "unit": unit, "vs_baseline": None,
                 "suite": "fleet_scaling", "tier_label": "sim",
                 "P": P, "hosts": math.ceil(P / 8)}, **kv))

        # binomial bcast: the root's O(log P) fan-out
        val = np.arange(16, dtype=np.int32)
        rep = fleet.run(
            lambda x, p: hs.bcast_binomial(
                x, procs, p, 0, val if p == 0 else None),
            label="bcast")
        line("sim_bcast_root_sends", rep.msgs_sent[0], "msgs",
             expect=logp)
        line("sim_bcast_makespan", round(rep.makespan * 1e3, 6),
             "sim_ms")

        # recursive-doubling partial exchange: ceil(log2 P) rounds
        data = {p: np.full(8, p + 1, np.int64) for p in procs}
        rep = fleet.run(
            lambda x, p: hs.allgather_bruck(x, procs, p, data[p],
                                            [8] * P),
            label="allgather")
        line("sim_rd_rounds", rep.max_rounds(), "rounds",
             expect=logp)

        # Rabenseifner allreduce: ~2n(P-1)/P inter bytes per rank
        # (vs (P-1)n linear) in 2*ceil(log2 P) rounds
        n_el = 2 * P
        fdata = {p: np.arange(n_el, dtype=np.float32) * ((p % 7) + 1)
                 for p in procs}
        rep = fleet.run(
            lambda x, p: hs.allreduce_rabenseifner(
                x, procs, p, fdata[p], np.add, 0.0),
            label="allreduce")
        line("sim_rab_bytes_per_rank", rep.max_bytes_sent(), "bytes",
             expect=fs.rabenseifner_bytes_per_rank(n_el, 4, P),
             payload_bytes=n_el * 4)
        line("sim_rab_rounds", rep.max_rounds(), "rounds",
             expect=2 * logp)
        line("sim_allreduce_makespan", round(rep.makespan * 1e3, 6),
             "sim_ms")

        # 2D-torus allreduce on the hosts_per=8 grid: DCN carries only
        # the 1/d0-sized partials — measured inter-host bytes equal
        # the closed form exactly, and the flat-ring baseline (also
        # closed form: H boundary NICs each shipping every chunk) is
        # strictly above it; topo_* = higher-better speedup ratios
        from ompi_release_tpu.coll import topo_schedules as ts

        d0, d1 = 8, P // 8
        n_t = 8 * P  # divisible by P, d0, d1: exact closed forms
        tdata = {p: np.arange(n_t, dtype=np.float32) * ((p % 5) + 1)
                 for p in procs}
        tfleet = fs.FleetSim(P, hosts_per=8, seed=1)
        host_of = tfleet.fabric.host_of
        rep_t = tfleet.run(
            lambda x, p: ts.allreduce_torus2d(
                x, procs, p, tdata[p], np.add, 0.0, host_of),
            label="allreduce_torus")
        torus_total = sum(rep_t.inter_bytes_sent.values())
        flat_total = ts.flat_ring_inter_bytes_total(n_t, 4, P, d1)
        line("sim_torus_inter_bytes_per_rank",
             max(rep_t.inter_bytes_sent.values()), "bytes",
             expect=ts.torus_inter_bytes_per_rank(n_t, 4, d0, d1),
             payload_bytes=n_t * 4)
        line("sim_torus_rounds", rep_t.max_rounds(), "rounds",
             expect=ts.torus_rounds(d0, d1))
        line("sim_torus_makespan", round(rep_t.makespan * 1e3, 6),
             "sim_ms")
        line("topo_torus_inter_bytes_x",
             round(flat_total / torus_total, 6), "x_inter_bytes")
        if P <= 256:
            # the flat-ring ACTUAL run (2(P-1) rounds — affordable at
            # this P) anchors the virtual-makespan speedup
            rfleet = fs.FleetSim(P, hosts_per=8, seed=1)
            rep_r = rfleet.run(
                lambda x, p: hs.allreduce_ring(
                    x, procs, p, tdata[p], np.add, 0.0),
                label="allreduce_ring")
            line("topo_torus_makespan_x",
                 round(rep_r.makespan / rep_t.makespan, 6),
                 "x_makespan")
            # multiring: k disjoint stride rings driven in parallel —
            # the k× ring-bandwidth claim, on a bandwidth-bound
            # UNIFORM wire (striping is topology-oblivious; the torus
            # is the hierarchy answer)
            def bw_fleet():
                return fs.FleetSim(P, fabric=fs.Fabric(
                    P, hosts_per=P, intra=fs.LinkSpec(1e-7, 0.1),
                    seed=1))

            f_r = bw_fleet()
            rep_br = f_r.run(
                lambda x, p: hs.allreduce_ring(
                    x, procs, p, tdata[p], np.add, 0.0),
                label="allreduce_ring_bw")
            f_m = bw_fleet()
            rep_bm = f_m.run(
                lambda x, p: ts.allreduce_multiring(
                    x, procs, p, tdata[p], np.add, 0.0, 4),
                label="allreduce_multiring_bw")
            line("topo_multiring_makespan_x",
                 round(rep_br.makespan / rep_bm.makespan, 6),
                 "x_makespan")
    return lines


def _multi_tenant_micro_suite(sizes=(256,)):
    """multi_tenant lines: the service plane's fairness story on the
    deterministic fleet simulator (testing/scenarios.multi_tenant) —
    N tenants x small fleets over ONE shared fabric. Three legs per
    P: the latency tenant SOLO (full wire), both tenants contended
    under the weighted-fair QoS shares (latency:8,bulk:2), and the
    same contention on a FIFO (no-QoS) wire. The headline ratio
    ``tenant_latency_isolation`` = contended-p99 / solo-p99 is THE
    gate-checked degradation factor of acceptance: bounded by
    1/fair_share (1.25x at 8:2) + the schedule margin, where the
    FIFO wire blows to ~hosts_per x. tenant_* metrics are
    lower-better (tpu_bench_gate registers the prefix); tier "sim"
    keeps the deterministic numbers out of wall-clock fits.
    Device-free: no backend involved."""
    from ompi_release_tpu.testing import scenarios as sc

    lines = []
    for P in sizes:
        r = sc.multi_tenant(P=P, seed=1, kill_bulk=False)

        def line(metric, value, unit, **kv):
            lines.append(dict(
                {"metric": f"{metric}_p{P}", "value": value,
                 "unit": unit, "vs_baseline": None,
                 "suite": "multi_tenant", "tier_label": "sim",
                 "P": P, "classes": "latency:8,bulk:2"}, **kv))

        solo_p99 = r.p99(r.solo_durations)
        qos_p99 = r.p99(r.qos_durations)
        fifo_p99 = r.p99(r.fifo_durations)
        bulk_p99 = r.p99(r.bulk_durations)
        line("tenant_lat_solo_p99", round(solo_p99 * 1e3, 6),
             "sim_ms", qos="latency")
        line("tenant_lat_contended_p99", round(qos_p99 * 1e3, 6),
             "sim_ms", qos="latency")
        line("tenant_lat_fifo_p99", round(fifo_p99 * 1e3, 6),
             "sim_ms", qos="latency")
        line("tenant_bulk_contended_p99", round(bulk_p99 * 1e3, 6),
             "sim_ms", qos="bulk")
        # THE acceptance ratio: contended/solo p99 under QoS, bounded
        # by the latency class's inverse fair share...
        line("tenant_latency_isolation",
             round(qos_p99 / solo_p99, 6), "p99_ratio",
             bound=round(1.0 / r.share_lat, 6), qos="latency")
        # ...vs what the same contention costs on a fair-less wire
        # (the head-of-line factor QoS buys back)
        line("tenant_fifo_hol_ratio",
             round(fifo_p99 / solo_p99, 6), "p99_ratio", qos="latency")
        assert qos_p99 <= solo_p99 / r.share_lat * 1.10, \
            "isolation bound violated in-suite"
    return lines


def _sweep_lines(specs, ceiling_names, slopes, n):
    """Metric lines + headline from the sweep's slope matrix
    ``(n_specs, rounds_measured)``. Pure computation so the salvage
    path can run it on a partial matrix (fewer rounds than planned)
    with exactly the same ceiling/CV/tiering rules as a healthy run."""
    # per-round bandwidths; ceiling_r = best bw ANY copy candidate or
    # the line itself achieved that round (vs_baseline <= 1.0 by
    # construction; see module docstring)
    bw = {}
    for i, s in enumerate(specs):
        if s["nbytes"] is not None:
            bw[s["name"]] = s["nbytes"] / slopes[i] / 1e9
    cand = np.stack([bw[nm] for nm in ceiling_names])
    ceil_r = cand.max(axis=0)
    ceil_med = float(np.median(ceil_r))
    # the CV must be robust to a contaminated round: a tunnel hiccup
    # (or a concurrent job on the chip) can drive one round's slope to
    # the 1e-12 clamp, producing an absurd per-round bandwidth that
    # explodes a plain std while the median stays sane — compute
    # variability over rounds within a sane band of the median and
    # surface how many rounds were discarded
    sane = ceil_r[(ceil_r > 0.2 * ceil_med) & (ceil_r < 5 * ceil_med)]
    dropped_rounds = int(ceil_r.size - sane.size)
    if sane.size:
        ceil_cv = float(np.std(sane) / max(float(np.median(sane)), 1e-12))
    else:
        ceil_cv = float("nan")

    lines = []
    headline = None
    for i, s in enumerate(specs):
        nm = s["name"]
        if nm.startswith("ceiling_copy"):
            continue  # ceiling candidates feed the denominator only
        if s["nbytes"] is None:  # latency line (ring)
            per_hop = np.median(slopes[i]) / s["hops"] * 1e6
            lines.append({
                "metric": f"{nm}_latency", "value": round(per_hop, 4),
                "unit": "us/hop", "vs_baseline": None,
                "note": "no published ref latency; tracked across rounds",
            })
            continue
        value = float(np.median(bw[nm]))
        if s.get("unstable"):
            lines.append({
                "metric": nm, "value": round(value, 3), "unit": "GB/s",
                "vs_baseline": None, "unstable": True,
                "note": "K-delta inside tunnel jitter; value unreliable",
            })
            continue
        if value > 1.15 * ceil_med and s.get("ws", float("inf")) \
                <= ONCHIP_WS:
            # working set fits on-chip: the loop legitimately runs at
            # VMEM bandwidth (iterations checksum-verified), so an HBM
            # ratio would be meaningless — label the tier instead of
            # faking a ceiling.  The ws gate keeps a lucky round from
            # misfiling an HBM-bound line (a 256 MiB transpose at
            # ceiling parity + the +-20% wobble can median past
            # 1.15x): only working sets that can physically reside in
            # VMEM are eligible for the tier; everything else takes
            # the vs_baseline path, whose per-round max(ceil, self)
            # already handles value > ceiling honestly
            entry = {
                "metric": nm, "value": round(value, 3), "unit": "GB/s",
                "vs_baseline": None, "tier": "on-chip",
                "ceiling_gbps": round(ceil_med, 1),
            }
            lines.append(entry)
            continue
        line_ceil = np.maximum(ceil_r, bw[nm])
        vs = float(np.median(bw[nm] / line_ceil))
        entry = {
            "metric": nm,
            "value": round(value, 3),
            "unit": "GB/s",
            "vs_baseline": round(vs, 4),
            "ceiling_gbps": round(ceil_med, 1),
            "ceiling_cv": round(ceil_cv, 4),
        }
        if dropped_rounds:
            entry["ceiling_rounds_dropped"] = dropped_rounds
        if nm == "allreduce_256MiB" and n < 2:
            headline = {
                "metric": "op_sum_256MiB_f32_hbm_bw",
                "value": entry["value"], "unit": "GB/s",
                "vs_baseline": entry["vs_baseline"],
                "ceiling_gbps": entry["ceiling_gbps"],
                "ceiling_cv": entry["ceiling_cv"],
                "parity": True,
            }
        elif nm == "allreduce_256MiB" and n >= 2:
            headline = {
                "metric": f"allreduce_256MiB_f32_busbw_{n}dev",
                "value": entry["value"], "unit": "GB/s",
                "vs_baseline": entry["vs_baseline"],
                "ceiling_gbps": entry["ceiling_gbps"],
                "ceiling_cv": entry["ceiling_cv"],
                "parity": True,
            }
        lines.append(entry)

    if headline is None:  # CPU dev runs (truncated sweep): largest point
        biggest = max(
            (s for s in specs if s["nbytes"] is not None
             and s["name"].startswith("allreduce_")),
            key=lambda s: s["nbytes"],
        )
        headline = {
            "metric": "op_sum_small_f32_hbm_bw" if n < 2
            else f"allreduce_f32_busbw_{n}dev",
            "value": round(float(np.median(bw[biggest["name"]])), 3),
            "unit": "GB/s",
            "vs_baseline": round(float(np.median(
                bw[biggest["name"]]
                / np.maximum(ceil_r, bw[biggest["name"]]))), 4),
            "ceiling_gbps": round(ceil_med, 1),
            "ceiling_cv": round(ceil_cv, 4),
            "parity": True,
        }
        if dropped_rounds:
            headline["ceiling_rounds_dropped"] = dropped_rounds
    return lines, headline


def main():
    import jax
    import jax.numpy as jnp

    from ompi_release_tpu.utils import jaxcompat

    jaxcompat.install()  # jax.shard_map/typeof/pvary on 0.4.x jaxlibs
    watchdog = _arm_global_watchdog()
    devices = _init_backend(jax)
    backend_label = None
    if devices is None:
        # tpu_unavailable: emit the CPU-backend numbers, labelled, so
        # the round record carries data instead of a bare bench_error
        try:
            devices = jax.devices("cpu")
            backend_label = "cpu"
            print(json.dumps({"event": "tpu_unavailable",
                              "fallback": "cpu"}), flush=True)
        except Exception as e:
            print(json.dumps({
                "metric": "bench_error", "value": None, "unit": None,
                "vs_baseline": None, "error": "tpu_unavailable",
                "detail": f"cpu fallback failed: "
                          f"{type(e).__name__}: {e}"[:300],
            }))
            return 0
    n = len(devices)
    on_tpu = backend_label is None and jax.default_backend() == "tpu"

    if n >= 2:
        specs, ceiling_names = _mesh_specs(jax, jnp, devices, on_tpu)
    else:
        specs, ceiling_names = _single_chip_specs(
            jax, jnp, devices[0], on_tpu
        )

    if on_tpu:
        # compile/warm at the static guess, then size K from measured
        # per-iteration time (VMEM-resident loops are 5-20x faster
        # than the HBM estimate)
        for s in specs:
            s["k_lo"], s["k_hi"] = _calibrate_k(
                s["loop"], s["args"], s["k_hi"]
            )

    rounds = 5 if on_tpu else 3

    emitted = []  # every metric line of this round, for the gate

    tier = "tpu" if on_tpu else "loopback-cpu"

    def emit(ln):
        if backend_label:
            ln["backend"] = backend_label
        # explicit tier label on EVERY line: tpu rounds and
        # loopback-CPU rounds (fallback OR forced JAX_PLATFORMS=cpu)
        # stay comparable within their own tier (the bench gate
        # groups by it) instead of a cpu round poisoning the tpu
        # noise fit — or vanishing entirely
        ln.setdefault("tier_label", tier)
        if ln.get("metric"):
            emitted.append(ln)
        print(json.dumps(ln), flush=True)

    # INCREMENTAL emission: every completed metric line prints
    # (flushed) the moment it exists, so a mid-sweep TPU outage — the
    # global watchdog's os._exit, a tunnel hang killed by the driver —
    # preserves the numbers already measured instead of leaving only
    # the tpu_unavailable marker (round 5 lost two consecutive BENCH
    # records exactly this way). The sweep itself can compute nothing
    # until every interleaved round is in (the ceiling is a cross-spec
    # per-round max), so it additionally publishes per-round timings
    # into ``progress``, and the abort paths — watchdog hard-exit,
    # backend crash — salvage metric lines from whatever rounds
    # finished, marked with "partial_rounds".
    progress = {}

    def salvage_sweep():
        done = progress.get("rounds_done", 0)
        if progress.get("emitted") or not done:
            return
        _flag_unstable(specs, progress["lo_t"], progress["hi_t"])
        lines, headline = _sweep_lines(
            specs, ceiling_names, np.asarray(progress["slopes"]), n)
        for ln in lines + [headline]:
            ln["partial_rounds"] = done
            emit(ln)
        # the crash path and a later watchdog fire must not both
        # salvage: duplicate metric rows would corrupt the record
        progress["emitted"] = True

    _SALVAGE_HOOKS.append(salvage_sweep)
    try:
        slopes = _run_rounds(specs, rounds, progress)
    except BaseException:
        try:
            salvage_sweep()
        except Exception:
            pass  # never mask the real failure
        raise

    lines, headline = _sweep_lines(specs, ceiling_names, slopes, n)
    progress["emitted"] = True  # the normal path owns emission now

    for ln in lines:
        emit(ln)

    # compute-bound line (single-chip fwd+bwd MFU): measured after the
    # bandwidth sweep so its compile time cannot contaminate those
    # loops' interleaved rounds
    try:
        emit(_mfu_metric(jax, jnp, devices[0], on_tpu,
                         rounds=max(3, rounds)))
    except Exception as e:
        emit({
            "metric": "transformer_fwdbwd_step", "value": None,
            "unit": "TFLOP/s", "vs_baseline": None,
            "error": f"{type(e).__name__}: {e}"[:200],
        })

    # micro-suites, each behind a cached-backend probe with bounded
    # retry/backoff (BENCH r04/r05 lost whole rounds to one 180 s
    # backend hang; a dead backend now costs one labelled error line):
    #   coll: pipeline/fusion framework-driver lines with pvar labels
    #   wire: cross-process p2p bandwidth, HOL lanes, allgatherv overlap
    #   hier: spanning-collective inter schedules at 4 loopback procs
    #   overlap: exposed vs hidden comm time for iallreduce buckets
    #            under the async progress engine vs polling fallback
    #   tree_overlap: planned whole-tree gradient pass vs per-leaf
    #            loop on a real transformer trainer, hidden-comm
    #            fraction + nonblocking pipeline boundaries
    #   ft_recovery: detect->revoke->shrink->rollback wall time of a
    #            3-proc job whose rank 2 is SIGKILLed mid-run
    #   sentinel: contract-sentinel overhead, enabled vs disabled,
    #            with the sentinel_ops_hashed pvar as witness
    #   fleet_scaling: the simulated-fleet harness runs the real
    #            hier_schedules at P=256/1024 virtual ranks and emits
    #            sim_* scaling observables (rounds, bytes/rank,
    #            makespan), tier_label "sim", all gate-guarded
    #   multi_tenant: the service plane's fairness story — latency
    #            tenant p99 solo vs contended-under-QoS vs FIFO on
    #            one shared simulated fabric; the gate-checked
    #            tenant_latency_isolation degradation ratio
    #   steady_state: interpreted-vs-compiled Python-orchestration
    #            time (frozen schedule plans, coll/plan) for one-shot,
    #            persistent, and 3-proc spanning allreduce legs
    #   native_rounds: the native C plan executor vs the PlannedXchg
    #            Python replay vs interpreted, 3-proc loopback:
    #            orchestration split per leg, bitwise parity in-app,
    #            the >= 2x orch-speedup acceptance at <= 256 KiB
    #   rma_steady: the one-sided twin (frozen epoch plans, osc/plan)
    #            — interpreted-vs-planned fence epochs plus the
    #            planned symmetric-heap bulk path vs per-call
    _run_suite("coll_micro_suite", _coll_micro_suite, emit, jax)
    _run_suite("steady_state_suite", _steady_state_micro_suite, emit,
               jax)
    _run_suite("native_rounds_suite", _native_rounds_micro_suite,
               emit, jax)
    _run_suite("rma_steady_suite", _rma_steady_micro_suite, emit, jax)
    _run_suite("sentinel_suite", _sentinel_micro_suite, emit, jax)
    _run_suite("wire_micro_suite",
               lambda: _wire_micro_suite(backend_label), emit, jax)
    _run_suite("native_wire_suite",
               lambda: _native_wire_micro_suite(backend_label), emit,
               jax)
    _run_suite("native_obs_suite",
               lambda: _native_obs_micro_suite(backend_label), emit,
               jax)
    _run_suite("hier_scaling_suite",
               lambda: _hier_micro_suite(backend_label), emit, jax)
    _run_suite("overlap_suite",
               lambda: _overlap_micro_suite(backend_label), emit, jax)
    _run_suite("tree_overlap_suite",
               lambda: _tree_micro_suite(backend_label), emit, jax)
    _run_suite("ft_recovery_suite",
               lambda: _ft_micro_suite(backend_label), emit, jax)
    _run_suite("fleet_scaling_suite", _fleet_micro_suite, emit, jax,
               needs_backend=False)
    _run_suite("multi_tenant_suite", _multi_tenant_micro_suite, emit,
               jax, needs_backend=False)

    # perf-regression gate: judge THIS round's lines against the
    # on-disk BENCH_r*.json history (fitted noise bounds per metric
    # line, grouped by tier label) so the round record itself says
    # whether the trajectory regressed — tpu_bench_gate's CLI runs the
    # same evaluate() standalone
    try:
        import glob as _glob
        import os as _os

        from ompi_release_tpu.tools import tpu_bench_gate as _gate

        hist_files = sorted(_glob.glob(_os.path.join(
            _os.path.dirname(_os.path.abspath(__file__)),
            "BENCH_r*.json")))
        if hist_files:
            rounds_hist = [_gate.parse_round_file(p)
                           for p in hist_files]
            # the headline prints after this block (it must stay the
            # LAST line) but belongs in the gated set
            cand = list(emitted) + [dict(headline, tier_label=tier)]
            verdict = _gate.evaluate(rounds_hist, cand)
            emit({
                "metric": "bench_gate",
                "value": len(verdict["regressions"]),
                "unit": "regressions", "vs_baseline": None,
                "checked": verdict["checked"],
                "skipped": verdict["skipped"],
                "history_rounds": len(hist_files),
                "regressions": verdict["regressions"][:10],
            })
    except Exception as e:
        emit({
            "metric": "bench_gate", "value": None, "unit": None,
            "vs_baseline": None,
            "error": f"{type(e).__name__}: {e}"[:300],
        })

    # ONE cumulative snapshot: the configs run interleaved (see
    # _run_rounds), so per-config pvar deltas do not exist — emitting
    # the same blob per line would only masquerade as them
    snapshot = json.dumps(
        {"pvars": _pvar_snapshot(), "cumulative": True}, default=str
    )
    if backend_label:
        headline["backend"] = backend_label
    headline.setdefault("tier_label", tier)
    print(snapshot, flush=True)
    print(json.dumps(headline), flush=True)  # headline stays LAST
    watchdog.cancel()


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:  # keep the round record parseable, always
        print(json.dumps({
            "metric": "bench_error", "value": None, "unit": None,
            "vs_baseline": None, "error": "bench_failed",
            "detail": f"{type(e).__name__}: {e}"[:300],
        }))
        sys.exit(0)
