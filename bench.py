"""Headline benchmark: allreduce bus-bandwidth at 256 MiB float32.

Mirrors BASELINE.json config #2 (OSU-style MPI_Allreduce sweep; the
north-star size is 256 MiB f32). With n >= 2 devices this times the
framework's psum allreduce over a 1-D mesh and reports ring bus
bandwidth 2(n-1)/n * bytes / t. On a single chip (the driver's bench
environment) it times the on-device SUM op kernel (out = acc + a, the
``ompi/op`` hot loop of BASELINE's north star): 3x bytes through HBM
per iteration.

Timing method: the tunneled single-chip backend has ~100 ms fixed
per-call round-trip latency, so each measurement jits a fori_loop of K
kernel iterations and takes the slope between K_lo and K_hi — pure
device time, latency cancelled. Completion is forced by fetching an
8-byte checksum (block_until_ready alone can return early through the
tunnel).

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so
the baseline is the measured HBM copy ceiling of the same chip — the
ratio is "fraction of achievable memory bandwidth", target >= 0.8 per
the north star.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time
from functools import partial

import numpy as np

K_LO, K_HI = 2, 66


def _median_call(fn, *args, iters=5):
    def sync(r):
        np.asarray(r)  # tiny checksum fetch forces remote completion

    sync(fn(*args))  # compile + warm
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        sync(fn(*args))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _per_iter_time(loop_fn, *args):
    """Seconds per kernel iteration via the K_hi/K_lo slope."""
    t_lo = _median_call(loop_fn, *args, K_LO)
    t_hi = _median_call(loop_fn, *args, K_HI)
    return max((t_hi - t_lo) / (K_HI - K_LO), 1e-12)


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    n = len(devices)
    size_bytes = 256 * 1024 * 1024
    elems = size_bytes // 4

    if n >= 2:
        mesh = Mesh(np.array(devices), ("rank",))
        sh = NamedSharding(mesh, P("rank"))
        x = jax.device_put(
            jnp.ones((n * elems,), jnp.float32), sh
        )
        inv_n = np.float32(1.0 / n)

        @partial(jax.jit, static_argnums=1)
        def allreduce_loop(x, k):
            def spmd(b):
                def body(i, acc):
                    return lax.psum(acc, "rank") * inv_n

                acc = lax.fori_loop(0, k, body, b)
                return (acc[0] + acc[-1])[None]

            s = jax.shard_map(spmd, mesh=mesh, in_specs=P("rank"),
                              out_specs=P("rank"))(x)
            return s[0]

        per = _per_iter_time(allreduce_loop, x)
        # each rank holds `elems` f32; the ring moves 2(n-1)/n of the
        # full payload per allreduce
        value = (2 * (n - 1) / n) * size_bytes / per / 1e9
        metric = f"allreduce_256MiB_f32_busbw_{n}dev"
    else:
        a = jax.device_put(jnp.ones((elems,), jnp.float32), devices[0])

        @partial(jax.jit, static_argnums=1)
        def op_loop(a, k):
            def body(i, acc):
                return acc * np.float32(0.999) + a  # read acc,a; write

            acc = lax.fori_loop(0, k, body, jnp.zeros_like(a))
            return acc[0] + acc[-1]

        per = _per_iter_time(op_loop, a)
        value = 3 * size_bytes / per / 1e9
        metric = "op_sum_256MiB_f32_hbm_bw"

    # HBM copy ceiling on device 0: read + write = 2x bytes per iter
    c = jax.device_put(jnp.ones((elems,), jnp.float32), devices[0])

    @partial(jax.jit, static_argnums=1)
    def copy_loop(c, k):
        def body(i, acc):
            # add the (varying) loop counter: a streaming read+write
            # XLA cannot algebraically collapse across iterations (a
            # constant multiply/add chain gets folded to one op)
            return acc + lax.convert_element_type(i, jnp.float32)

        acc = lax.fori_loop(0, k, body, c)
        return acc[0] + acc[-1]

    per_copy = _per_iter_time(copy_loop, c)
    ceiling = 2 * size_bytes / per_copy / 1e9

    print(json.dumps({
        "metric": metric,
        "value": round(value, 3),
        "unit": "GB/s",
        "vs_baseline": round(value / ceiling, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
