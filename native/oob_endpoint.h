// OOB endpoint internals, extracted from oob.cc so the datapath BTL
// translation units (btl_tcp.cc, btl_shm.cc) can speak the SAME frame
// format over the SAME sockets — a nativewire fragment is an ordinary
// OOB frame, byte-identical to one built in Python, it just never
// transits a Python bytes object on the sending side.
//
// Everything here is header-only (inline) and lives in namespace
// ompitpu; oob.cc keeps the extern "C" control-plane ABI, the BTL
// files add the extern "C" datapath ABI on top of the same Endpoint.
#ifndef OMPITPU_OOB_ENDPOINT_H_
#define OMPITPU_OOB_ENDPOINT_H_

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <random>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace ompitpu {

constexpr uint32_t kMagic = 0x4f4d5054;  // "OMPT"
// Hop budget: a mis-set routing table (two default routes pointing at
// each other) would otherwise relay a frame in a cycle forever.
constexpr int32_t kMaxTtl = 32;

// Control-plane authentication (the opal/mca/sec credential framework
// analogue, sec.h:79-91 `authenticate`): when a per-job secret is set,
// every INBOUND connection must answer a fresh-nonce challenge with
// SipHash-2-4(secret, nonce) before any frame it sends is accepted —
// without this, any local user could inject TAG_DIE/TAG_MIGRATE frames
// into a running job's control plane.
constexpr int32_t kTagChallenge = -998;
constexpr int32_t kTagAuth = -997;
constexpr int kNonceLen = 16;

inline uint64_t rotl64(uint64_t x, int b) {
  return (x << b) | (x >> (64 - b));
}

// SipHash-2-4 (Aumasson & Bernstein; public-domain reference
// algorithm): a keyed PRF designed for exactly this short-input
// authentication job — no crypto library dependency needed.
inline uint64_t siphash24(const uint8_t key[16], const uint8_t* in,
                          size_t inlen) {
  uint64_t k0, k1;
  std::memcpy(&k0, key, 8);
  std::memcpy(&k1, key + 8, 8);
  uint64_t v0 = 0x736f6d6570736575ULL ^ k0;
  uint64_t v1 = 0x646f72616e646f6dULL ^ k1;
  uint64_t v2 = 0x6c7967656e657261ULL ^ k0;
  uint64_t v3 = 0x7465646279746573ULL ^ k1;
  auto sipround = [&] {
    v0 += v1; v1 = rotl64(v1, 13); v1 ^= v0; v0 = rotl64(v0, 32);
    v2 += v3; v3 = rotl64(v3, 16); v3 ^= v2;
    v0 += v3; v3 = rotl64(v3, 21); v3 ^= v0;
    v2 += v1; v1 = rotl64(v1, 17); v1 ^= v2; v2 = rotl64(v2, 32);
  };
  const uint8_t* end = in + (inlen & ~size_t{7});
  for (; in != end; in += 8) {
    uint64_t m;
    std::memcpy(&m, in, 8);
    v3 ^= m;
    sipround();
    sipround();
    v0 ^= m;
  }
  uint64_t b = static_cast<uint64_t>(inlen) << 56;
  for (size_t i = 0; i < (inlen & 7); ++i)
    b |= static_cast<uint64_t>(in[i]) << (8 * i);
  v3 ^= b;
  sipround();
  sipround();
  v0 ^= b;
  v2 ^= 0xff;
  sipround();
  sipround();
  sipround();
  sipround();
  return v0 ^ v1 ^ v2 ^ v3;
}

inline bool read_full_timeout(int fd, void* buf, size_t n,
                              int timeout_ms) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n) {
    pollfd pfd{fd, POLLIN, 0};
    int pr = ::poll(&pfd, 1, timeout_ms);
    if (pr <= 0) return false;
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

struct Frame {
  int32_t src;
  int32_t dst;
  int32_t tag;
  int32_t ttl = kMaxTtl;
  std::vector<uint8_t> payload;
};

struct Header {
  uint32_t magic;
  int32_t src;
  int32_t dst;
  int32_t tag;
  int32_t ttl;
  uint32_t len;
} __attribute__((packed));

inline bool read_full(int fd, void* buf, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

inline bool write_full(int fd, const void* buf, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

struct Endpoint {
  int32_t id = -1;
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stopping{false};
  bool has_secret = false;
  uint8_t secret[16] = {0};
  std::atomic<int> auth_rejected{0};  // refused inbound connections

  std::mutex mu;                     // guards peers/routes/queue
  std::mutex wmu;                    // serializes frame writes
  std::map<int32_t, int> peer_fd;    // directly connected peers
  std::set<int> open_fds;            // EVERY live connection fd (incl.
                                     // inbound ones not yet announced)
  std::map<int32_t, int32_t> route;  // dst -> next-hop peer
  std::deque<Frame> queue;
  std::deque<Frame> undeliverable;   // forwards awaiting a peer/route
  std::atomic<int> ttl_dropped{0};   // frames dropped at ttl 0

  // native-wire telemetry block (the tcp analogue of the shm ring
  // header counters): relaxed, always-on, bumped by wire_sendv /
  // wire_recv_frag in btl_tcp.cc. tx_* counts vectored sends (bytes =
  // payload, header excluded); rx_* counts fragments copied into a
  // reassembly buffer; rx_stalls/rx_stall_ns accumulate time
  // wire_recv_frag spent parked on the queue cv with nothing to match.
  std::atomic<uint64_t> tx_frames{0};
  std::atomic<uint64_t> tx_bytes{0};
  std::atomic<uint64_t> rx_frames{0};
  std::atomic<uint64_t> rx_bytes{0};
  std::atomic<uint64_t> rx_stalls{0};
  std::atomic<uint64_t> rx_stall_ns{0};
  std::condition_variable cv;
  std::vector<std::thread> threads;
  std::thread acceptor;

  ~Endpoint() { stop(); }

  void stop() {
    if (stopping.exchange(true)) return;
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      ::close(listen_fd);
    }
    {
      // shutdown (not close) every connection fd — including inbound
      // ones whose announce frame never arrived; each reader_loop
      // unblocks, deregisters, and closes its own fd, so no fd is
      // closed twice and no reader blocks forever in read()
      std::lock_guard<std::mutex> l(mu);
      for (int fd : open_fds) ::shutdown(fd, SHUT_RDWR);
    }
    cv.notify_all();
    if (acceptor.joinable()) acceptor.join();
    for (auto& t : threads)
      if (t.joinable()) t.join();
  }

  int next_hop_fd(int32_t dst) {
    std::lock_guard<std::mutex> l(mu);
    auto it = peer_fd.find(dst);
    if (it != peer_fd.end()) return it->second;
    auto r = route.find(dst);
    if (r != route.end()) {
      auto h = peer_fd.find(r->second);
      if (h != peer_fd.end()) return h->second;
    }
    auto d = route.find(-1);  // default route (toward the root)
    if (d != route.end()) {
      auto h = peer_fd.find(d->second);
      if (h != peer_fd.end()) return h->second;
    }
    return -1;
  }

  bool send_frame(const Frame& f) {
    int fd = next_hop_fd(f.dst);
    if (fd < 0) return false;
    Header h{kMagic, f.src, f.dst, f.tag, f.ttl,
             static_cast<uint32_t>(f.payload.size())};
    std::lock_guard<std::mutex> l(wmu);  // serialize frame writes
    if (!write_full(fd, &h, sizeof h)) return false;
    return f.payload.empty() ||
           write_full(fd, f.payload.data(), f.payload.size());
  }

  void deliver_or_forward(Frame&& f, bool spend_ttl = true) {
    if (f.dst == id || f.dst == -1) {
      std::lock_guard<std::mutex> l(mu);
      queue.push_back(std::move(f));
      cv.notify_all();
      return;
    }
    // relay hop: spend one ttl unit; at zero the frame dies here
    // (cycle guard — see kMaxTtl). Retries from the undeliverable
    // queue already paid for this hop (spend_ttl=false).
    if (spend_ttl && --f.ttl <= 0) {
      ttl_dropped.fetch_add(1);
      return;
    }
    if (!send_frame(f)) {
      // tree relay (routed analogue); a frame can arrive before the
      // next hop has announced itself — hold it until a peer registers
      std::lock_guard<std::mutex> l(mu);
      undeliverable.push_back(std::move(f));
    }
  }

  void flush_undeliverable() {
    std::deque<Frame> retry;
    {
      std::lock_guard<std::mutex> l(mu);
      retry.swap(undeliverable);
    }
    for (auto& f : retry) deliver_or_forward(std::move(f), false);
  }

  // Pre-auth gate for an inbound connection: the FIRST frame must be
  // the 8-byte SipHash of the challenge nonce. Header and MAC are
  // read with a deadline and a hard length bound — an attacker must
  // not be able to park a reader thread forever or make it allocate
  // an arbitrary h.len before proving knowledge of the secret.
  bool authenticate_inbound(int fd, const std::vector<uint8_t>& nonce) {
    Header h;
    if (!read_full_timeout(fd, &h, sizeof h, 10'000) ||
        h.magic != kMagic || h.tag != kTagAuth || h.len != 8) {
      auth_rejected.fetch_add(1);
      return false;
    }
    uint64_t got;
    if (!read_full_timeout(fd, &got, 8, 10'000)) {
      auth_rejected.fetch_add(1);
      return false;
    }
    uint64_t want = siphash24(secret, nonce.data(), nonce.size());
    if (got != want) {
      auth_rejected.fetch_add(1);
      return false;
    }
    return true;
  }

  // nonce non-empty = inbound connection that must authenticate
  // before any frame it sends is processed — a well-formed
  // announce/data frame from an unauthenticated peer is refused,
  // never queued.
  void reader_loop(int fd, std::vector<uint8_t> nonce = {}) {
    bool authed = nonce.empty() || authenticate_inbound(fd, nonce);
    while (authed) {
      Header h;
      if (!read_full(fd, &h, sizeof h) || h.magic != kMagic) break;
      Frame f;
      f.src = h.src;
      f.dst = h.dst;
      f.tag = h.tag;
      f.ttl = h.ttl;
      f.payload.resize(h.len);
      if (h.len && !read_full(fd, f.payload.data(), h.len)) break;
      // first frame on an inbound connection announces the peer id
      if (h.tag == -999) {
        {
          std::lock_guard<std::mutex> l(mu);
          peer_fd[h.src] = fd;
        }
        flush_undeliverable();
        continue;
      }
      deliver_or_forward(std::move(f));
    }
    // connection over: deregister and close OUR fd exactly once (a
    // disconnected peer must not linger in peer_fd, and stop() must
    // not double-close it)
    {
      std::lock_guard<std::mutex> l(mu);
      open_fds.erase(fd);
      for (auto it = peer_fd.begin(); it != peer_fd.end();) {
        if (it->second == fd)
          it = peer_fd.erase(it);
        else
          ++it;
      }
    }
    ::close(fd);
  }

  void accept_loop() {
    std::random_device rd;
    for (;;) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) return;  // listener closed
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
      std::vector<uint8_t> nonce;
      if (has_secret) {
        // fresh per-connection nonce: replaying a captured response
        // cannot authenticate a new connection
        nonce.resize(kNonceLen);
        for (int i = 0; i < kNonceLen; i += 4) {
          uint32_t r = rd();
          std::memcpy(nonce.data() + i, &r, 4);
        }
        Header ch{kMagic, id, -1, kTagChallenge, kMaxTtl,
                  static_cast<uint32_t>(nonce.size())};
        if (!write_full(fd, &ch, sizeof ch) ||
            !write_full(fd, nonce.data(), nonce.size())) {
          ::close(fd);
          continue;
        }
      }
      std::lock_guard<std::mutex> l(mu);
      if (stopping) {
        // stop() already swept open_fds; registering now would leave
        // a reader blocked forever — drop the connection instead
        ::close(fd);
        return;
      }
      open_fds.insert(fd);
      threads.emplace_back(
          [this, fd, nonce] { reader_loop(fd, nonce); });
    }
  }
};

}  // namespace ompitpu

#endif  // OMPITPU_OOB_ENDPOINT_H_
