// nativewire TCP datapath — vectored, zero-copy fragment IO over the
// SAME sockets (and the SAME frame format) as the OOB control plane.
//
// The reference's btl/tcp moves user bytes with writev over the
// endpoint's socket while the OOB keeps its own connection; here the
// footprint is smaller — one authenticated TCP mesh — so the datapath
// shares it. That sharing is what makes nativewire's wire format
// byte-identical BY CONSTRUCTION: wire_sendv emits an ordinary OOB
// Header followed by the scatter-gather parts, indistinguishable on
// the wire from ``ep.send(dst, tag, b"".join(parts))`` — except the
// join (one full payload copy into a Python bytes) never happens, and
// neither do the per-part ctypes staging copies.
//
// Receive side: wire_recv_frag scans the endpoint's frame queue for
// the next SGC2 fragment of one specific transfer and memcpys its
// payload STRAIGHT into the caller's preallocated reassembly buffer
// (recv_into discipline) — the fragment never surfaces as a Python
// bytes object. Sentinel frames, headers, stale fragments and
// anything else stay queued for the portable Python path (return -4),
// so all any-source/stash/ULFM machinery keeps working unchanged.

#include <limits.h>
#include <sys/uio.h>

#include "nativeev.h"
#include "oob_endpoint.h"

namespace {

using ompitpu::Endpoint;
using ompitpu::Frame;
using ompitpu::Header;
using ompitpu::kMagic;

#ifndef IOV_MAX
#define IOV_MAX 1024
#endif

// writev with partial-write recovery and IOV_MAX batching. Mutates
// the iovec array in place (already-sent entries zeroed) — callers
// pass a scratch copy.
bool writev_full(int fd, struct iovec* iov, size_t cnt) {
  size_t i = 0;
  while (i < cnt) {
    size_t batch = cnt - i;
    if (batch > IOV_MAX) batch = IOV_MAX;
    ssize_t w = ::writev(fd, iov + i, static_cast<int>(batch));
    if (w <= 0) return false;
    size_t left = static_cast<size_t>(w);
    while (i < cnt && left >= iov[i].iov_len) {
      left -= iov[i].iov_len;
      ++i;
    }
    if (left) {  // partial write inside entry i: advance its base
      iov[i].iov_base = static_cast<uint8_t*>(iov[i].iov_base) + left;
      iov[i].iov_len -= left;
    }
  }
  return true;
}

// SGC2 fragment layout (pinned by btl/components.py staged_frames):
//   b"SGC2" + xfer u64 BE + idx u64 BE + payload
constexpr size_t kSgPrefix = 4 + 8 + 8;

inline uint64_t be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | p[i];
  return v;
}

// Event-ring peek: gather the SGC2 prefix out of the scatter-gather
// list (non-fragment frames — headers, sentinels — emit no event).
bool sg_peek(const uint8_t** parts, const int64_t* lens,
             int32_t nparts, uint64_t* xfer, uint64_t* idx) {
  uint8_t pre[kSgPrefix];
  size_t got = 0;
  for (int32_t i = 0; i < nparts && got < kSgPrefix; ++i) {
    size_t take = static_cast<size_t>(lens[i]);
    if (take > kSgPrefix - got) take = kSgPrefix - got;
    std::memcpy(pre + got, parts[i], take);
    got += take;
  }
  if (got < kSgPrefix || std::memcmp(pre, "SGC2", 4) != 0)
    return false;
  *xfer = be64(pre + 4);
  *idx = be64(pre + 12);
  return true;
}

inline void bump(std::atomic<uint64_t>& c, uint64_t v) {
  c.fetch_add(v, std::memory_order_relaxed);
}

}  // namespace

extern "C" {

// Send one frame whose payload is the concatenation of `nparts`
// scatter-gather parts, without materializing the concatenation.
// Returns 0, or -1 when no route to dst exists / the write failed
// (same contract as oob_send — caller falls back or raises).
int wire_sendv(void* h, int32_t dst, int32_t tag,
               const uint8_t** parts, const int64_t* lens,
               int32_t nparts) {
  auto* ep = static_cast<Endpoint*>(h);
  uint64_t total = 0;
  for (int32_t i = 0; i < nparts; ++i)
    total += static_cast<uint64_t>(lens[i]);
  if (dst == ep->id) {
    // self-send lands in our own queue; the copy into the queued
    // frame is the delivery itself, not wire overhead
    Frame f;
    f.src = ep->id;
    f.dst = dst;
    f.tag = tag;
    f.payload.reserve(total);
    for (int32_t i = 0; i < nparts; ++i)
      f.payload.insert(f.payload.end(), parts[i], parts[i] + lens[i]);
    ep->deliver_or_forward(std::move(f));
    bump(ep->tx_frames, 1);
    bump(ep->tx_bytes, total);
    uint64_t xfer, idx;
    if (sg_peek(parts, lens, nparts, &xfer, &idx))
      ompitpu::nativeev_emit(
          tag, xfer, static_cast<uint32_t>(total - kSgPrefix),
          static_cast<uint32_t>(idx), /*recv_side=*/false, 0);
    return 0;
  }
  int fd = ep->next_hop_fd(dst);
  if (fd < 0) return -1;
  Header hdr{kMagic, ep->id, dst, tag, ompitpu::kMaxTtl,
             static_cast<uint32_t>(total)};
  std::vector<struct iovec> iov(static_cast<size_t>(nparts) + 1);
  iov[0].iov_base = &hdr;
  iov[0].iov_len = sizeof hdr;
  for (int32_t i = 0; i < nparts; ++i) {
    iov[i + 1].iov_base = const_cast<uint8_t*>(parts[i]);
    iov[i + 1].iov_len = static_cast<size_t>(lens[i]);
  }
  // same wmu discipline as send_frame: frames on a shared socket must
  // not interleave, and the control plane writes on this fd too
  {
    std::lock_guard<std::mutex> l(ep->wmu);
    if (!writev_full(fd, iov.data(), iov.size())) return -1;
  }
  bump(ep->tx_frames, 1);
  bump(ep->tx_bytes, total);
  uint64_t xfer, idx;
  if (sg_peek(parts, lens, nparts, &xfer, &idx))
    ompitpu::nativeev_emit(
        tag, xfer, static_cast<uint32_t>(total - kSgPrefix),
        static_cast<uint32_t>(idx), /*recv_side=*/false, 0);
  return 0;
}

// Pop the next SGC2 fragment of transfer `xfer` from (src, tag) and
// copy its payload straight into `base` (an nbytes reassembly buffer
// laid out as nchunks fragments of `chunk` bytes, last one short).
// src == -1 matches any source. Returns the fragment index (>= 0), or:
//   -1  timeout — nothing matching arrived
//   -2  malformed/overrun fragment (CONSUMED; caller raises truncate)
//   -4  the next (src, tag) frame is not an SGC2 fragment of this
//       transfer (LEFT QUEUED; caller drains it via the portable path
//       — stale-transfer drop, stash, sentinel handling all live there)
int64_t wire_recv_frag(void* h, int32_t src, int32_t tag, int64_t xfer,
                       int64_t nchunks, int64_t chunk, uint8_t* base,
                       int64_t nbytes, int timeout_ms) {
  auto* ep = static_cast<Endpoint*>(h);
  std::unique_lock<std::mutex> l(ep->mu);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  // empty-queue stall accounting (the cv analogue of the shm ring's
  // Deadline-loop stall block): armed on the first wait, settled on
  // every exit path
  bool stalled = false;
  std::chrono::steady_clock::time_point stall_t0;
  auto settle = [&]() -> uint64_t {
    if (!stalled) return 0;
    stalled = false;
    uint64_t w = static_cast<uint64_t>(
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now() - stall_t0)
            .count());
    bump(ep->rx_stall_ns, w);
    return w;
  };
  for (;;) {
    for (auto it = ep->queue.begin(); it != ep->queue.end(); ++it) {
      if (it->tag != tag || (src != -1 && it->src != src)) continue;
      const auto& p = it->payload;
      if (p.size() < kSgPrefix || std::memcmp(p.data(), "SGC2", 4) != 0 ||
          be64(p.data() + 4) != static_cast<uint64_t>(xfer)) {
        settle();
        return -4;
      }
      int64_t idx = static_cast<int64_t>(be64(p.data() + 12));
      int64_t flen = static_cast<int64_t>(p.size() - kSgPrefix);
      if (idx < 0 || idx >= nchunks || idx * chunk + flen > nbytes) {
        ep->queue.erase(it);  // poisoned fragment: consume, report
        settle();
        return -2;
      }
      if (flen)
        std::memcpy(base + idx * chunk, p.data() + kSgPrefix,
                    static_cast<size_t>(flen));
      ep->queue.erase(it);  // `p` dangles past this point
      uint64_t waited = settle();
      bump(ep->rx_frames, 1);
      bump(ep->rx_bytes, static_cast<uint64_t>(flen) + kSgPrefix);
      ompitpu::nativeev_emit(tag, static_cast<uint64_t>(xfer),
                             static_cast<uint32_t>(flen),
                             static_cast<uint32_t>(idx),
                             /*recv_side=*/true, waited);
      return idx;
    }
    if (!stalled) {
      stalled = true;
      stall_t0 = std::chrono::steady_clock::now();
      bump(ep->rx_stalls, 1);
    }
    if (ep->stopping ||
        ep->cv.wait_until(l, deadline) == std::cv_status::timeout) {
      settle();
      return -1;
    }
  }
}

// Endpoint telemetry block reader. Indices:
//   0 tx_frames  1 tx_bytes  2 rx_frames  3 rx_bytes
//   4 rx_stalls  5 rx_stall_ns
// -1 for an unknown index.
int64_t wire_stats(void* h, int32_t which) {
  auto* ep = static_cast<Endpoint*>(h);
  const std::atomic<uint64_t>* fields[] = {
      &ep->tx_frames, &ep->tx_bytes,  &ep->rx_frames,
      &ep->rx_bytes,  &ep->rx_stalls, &ep->rx_stall_ns};
  if (which < 0 || which >= static_cast<int32_t>(
                                sizeof(fields) / sizeof(fields[0])))
    return -1;
  return static_cast<int64_t>(
      fields[which]->load(std::memory_order_relaxed));
}

}  // extern "C"
