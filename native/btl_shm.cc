// nativewire shared-memory datapath — single-producer single-consumer
// byte rings over POSIX shm for co-hosted ranks.
//
// The reference's btl/sm moves eager fragments through per-peer FIFOs
// in a mapped segment instead of the loopback TCP stack; this is that
// idea for the TPU framework's tpurun worker processes. Each DIRECTED
// (producer -> consumer) pair gets its own ring, and a peer pair
// stripes lanes across a small slot set (slot = tag % nslots), so one
// bulk lane can never head-of-line-block another lane's ring — the
// shm analogue of the QoS lane striping the TCP path already does.
//
// Ring layout (one shm object):
//   [128-byte header][capacity bytes of ring data]
//   header: u64 magic, u64 capacity, u64 widx, u64 ridx,
//           i64 producer_pid, i64 consumer_pid,
//           then the telemetry block (see RingHdr)
// widx/ridx are MONOTONIC byte counters (offset = idx % capacity);
// they are only ever written by their owning side, with release
// stores paired against acquire loads on the other side — the
// classic SPSC discipline, no locks in the byte path.
//
// Records: [u32 payload_len][i32 tag][payload], byte-wrapped (no
// padding); the payload of a fragment record is EXACTLY the frame
// payload the TCP path would carry (SGC2 prefix + bytes), so the
// byte-identity contract holds across both native transports.
//
// Fault model: same-host liveness is authoritative — kill(pid, 0)
// answering ESRCH means the peer is GONE, not slow. Both blocking
// entry points poll the counterpart pid and return -3 so Python can
// raise the PR 9 typed error (ERR_PROC_FAILED) instead of wedging on
// a ring that will never drain.

#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <thread>

#include "nativeev.h"

namespace {

// v2: the header grew a telemetry block, which moves the data offset
// — a v1 peer interpreting v2 bytes would corrupt frames, so the
// magic changes with the layout. Safe across the fleet because every
// rank builds the .so from the same sources (bindings stamp-check).
constexpr uint64_t kRingMagic = 0x6f6d707473687232ULL;  // "omptshr2"
constexpr size_t kHdrSize = 128;
constexpr size_t kRecHdr = 8;  // u32 len + i32 tag
constexpr size_t kSgPrefix = 4 + 8 + 8;  // "SGC2" + xfer + idx

struct RingHdr {
  uint64_t magic;
  uint64_t capacity;
  uint64_t widx;
  uint64_t ridx;
  int64_t producer_pid;
  int64_t consumer_pid;
  // telemetry block — always-on relaxed counters, each field written
  // by exactly one side (SPSC carries over), read by anyone. w_* and
  // hwm belong to the producer, r_* to the consumer. Bytes count
  // record payloads (the fragment bytes Python used to count), hwm is
  // the occupancy high-water mark in ring bytes, stall_ns accumulates
  // time spent blocked in the Deadline wait loops.
  uint64_t w_frames;
  uint64_t w_bytes;
  uint64_t w_stalls;
  uint64_t w_stall_ns;
  uint64_t hwm;
  uint64_t r_frames;
  uint64_t r_bytes;
  uint64_t r_stalls;
  uint64_t r_stall_ns;
};
static_assert(sizeof(RingHdr) <= kHdrSize, "ring header grew");

struct ShmRing {
  uint8_t* map = nullptr;
  uint64_t cap = 0;
  bool creator = false;
};

inline RingHdr* hdr(ShmRing* r) {
  return reinterpret_cast<RingHdr*>(r->map);
}
inline uint8_t* data(ShmRing* r) { return r->map + kHdrSize; }

inline uint64_t load_acq(uint64_t* p) {
  return __atomic_load_n(p, __ATOMIC_ACQUIRE);
}
inline void store_rel(uint64_t* p, uint64_t v) {
  __atomic_store_n(p, v, __ATOMIC_RELEASE);
}

// telemetry: each counter has a single writer, so load+store relaxed
// is enough — no RMW, no fence, unmeasurable next to the memcpy
inline uint64_t load_rlx(uint64_t* p) {
  return __atomic_load_n(p, __ATOMIC_RELAXED);
}
inline void bump_rlx(uint64_t* p, uint64_t v) {
  __atomic_store_n(p, __atomic_load_n(p, __ATOMIC_RELAXED) + v,
                   __ATOMIC_RELAXED);
}
inline void max_rlx(uint64_t* p, uint64_t v) {
  if (v > __atomic_load_n(p, __ATOMIC_RELAXED))
    __atomic_store_n(p, v, __ATOMIC_RELAXED);
}

// one blocked wait = one stall; construct when the fast check fails,
// settle() once on the way out (every exit path, including errors)
struct StallTimer {
  uint64_t* count;
  uint64_t* ns;
  std::chrono::steady_clock::time_point t0;
  bool armed = false;
  StallTimer(uint64_t* c, uint64_t* n) : count(c), ns(n) {}
  void arm() {
    if (armed) return;
    armed = true;
    t0 = std::chrono::steady_clock::now();
    bump_rlx(count, 1);
  }
  uint64_t settle() {
    if (!armed) return 0;
    armed = false;
    auto dt = std::chrono::steady_clock::now() - t0;
    uint64_t w =
        static_cast<uint64_t>(std::chrono::duration_cast<
                              std::chrono::nanoseconds>(dt).count());
    bump_rlx(ns, w);
    return w;
  }
};

inline bool pid_dead(int64_t pid) {
  // pid 0 = counterpart not attached yet: still coming up, not dead
  return pid > 0 && ::kill(static_cast<pid_t>(pid), 0) != 0 &&
         errno == ESRCH;
}

// modular copies between the ring and linear buffers
void ring_put(ShmRing* r, uint64_t pos, const uint8_t* src, size_t n) {
  uint64_t off = pos % r->cap;
  size_t first = static_cast<size_t>(
      n < r->cap - off ? n : r->cap - off);
  std::memcpy(data(r) + off, src, first);
  if (n > first) std::memcpy(data(r), src + first, n - first);
}

void ring_get(ShmRing* r, uint64_t pos, uint8_t* dst, size_t n) {
  uint64_t off = pos % r->cap;
  size_t first = static_cast<size_t>(
      n < r->cap - off ? n : r->cap - off);
  std::memcpy(dst, data(r) + off, first);
  if (n > first) std::memcpy(dst + first, data(r), n - first);
}

inline uint64_t be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | p[i];
  return v;
}

// Peek the SGC2 prefix out of a scatter-gather list (the event ring
// wants xfer/idx and the producer only has the iovec). True iff the
// payload starts with a full prefix.
bool sg_peek(const uint8_t** parts, const int64_t* lens,
             int32_t nparts, uint64_t* xfer, uint64_t* idx) {
  uint8_t pre[kSgPrefix];
  size_t got = 0;
  for (int32_t i = 0; i < nparts && got < kSgPrefix; ++i) {
    size_t take = static_cast<size_t>(lens[i]);
    if (take > kSgPrefix - got) take = kSgPrefix - got;
    std::memcpy(pre + got, parts[i], take);
    got += take;
  }
  if (got < kSgPrefix || std::memcmp(pre, "SGC2", 4) != 0)
    return false;
  *xfer = be64(pre + 4);
  *idx = be64(pre + 12);
  return true;
}

struct Deadline {
  std::chrono::steady_clock::time_point t;
  explicit Deadline(int timeout_ms)
      : t(std::chrono::steady_clock::now() +
          std::chrono::milliseconds(timeout_ms)) {}
  bool expired() const { return std::chrono::steady_clock::now() >= t; }
};

inline void ring_nap() {
  // short sleep, not sched_yield: rings pair with device work, a
  // spinning consumer would steal the XLA threads' cores
  std::this_thread::sleep_for(std::chrono::microseconds(50));
}

ShmRing* map_ring(int fd, uint64_t total, bool creator) {
  void* m = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  ::close(fd);  // mapping keeps the object alive
  if (m == MAP_FAILED) return nullptr;
  auto* r = new ShmRing();
  r->map = static_cast<uint8_t*>(m);
  r->cap = total - kHdrSize;
  r->creator = creator;
  return r;
}

}  // namespace

extern "C" {

// Create (O_CREAT|O_EXCL) a ring named `name` (leading '/', per
// shm_open) with `capacity` data bytes and stamp ourselves producer.
// NULL when the name exists already or the mapping failed.
void* shmring_create(const char* name, int64_t capacity,
                     int64_t producer_pid) {
  if (capacity < static_cast<int64_t>(kRecHdr) * 2) return nullptr;
  int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = kHdrSize + static_cast<uint64_t>(capacity);
  if (::ftruncate(fd, static_cast<off_t>(total)) != 0) {
    ::close(fd);
    ::shm_unlink(name);
    return nullptr;
  }
  ShmRing* r = map_ring(fd, total, true);
  if (!r) {
    ::shm_unlink(name);
    return nullptr;
  }
  RingHdr* h = hdr(r);
  h->capacity = static_cast<uint64_t>(capacity);
  h->widx = 0;
  h->ridx = 0;
  h->producer_pid = producer_pid;
  h->consumer_pid = 0;
  // telemetry block starts zeroed (ftruncate guarantees it; be
  // explicit so a future re-create-in-place stays correct)
  h->w_frames = h->w_bytes = h->w_stalls = h->w_stall_ns = 0;
  h->hwm = 0;
  h->r_frames = h->r_bytes = h->r_stalls = h->r_stall_ns = 0;
  // magic LAST (release): an attacher seeing the magic sees a fully
  // initialized header
  __atomic_store_n(&h->magic, kRingMagic, __ATOMIC_RELEASE);
  return r;
}

// Attach an existing ring; stamp ourselves consumer when
// consumer_pid > 0. NULL when absent / not yet initialized.
void* shmring_attach(const char* name, int64_t consumer_pid) {
  int fd = ::shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st{};
  if (::fstat(fd, &st) != 0 ||
      static_cast<size_t>(st.st_size) <= kHdrSize) {
    ::close(fd);
    return nullptr;
  }
  ShmRing* r = map_ring(fd, static_cast<uint64_t>(st.st_size), false);
  if (!r) return nullptr;
  RingHdr* h = hdr(r);
  if (__atomic_load_n(&h->magic, __ATOMIC_ACQUIRE) != kRingMagic ||
      h->capacity != r->cap) {
    ::munmap(r->map, r->cap + kHdrSize);
    delete r;
    return nullptr;
  }
  if (consumer_pid > 0) h->consumer_pid = consumer_pid;
  return r;
}

int shmring_unlink(const char* name) { return ::shm_unlink(name); }

void shmring_close(void* vr) {
  auto* r = static_cast<ShmRing*>(vr);
  ::munmap(r->map, r->cap + kHdrSize);
  delete r;
}

int64_t shmring_capacity(void* vr) {
  return static_cast<int64_t>(static_cast<ShmRing*>(vr)->cap);
}

int64_t shmring_producer_pid(void* vr) {
  return hdr(static_cast<ShmRing*>(vr))->producer_pid;
}

int64_t shmring_consumer_pid(void* vr) {
  return hdr(static_cast<ShmRing*>(vr))->consumer_pid;
}

// Bytes currently queued (tests/observability).
int64_t shmring_pending(void* vr) {
  auto* r = static_cast<ShmRing*>(vr);
  RingHdr* h = hdr(r);
  return static_cast<int64_t>(load_acq(&h->widx) - load_acq(&h->ridx));
}

// Telemetry block reader. Indices:
//   0 w_frames  1 w_bytes  2 w_stalls  3 w_stall_ns  4 hwm (bytes)
//   5 r_frames  6 r_bytes  7 r_stalls  8 r_stall_ns
// -1 for an unknown index. Reads are relaxed — the block is
// monotonic diagnostics, not synchronization.
int64_t shmring_stat(void* vr, int32_t which) {
  RingHdr* h = hdr(static_cast<ShmRing*>(vr));
  uint64_t* fields[] = {&h->w_frames, &h->w_bytes,   &h->w_stalls,
                        &h->w_stall_ns, &h->hwm,     &h->r_frames,
                        &h->r_bytes,  &h->r_stalls,  &h->r_stall_ns};
  if (which < 0 || which >= static_cast<int32_t>(
                                sizeof(fields) / sizeof(fields[0])))
    return -1;
  return static_cast<int64_t>(load_rlx(fields[which]));
}

// Producer side: append one record whose payload is the concatenation
// of the scatter-gather parts. 0 on success, -1 timeout (ring full),
// -2 record can never fit (caller must route via TCP), -3 consumer
// process is gone.
int shmring_writev(void* vr, int32_t tag, const uint8_t** parts,
                   const int64_t* lens, int32_t nparts,
                   int timeout_ms) {
  auto* r = static_cast<ShmRing*>(vr);
  RingHdr* h = hdr(r);
  uint64_t plen = 0;
  for (int32_t i = 0; i < nparts; ++i)
    plen += static_cast<uint64_t>(lens[i]);
  uint64_t total = kRecHdr + plen;
  if (total > r->cap) return -2;
  Deadline dl(timeout_ms);
  StallTimer stall(&h->w_stalls, &h->w_stall_ns);
  uint64_t w = h->widx;  // we are the only writer
  for (;;) {
    uint64_t used = w - load_acq(&h->ridx);
    if (r->cap - used >= total) break;
    stall.arm();  // ring full: this write is a stall until it drains
    if (pid_dead(h->consumer_pid)) {
      stall.settle();
      return -3;
    }
    if (dl.expired()) {
      stall.settle();
      return -1;
    }
    ring_nap();
  }
  uint64_t waited = stall.settle();
  uint8_t rec[kRecHdr];
  uint32_t l32 = static_cast<uint32_t>(plen);
  std::memcpy(rec, &l32, 4);
  std::memcpy(rec + 4, &tag, 4);
  ring_put(r, w, rec, kRecHdr);
  uint64_t pos = w + kRecHdr;
  for (int32_t i = 0; i < nparts; ++i) {
    ring_put(r, pos, parts[i], static_cast<size_t>(lens[i]));
    pos += static_cast<uint64_t>(lens[i]);
  }
  store_rel(&h->widx, w + total);
  bump_rlx(&h->w_frames, 1);
  bump_rlx(&h->w_bytes, plen);
  max_rlx(&h->hwm, (w + total) - load_acq(&h->ridx));
  uint64_t xfer, idx;
  if (sg_peek(parts, lens, nparts, &xfer, &idx))
    ompitpu::nativeev_emit(
        tag, xfer,
        static_cast<uint32_t>(plen - kSgPrefix),
        static_cast<uint32_t>(idx), /*recv_side=*/false, waited);
  return 0;
}

// Consumer side, fragment fast path: pop the head record IF it is an
// SGC2 fragment of transfer `xfer` on `tag`, copying its payload
// straight into the reassembly buffer. Returns the fragment index, or
//   -1 timeout   -2 malformed/overrun (consumed)   -3 producer dead
//   -4 same-tag stale fragment (consumed + dropped, like the portable
//      path's want-prefix filter)
//   -5 head record carries a DIFFERENT tag (left; pop via
//      shmring_read_into and stash it)
int64_t shmring_read_frag(void* vr, int32_t tag, int64_t xfer,
                          int64_t nchunks, int64_t chunk, uint8_t* base,
                          int64_t nbytes, int timeout_ms) {
  auto* r = static_cast<ShmRing*>(vr);
  RingHdr* h = hdr(r);
  Deadline dl(timeout_ms);
  StallTimer stall(&h->r_stalls, &h->r_stall_ns);
  uint64_t rd = h->ridx;  // we are the only reader
  for (;;) {
    if (load_acq(&h->widx) != rd) break;
    stall.arm();  // ring empty: this read is a stall until data lands
    if (pid_dead(h->producer_pid)) {
      stall.settle();
      return -3;
    }
    if (dl.expired()) {
      stall.settle();
      return -1;
    }
    ring_nap();
  }
  uint64_t waited = stall.settle();
  uint8_t rec[kRecHdr];
  ring_get(r, rd, rec, kRecHdr);
  uint32_t plen;
  int32_t rtag;
  std::memcpy(&plen, rec, 4);
  std::memcpy(&rtag, rec + 4, 4);
  if (rtag != tag) return -5;
  uint64_t next = rd + kRecHdr + plen;
  if (plen < kSgPrefix) {
    store_rel(&h->ridx, next);
    bump_rlx(&h->r_frames, 1);
    bump_rlx(&h->r_bytes, plen);
    return -4;
  }
  uint8_t pre[kSgPrefix];
  ring_get(r, rd + kRecHdr, pre, kSgPrefix);
  if (std::memcmp(pre, "SGC2", 4) != 0 ||
      be64(pre + 4) != static_cast<uint64_t>(xfer)) {
    store_rel(&h->ridx, next);
    bump_rlx(&h->r_frames, 1);
    bump_rlx(&h->r_bytes, plen);
    return -4;
  }
  int64_t idx = static_cast<int64_t>(be64(pre + 12));
  int64_t flen = static_cast<int64_t>(plen - kSgPrefix);
  if (idx < 0 || idx >= nchunks || idx * chunk + flen > nbytes) {
    store_rel(&h->ridx, next);
    bump_rlx(&h->r_frames, 1);
    bump_rlx(&h->r_bytes, plen);
    return -2;
  }
  if (flen)
    ring_get(r, rd + kRecHdr + kSgPrefix, base + idx * chunk,
             static_cast<size_t>(flen));
  store_rel(&h->ridx, next);
  bump_rlx(&h->r_frames, 1);
  bump_rlx(&h->r_bytes, plen);
  ompitpu::nativeev_emit(tag, static_cast<uint64_t>(xfer),
                         static_cast<uint32_t>(flen),
                         static_cast<uint32_t>(idx),
                         /*recv_side=*/true, waited);
  return idx;
}

// Consumer side, generic pop: copy the head record's payload into
// `out` and report its tag. Returns payload length, -1 timeout,
// -2 out buffer too small (record stays), -3 producer dead.
int64_t shmring_read_into(void* vr, int32_t* tag, uint8_t* out,
                          int64_t maxlen, int timeout_ms) {
  auto* r = static_cast<ShmRing*>(vr);
  RingHdr* h = hdr(r);
  Deadline dl(timeout_ms);
  StallTimer stall(&h->r_stalls, &h->r_stall_ns);
  uint64_t rd = h->ridx;
  for (;;) {
    if (load_acq(&h->widx) != rd) break;
    stall.arm();
    if (pid_dead(h->producer_pid)) {
      stall.settle();
      return -3;
    }
    if (dl.expired()) {
      stall.settle();
      return -1;
    }
    ring_nap();
  }
  stall.settle();
  uint8_t rec[kRecHdr];
  ring_get(r, rd, rec, kRecHdr);
  uint32_t plen;
  std::memcpy(&plen, rec, 4);
  std::memcpy(tag, rec + 4, 4);
  if (static_cast<int64_t>(plen) > maxlen) return -2;
  if (plen) ring_get(r, rd + kRecHdr, out, plen);
  store_rel(&h->ridx, rd + kRecHdr + plen);
  bump_rlx(&h->r_frames, 1);
  bump_rlx(&h->r_bytes, plen);
  return static_cast<int64_t>(plen);
}

}  // extern "C"
