// OOB/RML — tagged, tree-routable TCP messaging for the control plane.
//
// The reference's out-of-band stack: oob/tcp moves framed bytes over
// sockets with a connection state machine, rml adds tagged send/recv
// on top, routed supplies the overlay tree so daemons relay messages
// they are not the destination of (SURVEY §2.2 oob/rml/routed). This
// is that stack rebuilt small and native for the TPU framework's
// multi-host coordinator: every endpoint has a listener, frames carry
// (src, dst, tag), a routing table forwards frames not addressed to
// this node (tree routing), and received frames land in a
// condition-variable-guarded queue that Python drains.
//
// The Endpoint itself lives in oob_endpoint.h (shared with the
// nativewire datapath BTLs); this file is the extern "C" control
// surface ctypes binds to.
//
// C ABI for ctypes; threads: one acceptor + one reader per connection.

#include "oob_endpoint.h"

using ompitpu::Endpoint;
using ompitpu::Frame;
using ompitpu::Header;
using ompitpu::kMagic;
using ompitpu::kMaxTtl;
using ompitpu::kNonceLen;
using ompitpu::kTagAuth;
using ompitpu::kTagChallenge;
using ompitpu::read_full_timeout;
using ompitpu::siphash24;
using ompitpu::write_full;

extern "C" {

namespace {
void fold_secret(Endpoint* ep, const uint8_t* key, int32_t len) {
  std::memset(ep->secret, 0, sizeof ep->secret);
  for (int32_t i = 0; i < len; ++i)
    ep->secret[i % 16] ^= key[i];
  ep->has_secret = len > 0;
}
}  // namespace

// Create an endpoint listening on bind_addr:port (0 = ephemeral).
// bind_addr "0.0.0.0" listens on every interface — required for the
// multi-host PLM (plm_rsh analogue) where tree peers connect across
// machines; the default remains loopback for single-host jobs.
// The secret (optional; len 0 = auth disabled) is installed BEFORE
// the listener starts accepting: installing it afterwards would leave
// a window in which connections are accepted — and trusted forever —
// without a challenge.
void* oob_create_auth(int32_t id, int port, const char* bind_addr,
                      const uint8_t* key, int32_t keylen) {
  auto* ep = new Endpoint();
  ep->id = id;
  if (key != nullptr && keylen > 0) fold_secret(ep, key, keylen);
  ep->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(ep->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (bind_addr == nullptr || *bind_addr == '\0') {
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  } else if (inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1) {
    // an unparseable address must fail loudly, not silently bind
    // loopback and leave remote peers' connects refused far from
    // the cause
    ::close(ep->listen_fd);
    delete ep;
    return nullptr;
  }
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(ep->listen_fd, reinterpret_cast<sockaddr*>(&addr),
           sizeof addr) != 0 ||
      listen(ep->listen_fd, 64) != 0) {
    delete ep;
    return nullptr;
  }
  socklen_t alen = sizeof addr;
  getsockname(ep->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  ep->port = ntohs(addr.sin_port);
  ep->acceptor = std::thread([ep] { ep->accept_loop(); });
  return ep;
}

void* oob_create_bound(int32_t id, int port, const char* bind_addr) {
  return oob_create_auth(id, port, bind_addr, nullptr, 0);
}

// Back-compat loopback-only entry point.
void* oob_create(int32_t id, int port) {
  return oob_create_bound(id, port, "127.0.0.1");
}

int oob_port(void* h) { return static_cast<Endpoint*>(h)->port; }

// Inbound connections refused by the challenge (observability/tests).
int oob_auth_rejected(void* h) {
  return static_cast<Endpoint*>(h)->auth_rejected.load();
}

// Outbound connection to a peer's listener; answers the listener's
// auth challenge when a secret is installed, then announces our id.
int oob_connect(void* h, int32_t peer_id, const char* host, int port) {
  auto* ep = static_cast<Endpoint*>(h);
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  inet_pton(AF_INET, host, &addr.sin_addr);
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0) {
    ::close(fd);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
  if (ep->has_secret) {
    // the listener speaks first: challenge nonce, bounded wait (a
    // secretless listener never sends one — mismatched configs fail
    // here loudly instead of hanging)
    Header ch;
    if (!read_full_timeout(fd, &ch, sizeof ch, 10'000) ||
        ch.magic != kMagic || ch.tag != kTagChallenge ||
        ch.len != kNonceLen) {
      ::close(fd);
      return -1;
    }
    uint8_t nonce[kNonceLen];
    if (!read_full_timeout(fd, nonce, kNonceLen, 10'000)) {
      ::close(fd);
      return -1;
    }
    uint64_t mac = siphash24(ep->secret, nonce, kNonceLen);
    Header auth{kMagic, ep->id, peer_id, kTagAuth, kMaxTtl, 8};
    if (!write_full(fd, &auth, sizeof auth) ||
        !write_full(fd, &mac, 8)) {
      ::close(fd);
      return -1;
    }
  }
  Header hello{kMagic, ep->id, peer_id, -999, kMaxTtl, 0};
  if (!write_full(fd, &hello, sizeof hello)) {
    ::close(fd);
    return -1;
  }
  std::lock_guard<std::mutex> l(ep->mu);
  ep->peer_fd[peer_id] = fd;
  ep->open_fds.insert(fd);
  ep->threads.emplace_back([ep, fd] { ep->reader_loop(fd); });
  return 0;
}

// Static route: frames for dst leave via directly-connected peer `via`.
// dst == -1 installs the default route (toward the tree root).
void oob_add_route(void* h, int32_t dst, int32_t via) {
  auto* ep = static_cast<Endpoint*>(h);
  std::lock_guard<std::mutex> l(ep->mu);
  ep->route[dst] = via;
}

int oob_send(void* h, int32_t dst, int32_t tag, const uint8_t* data,
             int32_t len) {
  auto* ep = static_cast<Endpoint*>(h);
  Frame f;
  f.src = ep->id;
  f.dst = dst;
  f.tag = tag;
  f.payload.assign(data, data + len);
  if (dst == ep->id) {  // self-send: straight to the queue
    ep->deliver_or_forward(std::move(f));
    return 0;
  }
  return ep->send_frame(f) ? 0 : -1;
}

// Pop the next frame matching tag (-1 = any). Returns payload length,
// -1 on timeout, -2 if the output buffer is too small (frame stays).
int oob_recv(void* h, int32_t* src, int32_t* tag, uint8_t* out,
             int32_t maxlen, int timeout_ms) {
  auto* ep = static_cast<Endpoint*>(h);
  std::unique_lock<std::mutex> l(ep->mu);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  for (;;) {
    for (auto it = ep->queue.begin(); it != ep->queue.end(); ++it) {
      if (*tag == -1 || it->tag == *tag) {
        if (static_cast<int32_t>(it->payload.size()) > maxlen) return -2;
        *src = it->src;
        *tag = it->tag;
        int n = static_cast<int>(it->payload.size());
        if (n) std::memcpy(out, it->payload.data(), n);
        ep->queue.erase(it);
        return n;
      }
    }
    if (ep->stopping ||
        ep->cv.wait_until(l, deadline) == std::cv_status::timeout)
      return -1;
  }
}

int oob_pending(void* h) {
  auto* ep = static_cast<Endpoint*>(h);
  std::lock_guard<std::mutex> l(ep->mu);
  return static_cast<int>(ep->queue.size());
}

// Frames dropped by the ttl cycle guard (observability for tests).
int oob_ttl_dropped(void* h) {
  return static_cast<Endpoint*>(h)->ttl_dropped.load();
}

// Wait until a frame matching tag (-1 = any) is queued; return its
// payload length without consuming it (-1 on timeout). Lets callers
// size the recv buffer exactly instead of allocating a worst case.
int oob_next_len(void* h, int32_t tag, int timeout_ms) {
  auto* ep = static_cast<Endpoint*>(h);
  std::unique_lock<std::mutex> l(ep->mu);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  for (;;) {
    for (auto& f : ep->queue)
      if (tag == -1 || f.tag == tag)
        return static_cast<int>(f.payload.size());
    if (ep->stopping ||
        ep->cv.wait_until(l, deadline) == std::cv_status::timeout)
      return -1;
  }
}

void oob_destroy(void* h) { delete static_cast<Endpoint*>(h); }

}  // extern "C"
