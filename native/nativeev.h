// native event ring — optional fixed-record telemetry for the
// zero-copy datapath ("ompitpu-nativeev-v1").
//
// The PR 16 ledger kept Python-side tracing from de-optimizing the
// compiled hot path with fixed-size binary records expanded lazily;
// this is the same discipline one layer down. When a process installs
// an event ring (cvar-gated, off by default), the native transports
// (btl_shm.cc writev/read_frag, btl_tcp.cc sendv/recv_frag) append
// one 32-byte record per SGC2 fragment — timestamp, tag, transfer id,
// byte count, fragment index, direction, and how long the call waited
// — into a process-local mmap'd shm ring with drop-oldest wrap.
// Python never sees a per-fragment call; it decodes the ring at dump
// time (finalize / postmortem) and the doctor expands records into
// wire-layer spans whose flow ids re-derive from (tag, xfer, idx).
//
// Record layout (little-endian, 32 bytes):
//   u64 t_ns      CLOCK_REALTIME nanoseconds at emit
//   u64 xfer      transfer id from the SGC2 prefix
//   i32 tag       ring/frame tag
//   u32 bytes     fragment payload bytes (SGC2 prefix excluded)
//   u32 idx_dir   fragment index; bit 31 set = receive side
//   u32 wait_ns   time the emitting call spent blocked (saturating)

#ifndef OMPITPU_NATIVEEV_H_
#define OMPITPU_NATIVEEV_H_

#include <cstdint>

namespace ompitpu {

// Append one record to the process-installed event ring; no-op (a
// single relaxed pointer load) when no ring is installed. Thread-safe.
void nativeev_emit(int32_t tag, uint64_t xfer, uint32_t bytes,
                   uint32_t idx, bool recv_side, uint64_t wait_ns);

}  // namespace ompitpu

#endif  // OMPITPU_NATIVEEV_H_
