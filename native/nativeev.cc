// native event ring implementation — see nativeev.h for the contract.
//
// One ring PER PROCESS (the ledger discipline: each rank owns its own
// fixed-record store, merged offline), mmap'd over POSIX shm so the
// bytes survive the emitting process for postmortem attach and so
// live tools can read without stopping the writer.
//
// Ring layout (one shm object):
//   [64-byte header][nslots * 32-byte records]
//   header: u64 magic, u64 nslots, u64 widx (monotonic record count)
// widx only grows; slot = seq % nslots, so the ring drops oldest on
// wrap and `widx - min(widx, nslots)` is the first still-live seq.
// Appends from one process can race across threads (main thread plus
// oob reader threads), so the writer side takes a small mutex — this
// ring is opt-in diagnostics, not the always-on counter block, and
// the uncontended lock is noise next to the fragment copy it logs.
// Readers are lock-free: copy records, then re-check widx and drop
// anything the writer may have overwritten mid-copy (seqlock style).

#include "nativeev.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <cstring>
#include <mutex>

namespace {

constexpr uint64_t kEvMagic = 0x6f6d70746e657631ULL;  // "omptnev1"
constexpr size_t kEvHdrSize = 64;
constexpr size_t kEvRecSize = 32;

struct EvHdr {
  uint64_t magic;
  uint64_t nslots;
  uint64_t widx;
};
static_assert(sizeof(EvHdr) <= kEvHdrSize, "event header grew");

struct EvRec {
  uint64_t t_ns;
  uint64_t xfer;
  int32_t tag;
  uint32_t bytes;
  uint32_t idx_dir;
  uint32_t wait_ns;
};
static_assert(sizeof(EvRec) == kEvRecSize, "event record resized");

struct EvRing {
  uint8_t* map = nullptr;
  uint64_t nslots = 0;
  std::mutex wmu;  // writer side only; readers never take it
};

inline EvHdr* hdr(EvRing* r) { return reinterpret_cast<EvHdr*>(r->map); }
inline EvRec* slot(EvRing* r, uint64_t seq) {
  return reinterpret_cast<EvRec*>(r->map + kEvHdrSize +
                                  (seq % r->nslots) * kEvRecSize);
}

// process-global sink for nativeev_emit; relaxed is enough — install
// happens before traffic, and a stale NULL just skips one record
std::atomic<EvRing*> g_sink{nullptr};

inline uint64_t realtime_ns() {
  struct timespec ts{};
  ::clock_gettime(CLOCK_REALTIME, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000000ULL +
         static_cast<uint64_t>(ts.tv_nsec);
}

EvRing* map_ev(int fd, uint64_t total) {
  void* m = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  ::close(fd);
  if (m == MAP_FAILED) return nullptr;
  auto* r = new EvRing();
  r->map = static_cast<uint8_t*>(m);
  r->nslots = (total - kEvHdrSize) / kEvRecSize;
  return r;
}

}  // namespace

namespace ompitpu {

void nativeev_emit(int32_t tag, uint64_t xfer, uint32_t bytes,
                   uint32_t idx, bool recv_side, uint64_t wait_ns) {
  EvRing* r = g_sink.load(std::memory_order_relaxed);
  if (!r) return;
  uint32_t w32 = wait_ns > 0xffffffffULL
                     ? 0xffffffffU
                     : static_cast<uint32_t>(wait_ns);
  std::lock_guard<std::mutex> l(r->wmu);
  EvHdr* h = hdr(r);
  uint64_t seq = __atomic_load_n(&h->widx, __ATOMIC_RELAXED);
  EvRec* rec = slot(r, seq);
  rec->t_ns = realtime_ns();
  rec->xfer = xfer;
  rec->tag = tag;
  rec->bytes = bytes;
  rec->idx_dir = (idx & 0x7fffffffU) | (recv_side ? 0x80000000U : 0);
  rec->wait_ns = w32;
  // publish AFTER the record body (release): a reader seeing seq+1
  // sees a complete record in that slot
  __atomic_store_n(&h->widx, seq + 1, __ATOMIC_RELEASE);
}

}  // namespace ompitpu

extern "C" {

// Create (O_CREAT|O_EXCL) an event ring named `name` with `nslots`
// 32-byte record slots. NULL when the name exists or mapping failed.
void* nativeev_create(const char* name, int64_t nslots) {
  if (nslots < 2) return nullptr;
  int fd = ::shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t total =
      kEvHdrSize + static_cast<uint64_t>(nslots) * kEvRecSize;
  if (::ftruncate(fd, static_cast<off_t>(total)) != 0) {
    ::close(fd);
    ::shm_unlink(name);
    return nullptr;
  }
  EvRing* r = map_ev(fd, total);
  if (!r) {
    ::shm_unlink(name);
    return nullptr;
  }
  EvHdr* h = hdr(r);
  h->nslots = static_cast<uint64_t>(nslots);
  h->widx = 0;
  __atomic_store_n(&h->magic, kEvMagic, __ATOMIC_RELEASE);
  return r;
}

// Attach an existing event ring read-only-in-spirit (the mapping is
// RW but attachers never write). NULL when absent / uninitialized.
void* nativeev_attach(const char* name) {
  int fd = ::shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st{};
  if (::fstat(fd, &st) != 0 ||
      static_cast<size_t>(st.st_size) <= kEvHdrSize) {
    ::close(fd);
    return nullptr;
  }
  EvRing* r = map_ev(fd, static_cast<uint64_t>(st.st_size));
  if (!r) return nullptr;
  EvHdr* h = hdr(r);
  if (__atomic_load_n(&h->magic, __ATOMIC_ACQUIRE) != kEvMagic ||
      h->nslots != r->nslots) {
    ::munmap(r->map, kEvHdrSize + r->nslots * kEvRecSize);
    delete r;
    return nullptr;
  }
  return r;
}

int nativeev_unlink(const char* name) { return ::shm_unlink(name); }

void nativeev_close(void* vr) {
  auto* r = static_cast<EvRing*>(vr);
  if (g_sink.load(std::memory_order_relaxed) == r)
    g_sink.store(nullptr, std::memory_order_relaxed);
  ::munmap(r->map, kEvHdrSize + r->nslots * kEvRecSize);
  delete r;
}

// Install `vr` as the process-global emit sink (NULL uninstalls).
void nativeev_install(void* vr) {
  g_sink.store(static_cast<EvRing*>(vr), std::memory_order_release);
}

int64_t nativeev_nslots(void* vr) {
  return static_cast<int64_t>(static_cast<EvRing*>(vr)->nslots);
}

// Records ever appended (monotonic; wraps drop oldest, not this).
int64_t nativeev_count(void* vr) {
  auto* r = static_cast<EvRing*>(vr);
  return static_cast<int64_t>(
      __atomic_load_n(&hdr(r)->widx, __ATOMIC_ACQUIRE));
}

// Copy up to `max` records starting at sequence `start` into `out`
// (max * 32 bytes). Clamps `start` up to the oldest still-live seq;
// writes the first copied seq to *first_seq. Returns records copied.
// Seqlock discipline: records overwritten during the copy are cut off
// by re-reading widx afterwards.
int64_t nativeev_read(void* vr, int64_t start, uint8_t* out,
                      int64_t max, int64_t* first_seq) {
  auto* r = static_cast<EvRing*>(vr);
  EvHdr* h = hdr(r);
  uint64_t w = __atomic_load_n(&h->widx, __ATOMIC_ACQUIRE);
  uint64_t lo = w > r->nslots ? w - r->nslots : 0;
  uint64_t s = static_cast<uint64_t>(start < 0 ? 0 : start);
  if (s < lo) s = lo;
  uint64_t n = w - s;
  if (n > static_cast<uint64_t>(max)) n = static_cast<uint64_t>(max);
  for (uint64_t i = 0; i < n; ++i)
    std::memcpy(out + i * kEvRecSize, slot(r, s + i), kEvRecSize);
  // anything the writer lapped while we copied is torn: drop it
  uint64_t w2 = __atomic_load_n(&h->widx, __ATOMIC_ACQUIRE);
  uint64_t lo2 = w2 > r->nslots ? w2 - r->nslots : 0;
  if (s < lo2) {
    uint64_t skip = lo2 - s;
    if (skip >= n) {
      n = 0;
      s = lo2;
    } else {
      std::memmove(out, out + skip * kEvRecSize,
                   (n - skip) * kEvRecSize);
      n -= skip;
      s = lo2;
    }
  }
  if (first_seq) *first_seq = static_cast<int64_t>(s);
  return static_cast<int64_t>(n);
}

}  // extern "C"
