// planexec.cc — native executor for frozen wire plans.
//
// coll/plan.py freezes a spanning collective's wire schedule into a
// WirePlan (per-round peer lists, FrameTemplates, expected recvs).
// Until now every compiled fire still re-entered Python once per
// round: generator next() per fragment in WireRouter._stripe, a reap
// callback per arrival, a fresh dict of reassembly buffers per round.
// This file lowers the WHOLE plan below the interpreter: Python
// compiles the plan once into a flat descriptor blob (rounds, peers,
// precomposed SGH2 header bytes, scatter-gather payload maps,
// expected-recv headers and pool placements), binds the live
// endpoint/ring handles, and then a steady-state fire is one
// fire_begin + a fire_step loop that walks every round C-side.
//
// Wire parity is structural, not aspirational: headers are composed
// from the SAME precomposed pre/mid byte strings FrameTemplate uses
// (pre + int64rec(xfer) + mid + int64rec(crc)), fragments carry the
// same "SGC2"+xfer+idx prefix, and they travel through the SAME
// shmring_writev / wire_sendv legs as the interpreted path — a
// receiver cannot tell which executor sent a frame.
//
// Receives land in a per-plan reassembly pool: one slab sized at
// compile time from the frozen recv metadata, each (round, src, msg)
// assigned a fixed offset, reused across fires (the mpool/rcache
// analogue — zero steady-state allocation).
//
// Blocking discipline: fire_step(slice_ms) returns RC_AGAIN at safe
// points when the slice expires so Python can run the ULFM failure
// detector between slices (the same ~100 ms cadence as the
// interpreted _sliced_recv); a per-comm fault word (set by Python
// from FtState) is polled inside the wait loops so death/revoke
// aborts the fire within the detection interval even mid-slice.
// Foreign frames met on the coll channel (stale fragments are
// dropped exactly like the portable resync; anything else) are
// stashed verbatim for Python to re-inject into the btl stashes
// after the run — the executor never eats another channel's bytes.

#include <cstdint>
#include <cstring>
#include <ctime>
#include <deque>
#include <mutex>
#include <vector>

#include "oob_endpoint.h"

using ompitpu::Endpoint;
using ompitpu::Frame;

// Datapath legs from btl_shm.cc / btl_tcp.cc / oob.cc — same .so,
// linked together; declared here instead of a shared header because
// the extern "C" ABI *is* the contract (ctypes loads these too).
extern "C" {
int oob_send(void* h, int32_t dst, int32_t tag, const uint8_t* data,
             int32_t len);
int wire_sendv(void* h, int32_t dst, int32_t tag, const uint8_t** parts,
               const int64_t* lens, int32_t nparts);
int64_t wire_recv_frag(void* h, int32_t src, int32_t tag, int64_t xfer,
                       int64_t nchunks, int64_t chunk, uint8_t* base,
                       int64_t nbytes, int timeout_ms);
int shmring_writev(void* vr, int32_t tag, const uint8_t** parts,
                   const int64_t* lens, int32_t nparts, int timeout_ms);
int64_t shmring_read_frag(void* vr, int32_t tag, int64_t xfer,
                          int64_t nchunks, int64_t chunk, uint8_t* base,
                          int64_t nbytes, int timeout_ms);
int64_t shmring_read_into(void* vr, int32_t* tag, uint8_t* out,
                          int64_t maxlen, int timeout_ms);
}

namespace {

// ---- return codes (mirrored in native/bindings.py PlanExec) ----
constexpr int RC_DONE = 0;
constexpr int RC_AGAIN = 1;        // slice expired; call fire_step again
constexpr int RC_FTSTOP = 2;       // fault word set; Python runs check_wait
constexpr int RC_BADARG = -1;
constexpr int RC_PEERDEAD = -2;    // err_peer() names the pidx
constexpr int RC_TIMEOUT = -3;     // plan timeout exhausted
constexpr int RC_DIVERGED = -4;    // inbound header != frozen expectation
constexpr int RC_TRUNCATED = -5;   // reassembled payload failed CRC
constexpr int RC_WOULDBLOCK = -100;  // internal: ring full, try later

constexpr uint64_t kBlobMagic = 0x314345584C504FULL;  // "OPLXEC1"
constexpr int64_t kBlobVersion = 1;

// DSS int64 single-value record marker: type tag DSS_INT64 (1) +
// u32 LE count 1 — the 5 bytes btl/components._int64_rec prepends.
constexpr uint8_t kI64Marker[5] = {0x01, 0x01, 0x00, 0x00, 0x00};
constexpr int64_t kI64Rec = 13;    // marker + 8-byte LE value

// zlib-compatible IEEE CRC-32 (polynomial 0xEDB88320), chained like
// zlib.crc32(data, prior) so scatter-gather payloads CRC segment by
// segment without a join.
uint32_t crc_table[256];
std::once_flag crc_once;

void crc_init() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
}

uint32_t crc32_update(uint32_t crc, const uint8_t* p, size_t n) {
  std::call_once(crc_once, crc_init);
  crc = ~crc;
  while (n--) crc = (crc >> 8) ^ crc_table[(crc ^ *p++) & 0xFF];
  return ~crc;
}

double mono_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<double>(ts.tv_sec) + 1e-9 * ts.tv_nsec;
}

void nap_us(long us) {
  timespec ts{0, us * 1000L};
  nanosleep(&ts, nullptr);
}

// ---- frozen descriptor (parsed once from the Python-built blob) ----

struct Seg {          // one scatter-gather span of a composed payload
  int64_t kind;       // 0 = input region (live pointer), 1 = pool
  int64_t idx;        // region index within its kind
  int64_t off;
  int64_t len;
};

struct SendMsg {
  std::vector<uint8_t> pre, mid;   // FrameTemplate header constants
  int64_t nbytes, nchunks, chunk;
  std::vector<Seg> segs;
};

struct Stream {       // one peer's message sequence within a round
  int64_t peer;       // index into PlanExec::peers
  std::vector<SendMsg> msgs;
};

struct RecvMsg {
  int64_t pool_idx;
  int64_t nbytes, nchunks, chunk;
  std::vector<uint8_t> pre, mid;   // expected header constants
};

struct RecvSrc {
  int64_t peer;
  std::vector<RecvMsg> msgs;
};

struct Round {
  int64_t depth;
  std::vector<Stream> streams;
  std::vector<RecvSrc> rsrcs;
};

struct PoolBuf {
  int64_t off, nbytes;
};

struct PeerBind {
  int64_t pidx;
  int32_t nid = -1;
  void* tx_ring = nullptr;   // null → vectored-socket leg
  void* rx_ring = nullptr;   // null → endpoint-queue leg
};

struct StashFrame {   // foreign bytes met on the coll channel
  int64_t kind;       // 0 = endpoint queue frame, 1 = ring record
  int64_t peer;       // pidx it arrived from
  int64_t tag;
  std::vector<uint8_t> bytes;
};

// ---- per-fire resumable state ----

struct StreamState {
  size_t msg = 0;
  int64_t frame = 0;   // 0 = header, 1..nchunks = fragments
  int64_t xfer = 0;
  uint32_t crc = 0;
  bool done = false;
};

struct SrcState {
  size_t msg = 0;
  int mode = 0;        // 0 = want header, 1 = want fragments
  int64_t xfer = 0;
  uint32_t crc_exp = 0;
  int64_t got = 0;
  bool done = false;
};

struct PlanExec {
  // frozen
  int32_t tag = 0;
  std::vector<int64_t> input_lens;
  std::vector<PoolBuf> pool;
  int64_t pool_total = 0;
  std::vector<PeerBind> peers;
  std::vector<Round> rounds;
  std::vector<uint8_t> slab;

  // bound
  Endpoint* ep = nullptr;
  int32_t my_nid = -1;
  const volatile int64_t* ftword = nullptr;

  // fire state
  bool firing = false;
  std::vector<const uint8_t*> inputs;
  int64_t xfer_next = 0;
  double deadline_total = 0.0;
  size_t cur_round = 0;
  std::vector<StreamState> sst;
  std::vector<SrcState> rst;
  std::vector<double> ts;          // per-round end stamps
  std::vector<StashFrame> stash;
  int64_t err_peer = -1;
  int64_t err_round = -1;
  double slice_deadline = 0.0;
};

// ---- blob parsing ----

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  int64_t i64() {
    if (!ok || end - p < 8) { ok = false; return 0; }
    int64_t v;
    std::memcpy(&v, p, 8);
    p += 8;
    return v;
  }
  bool bytes(std::vector<uint8_t>* out) {
    int64_t n = i64();
    if (!ok || n < 0 || end - p < n) { ok = false; return false; }
    out->assign(p, p + n);
    p += n;
    return true;
  }
};

PlanExec* parse_blob(const uint8_t* blob, int64_t len) {
  Cursor c{blob, blob + len};
  if (static_cast<uint64_t>(c.i64()) != kBlobMagic) return nullptr;
  if (c.i64() != kBlobVersion) return nullptr;
  auto x = new PlanExec();
  x->tag = static_cast<int32_t>(c.i64());
  int64_t n_inputs = c.i64();
  for (int64_t i = 0; c.ok && i < n_inputs; ++i)
    x->input_lens.push_back(c.i64());
  int64_t n_pool = c.i64();
  for (int64_t i = 0; c.ok && i < n_pool; ++i) {
    PoolBuf b;
    b.off = c.i64();
    b.nbytes = c.i64();
    x->pool.push_back(b);
  }
  x->pool_total = c.i64();
  int64_t n_peers = c.i64();
  for (int64_t i = 0; c.ok && i < n_peers; ++i) {
    PeerBind pb;
    pb.pidx = c.i64();
    x->peers.push_back(pb);
  }
  int64_t n_rounds = c.i64();
  for (int64_t r = 0; c.ok && r < n_rounds; ++r) {
    Round rd;
    rd.depth = c.i64();
    int64_t n_streams = c.i64();
    for (int64_t s = 0; c.ok && s < n_streams; ++s) {
      Stream st;
      st.peer = c.i64();
      int64_t n_msgs = c.i64();
      for (int64_t m = 0; c.ok && m < n_msgs; ++m) {
        SendMsg sm;
        c.bytes(&sm.pre);
        c.bytes(&sm.mid);
        sm.nbytes = c.i64();
        sm.nchunks = c.i64();
        sm.chunk = c.i64();
        int64_t n_segs = c.i64();
        for (int64_t g = 0; c.ok && g < n_segs; ++g) {
          Seg sg;
          sg.kind = c.i64();
          sg.idx = c.i64();
          sg.off = c.i64();
          sg.len = c.i64();
          sm.segs.push_back(sg);
        }
        st.msgs.push_back(std::move(sm));
      }
      rd.streams.push_back(std::move(st));
    }
    int64_t n_rsrcs = c.i64();
    for (int64_t s = 0; c.ok && s < n_rsrcs; ++s) {
      RecvSrc rs;
      rs.peer = c.i64();
      int64_t n_msgs = c.i64();
      for (int64_t m = 0; c.ok && m < n_msgs; ++m) {
        RecvMsg rm;
        rm.pool_idx = c.i64();
        rm.nbytes = c.i64();
        rm.nchunks = c.i64();
        rm.chunk = c.i64();
        c.bytes(&rm.pre);
        c.bytes(&rm.mid);
        rs.msgs.push_back(std::move(rm));
      }
      rd.rsrcs.push_back(std::move(rs));
    }
    x->rounds.push_back(std::move(rd));
  }
  // structural sanity: every index in range, sizes consistent
  if (c.ok) {
    for (auto& rd : x->rounds) {
      for (auto& st : rd.streams) {
        if (st.peer < 0 ||
            st.peer >= static_cast<int64_t>(x->peers.size()))
          c.ok = false;
        for (auto& sm : st.msgs) {
          int64_t tot = 0;
          for (auto& sg : sm.segs) {
            tot += sg.len;
            if (sg.kind == 0) {
              if (sg.idx < 0 ||
                  sg.idx >= static_cast<int64_t>(x->input_lens.size()) ||
                  sg.off < 0 || sg.off + sg.len > x->input_lens[sg.idx])
                c.ok = false;
            } else if (sg.kind == 1) {
              if (sg.idx < 0 ||
                  sg.idx >= static_cast<int64_t>(x->pool.size()) ||
                  sg.off < 0 ||
                  sg.off + sg.len > x->pool[sg.idx].nbytes)
                c.ok = false;
            } else {
              c.ok = false;
            }
          }
          if (tot != sm.nbytes) c.ok = false;
        }
      }
      for (auto& rs : rd.rsrcs) {
        if (rs.peer < 0 ||
            rs.peer >= static_cast<int64_t>(x->peers.size()))
          c.ok = false;
        for (auto& rm : rs.msgs) {
          if (rm.pool_idx < 0 ||
              rm.pool_idx >= static_cast<int64_t>(x->pool.size()) ||
              x->pool[rm.pool_idx].nbytes != rm.nbytes)
            c.ok = false;
        }
      }
    }
    for (auto& b : x->pool)
      if (b.off < 0 || b.nbytes < 0 || b.off + b.nbytes > x->pool_total)
        c.ok = false;
  }
  if (!c.ok) {
    delete x;
    return nullptr;
  }
  x->slab.resize(static_cast<size_t>(x->pool_total));
  x->ts.assign(x->rounds.size(), 0.0);
  return x;
}

// ---- send side ----

// Compose and send one message header: pre + int64rec(xfer) + mid +
// int64rec(crc) — byte-identical to FrameTemplate.header().
int send_header(PlanExec* x, const PeerBind& pb, const SendMsg& m,
                int64_t xfer, uint32_t crc) {
  std::vector<uint8_t> h;
  h.reserve(m.pre.size() + m.mid.size() + 2 * kI64Rec);
  h.insert(h.end(), m.pre.begin(), m.pre.end());
  h.insert(h.end(), kI64Marker, kI64Marker + 5);
  int64_t xv = xfer;
  uint8_t tmp[8];
  std::memcpy(tmp, &xv, 8);
  h.insert(h.end(), tmp, tmp + 8);
  h.insert(h.end(), m.mid.begin(), m.mid.end());
  h.insert(h.end(), kI64Marker, kI64Marker + 5);
  int64_t cv = static_cast<int64_t>(crc);
  std::memcpy(tmp, &cv, 8);
  h.insert(h.end(), tmp, tmp + 8);
  return oob_send(x->ep, pb.nid, x->tag,
                  h.data(), static_cast<int32_t>(h.size()));
}

uint32_t crc_of_msg(PlanExec* x, const SendMsg& m) {
  uint32_t crc = 0;
  for (auto& sg : m.segs) {
    const uint8_t* base = sg.kind == 0
        ? x->inputs[static_cast<size_t>(sg.idx)]
        : x->slab.data() + x->pool[static_cast<size_t>(sg.idx)].off;
    crc = crc32_update(crc, base + sg.off, static_cast<size_t>(sg.len));
  }
  return crc;
}

// Build the scatter-gather part list for fragment `ci` of msg `m`:
// ["SGC2"+xfer(8B BE), idx(8B BE), payload sub-spans...] — the same
// frame FrameTemplate.sg_lists yields, except composed payloads go
// to the wire straight from their source regions (the interpreted
// path joins them into a staging array first).
int send_frag(PlanExec* x, const PeerBind& pb, const SendMsg& m,
              int64_t xfer, int64_t ci, int* rc_out) {
  uint8_t pre12[12];
  std::memcpy(pre12, "SGC2", 4);
  for (int i = 0; i < 8; ++i)
    pre12[4 + i] = static_cast<uint8_t>((xfer >> (8 * (7 - i))) & 0xFF);
  uint8_t idx8[8];
  for (int i = 0; i < 8; ++i)
    idx8[i] = static_cast<uint8_t>((ci >> (8 * (7 - i))) & 0xFF);

  int64_t lo = ci * m.chunk;
  int64_t hi = lo + m.chunk;
  if (hi > m.nbytes) hi = m.nbytes;

  const uint8_t* parts[2 + 64];
  int64_t lens[2 + 64];
  std::vector<const uint8_t*> pvec;
  std::vector<int64_t> lvec;
  const uint8_t** pp = parts;
  int64_t* pl = lens;
  int32_t np = 0;
  auto push = [&](const uint8_t* ptr, int64_t n) {
    if (np >= 2 + 64 && pvec.empty()) {   // spill: rare, deep SG maps
      pvec.assign(parts, parts + np);
      lvec.assign(lens, lens + np);
    }
    if (!pvec.empty()) {
      pvec.push_back(ptr);
      lvec.push_back(n);
    } else {
      pp[np] = ptr;
      pl[np] = n;
    }
    ++np;
  };
  push(pre12, 12);
  push(idx8, 8);
  int64_t pos = 0;
  for (auto& sg : m.segs) {
    int64_t s0 = pos, s1 = pos + sg.len;
    pos = s1;
    if (s1 <= lo || s0 >= hi) continue;
    int64_t a = lo > s0 ? lo : s0;
    int64_t b = hi < s1 ? hi : s1;
    const uint8_t* base = sg.kind == 0
        ? x->inputs[static_cast<size_t>(sg.idx)]
        : x->slab.data() + x->pool[static_cast<size_t>(sg.idx)].off;
    push(base + sg.off + (a - s0), b - a);
  }
  const uint8_t** P = pvec.empty() ? parts : pvec.data();
  int64_t* L = lvec.empty() ? lens : lvec.data();

  if (pb.tx_ring != nullptr) {
    // same discipline as NativeWireBtl._ring_put: never-fits falls
    // back to the vectored socket, dead consumer is a typed error, a
    // full ring yields to the caller (which reaps our own arrivals
    // so opposing full-ring senders cannot deadlock, then retries)
    int rc = shmring_writev(pb.tx_ring, x->tag, P, L, np, 5);
    if (rc == 0) return 0;
    if (rc == -3) { *rc_out = RC_PEERDEAD; return -1; }
    if (rc == -1) { *rc_out = RC_WOULDBLOCK; return -1; }
    // rc == -2: frame can never fit → socket leg below
  }
  if (wire_sendv(x->ep, pb.nid, x->tag, P, L, np) != 0) {
    *rc_out = RC_PEERDEAD;
    return -1;
  }
  return 0;
}

// ---- receive side ----

bool header_matches(const RecvMsg& rm, const std::vector<uint8_t>& pay,
                    int64_t* xfer, uint32_t* crc) {
  size_t want = rm.pre.size() + rm.mid.size() + 2 * kI64Rec;
  if (pay.size() != want) return false;
  const uint8_t* p = pay.data();
  if (std::memcmp(p, rm.pre.data(), rm.pre.size()) != 0) return false;
  p += rm.pre.size();
  if (std::memcmp(p, kI64Marker, 5) != 0) return false;
  int64_t xv;
  std::memcpy(&xv, p + 5, 8);
  p += kI64Rec;
  if (std::memcmp(p, rm.mid.data(), rm.mid.size()) != 0) return false;
  p += rm.mid.size();
  if (std::memcmp(p, kI64Marker, 5) != 0) return false;
  int64_t cv;
  std::memcpy(&cv, p + 5, 8);
  *xfer = xv;
  *crc = static_cast<uint32_t>(cv);
  return true;
}

bool is_sgh2_pre(const RecvMsg& rm, const std::vector<uint8_t>& pay) {
  return pay.size() >= rm.pre.size() &&
         std::memcmp(pay.data(), rm.pre.data(), rm.pre.size()) == 0;
}

// Pop the first queued frame from (nid, tag) off the endpoint.
// Returns false when none is queued. No waiting — the reap sweep is
// a poll; blocking happens via the sweep's nap.
bool pop_queue_frame(PlanExec* x, int32_t nid,
                     std::vector<uint8_t>* out) {
  std::lock_guard<std::mutex> l(x->ep->mu);
  for (auto it = x->ep->queue.begin(); it != x->ep->queue.end(); ++it) {
    if (it->src == nid && it->tag == x->tag) {
      *out = std::move(it->payload);
      x->ep->queue.erase(it);
      return true;
    }
  }
  return false;
}

// Drain one foreign record off an rx ring into the stash (ring head
// is blocked on a record for another channel — a cross-tag p2p
// transfer sharing this slot). Python re-injects it post-run.
bool stash_ring_head(PlanExec* x, const PeerBind& pb) {
  std::vector<uint8_t> buf(4096);
  int32_t tag = 0;
  for (;;) {
    int64_t rc = shmring_read_into(pb.rx_ring, &tag, buf.data(),
                                   static_cast<int64_t>(buf.size()), 0);
    if (rc >= 0) {
      buf.resize(static_cast<size_t>(rc));
      x->stash.push_back({1, pb.pidx, tag, std::move(buf)});
      return true;
    }
    if (rc == -2) {                  // record larger than buf: grow
      buf.resize(buf.size() * 2);
      continue;
    }
    return false;                    // empty or producer dead: no-op
  }
}

// One reap sweep over the current round's pending sources. Returns
// >0 on progress, 0 on none, <0 (via rc_out) on typed error.
int reap_sweep(PlanExec* x, int* rc_out) {
  Round& rd = x->rounds[x->cur_round];
  int progress = 0;
  for (size_t si = 0; si < rd.rsrcs.size(); ++si) {
    RecvSrc& rs = rd.rsrcs[si];
    SrcState& st = x->rst[si];
    if (st.done) continue;
    PeerBind& pb = x->peers[static_cast<size_t>(rs.peer)];
    RecvMsg& rm = rs.msgs[st.msg];
    uint8_t* dst = x->slab.data() +
                   x->pool[static_cast<size_t>(rm.pool_idx)].off;

    if (st.mode == 0) {
      // headers always ride the endpoint queue
      std::vector<uint8_t> pay;
      if (!pop_queue_frame(x, pb.nid, &pay)) continue;
      progress = 1;
      int64_t xfer;
      uint32_t crc;
      if (header_matches(rm, pay, &xfer, &crc)) {
        st.mode = 1;
        st.xfer = xfer;
        st.crc_exp = crc;
        st.got = 0;
      } else if (pay.size() >= 4 &&
                 std::memcmp(pay.data(), "SGC2", 4) == 0) {
        // stale fragment from an abandoned transfer: drop, exactly
        // like the portable receiver's resync-to-next-header
        continue;
      } else if (is_sgh2_pre(rm, pay)) {
        // a real header whose dtype/shape/chunking differs from the
        // frozen expectation: the schedule diverged
        x->err_peer = pb.pidx;
        x->err_round = static_cast<int64_t>(x->cur_round);
        *rc_out = RC_DIVERGED;
        return -1;
      } else {
        // not ours — preserve for Python's stash re-injection
        x->stash.push_back({0, pb.pidx, x->tag, std::move(pay)});
      }
      continue;
    }

    // fragment mode
    int64_t rc;
    if (pb.rx_ring != nullptr) {
      rc = shmring_read_frag(pb.rx_ring, x->tag, st.xfer, rm.nchunks,
                             rm.chunk, dst, rm.nbytes, 0);
      if (rc == -5) {                // foreign tag parked at ring head
        if (stash_ring_head(x, pb)) progress = 1;
        continue;
      }
      if (rc == -3) {
        x->err_peer = pb.pidx;
        x->err_round = static_cast<int64_t>(x->cur_round);
        *rc_out = RC_PEERDEAD;
        return -1;
      }
      if (rc == -4) { progress = 1; continue; }  // stale, consumed
      if (rc == -2) { progress = 1; continue; }  // malformed, consumed
    } else {
      rc = wire_recv_frag(x->ep, pb.nid, x->tag, st.xfer, rm.nchunks,
                          rm.chunk, dst, rm.nbytes, 0);
      if (rc == -4) {
        // head frame for (src, tag) is not our fragment: either a
        // stale fragment (drop) or something foreign (stash)
        std::vector<uint8_t> pay;
        if (pop_queue_frame(x, pb.nid, &pay)) {
          progress = 1;
          if (!(pay.size() >= 4 &&
                std::memcmp(pay.data(), "SGC2", 4) == 0))
            x->stash.push_back({0, pb.pidx, x->tag, std::move(pay)});
        }
        continue;
      }
      if (rc == -2) { progress = 1; continue; }
    }
    if (rc < 0) continue;            // timeout: no fragment queued

    progress = 1;
    if (++st.got < rm.nchunks) continue;

    // message complete: end-to-end integrity before it becomes a
    // source region for later rounds
    uint32_t crc = crc32_update(0, dst, static_cast<size_t>(rm.nbytes));
    if (crc != st.crc_exp) {
      x->err_peer = pb.pidx;
      x->err_round = static_cast<int64_t>(x->cur_round);
      *rc_out = RC_TRUNCATED;
      return -1;
    }
    st.mode = 0;
    if (++st.msg >= rs.msgs.size()) st.done = true;
  }
  return progress;
}

void enter_round(PlanExec* x) {
  Round& rd = x->rounds[x->cur_round];
  x->sst.assign(rd.streams.size(), StreamState());
  for (size_t i = 0; i < rd.streams.size(); ++i)
    if (rd.streams[i].msgs.empty()) x->sst[i].done = true;
  x->rst.assign(rd.rsrcs.size(), SrcState());
  for (size_t i = 0; i < rd.rsrcs.size(); ++i)
    if (rd.rsrcs[i].msgs.empty()) x->rst[i].done = true;
}

}  // namespace

extern "C" {

void* planexec_create(const uint8_t* blob, int64_t len) {
  if (blob == nullptr || len < 16) return nullptr;
  return parse_blob(blob, len);
}

void planexec_destroy(void* h) { delete static_cast<PlanExec*>(h); }

int planexec_bind(void* h, void* ep, int64_t my_nid,
                  const int64_t* peer_nids, void** tx_rings,
                  void** rx_rings, int64_t n_peers) {
  auto* x = static_cast<PlanExec*>(h);
  if (ep == nullptr ||
      n_peers != static_cast<int64_t>(x->peers.size()))
    return RC_BADARG;
  x->ep = static_cast<Endpoint*>(ep);
  x->my_nid = static_cast<int32_t>(my_nid);
  for (int64_t i = 0; i < n_peers; ++i) {
    x->peers[static_cast<size_t>(i)].nid =
        static_cast<int32_t>(peer_nids[i]);
    x->peers[static_cast<size_t>(i)].tx_ring = tx_rings[i];
    x->peers[static_cast<size_t>(i)].rx_ring = rx_rings[i];
  }
  return 0;
}

void planexec_set_ftword(void* h, const int64_t* word) {
  static_cast<PlanExec*>(h)->ftword =
      static_cast<const volatile int64_t*>(word);
}

int planexec_fire_begin(void* h, const uint8_t** inputs,
                        const int64_t* lens, int64_t n,
                        int64_t xfer_base, int64_t timeout_ms) {
  auto* x = static_cast<PlanExec*>(h);
  if (x->ep == nullptr ||
      n != static_cast<int64_t>(x->input_lens.size()))
    return RC_BADARG;
  for (int64_t i = 0; i < n; ++i)
    if (lens[i] != x->input_lens[static_cast<size_t>(i)])
      return RC_BADARG;
  x->inputs.assign(inputs, inputs + n);
  x->xfer_next = xfer_base;
  x->deadline_total = mono_s() + 1e-3 * static_cast<double>(timeout_ms);
  x->cur_round = 0;
  x->ts.assign(x->rounds.size(), 0.0);
  x->err_peer = -1;
  x->err_round = -1;
  x->firing = true;
  if (!x->rounds.empty()) enter_round(x);
  return 0;
}

// Walk rounds until done, error, fault-word stop, or slice expiry.
// Send legs stripe round-robin across peer streams in depth-sized
// bursts (the _stripe discipline); a blocked ring write yields to a
// reap sweep so opposing full-ring senders cannot deadlock.
int planexec_fire_step(void* h, int64_t slice_ms) {
  auto* x = static_cast<PlanExec*>(h);
  if (!x->firing) return RC_BADARG;
  x->slice_deadline = mono_s() + 1e-3 * static_cast<double>(slice_ms);

  while (x->cur_round < x->rounds.size()) {
    Round& rd = x->rounds[x->cur_round];

    // ---- send phase: striped depth bursts over live streams ----
    bool sends_left = false;
    for (auto& ss : x->sst) sends_left |= !ss.done;
    while (sends_left) {
      sends_left = false;
      for (size_t si = 0; si < rd.streams.size(); ++si) {
        StreamState& ss = x->sst[si];
        if (ss.done) continue;
        Stream& stm = rd.streams[si];
        PeerBind& pb = x->peers[static_cast<size_t>(stm.peer)];
        int64_t b = 0;
        while (b < rd.depth && !ss.done) {
          SendMsg& m = stm.msgs[ss.msg];
          int rc = 0;
          if (ss.frame == 0) {
            ss.xfer = x->xfer_next++;
            ss.crc = crc_of_msg(x, m);
            if (send_header(x, pb, m, ss.xfer, ss.crc) != 0) {
              x->err_peer = pb.pidx;
              x->err_round = static_cast<int64_t>(x->cur_round);
              x->firing = false;
              return RC_PEERDEAD;
            }
            ss.frame = 1;
            ++b;
            continue;
          }
          if (send_frag(x, pb, m, ss.xfer, ss.frame - 1, &rc) != 0) {
            if (rc == RC_WOULDBLOCK) {
              // peer's ring is full: drain our own arrivals (the
              // peer may be wedged on OUR full ring), check fault /
              // deadlines, then retry this same fragment
              int rc2 = 0;
              if (reap_sweep(x, &rc2) < 0) {
                x->firing = false;
                return rc2;
              }
              if (x->ftword != nullptr && *x->ftword != 0)
                return RC_FTSTOP;
              double now = mono_s();
              if (now >= x->deadline_total) {
                x->err_peer = pb.pidx;
                x->err_round = static_cast<int64_t>(x->cur_round);
                x->firing = false;
                return RC_TIMEOUT;
              }
              if (now >= x->slice_deadline) return RC_AGAIN;
              continue;
            }
            x->err_peer = pb.pidx;
            x->err_round = static_cast<int64_t>(x->cur_round);
            x->firing = false;
            return rc;
          }
          ++ss.frame;
          ++b;
          if (ss.frame > m.nchunks) {
            ss.frame = 0;
            if (++ss.msg >= stm.msgs.size()) ss.done = true;
          }
        }
        if (!ss.done) sends_left = true;
      }
      if (x->ftword != nullptr && *x->ftword != 0) return RC_FTSTOP;
      if (mono_s() >= x->slice_deadline && sends_left) return RC_AGAIN;
    }

    // ---- reap phase: poll + nap until the round's recvs land ----
    for (;;) {
      bool pending = false;
      for (auto& st : x->rst) pending |= !st.done;
      if (!pending) break;
      int rc = 0;
      int prog = reap_sweep(x, &rc);
      if (prog < 0) {
        x->firing = false;
        return rc;
      }
      if (x->ftword != nullptr && *x->ftword != 0) return RC_FTSTOP;
      double now = mono_s();
      if (now >= x->deadline_total) {
        x->err_round = static_cast<int64_t>(x->cur_round);
        x->firing = false;
        return RC_TIMEOUT;
      }
      if (now >= x->slice_deadline) return RC_AGAIN;
      if (prog == 0) nap_us(100);
    }

    x->ts[x->cur_round] = mono_s();
    if (++x->cur_round < x->rounds.size()) enter_round(x);
  }

  x->firing = false;
  return RC_DONE;
}

const uint8_t* planexec_pool_ptr(void* h) {
  return static_cast<PlanExec*>(h)->slab.data();
}

int64_t planexec_pool_total(void* h) {
  return static_cast<PlanExec*>(h)->pool_total;
}

int64_t planexec_pool_count(void* h) {
  return static_cast<int64_t>(static_cast<PlanExec*>(h)->pool.size());
}

int64_t planexec_round_count(void* h) {
  return static_cast<int64_t>(static_cast<PlanExec*>(h)->rounds.size());
}

int64_t planexec_input_count(void* h) {
  return static_cast<int64_t>(
      static_cast<PlanExec*>(h)->input_lens.size());
}

const double* planexec_ts_ptr(void* h) {
  return static_cast<PlanExec*>(h)->ts.data();
}

int64_t planexec_err_peer(void* h) {
  return static_cast<PlanExec*>(h)->err_peer;
}

int64_t planexec_err_round(void* h) {
  return static_cast<PlanExec*>(h)->err_round;
}

int64_t planexec_stash_count(void* h) {
  return static_cast<int64_t>(static_cast<PlanExec*>(h)->stash.size());
}

// len of stash entry i; kind 0 = endpoint frame, 1 = ring record
int64_t planexec_stash_info(void* h, int64_t i, int64_t* kind,
                            int64_t* peer, int64_t* tag) {
  auto* x = static_cast<PlanExec*>(h);
  if (i < 0 || i >= static_cast<int64_t>(x->stash.size())) return -1;
  auto& s = x->stash[static_cast<size_t>(i)];
  *kind = s.kind;
  *peer = s.peer;
  *tag = s.tag;
  return static_cast<int64_t>(s.bytes.size());
}

const uint8_t* planexec_stash_data(void* h, int64_t i) {
  auto* x = static_cast<PlanExec*>(h);
  if (i < 0 || i >= static_cast<int64_t>(x->stash.size()))
    return nullptr;
  return x->stash[static_cast<size_t>(i)].bytes.data();
}

void planexec_stash_clear(void* h) {
  static_cast<PlanExec*>(h)->stash.clear();
}

}  // extern "C"
