"""Multi-host coordinator over the native OOB — the HNP/orted wire-up.

The reference's launch wire-up (SURVEY §3.2): daemons report to the
HNP, the modex allgathers every proc's business card through the
daemon tree, and a runtime barrier gates MPI_Init completion. Here the
HNP is the job coordinator process (the ``tpurun`` launcher or rank 0)
and each worker process runs a WorkerAgent; messages are DSS-packed
frames over the native tree-routable OOB (``native/oob.cc``). In a
real multi-host TPU job this wire-up runs BEFORE
``jax.distributed.initialize`` — the modex distributes each host's
coordinator address/device coords; jax's own runtime then forms the
ICI/DCN data plane.

Topology: joins/barriers/heartbeats flow directly worker->HNP (every
worker holds an HNP link — the lifeline, ``errmgr_default_orted.c:252``),
while **xcast descends a binomial tree** (``grpcomm_bad_module.c:99``
through ``routed/binomial``): the HNP sends only to its tree children;
each worker, on receiving an xcast frame, forwards it to its own
children before delivering locally. Tree links are worker-to-worker
OOB connections established from the modex cards (each card carries
the worker's OOB listen port).

Failure detection mirrors ``sensor_heartbeat.c:61,78``: workers beat
periodically; the HNP-side monitor marks a worker failed after
``miss_limit`` silent intervals and invokes the registered callback
(the errmgr hook).

Tags mirror the RML usage pattern (``rml.h:318`` tagged send/recv).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..native import DssBuffer, OobEndpoint
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("coord")

TAG_JOIN = 1
TAG_MODEX = 2
TAG_BARRIER_ENTER = 3
TAG_BARRIER_RELEASE = 4
TAG_XCAST = 5
TAG_FIN = 6
TAG_HEARTBEAT = 7
TAG_XCAST_ORPHAN = 8  # worker->HNP: deliver xcast to unreachable child
TAG_PS = 13           # ps/top client->HNP: live job snapshot query
TAG_MIGRATE = 14      # migrate client->HNP: move ranks off a host
TAG_DIE = 15          # HNP->worker: exit immediately (odls kill)
TAG_CLOCK = 16        # worker->HNP ping-pong: clock-offset estimation
TAG_SERIES = 17       # worker->HNP: pvar time-series delta push;
#                       client->HNP: fleet series query (empty frame)
#                       (9-12 are the pubsub name-service tags)
TAG_PROC_FAILED = 18  # HNP->worker: job-epoch failure notice (ULFM
#                       detection plane: epoch + failed/restarted/
#                       rejoined process-index sets, JSON)
TAG_FT = 19           # worker->HNP RPC: failure-state query + the
#                       fault-tolerant agreement (MPIX_Comm_agree)
TAG_FT_REVOKE = 20    # worker->worker: comm-revocation poison frame
#                       ({cid, epoch, origin} JSON, sent direct over
#                       the full wire-up — no tree relay involved)

#: per-process cap on buffered fleet series points at the HNP (the
#: aggregation store is a ring too — a chatty worker cannot grow the
#: launcher without bound)
SERIES_KEEP = 8192
# pubsub tags + protocol live in runtime/pubsub.py (shared with the
# standalone tpu-server); re-exported here for the worker-facing API
from .pubsub import (  # noqa: E402
    TAG_LOOKUP, TAG_PUBLISH, TAG_PUBSUB_REPLY, TAG_UNPUBLISH,
)


# ---------------------------------------------------------------------------
# binomial tree (routed/binomial analogue)
# ---------------------------------------------------------------------------

def binomial_parent(v: int) -> int:
    """Parent of node v in the 0-rooted binomial tree (clear lowest
    set bit — the classic MPI virtual-rank rule)."""
    return v & (v - 1)


def binomial_children(v: int, n: int) -> List[int]:
    """Children of node v among nodes 0..n-1."""
    out = []
    low = (v & -v) if v else (1 << max(1, n.bit_length()))
    b = 1
    while b < low and v + b < n:
        out.append(v + b)
        b <<= 1
    return out


def local_addr_toward(host: str, port: int = 9) -> str:
    """The local interface address a connection to ``host`` leaves
    from (UDP connect trick — no packet is sent). This is the REAL
    address to advertise in a modex card: tree peers on other machines
    must be able to dial it, so the 127.0.0.1 placeholder only
    survives when the HNP itself is on loopback."""
    import socket

    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect((host, port or 9))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def _pack_card(node_id: int, card: Dict[str, Any]) -> bytes:
    b = DssBuffer()
    b.pack_int64(node_id)
    b.pack_string(json.dumps(card))
    return b.tobytes()


def _unpack_card(raw: bytes):
    b = DssBuffer(raw)
    (node_id,) = b.unpack_int64()
    return int(node_id), json.loads(b.unpack_string())


class HnpCoordinator:
    """Node-0 side: owns the root listener, drives modex/barrier/xcast
    and monitors worker health.

    ``num_nodes`` counts every tree node including the HNP. When the
    HNP is a launcher (tpurun) rather than a participant, pass
    ``my_card=None`` to :meth:`run_modex` — the card list then holds
    only the workers' cards, ordered by node id (index = node_id - 1).
    """

    def __init__(self, num_nodes: int, port: int = 0,
                 bind_addr: str = "127.0.0.1") -> None:
        if num_nodes < 1:
            raise MPIError(ErrorCode.ERR_ARG, "num_nodes must be >= 1")
        self.num_nodes = num_nodes
        self.ep = OobEndpoint(0, port, bind_addr)
        self._barrier_seq = 0
        self._monitor: Optional[threading.Thread] = None
        self._monitor_stop = threading.Event()
        # shared stop for the ps AND migrate responders: created here
        # so either can be started standalone, in any order
        self._ps_stop = threading.Event()
        self._finished: set = set()
        self._failed: set = set()
        self._hb_lock = threading.Lock()
        # ULFM detection plane: the job epoch is bumped (and a
        # TAG_PROC_FAILED notice pushed to every live worker) whenever
        # the failure picture changes — promotion to failed, a respawn
        # grant, a replacement's rejoin
        self._ft_epoch = 0
        self._ft_restarted: set = set()   # node ids granted a respawn
        self._ft_rejoined: set = set()    # replacements re-wired
        #: nid -> epoch at which its current failure episode began:
        #: the AUTHORITATIVE episode record consumers like shrink()
        #: need — the transient `failed` set empties milliseconds
        #: after promotion under the restart policy, but the episode
        #: epoch is what decides deadness per communicator
        self._ft_failed_at: Dict[int, int] = {}
        # parked fault-tolerant agreements: (cid, aseq) -> slot
        self._ft_agree_lock = threading.Lock()
        self._ft_pending: Dict[tuple, Dict[str, Any]] = {}
        self._resusage: Dict[int, Dict[str, int]] = {}
        self._last_beat: Dict[int, float] = {}
        #: nid -> deadline until which SILENCE is excused: a respawned
        #: worker's first beat is gated on full process startup
        #: (interpreter + jax import can exceed the whole
        #: interval*miss_limit window cold), so the monitor must not
        #: re-promote the replacement before it had any chance to
        #: beat — cleared by its first beat, bounded by the grace
        self._hb_restart_grace: Dict[int, float] = {}
        # Orphaned-subtree xcast fallback is the HNP's OWN duty, not an
        # optional caller poll: any HnpCoordinator user (tpurun,
        # participant-mode rank 0, direct tests) gets the drain.
        self._orphan_stop = threading.Event()
        self._orphan_thread = threading.Thread(
            target=self._orphan_loop, daemon=True
        )
        self._orphan_thread.start()

    def _orphan_loop(self) -> None:
        while not self._orphan_stop.is_set():
            try:
                self.serve_orphan_relay(timeout_ms=100)
            except Exception:
                if self._orphan_stop.is_set():
                    return
                time.sleep(0.1)

    @property
    def port(self) -> int:
        return self.ep.port

    @property
    def _worker_ids(self) -> List[int]:
        return list(range(1, self.num_nodes))

    def run_modex(self, my_card: Optional[Dict[str, Any]] = None, *,
                  timeout_ms: int = 30_000) -> List[Dict[str, Any]]:
        """Collect every worker's card, broadcast the full list
        (grpcomm_base_modex.c:67 allgather-through-daemons).

        my_card=None = launcher mode: the HNP contributes no card and
        the returned list is the workers', ordered by node id.
        """
        cards: Dict[int, Dict[str, Any]] = {}
        if my_card is not None:
            cards[0] = my_card
        expect = self.num_nodes if my_card is not None else self.num_nodes - 1
        first = 0 if my_card is not None else 1
        deadline = time.monotonic() + timeout_ms / 1000
        while len(cards) < expect:
            left = max(1, int((deadline - time.monotonic()) * 1000))
            src, _, raw = self.ep.recv(tag=TAG_JOIN, timeout_ms=left)
            nid, card = _unpack_card(raw)
            cards[nid] = card
            _log.verbose(2, f"modex: node {nid} joined ({len(cards)}/"
                            f"{expect})")
        ordered = [cards[i] for i in range(first, self.num_nodes)]
        payload = DssBuffer().pack_string(json.dumps(ordered)).tobytes()
        for nid in self._worker_ids:
            self.ep.send(nid, TAG_MODEX, payload)
        return ordered

    def barrier(self, *, timeout_ms: int = 30_000) -> None:
        """Wait for every worker's ENTER, then release all (the rte
        barrier of ompi_mpi_init.c:811)."""
        self._barrier_seq += 1
        seen = set()
        deadline = time.monotonic() + timeout_ms / 1000
        while len(seen) < self.num_nodes - 1:
            left = max(1, int((deadline - time.monotonic()) * 1000))
            src, _, raw = self.ep.recv(tag=TAG_BARRIER_ENTER,
                                       timeout_ms=left)
            seen.add(src)
        rel = DssBuffer().pack_int64(self._barrier_seq).tobytes()
        for nid in self._worker_ids:
            self.ep.send(nid, TAG_BARRIER_RELEASE, rel)

    def xcast(self, payload: bytes, tag: int = TAG_XCAST) -> None:
        """Broadcast down the binomial tree: send only to our tree
        children; workers relay to theirs (grpcomm xcast through
        routed/binomial — NOT a star loop)."""
        for nid in binomial_children(0, self.num_nodes):
            self.ep.send(nid, tag, payload)

    # -- health (sensor/heartbeat + errmgr hook) ---------------------------
    def start_heartbeat_monitor(
        self, on_failure: Callable[[int], None], *,
        interval_s: float = 1.0, miss_limit: int = 3,
    ) -> None:
        """Watch TAG_HEARTBEAT beats; a worker silent for
        ``miss_limit`` intervals (and not cleanly finished) is reported
        once via ``on_failure(node_id)``."""
        last = {nid: time.monotonic() for nid in self._worker_ids}
        self._last_beat = last  # ps snapshot reads beat ages

        def run() -> None:
            while not self._monitor_stop.is_set():
                try:
                    src, _, raw = self.ep.recv(
                        tag=TAG_HEARTBEAT,
                        timeout_ms=max(50, int(interval_s * 500)),
                    )
                    with self._hb_lock:
                        last[src] = time.monotonic()
                        # first beat of a respawned incarnation ends
                        # its startup grace: normal monitoring resumes
                        self._hb_restart_grace.pop(src, None)
                        if raw:  # piggybacked resusage sample
                            try:
                                self._resusage[src] = json.loads(raw)
                            except ValueError:
                                pass  # legacy empty/garbled beat
                except MPIError:
                    pass  # timeout: fall through to the check
                now = time.monotonic()
                newly_failed = []
                with self._hb_lock:
                    for nid in self._worker_ids:
                        if nid in self._finished or nid in self._failed:
                            continue
                        grace = self._hb_restart_grace.get(nid)
                        if grace is not None:
                            if now < grace:
                                continue  # still booting: excused
                            # grace expired with no beat: judge below
                            self._hb_restart_grace.pop(nid, None)
                        if now - last[nid] > interval_s * miss_limit:
                            self._failed.add(nid)
                            newly_failed.append(nid)
                # callback runs OUTSIDE the lock: errmgr policies may
                # re-enter (note_finished/recv_fin) or take seconds
                # (teardown) — neither may stall or deadlock the monitor
                for nid in newly_failed:
                    _log.verbose(
                        1, f"worker {nid} heartbeat lost "
                           f"({now - last[nid]:.1f}s silent)")
                    # ULFM promotion FIRST: bump the job epoch and push
                    # the TAG_PROC_FAILED notice before the errmgr
                    # policy runs, so survivors' bounded waits start
                    # raising ERR_PROC_FAILED even while the policy
                    # (teardown/respawn) is still deciding
                    self._ft_note_change(failed_nid=nid)
                    on_failure(nid)

        self._monitor = threading.Thread(target=run, daemon=True)
        self._monitor.start()

    def note_finished(self, nid: int) -> None:
        """Stop expecting beats from a cleanly-finished worker."""
        with self._hb_lock:
            self._finished.add(nid)

    # -- ULFM detection/agreement plane ------------------------------------
    def promote_failed(self, nid: int) -> bool:
        """Promote a worker to *failed* from an out-of-band observer
        (the launcher's waitpid loop seeing a nonzero exit long before
        the heartbeat window closes). Idempotent with the heartbeat
        monitor's own promotion; returns True when this call changed
        the picture (epoch bumped + notice pushed)."""
        with self._hb_lock:
            if nid in self._failed or nid in self._finished:
                return False
            self._failed.add(nid)
        self._ft_note_change(failed_nid=nid)
        return True

    def _ft_doc(self) -> Dict[str, Any]:
        """The authoritative failure picture as PROCESS indices (node
        ids and pidx differ by one — workers think in pidx)."""
        with self._hb_lock:
            return {
                "epoch": self._ft_epoch,
                "failed": sorted(n - 1 for n in self._failed),
                "restarted": sorted(n - 1 for n in self._ft_restarted),
                "rejoined": sorted(n - 1 for n in self._ft_rejoined),
                "failed_at": {str(n - 1): e for n, e
                              in sorted(self._ft_failed_at.items())},
            }

    def _ft_note_change(self, failed_nid: Optional[int] = None,
                        what: str = "") -> None:
        """Bump the job epoch and push a TAG_PROC_FAILED notice to
        every live worker (``failed_nid``, when given, is marked
        failed as part of the same epoch bump — callers that already
        marked it are unaffected, the add is idempotent). Notices go
        DIRECTLY over the lifelines (the HNP holds a link to every
        worker), not down the binomial tree: the dead worker may be
        exactly the relay node a tree descent would depend on."""
        with self._hb_lock:
            self._ft_epoch += 1
            if failed_nid is not None:
                self._failed.add(failed_nid)
                self._ft_failed_at[failed_nid] = self._ft_epoch
            live = [n for n in self._worker_ids
                    if n not in self._failed and n not in self._finished]
        if failed_nid is not None:
            # lifeline loss evicts the dead worker's published names:
            # a stale name must never be looked up by a later joiner
            # (the pubsub owner/TTL hygiene rule)
            tbl = getattr(self, "_ns_table", None)
            if tbl is not None:
                try:
                    tbl.evict_owner(failed_nid)
                except Exception:
                    pass  # name hygiene must not block the FT notice
        doc = self._ft_doc()
        payload = json.dumps(doc).encode()
        for nid in live:
            try:
                self.ep.send(nid, TAG_PROC_FAILED, payload)
            except MPIError:
                pass  # a link mid-death: that worker is next to fail
        _log.verbose(1, f"ft epoch {doc['epoch']}"
                        + (f" ({what})" if what else "")
                        + f": failed={doc['failed']} "
                          f"restarted={doc['restarted']} "
                          f"rejoined={doc['rejoined']}")
        # the failure picture changed: parked agreements may have lost
        # a participant they were waiting on
        self._ft_eval_agreements()

    def start_ft_responder(self) -> None:
        """Serve TAG_FT RPCs: ``{"op": "state"}`` queries answer with
        the current epoch/failed/restarted/rejoined picture; ``{"op":
        "agree"}`` contributions park until every live process of the
        agreement's group contributed (failed processes are excluded
        as they fail — re-evaluated on every epoch change), then every
        contributor gets the AND of the flags plus ONE consistent
        failure snapshot — the MPIX_Comm_agree contract that makes
        shrink's survivor group identical on every process. Shares the
        ps responder's stop event (created in __init__), so start
        order does not matter."""

        def run() -> None:
            while not self._ps_stop.is_set():
                try:
                    src, _, raw = self.ep.recv(tag=TAG_FT,
                                               timeout_ms=200)
                except MPIError:
                    self._ft_eval_agreements()
                    continue
                try:
                    req = json.loads(raw or b"{}")
                except ValueError:
                    continue  # malformed frame: never kill the plane
                if req.get("op") == "agree":
                    try:
                        self._ft_park_agreement(src, req)
                    except Exception:
                        pass  # a garbled field costs that frame only
                    self._ft_eval_agreements()
                    continue
                doc = self._ft_doc()
                doc["seq"] = req.get("seq")
                try:
                    self.ep.send(src, TAG_FT, json.dumps(doc).encode())
                except MPIError:
                    pass  # client vanished between query and reply

        self._ft_thread = threading.Thread(
            target=run, daemon=True, name="hnp-ft")
        self._ft_thread.start()

    def _ft_park_agreement(self, src: int, req: Dict[str, Any]) -> None:
        key = (int(req["cid"]), int(req["aseq"]))
        pidx = int(req["pidx"])
        with self._ft_agree_lock:
            slot = self._ft_pending.setdefault(key, {
                "flags": {}, "src": {}, "seq": {},
                "procs": set(int(p) for p in req.get("procs", ())),
                "t": time.monotonic(),
            })
            slot["procs"] |= set(int(p) for p in req.get("procs", ()))
            slot["flags"][pidx] = int(req.get("flag", 0))
            slot["src"][pidx] = src
            slot["seq"][pidx] = req.get("seq")

    def _ft_eval_agreements(self) -> None:
        """Complete every parked agreement whose live participants all
        contributed (failed ones excused), and prune abandoned slots.
        The AND folds every flag that ARRIVED — including one from a
        process that failed after contributing, per the ULFM rule."""
        now = time.monotonic()
        done = []
        with self._hb_lock:
            failed_pidx = set(n - 1 for n in self._failed)
        with self._ft_agree_lock:
            for key, slot in list(self._ft_pending.items()):
                live = slot["procs"] - failed_pidx
                if live and not live.issubset(slot["flags"].keys()):
                    if now - slot["t"] > 120:
                        del self._ft_pending[key]  # abandoned
                    continue
                done.append(slot)
                del self._ft_pending[key]
        for slot in done:
            flag = 1
            for f in slot["flags"].values():
                flag &= int(f)
            doc = self._ft_doc()
            doc["flag"] = flag
            for pidx, src in slot["src"].items():
                doc["seq"] = slot["seq"].get(pidx)
                try:
                    self.ep.send(src, TAG_FT, json.dumps(doc).encode())
                except MPIError:
                    pass  # contributor died since; excused above next time

    def serve_orphan_relay(self, timeout_ms: int = 50) -> bool:
        """Drain one orphaned-subtree relay request: a worker whose
        tree-child link failed asks us to deliver the xcast directly
        (we hold a lifeline link to every worker). Returns True if a
        frame was served."""
        try:
            _, _, raw = self.ep.recv(tag=TAG_XCAST_ORPHAN,
                                     timeout_ms=max(1, timeout_ms))
        except MPIError:
            return False
        child = int.from_bytes(raw[:4], "big")
        tag = int.from_bytes(raw[4:8], "big")
        try:
            self.ep.send(child, tag, raw[8:])
            _log.verbose(1, f"delivered xcast directly to orphaned "
                            f"node {child}")
        except MPIError:
            _log.verbose(1, f"direct delivery to orphaned node "
                            f"{child} failed")
        return True

    # -- rejoin service (resilient-restart wire-up) ------------------------
    def start_rejoin_service(self, cards: List[Dict[str, Any]]) -> None:
        """After the initial wire-up, keep serving JOIN + init-barrier
        frames so a RESTARTED worker (rmaps/resilient respawn) can run
        the normal ESS bootstrap against a live job: its JOIN updates
        its card in place and gets the current card list back; its
        barrier ENTER is released immediately (the collective init
        barrier already happened — a lone rejoiner must not hang on
        it). Post-init ENTERs only ever come from rejoiners: the
        in-job data plane barriers ride the wire router, not the HNP.
        """
        self._rejoin_cards = cards
        self._rejoin_stop = threading.Event()

        def run() -> None:
            while not self._rejoin_stop.is_set():
                served = False
                try:
                    _, _, raw = self.ep.recv(tag=TAG_JOIN,
                                             timeout_ms=100)
                    served = True
                    try:
                        nid, card = _unpack_card(raw)
                    except Exception:
                        # a malformed JOIN must not kill the service:
                        # every later restart would hang at bootstrap
                        _log.verbose(1, "rejoin: dropping malformed "
                                        "JOIN frame")
                        continue
                    if not 1 <= nid <= len(self._rejoin_cards):
                        _log.verbose(1, f"rejoin: JOIN from unknown "
                                        f"node {nid}; dropped")
                        continue
                    self._rejoin_cards[nid - 1] = card
                    payload = DssBuffer().pack_string(
                        json.dumps(self._rejoin_cards)).tobytes()
                    self.ep.send(nid, TAG_MODEX, payload)
                    _log.verbose(1, f"rejoin: node {nid} re-wired")
                    # a RESPAWNED worker's rejoin completes the
                    # recovery wire-up: mark it and bump the epoch so
                    # survivors waiting in errmgr.recover() proceed.
                    # Survivors also re-JOIN (to refresh their card
                    # list) — those are not marked, only respawns.
                    with self._hb_lock:
                        respawned = (nid in self._ft_restarted
                                     and nid not in self._ft_rejoined)
                        if respawned:
                            self._ft_rejoined.add(nid)
                    if respawned:
                        self._ft_note_change(
                            what=f"worker {nid} rejoined")
                except MPIError:
                    pass
                try:
                    src, _, _ = self.ep.recv(tag=TAG_BARRIER_ENTER,
                                             timeout_ms=100)
                    rel = DssBuffer().pack_int64(-1).tobytes()
                    self.ep.send(src, TAG_BARRIER_RELEASE, rel)
                    served = True
                except MPIError:
                    pass
                if not served:
                    time.sleep(0.02)

        self._rejoin_thread = threading.Thread(target=run, daemon=True)
        self._rejoin_thread.start()

    def stop_rejoin_service(self) -> None:
        stop = getattr(self, "_rejoin_stop", None)
        if stop is not None:
            stop.set()
            self._rejoin_thread.join(timeout=2)

    #: seconds a respawned worker gets to deliver its FIRST beat
    #: before the monitor may judge it silent (cold process startup —
    #: interpreter + jax import — routinely exceeds a sub-second
    #: heartbeat window; a replacement that stays silent past this is
    #: genuinely stuck and fails the normal way)
    RESTART_GRACE_S = 60.0

    def note_restarted(self, nid: int) -> None:
        """Forget a worker's failure/finish marks and reset its beat
        clock: the respawned incarnation is monitored afresh, with a
        startup grace until its first beat (see RESTART_GRACE_S).
        Bumps the job epoch (failed -> restarted) so survivors parked
        in recovery learn a replacement is on its way."""
        with self._hb_lock:
            self._failed.discard(nid)
            self._finished.discard(nid)
            self._resusage.pop(nid, None)
            self._ft_restarted.add(nid)
            self._ft_rejoined.discard(nid)
            self._hb_restart_grace[nid] = (time.monotonic()
                                           + self.RESTART_GRACE_S)
            if self._last_beat:
                self._last_beat[nid] = time.monotonic()
        self._ft_note_change(what=f"worker {nid} respawning")

    # -- ps/top snapshot service (orte-ps / orte-top HNP side) -------------
    def start_ps_responder(self, extra_fn: Optional[Callable] = None
                           ) -> None:
        """Serve TAG_PS queries: any client that dialed our port gets
        a JSON snapshot of per-worker health — last-beat age, pid,
        vmsize/rss from the piggybacked samples — plus whatever the
        launcher adds via ``extra_fn()`` (proc states, argv). The
        orte-ps/orte-top query path (``orte-ps.c`` pretty-prints what
        the HNP's sensor data already holds)."""

        def run() -> None:
            while not self._ps_stop.is_set():
                try:
                    src, _, _ = self.ep.recv(tag=TAG_PS, timeout_ms=200)
                except MPIError:
                    continue
                now = time.monotonic()
                with self._hb_lock:
                    workers = {
                        str(nid): {
                            "beat_age_s": (
                                round(now - self._last_beat[nid], 3)
                                if nid in self._last_beat else None),
                            "finished": nid in self._finished,
                            "failed": nid in self._failed,
                            **self._resusage.get(nid, {}),
                        }
                        for nid in self._worker_ids
                    }
                snap = {"num_workers": self.num_nodes - 1,
                        "workers": workers}
                if extra_fn is not None:
                    try:
                        snap.update(extra_fn())
                    except Exception:
                        pass  # a snapshot must never kill the responder
                try:
                    self.ep.send(src, TAG_PS, json.dumps(snap).encode())
                except MPIError:
                    pass  # client vanished between query and reply

        self._ps_thread = threading.Thread(target=run, daemon=True)
        self._ps_thread.start()

    # -- clock alignment (the obs-plane merge timebase) --------------------
    def start_clock_responder(self) -> None:
        """Serve TAG_CLOCK ping-pongs: echo the worker's payload back
        with OUR ``perf_counter`` reading appended. Workers run the
        classic NTP-style estimator (min-RTT sample, midpoint offset)
        against these replies, so every rank's journal timestamps can
        be mapped into ONE timebase — the HNP's — when tpu-doctor
        merges them. Shares the ps responder's stop event (created in
        __init__), so start order does not matter."""

        def run() -> None:
            while not self._ps_stop.is_set():
                try:
                    src, _, raw = self.ep.recv(tag=TAG_CLOCK,
                                               timeout_ms=200)
                except MPIError:
                    continue
                b = DssBuffer()
                b.pack_string(raw.decode("utf-8", "replace"))
                b.pack_string(repr(time.perf_counter()))
                try:
                    self.ep.send(src, TAG_CLOCK, b.tobytes())
                except MPIError:
                    pass  # client vanished between ping and pong

        self._clock_thread = threading.Thread(
            target=run, daemon=True, name="hnp-clock")
        self._clock_thread.start()

    # -- fleet series aggregation (the continuous metrics plane) -----------
    def start_series_responder(self) -> None:
        """Serve TAG_SERIES frames: a worker **push** (JSON with a
        ``points`` list) is folded into the per-process fleet store —
        a bounded ring per pidx, newest SERIES_KEEP points kept, with
        the worker's clock offset and push time alongside; any other
        frame is a **query** (tpu_top --fleet, tpu-doctor) answered
        with the whole fleet document. Shares the ps responder's stop
        event (created in __init__), so start order does not matter."""
        self._series_lock = threading.Lock()
        # pidx -> {"points": [..ring..], "clock_offset_s": float|None,
        #          "last_push": monotonic seconds}
        self._fleet_series: Dict[int, Dict[str, Any]] = {}

        def run() -> None:
            while not self._ps_stop.is_set():
                try:
                    src, _, raw = self.ep.recv(tag=TAG_SERIES,
                                               timeout_ms=200)
                except MPIError:
                    continue
                try:
                    doc = json.loads(raw) if raw else {}
                except ValueError:
                    continue  # malformed frame: never kill the store
                if isinstance(doc, dict) and "points" in doc:
                    try:
                        self._ingest_series(src, doc)
                    except Exception:
                        # a garbled push field (non-numeric pidx or
                        # offset from a version-skewed worker) costs
                        # that frame only — never the responder
                        pass
                    continue  # pushes are fire-and-forget
                try:
                    self.ep.send(src, TAG_SERIES,
                                 json.dumps(self.fleet_series()).encode())
                except MPIError:
                    pass  # client vanished between query and reply

        self._series_thread = threading.Thread(
            target=run, daemon=True, name="hnp-series")
        self._series_thread.start()

    def _ingest_series(self, src: int, doc: Dict[str, Any]) -> None:
        pidx = int(doc.get("pidx", src - 1))
        pts = [p for p in doc.get("points", ()) if isinstance(p, dict)]
        with self._series_lock:
            ent = self._fleet_series.setdefault(
                pidx, {"points": [], "clock_offset_s": None,
                       "last_push": None, "meta": {}})
            ent["points"].extend(pts)
            if len(ent["points"]) > SERIES_KEEP:
                del ent["points"][:len(ent["points"]) - SERIES_KEEP]
            if doc.get("clock_offset_s") is not None:
                ent["clock_offset_s"] = float(doc["clock_offset_s"])
            if isinstance(doc.get("meta"), dict):
                ent["meta"] = doc["meta"]
            ent["last_push"] = time.monotonic()

    def fleet_series(self) -> Dict[str, Any]:
        """The aggregated fleet document: per-pidx point rings with
        each worker's clock offset (consumers correct ``t`` into the
        HNP timebase by adding it) and the seconds since its last
        push (staleness marker for the dashboard)."""
        now = time.monotonic()
        lock = getattr(self, "_series_lock", None)
        if lock is None:
            return {"procs": {}}
        with lock:
            return {"procs": {
                str(pidx): {
                    "points": list(ent["points"]),
                    "clock_offset_s": ent["clock_offset_s"],
                    "push_age_s": (round(now - ent["last_push"], 3)
                                   if ent["last_push"] is not None
                                   else None),
                    "meta": dict(ent.get("meta") or {}),
                }
                for pidx, ent in sorted(self._fleet_series.items())
            }}

    def kill_worker(self, node_id: int, code: int = 143) -> None:
        """Order a worker to exit via its die watcher (the odls kill
        path — reaches THE WORKER ITSELF even when it was launched
        through an ssh conduit whose local client process is all the
        launcher could otherwise signal)."""
        self.ep.send(node_id, TAG_DIE, str(code).encode())

    def start_migrate_responder(self, migrate_fn: Callable) -> None:
        """Serve TAG_MIGRATE requests (the ``orte-migrate`` command
        path): payload is JSON ``{"off": host}``; ``migrate_fn`` is
        the launcher's policy hook and its dict return is the reply.
        Runs on its own thread; shares the ps responder's stop event
        (created in __init__, so start order does not matter) and is
        stopped by the same stop_ps_responder call."""

        def run() -> None:
            while not self._ps_stop.is_set():
                try:
                    src, _, raw = self.ep.recv(tag=TAG_MIGRATE,
                                               timeout_ms=200)
                except MPIError:
                    continue
                try:
                    req = json.loads(raw or b"{}")
                    reply = migrate_fn(req)
                except Exception as exc:  # never kill the responder
                    reply = {"ok": False, "error": str(exc)}
                try:
                    self.ep.send(src, TAG_MIGRATE,
                                 json.dumps(reply).encode())
                except MPIError:
                    pass

        self._migrate_thread = threading.Thread(
            target=run, daemon=True, name="hnp-migrate")
        self._migrate_thread.start()

    def stop_ps_responder(self) -> None:
        self._ps_stop.set()
        # join the migrate thread too, and with a much longer budget:
        # an in-flight migrate_fn kills/respawns ranks (seconds of
        # process teardown/launch) and mutates Job state — shutdown
        # must wait for it, not race it with ep.close()
        for name, budget in (("_ps_thread", 2), ("_migrate_thread", 30),
                             ("_clock_thread", 2), ("_series_thread", 2),
                             ("_ft_thread", 2)):
            t = getattr(self, name, None)
            if t is not None:
                t.join(timeout=budget)
                if t.is_alive():
                    _log.verbose(
                        1, f"{name} still running after {budget}s join "
                           "at shutdown; proceeding")

    # -- name service (pubsub_orte / orte-server analogue) -----------------
    def start_name_server(self) -> None:
        """Serve publish/lookup/unpublish frames: the HNP plays the
        ``orte-server`` role for its own job's workers. The protocol
        (seq correlation, parked lookups with client TTLs, malformed-
        frame tolerance) is the shared runtime/pubsub.py
        implementation — the standalone cross-job tpu-server runs the
        same table."""
        from .pubsub import PubsubTable

        self._ns_table = PubsubTable(self.ep)
        self._ns_stop = threading.Event()
        self._ns_thread = threading.Thread(
            target=self._ns_table.serve_loop, args=(self._ns_stop,),
            daemon=True,
        )
        self._ns_thread.start()

    def stop_name_server(self) -> None:
        stop = getattr(self, "_ns_stop", None)
        if stop is not None:
            stop.set()
            self._ns_thread.join(timeout=2)

    def recv_fin(self, timeout_ms: int = 1000) -> Optional[int]:
        """Drain one worker-completion report (returns node id)."""
        try:
            src, _, _ = self.ep.recv(tag=TAG_FIN, timeout_ms=timeout_ms)
        except MPIError:
            return None
        self.note_finished(src)
        return src

    def shutdown(self) -> None:
        self._monitor_stop.set()
        self._orphan_stop.set()
        self.stop_name_server()
        self.stop_ps_responder()
        self.stop_rejoin_service()
        try:
            # teardown release goes to every worker directly: tree
            # relays may already be gone at shutdown
            for nid in self._worker_ids:
                try:
                    self.ep.send(nid, TAG_FIN, b"")
                except MPIError:
                    pass
        finally:
            if self._monitor is not None:
                self._monitor.join(timeout=2)
            self._orphan_thread.join(timeout=2)
            self.ep.close()


class WorkerAgent:
    """Per-process agent (the orted-equivalent participant)."""

    def __init__(self, node_id: int, hnp_host: str, hnp_port: int,
                 num_nodes: Optional[int] = None) -> None:
        if node_id < 1:
            raise MPIError(ErrorCode.ERR_ARG,
                           "worker node_id must be >= 1 (0 is the HNP)")
        self.node_id = node_id
        self.num_nodes = num_nodes  # tree size (incl. HNP); set by modex
        # advertise the interface that actually faces the HNP; when
        # the HNP is off-host our listener must accept from other
        # machines too (tree links are worker-to-worker)
        self.local_addr = local_addr_toward(hnp_host, hnp_port)
        bind = ("127.0.0.1" if self.local_addr.startswith("127.")
                else "0.0.0.0")
        self.ep = OobEndpoint(node_id, 0, bind)
        self.ep.connect(0, hnp_host, hnp_port)
        self.ep.set_default_route(0)  # everything flows toward the root
        self.cards: List[Dict[str, Any]] = []
        self._hb_thread: Optional[threading.Thread] = None
        self._hb_stop = threading.Event()
        # created HERE, not lazily: two threads' first RPCs racing a
        # lazy check-then-set would mint two locks and defeat the
        # reply serialization pubsub_rpc requires
        self._pubsub_lock = threading.Lock()
        # same discipline for clock ping-pongs (the dump path and an
        # operator SIGUSR1 can race a finalize-time sync)
        self._clock_lock = threading.Lock()
        # and for series pushes (sampler tick vs finalize flush)
        self._series_lock = threading.Lock()
        # TAG_FT RPCs (state queries + agreements): one outstanding
        # per process, seq-correlated because a parked agreement's
        # reply can arrive arbitrarily late
        self._ft_lock = threading.Lock()
        self._ft_seq = 0
        self._ft_watcher: Optional[threading.Thread] = None

    def run_modex(self, my_card: Dict[str, Any], *,
                  timeout_ms: int = 30_000) -> List[Dict[str, Any]]:
        """JOIN with our card; receive the ordered card list. The card
        should carry ``oob_port`` (our listen port) so tree links can
        be formed afterwards (see :meth:`setup_tree`)."""
        my_card = dict(my_card)
        my_card.setdefault("oob_port", self.ep.port)
        my_card.setdefault("oob_host", self.local_addr)
        self.ep.send(0, TAG_JOIN, _pack_card(self.node_id, my_card))
        _, _, raw = self.ep.recv(tag=TAG_MODEX, timeout_ms=timeout_ms)
        self.cards = json.loads(DssBuffer(raw).unpack_string())
        return self.cards

    # -- tree (routed/binomial links for xcast relay) ----------------------
    def setup_tree(self, num_nodes: int,
                   worker_cards: List[Dict[str, Any]]) -> None:
        """Connect to our binomial-tree parent (if it is a worker; the
        HNP link already exists). ``worker_cards[i]`` MUST be node
        (i+1)'s card (launcher-mode modex returns exactly this;
        participant-mode callers pass ``cards[1:]`` to drop the HNP's
        card). Children connect to us the same way, so after the
        post-tree barrier every tree edge is live."""
        self.num_nodes = num_nodes
        parent = binomial_parent(self.node_id)
        if parent != 0:
            card = worker_cards[parent - 1]
            self.ep.connect(parent, card["oob_host"],
                            int(card["oob_port"]))

    @property
    def tree_children(self) -> List[int]:
        if not self.num_nodes:
            return []
        return binomial_children(self.node_id, self.num_nodes)

    def barrier(self, *, timeout_ms: int = 30_000) -> None:
        self.ep.send(0, TAG_BARRIER_ENTER, b"")
        self.ep.recv(tag=TAG_BARRIER_RELEASE, timeout_ms=timeout_ms)

    def recv_xcast(self, tag: int = TAG_XCAST, *,
                   timeout_ms: int = 30_000) -> bytes:
        """Receive a tree broadcast and relay it to our children
        FIRST (pipelined descent), then deliver locally."""
        _, _, raw = self.ep.recv(tag=tag, timeout_ms=timeout_ms)
        # The child's hello frame is processed on our reader thread
        # with no ordering guarantee against the HNP barrier release,
        # so the first relay can race peer_fd registration. First pass
        # attempts every child (keeping the descent pipelined for the
        # reachable ones), then only the failures are retried with
        # backoff; a child still unreachable is handed to the HNP,
        # which holds a lifeline link to every worker.
        failed = []
        for child in self.tree_children:
            try:
                self.ep.send(child, tag, raw)
            except MPIError:
                failed.append(child)
        for attempt in range(4):
            if not failed:
                break
            time.sleep(0.05 * (attempt + 1))
            still = []
            for child in failed:
                try:
                    self.ep.send(child, tag, raw)
                except MPIError:
                    still.append(child)
            failed = still
        for child in failed:
            _log.verbose(1, f"xcast relay to child {child} failed "
                            "after retries; deferring to HNP")
            try:
                hdr = (int(child).to_bytes(4, "big")
                       + int(tag).to_bytes(4, "big"))
                self.ep.send(0, TAG_XCAST_ORPHAN, hdr + raw)
            except MPIError:
                _log.verbose(1, "HNP fallback for orphaned "
                                f"subtree {child} also failed")
        return raw

    # -- name service client (MPI_Publish_name over the lifeline) ----------
    def _pubsub_rpc(self, tag: int, *fields: str, timeout_ms: int = 10_000):
        from .pubsub import pubsub_rpc

        return pubsub_rpc(self.ep, self._pubsub_lock, self, tag,
                          *fields, timeout_ms=timeout_ms)

    def publish_name(self, service: str, port: str) -> None:
        ok, msg = self._pubsub_rpc(TAG_PUBLISH, service, port)
        if not ok:
            raise MPIError(ErrorCode.ERR_NAME,
                           f"publish '{service}': {msg}")

    def lookup_name(self, service: str, *,
                    timeout_ms: int = 10_000) -> str:
        """Blocks until the name is published (the server parks us
        with our deadline, so abandoned lookups expire server-side)
        or the recv times out."""
        ok, value = self._pubsub_rpc(TAG_LOOKUP, service, str(timeout_ms),
                                     timeout_ms=timeout_ms)
        if not ok:
            raise MPIError(ErrorCode.ERR_NAME,
                           f"lookup '{service}' failed: {value}")
        return value

    def unpublish_name(self, service: str) -> None:
        ok, msg = self._pubsub_rpc(TAG_UNPUBLISH, service)
        if not ok:
            raise MPIError(ErrorCode.ERR_NAME,
                           f"unpublish '{service}': not published")

    # -- clock alignment ---------------------------------------------------
    def clock_sync(self, rounds: int = 8,
                   timeout_ms: int = 2000) -> tuple:
        """Estimate this process's ``perf_counter`` offset to the
        HNP's via TAG_CLOCK ping-pongs: offset = hnp_mid - local_mid
        of the MINIMUM-RTT sample (the NTP discipline — the tightest
        round trip bounds the asymmetry error by rtt/2). Returns
        ``(offset_s, rtt_s)``; adding ``offset_s`` to a local
        perf_counter reading yields HNP time. Raises ERR_PENDING when
        no pong arrives (responder not running)."""
        import uuid as _uuid

        best: Optional[tuple] = None
        with self._clock_lock:
            for i in range(max(1, rounds)):
                nonce = _uuid.uuid4().hex[:16]
                t0 = time.perf_counter()
                try:
                    self.ep.send(0, TAG_CLOCK, nonce.encode())
                    deadline = time.monotonic() + timeout_ms / 1000
                    while True:
                        left = max(1, int((deadline - time.monotonic())
                                          * 1000))
                        _, _, raw = self.ep.recv(tag=TAG_CLOCK,
                                                 timeout_ms=left)
                        t1 = time.perf_counter()
                        b = DssBuffer(raw)
                        if b.unpack_string() == nonce:
                            break  # stale pong from an abandoned
                            #        round: keep draining inside this
                            #        round's budget until ours arrives
                except MPIError:
                    if best is None:
                        raise  # responder absent: surface it
                    break      # got samples; a late timeout ends early
                th = float(b.unpack_string())
                rtt = t1 - t0
                off = th - (t0 + t1) / 2
                if best is None or rtt < best[1]:
                    best = (off, rtt)
        return best

    # -- fleet series push (the continuous metrics plane) ------------------
    def push_series(self, points, offset_s=None, meta=None) -> None:
        """Fire-and-forget push of new sampler points to the HNP's
        fleet store. The worker's process_index rides in the frame
        (node ids and pidx differ by one), plus the current clock
        offset so the HNP-side document is mergeable onto one
        timeline and optional identity meta (rank span) so dashboards
        can label rows. Raises MPIError when the lifeline is gone —
        the sampler counts failures and stops trying."""
        pidx = self.node_id - 1
        doc = {"pidx": pidx, "points": list(points),
               "clock_offset_s": offset_s}
        if meta:
            doc["meta"] = dict(meta)
        with self._series_lock:
            self.ep.send(0, TAG_SERIES, json.dumps(doc).encode())

    def query_fleet_series(self, *, timeout_ms: int = 5_000) -> Dict:
        """Ask the HNP for the aggregated fleet document (mostly for
        tests; dashboards use tools.tpu_top.FleetClient)."""
        with self._series_lock:
            self.ep.send(0, TAG_SERIES, b"{}")
            _, _, raw = self.ep.recv(tag=TAG_SERIES,
                                     timeout_ms=timeout_ms)
        return json.loads(raw)

    # -- ULFM failure plane ------------------------------------------------
    def start_ft_watcher(self, on_notice, on_revoke=None) -> None:
        """Watch the failure plane: TAG_PROC_FAILED notices from the
        HNP (epoch bumps) are handed to ``on_notice(doc)``, and
        TAG_FT_REVOKE poison frames from peer workers to
        ``on_revoke(cid, epoch)``. One thread alternates bounded
        receives on both tags (the OOB recv is tag-filtered, so this
        coexists with the heartbeat/die-watcher threads on the same
        endpoint); worst-case delivery latency is one loop pass —
        far inside the heartbeat detection interval. Stops with the
        heartbeat stop event (both are the process-management
        channel)."""
        if self._ft_watcher is not None and self._ft_watcher.is_alive():
            return

        def run() -> None:
            from ..utils.errors import ErrorCode as _EC

            while not self._hb_stop.is_set():
                for tag, timeout in ((TAG_PROC_FAILED, 150),
                                     (TAG_FT_REVOKE, 50)):
                    try:
                        _, _, raw = self.ep.recv(tag=tag,
                                                 timeout_ms=timeout)
                    except MPIError as e:
                        if e.code == _EC.ERR_PENDING:
                            continue  # plain timeout: keep watching
                        return        # endpoint closed/torn down
                    except Exception:
                        return
                    try:
                        doc = json.loads(raw or b"{}")
                    except ValueError:
                        continue  # malformed frame: never kill the plane
                    try:
                        if tag == TAG_PROC_FAILED:
                            on_notice(doc)
                        elif on_revoke is not None:
                            on_revoke(int(doc["cid"]),
                                      int(doc.get("epoch", -1)))
                    except Exception as e:
                        _log.verbose(1, f"ft watcher handler "
                                        f"failed: {e}")

        self._ft_watcher = threading.Thread(
            target=run, daemon=True, name="ft-watcher")
        self._ft_watcher.start()

    def _ft_rpc(self, req: Dict[str, Any], *,
                timeout_ms: int = 10_000) -> Dict[str, Any]:
        """One seq-correlated TAG_FT round trip. Replies carrying a
        stale seq (an agreement abandoned by an earlier timeout) are
        drained and dropped."""
        with self._ft_lock:
            self._ft_seq += 1
            seq = f"{self.node_id}:{self._ft_seq}"
            req = dict(req)
            req["seq"] = seq
            self.ep.send(0, TAG_FT, json.dumps(req).encode())
            deadline = time.monotonic() + timeout_ms / 1000
            while True:
                left = max(1, int((deadline - time.monotonic()) * 1000))
                _, _, raw = self.ep.recv(tag=TAG_FT, timeout_ms=left)
                try:
                    doc = json.loads(raw)
                except ValueError:
                    continue
                if doc.get("seq") == seq:
                    return doc

    def ft_query(self, *, timeout_ms: int = 10_000) -> Dict[str, Any]:
        """The authoritative failure picture from the HNP: epoch,
        failed/restarted/rejoined process indices. Raises ERR_PENDING
        when the ft responder is not running."""
        return self._ft_rpc({"op": "state"}, timeout_ms=timeout_ms)

    def ft_agree(self, cid: int, aseq: int, flag: int, procs,
                 *, timeout_ms: int = 60_000) -> Dict[str, Any]:
        """Fault-tolerant agreement (MPIX_Comm_agree): contribute
        ``flag`` for agreement ``(cid, aseq)`` among ``procs`` and
        block until every live participant contributed. The reply
        carries the AND of the contributed flags plus ONE consistent
        epoch/failed snapshot shared by all participants — the
        foundation shrink builds its survivor group on."""
        return self._ft_rpc(
            {"op": "agree", "cid": int(cid), "aseq": int(aseq),
             "pidx": self.node_id - 1, "flag": int(flag),
             "procs": [int(p) for p in procs]},
            timeout_ms=timeout_ms)

    def ft_revoke_notify(self, peer_pidx: int, cid: int,
                         epoch: int) -> None:
        """Push one revocation poison frame to a peer worker (the
        revoke propagation step; best-effort — a dead peer needs no
        poison)."""
        doc = {"cid": int(cid), "epoch": int(epoch),
               "origin": self.node_id - 1}
        self.ep.send(peer_pidx + 1, TAG_FT_REVOKE,
                     json.dumps(doc).encode())

    # -- health ------------------------------------------------------------
    def heartbeat(self) -> None:
        """Beat, piggybacking a resource-usage sample (the
        sensor/resusage data orte-ps/orte-top display,
        ``sensor_resusage.c`` feeding the HNP): pid + vmsize/rss ride
        every beat, so the HNP always holds a fresh per-rank sample
        without a second sampling channel."""
        from ..ft.sensor import resource_usage

        ru = resource_usage()
        ru["pid"] = os.getpid()
        self.ep.send(0, TAG_HEARTBEAT, json.dumps(ru).encode())

    def start_heartbeats(self, interval_s: float = 1.0) -> None:
        def run() -> None:
            while not self._hb_stop.wait(interval_s):
                try:
                    self.heartbeat()
                except MPIError:
                    return  # lifeline gone; process teardown follows

        self._hb_thread = threading.Thread(target=run, daemon=True)
        self._hb_thread.start()
        self._start_die_watcher()

    def _start_die_watcher(self) -> None:
        """Obey TAG_DIE from the HNP with ``os._exit`` (the odls
        kill_local_procs analogue, ``orte/mca/odls/base``): when the
        launcher reached the worker over ssh, terminating the LOCAL
        ssh client merely orphans the remote process — the reference
        kills through the remote orted, and this control-plane kill
        is that path here. Runs whenever heartbeats run (both are the
        process-management channel)."""

        def run() -> None:
            from ..utils.errors import ErrorCode as _EC

            while not self._hb_stop.is_set():
                try:
                    _, _, raw = self.ep.recv(tag=TAG_DIE,
                                             timeout_ms=500)
                except MPIError as e:
                    if e.code == _EC.ERR_PENDING:
                        continue  # plain timeout: keep watching
                    return        # endpoint closed/torn down
                except Exception:
                    return
                os._exit(int(raw or b"143"))

        threading.Thread(target=run, daemon=True,
                         name="die-watcher").start()

    def stop_heartbeats(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2)

    # -- teardown ----------------------------------------------------------
    def send_fin(self) -> None:
        """Report clean completion to the HNP (IOF_COMPLETE analogue)."""
        self.ep.send(0, TAG_FIN, b"")

    def wait_fin(self, *, timeout_ms: int = 60_000) -> None:
        self.ep.recv(tag=TAG_FIN, timeout_ms=timeout_ms)
        self.close()

    def close(self) -> None:
        self.stop_heartbeats()
        self.ep.close()
