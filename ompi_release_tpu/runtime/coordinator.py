"""Multi-host coordinator over the native OOB — the HNP/orted wire-up.

The reference's launch wire-up (SURVEY §3.2): daemons report to the
HNP, the modex allgathers every proc's business card through the
daemon tree, and a runtime barrier gates MPI_Init completion. Here the
HNP is the job coordinator process and each host runs a WorkerAgent;
messages are DSS-packed frames over the native tree-routable OOB
(``native/oob.cc``). In a real multi-host TPU job this wire-up runs
BEFORE ``jax.distributed.initialize`` — the modex distributes each
host's coordinator address/device coords; jax's own runtime then forms
the ICI/DCN data plane.

Tags mirror the RML usage pattern (``rml.h:318`` tagged send/recv).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from ..native import DssBuffer, OobEndpoint
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("coord")

TAG_JOIN = 1
TAG_MODEX = 2
TAG_BARRIER_ENTER = 3
TAG_BARRIER_RELEASE = 4
TAG_XCAST = 5
TAG_FIN = 6
TAG_HEARTBEAT = 7


def _pack_card(node_id: int, card: Dict[str, Any]) -> bytes:
    b = DssBuffer()
    b.pack_int64(node_id)
    b.pack_string(json.dumps(card))
    return b.tobytes()


def _unpack_card(raw: bytes):
    b = DssBuffer(raw)
    (node_id,) = b.unpack_int64()
    return int(node_id), json.loads(b.unpack_string())


class HnpCoordinator:
    """Rank-0 side: owns the listener, drives modex/barrier/xcast."""

    def __init__(self, num_nodes: int, port: int = 0) -> None:
        if num_nodes < 1:
            raise MPIError(ErrorCode.ERR_ARG, "num_nodes must be >= 1")
        self.num_nodes = num_nodes
        self.ep = OobEndpoint(0, port)
        self._barrier_seq = 0

    @property
    def port(self) -> int:
        return self.ep.port

    def run_modex(self, my_card: Dict[str, Any], *,
                  timeout_ms: int = 30_000) -> List[Dict[str, Any]]:
        """Collect every worker's card, broadcast the full list
        (grpcomm_base_modex.c:67 allgather-through-daemons)."""
        cards: Dict[int, Dict[str, Any]] = {0: my_card}
        deadline = time.monotonic() + timeout_ms / 1000
        while len(cards) < self.num_nodes:
            left = max(1, int((deadline - time.monotonic()) * 1000))
            src, _, raw = self.ep.recv(tag=TAG_JOIN, timeout_ms=left)
            nid, card = _unpack_card(raw)
            cards[nid] = card
            _log.verbose(2, f"modex: node {nid} joined ({len(cards)}/"
                            f"{self.num_nodes})")
        ordered = [cards[i] for i in range(self.num_nodes)]
        payload = DssBuffer().pack_string(json.dumps(ordered)).tobytes()
        for nid in range(1, self.num_nodes):
            self.ep.send(nid, TAG_MODEX, payload)
        return ordered

    def barrier(self, *, timeout_ms: int = 30_000) -> None:
        """Wait for every worker's ENTER, then release all (the rte
        barrier of ompi_mpi_init.c:811)."""
        self._barrier_seq += 1
        seen = set()
        deadline = time.monotonic() + timeout_ms / 1000
        while len(seen) < self.num_nodes - 1:
            left = max(1, int((deadline - time.monotonic()) * 1000))
            src, _, raw = self.ep.recv(tag=TAG_BARRIER_ENTER,
                                       timeout_ms=left)
            seen.add(src)
        rel = DssBuffer().pack_int64(self._barrier_seq).tobytes()
        for nid in range(1, self.num_nodes):
            self.ep.send(nid, TAG_BARRIER_RELEASE, rel)

    def xcast(self, payload: bytes, tag: int = TAG_XCAST) -> None:
        """Broadcast through the tree (grpcomm xcast analogue; with a
        star topology this is direct, with routes it relays)."""
        for nid in range(1, self.num_nodes):
            self.ep.send(nid, tag, payload)

    def shutdown(self) -> None:
        try:
            self.xcast(b"", tag=TAG_FIN)
        finally:
            self.ep.close()


class WorkerAgent:
    """Per-host agent (the orted-equivalent participant)."""

    def __init__(self, node_id: int, hnp_host: str, hnp_port: int) -> None:
        if node_id < 1:
            raise MPIError(ErrorCode.ERR_ARG,
                           "worker node_id must be >= 1 (0 is the HNP)")
        self.node_id = node_id
        self.ep = OobEndpoint(node_id)
        self.ep.connect(0, hnp_host, hnp_port)
        self.ep.set_default_route(0)  # everything flows toward the root

    def run_modex(self, my_card: Dict[str, Any], *,
                  timeout_ms: int = 30_000) -> List[Dict[str, Any]]:
        self.ep.send(0, TAG_JOIN, _pack_card(self.node_id, my_card))
        _, _, raw = self.ep.recv(tag=TAG_MODEX, timeout_ms=timeout_ms)
        return json.loads(DssBuffer(raw).unpack_string())

    def barrier(self, *, timeout_ms: int = 30_000) -> None:
        self.ep.send(0, TAG_BARRIER_ENTER, b"")
        self.ep.recv(tag=TAG_BARRIER_RELEASE, timeout_ms=timeout_ms)

    def recv_xcast(self, tag: int = TAG_XCAST, *,
                   timeout_ms: int = 30_000) -> bytes:
        _, _, raw = self.ep.recv(tag=tag, timeout_ms=timeout_ms)
        return raw

    def heartbeat(self) -> None:
        self.ep.send(0, TAG_HEARTBEAT, b"")

    def wait_fin(self, *, timeout_ms: int = 60_000) -> None:
        self.ep.recv(tag=TAG_FIN, timeout_ms=timeout_ms)
        self.ep.close()
