"""Async progress engine — the ``opal_progress`` analogue.

The reference hangs its whole comm engine off one loop:
``opal/runtime/opal_progress.c`` registers per-framework callbacks and
every blocked wait spins ``opal_progress()`` until its completion flag
flips, while libnbc (``ompi/mca/coll/libnbc/nbc.c``) advances
nonblocking-collective round schedules from that loop so an
``MPI_Iallreduce`` makes progress off the caller's critical path. This
module is that engine for the TPU runtime:

- a REGISTRY of in-flight scheduled operations (one
  :class:`ScheduledOp` per nonblocking collective on a spanning
  communicator, posted by :mod:`coll.nbc`), executed strictly in
  per-communicator posting order — the MPI same-order-on-every-process
  collective contract — with a per-thread posting ledger so a single
  SPMD program's deferred operations drain in program order;
- an explicit :func:`ProgressEngine.progress` TICK: advances the
  receive side of ``runtime/wire.py`` channels (each op carries a pump
  that reaps completed collective transfers into the router's
  early-transfer queue) and completes in-process async-dispatch
  requests whose device arrays became ready — one tick advances every
  pending request, which is what ``request.wait_all``/``test_all``
  and a bare ``Request.wait()`` call through the shared progress hook;
- an opt-in DEDICATED PROGRESS THREAD (``progress_thread`` cvar,
  default off): when enabled it claims queued schedules and runs them
  off the caller, turning i-collectives into true compute/comm overlap
  (measured by the ``nbc_hidden_seconds`` pvar and the bench
  ``overlap`` suite). The default is the polling fallback — operations
  execute at ``wait()`` in posting order on the caller's thread, so
  tier-1 CPU tests stay deterministic and single-threaded.

Execution model: an op is *claimed* (QUEUED -> RUNNING, exactly once)
only when it is the head of its communicator's FIFO — two collectives
on one communicator can never interleave frames on its wire channel,
and posting order is execution order on every process. A blocking
collective on a spanning communicator is expressed as "post + wait"
through this same machinery (``coll/nbc.run_blocking``), so there is
ONE round-advancing code path. Nested collectives issued from inside a
running op (two-phase IO's closing barrier, the hier shadow comm)
bypass the queue and run inline on the executing thread — sequential
on one thread, so frames cannot interleave.

Known limitation (documented, matching the driver-mode reality of one
controller thread per process): in polling mode, deferred i-collectives
posted from MULTIPLE user threads and waited cross-thread in divergent
orders across processes can stall until some thread waits the matching
op; the progress thread mode has no such coupling. Single-threaded SPMD
programs — the repo's driver convention — drain deterministically. A
test()-only completion loop is live in polling mode too: the first
test on a still-queued schedule kicks an on-demand background drainer
(:meth:`ProgressEngine.advance_toward`), because running the whole
schedule inline inside a nonblocking test could park on peers that
have not arrived yet.

Cost discipline: the obs emit sites here are gated on ``_obs.enabled``
(the PR-1 one-attribute-check contract, enforced by
``tests/test_obs_gating.py``), and pvars are module-level zero-cost
counters: ``progress_ticks`` (engine ticks), ``nbc_schedules_inflight``
(posted-but-incomplete schedules), ``nbc_hidden_seconds`` (schedule
run time that overlapped caller compute instead of blocking it).
"""

from __future__ import annotations

import itertools
import threading
import time as _time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import obs as _obs
from ..mca import pvar
from ..mca import var as mca_var
from ..obs import watchdog as _watchdog
from ..request import request as _request
from ..utils import output

_log = output.stream("progress")

_ticks = pvar.counter(
    "progress_ticks",
    "explicit/threaded progress-engine ticks (opal_progress analogue)",
)
_hidden = pvar.timer(
    "nbc_hidden_seconds",
    "nonblocking-schedule run time that overlapped caller compute "
    "(ran before the first wait) instead of blocking the critical path",
)


def register_vars() -> None:
    mca_var.register(
        "progress_thread", "bool", False,
        "Run the dedicated async-progress thread: queued nonblocking "
        "collective schedules execute off the caller (true "
        "compute/comm overlap). Off (default) = polling fallback: "
        "schedules advance when the caller ticks progress() or waits, "
        "in posting order — deterministic for single-threaded tests",
    )
    mca_var.register(
        "progress_poll_us", "int", 500,
        "Idle poll period of the progress thread in microseconds "
        "(bounds the latency between a peer's frame landing and the "
        "engine reaping it when no schedule is runnable)",
    )


register_vars()  # idempotent; cvars must exist before the first post


#: ScheduledOp lifecycle states
QUEUED = "queued"
RUNNING = "running"
DONE = "done"


class ScheduledOp:
    """One in-flight scheduled operation (a libnbc handle analogue).

    ``key`` serializes execution: ops sharing a key (one communicator)
    run strictly in posting order, never concurrently. ``fn`` is the
    whole round schedule — its wire exchanges ride the instrumented
    hier/wire touchpoints, so flow ids, pvars, and watchdog arming are
    identical to the blocking path's. ``pump`` (optional) is the
    nonblocking receive-side tick for the op's wire channel.
    """

    __slots__ = ("seq", "key", "name", "cid", "fn", "args", "kw",
                 "pump", "state", "claimed_by", "poster", "polls",
                 "result", "error", "done", "callbacks", "t_post",
                 "t_start", "t_done", "t_first_wait")

    def __init__(self, key: Any, name: str, fn: Callable, *,
                 cid: int = -1, args: Tuple = (), kw: Optional[Dict] = None,
                 pump: Optional[Callable[[], int]] = None) -> None:
        self.seq = 0  # assigned by post()
        self.key = key
        self.name = name
        self.cid = cid
        self.fn = fn
        self.args = args
        self.kw = kw or {}
        self.pump = pump
        self.state = QUEUED
        self.claimed_by: Optional[int] = None
        self.poster: Optional[int] = None  # assigned by post()
        self.polls = 0  # consecutive test()-style advances (kick gate)
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()
        #: completion callbacks, run BEFORE done is set (a waiter must
        #: observe the bound request already completed-with-value)
        self.callbacks: List[Callable] = []
        self.t_post = _time.perf_counter()
        self.t_start = 0.0
        self.t_done = 0.0
        self.t_first_wait: Optional[float] = None

    def hidden_seconds(self) -> float:
        """The part of this schedule's run the poster spent elsewhere
        (THE overlap accounting — one definition, used by the engine's
        ``nbc_hidden_seconds`` fold and per-pass consumers like
        ``parallel/tree``). Polling mode waits before the run starts
        -> 0; a run finished before the first wait hides its whole
        duration. Meaningful once the op is DONE; 0 before."""
        if not self.t_done:
            return 0.0
        tw = self.t_first_wait
        if tw is not None and tw <= self.t_start:
            return 0.0
        end = self.t_done if tw is None else min(self.t_done, tw)
        return max(0.0, end - self.t_start)

    def describe(self) -> Dict[str, Any]:
        """Postmortem line: THE answer to "which NBC schedule is
        stuck" in a flight-recorder dump."""
        now = _time.perf_counter()
        return {
            "name": self.name, "cid": self.cid, "seq": self.seq,
            "state": self.state, "claimed_by": self.claimed_by,
            "posted_s_ago": round(now - self.t_post, 3),
            "running_s": (round(now - self.t_start, 3)
                          if self.state == RUNNING else 0.0),
            "waited_on": self.t_first_wait is not None,
        }


class ProgressEngine:
    """Process-global progress engine (one per controller process)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._seq = itertools.count(1)
        #: key -> FIFO of not-yet-done ops (head = next to run)
        self._queues: Dict[Any, deque] = {}
        #: poster thread id -> ops in posting order (the drain ledger)
        self._posted: Dict[int, List[ScheduledOp]] = {}
        #: seq -> op, every posted-but-incomplete op (the registry the
        #: nbc_schedules_inflight pvar and the watchdog dump read)
        self._inflight: Dict[int, ScheduledOp] = {}
        #: token -> weakref of in-process async-dispatch Requests the
        #: tick completes when their device arrays turn ready (a dict
        #: mutated in place under the lock: completion pops its own
        #: token, so ticks stay O(outstanding) and a tick's sweep can
        #: never resurrect an entry a concurrent completion removed)
        self._poll: Dict[int, weakref.ref] = {}
        #: keys with an active test()-kicked background drainer
        self._kicked: set = set()
        self._tls = threading.local()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- registry ----------------------------------------------------------
    def inflight_count(self) -> int:
        return len(self._inflight)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            ops = sorted(self._inflight.values(), key=lambda o: o.seq)
        return [op.describe() for op in ops]

    # -- posting -----------------------------------------------------------
    def post(self, op: ScheduledOp) -> ScheduledOp:
        """Enqueue one scheduled op (never blocks, never executes)."""
        tid = threading.get_ident()
        with self._lock:
            op.seq = next(self._seq)
            op.poster = tid
            self._queues.setdefault(op.key, deque()).append(op)
            self._posted.setdefault(tid, []).append(op)
            self._inflight[op.seq] = op
            self._cond.notify_all()
        self.ensure_thread()
        return op

    # -- execution ---------------------------------------------------------
    def executing(self) -> Optional[ScheduledOp]:
        """The op the CURRENT thread is executing, if any (nested
        collective detection)."""
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def _execute(self, op: ScheduledOp) -> None:
        """Run one claimed op to completion on this thread."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        stack.append(op)
        op.t_start = _time.perf_counter()
        rec = _obs.enabled  # capture once: flag may flip mid-run
        try:
            op.result = op.fn(*op.args, **op.kw)
        except BaseException as e:
            op.error = e
        finally:
            stack.pop()
            t_done = op.t_done = _time.perf_counter()
            with self._lock:
                op.state = DONE
                q = self._queues.get(op.key)
                if q:
                    try:
                        q.remove(op)
                    except ValueError:
                        pass
                    if not q:
                        self._queues.pop(op.key, None)
                self._inflight.pop(op.seq, None)
                # drop from the poster's ledger too: an op completed
                # by the progress thread must not pile up in a thread
                # list its poster may never scan again
                ledger = self._posted.get(op.poster)
                if ledger is not None:
                    try:
                        ledger.remove(op)
                    except ValueError:
                        pass
                    if not ledger:
                        self._posted.pop(op.poster, None)
                self._cond.notify_all()
            # hidden time: the op's own accounting (the ONE definition
            # of overlap — see ScheduledOp.hidden_seconds)
            hidden = op.hidden_seconds()
            if hidden > 0:
                _hidden.add(hidden)
            if rec and _obs.enabled:
                _obs.record("nbc_" + op.name, "nbc", op.t_start,
                            t_done - op.t_start, comm_id=op.cid)
            # callbacks BEFORE the event: a thread woken by done must
            # find the bound request already completed with its value
            for cb in list(op.callbacks):
                try:
                    cb(op)
                except Exception as e:  # a callback must not kill the engine
                    _log.verbose(1, f"nbc completion callback failed: {e}")
            op.done.set()

    def _claim_locked(self, op: ScheduledOp) -> bool:
        """Claim ``op`` if it is the QUEUED head of its key's FIFO.
        Caller holds the lock."""
        q = self._queues.get(op.key)
        if not q or q[0] is not op or op.state != QUEUED:
            return False
        op.state = RUNNING
        op.claimed_by = threading.get_ident()
        return True

    def _next_runnable(self, op: ScheduledOp,
                       tid: int) -> Optional[ScheduledOp]:
        """Claim the op this thread should run next on the way to
        ``op``: the head of the queue owning the EARLIEST not-done op
        this thread posted at or before ``op`` (program posting order —
        identical across SPMD processes), else ``op``'s own queue head.
        Returns a CLAIMED op, or None (blocker runs elsewhere)."""
        with self._lock:
            posted = self._posted.get(tid)
            cand = None
            if posted:
                posted[:] = [o for o in posted if o.state != DONE]
                # earliest op this thread posted at or before op is the
                # drain target — but skip ops RUNNING on THIS thread:
                # they sit beneath us on the stack (a nested wait from
                # inside a schedule) and cannot progress until we
                # return, so waiting on them would self-deadlock
                for o in posted:
                    if o.seq > op.seq:
                        break
                    if o.state == RUNNING and o.claimed_by == tid:
                        continue
                    cand = o
                    break
            if cand is None:
                cand = op if op.state != DONE else None
            if cand is None:
                return None
            q = self._queues.get(cand.key)
            head = q[0] if q else None
            if head is not None and self._claim_locked(head):
                return head
            return None

    def wait(self, op: ScheduledOp) -> Any:
        """Complete ``op``: drain earlier same-thread/same-comm ops in
        posting order (polling mode), or park on the completion event
        while another thread — the progress thread, or another waiter —
        runs it. Re-raises the schedule's error; returns its result."""
        if op.t_first_wait is None:
            op.t_first_wait = _time.perf_counter()
        tid = threading.get_ident()
        while not op.done.is_set():
            target = self._next_runnable(op, tid)
            if target is not None:
                self._execute(target)
                continue
            with self._lock:
                evicted = (op.state != DONE
                           and op.seq not in self._inflight)
            if evicted:
                from ..utils.errors import ErrorCode, MPIError

                raise MPIError(
                    ErrorCode.ERR_REQUEST,
                    f"progress engine shut down with schedule "
                    f"'{op.name}' still pending (finalize with "
                    "outstanding nonblocking collectives?)",
                )
            if op.done.wait(0.02):
                break
            self.progress()
        if op.error is not None:
            raise op.error
        return op.result

    def advance_toward(self, op: ScheduledOp) -> int:
        """Nonblocking progress toward ``op`` — the MPI_Test progress
        rule. test() must stay nonblocking (running the whole schedule
        inline could park on peers that have not arrived), yet a
        test-only completion loop must still finish in polling mode
        (the deleted per-comm worker guaranteed background progress).
        So the SECOND consecutive test() on a still-queued schedule
        KICKS an on-demand background drainer for the op's queue —
        execution off the caller, exactly while the caller is
        poll-driven — and every test() also runs the ordinary
        nonblocking (shallow) tick. The second, not the first:
        Request.wait() performs exactly one internal test() before
        blocking, so wait-only users never see a thread (and the
        polling-mode hidden-seconds witness stays exactly 0); only a
        real poll LOOP crosses the threshold."""
        if op.done.is_set():
            return 0
        op.polls += 1
        if op.polls >= 2 and not self.thread_mode() \
                and self.executing() is None:
            self._kick(op)
        return self.progress(deep=False)  # test() must never park

    def _kick(self, op: ScheduledOp) -> None:
        """Ensure one background drainer runs ``op``'s queue until the
        op completes (one drainer per key at a time)."""
        with self._lock:
            if op.state == DONE or op.key in self._kicked:
                return
            self._kicked.add(op.key)
        threading.Thread(target=self._kick_loop, args=(op,),
                         daemon=True,
                         name=f"nbc-kick-{op.name}").start()

    def _kick_loop(self, op: ScheduledOp) -> None:
        try:
            while not op.done.is_set():
                target = None
                with self._lock:
                    if op.state != DONE and op.seq not in self._inflight:
                        return  # evicted (engine shutdown): don't spin
                    q = self._queues.get(op.key)
                    head = q[0] if q else None
                    if head is not None and self._claim_locked(head):
                        target = head
                if target is not None:
                    self._execute(target)
                    continue
                op.done.wait(0.05)
        finally:
            with self._lock:
                self._kicked.discard(op.key)

    def fail_queued(self, key: Any, exc_factory: Callable[[], BaseException]
                    ) -> int:
        """Complete every still-QUEUED op on ``key`` in error WITHOUT
        running it — the ULFM revoke interrupt: schedules posted on a
        revoked communicator must complete in error promptly, and
        running them would only park this process on a poisoned wire
        channel. A RUNNING op is left alone (it owns wire state; its
        own bounded waits surface the revocation within a slice).
        Returns how many ops were failed."""
        failed: List[ScheduledOp] = []
        with self._lock:
            q = self._queues.get(key)
            if not q:
                return 0
            for op in list(q):
                if op.state != QUEUED:
                    continue
                op.state = DONE
                op.error = exc_factory()
                q.remove(op)
                self._inflight.pop(op.seq, None)
                ledger = self._posted.get(op.poster)
                if ledger is not None:
                    try:
                        ledger.remove(op)
                    except ValueError:
                        pass
                    if not ledger:
                        self._posted.pop(op.poster, None)
                failed.append(op)
            if not q:
                self._queues.pop(key, None)
            self._cond.notify_all()
        for op in failed:
            # same completion contract as _execute: callbacks BEFORE
            # the event, so a woken waiter observes the bound request
            # already failed
            for cb in list(op.callbacks):
                try:
                    cb(op)
                except Exception as e:
                    _log.verbose(1, f"nbc completion callback "
                                    f"failed: {e}")
            op.done.set()
        return len(failed)

    def drain_key(self, key: Any) -> None:
        """Complete every posted op on one key, in order (comm free /
        shutdown path: peers participate in the queued collectives, so
        dropping them would strand the fleet). This is a synchronous
        wait: the ops are stamped as waited-on so their runtime never
        counts as hidden (the caller is blocked in free() for exactly
        that duration)."""
        while True:
            with self._lock:
                q = self._queues.get(key)
                head = q[0] if q else None
                if head is None:
                    return
                if head.t_first_wait is None:
                    head.t_first_wait = _time.perf_counter()
                claimed = self._claim_locked(head)
            if claimed:
                self._execute(head)
            else:
                head.done.wait(0.05)

    # -- the tick ----------------------------------------------------------
    def progress(self, deep: bool = True) -> int:
        """One engine tick: complete in-process async requests whose
        arrays became ready and — when ``deep`` — advance the receive
        side of every in-flight op's wire channel (early-transfer
        reap; may ride out one in-flight transfer's tail, which is the
        opal_progress discipline: completing in-flight fragments IS
        the progress). The IMPLICIT hook behind request test()/
        test_all() runs shallow (``deep=False``) so a nonblocking test
        can never park on a mid-stream transfer; deep ticks come from
        explicit calls, the progress thread, and blocked waits, where
        riding a transfer tail is the point. Never executes a schedule
        — execution belongs to wait()/kick drainers (polling) or the
        progress thread — and is reentrancy-safe (a tick from inside a
        tick is a no-op). Returns how many items progressed."""
        if getattr(self._tls, "ticking", False):
            return 0
        self._tls.ticking = True
        rec = _obs.enabled
        t0 = _time.perf_counter() if rec else 0.0
        try:
            _ticks.add()
            n = 0
            if deep:
                with self._lock:
                    pumps = {}
                    for o in self._inflight.values():
                        if o.pump is not None and o.key not in pumps:
                            pumps[o.key] = o.pump
                for fn in pumps.values():
                    try:
                        n += int(fn() or 0)
                    except Exception as e:  # dead channel: not fatal
                        _log.verbose(2, f"progress pump failed: {e}")
            n += self._poll_ready()
            if n and rec and _obs.enabled:
                _obs.record("progress_tick", "nbc", t0,
                            _time.perf_counter() - t0)
            return n
        finally:
            self._tls.ticking = False

    def add_poll(self, req) -> None:
        """Track an in-process async-dispatch Request: ticks (and the
        progress thread) complete it the moment its arrays are ready,
        so completion no longer requires the caller to test(). The
        entry is pruned the moment the request completes through ANY
        path (a bare wait() included) — the registry must not grow
        with collectives that never see a tick."""
        with self._lock:
            token = next(self._seq)
            self._poll[token] = weakref.ref(req)
            self._cond.notify_all()
        req.on_complete(lambda _r: self._discard_poll(token))
        self.ensure_thread()

    def _discard_poll(self, token: int) -> None:
        with self._lock:
            self._poll.pop(token, None)

    def _poll_ready(self) -> int:
        with self._lock:
            items = list(self._poll.items())
        if not items:
            return 0
        completed = 0
        dead = []
        for token, ref in items:
            req = ref()
            done = True  # a collected request needs no more polling
            if req is not None:
                try:
                    done = req.poll()
                except Exception:
                    pass  # surfaced at the request's own wait/test
            if done:
                completed += req is not None
                dead.append(token)
        if dead:
            with self._lock:
                for token in dead:
                    self._poll.pop(token, None)
        return completed

    # -- the opt-in thread -------------------------------------------------
    @staticmethod
    def thread_mode() -> bool:
        return bool(mca_var.get("progress_thread", False))

    def ensure_thread(self) -> None:
        """Start the dedicated progress thread iff the cvar asks for
        one (lazy: posting with the cvar flipped mid-run works; the
        loop retires itself when the cvar flips back off)."""
        if not self.thread_mode():
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive() \
                    and not self._stop.is_set():
                return
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._thread_loop, args=(self._stop,),
                daemon=True, name="nbc-progress",
            )
            self._thread.start()

    def _claim_next(self) -> Optional[ScheduledOp]:
        with self._lock:
            for op in sorted(self._inflight.values(),
                             key=lambda o: o.seq):
                if self._claim_locked(op):
                    return op
        return None

    def _thread_loop(self, stop: threading.Event) -> None:
        while not stop.is_set():
            if not self.thread_mode():
                break  # cvar flipped off: polling mode resumes
            op = self._claim_next()
            if op is not None:
                self._execute(op)
                continue
            self.progress()
            period = max(0.0002, min(
                0.05, int(mca_var.get("progress_poll_us", 500)) / 1e6))
            with self._cond:
                if not self._inflight and not self._poll:
                    self._cond.wait(period * 20)
                else:
                    self._cond.wait(period)
        with self._lock:
            if self._thread is threading.current_thread():
                self._thread = None

    def shutdown(self, timeout: float = 5.0, drain: bool = True) -> None:
        """Finalize-time teardown: stop the thread, DRAIN queued
        schedules (peers participate in them — a rank that posted an
        i-collective, never waited it, and finalized would otherwise
        strand every peer parked in that collective's reap), give
        RUNNING schedules (which own wire state) a bounded wait, then
        clear. The engine stays usable — a later post() re-arms it."""
        with self._lock:
            self._stop.set()
            self._cond.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        if drain:
            while True:
                with self._lock:
                    keys = [k for k, q in self._queues.items() if q]
                if not keys:
                    break
                for key in keys:
                    self.drain_key(key)  # errors land on the ops
        with self._lock:
            running = [o for o in self._inflight.values()
                       if o.state == RUNNING]
        deadline = _time.monotonic() + timeout
        for op in running:
            op.done.wait(max(0.0, deadline - _time.monotonic()))
        with self._lock:
            self._queues.clear()
            self._posted.clear()
            self._inflight.clear()
            self._poll.clear()
            self._thread = None


#: THE engine (opal_progress is process-global; so is this)
ENGINE = ProgressEngine()


def engine() -> ProgressEngine:
    return ENGINE


pvar.PVARS.register(
    "nbc_schedules_inflight", pvar.PvarClass.LEVEL,
    "nonblocking collective schedules posted but not yet complete",
    getter=lambda: ENGINE.inflight_count(),
)

# one shared tick advances EVERY pending request: wait_all/test_all and
# a bare Request.wait() drive the engine through this hook instead of
# spinning per-request or sleeping. SHALLOW tick: the hook runs inside
# nonblocking test paths, which must never ride a mid-stream wire
# transfer's tail — deep (wire-pumping) ticks come from the progress
# thread and blocked waits.
_request.register_progress_hook(lambda: ENGINE.progress(deep=False))

# flight-recorder contributor: the postmortem names every in-flight
# NBC schedule (op, comm, state, who claimed it, how long) — paired
# with coll/hier's round-state table this answers "which nonblocking
# collective is stuck and on whom"
_watchdog.add_contributor("nbc_inflight", lambda: ENGINE.snapshot())
