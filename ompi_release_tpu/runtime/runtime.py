"""Runtime bring-up/teardown — the ``MPI_Init``/``orte_init`` analogue.

Bring-up sequence mirrors ``ompi/runtime/ompi_mpi_init.c:376`` step for
step, collapsed where the TPU runtime already provides the service:

  1. config/core var registration        (opal_init_util)
  2. ESS select + bootstrap              (orte_init/ess.init)
  3. allocation → mesh mapping           (ras/rmaps)
  4. modex                               (grpcomm modex + barrier)
  5. WORLD/SELF communicator creation    (ompi_comm_init)
  6. coll component selection per comm   (mca_coll_base_comm_select)

with the ORTE job state machine activated at each boundary so failures
and observers land exactly where the reference's states are.
"""

from __future__ import annotations

import atexit
import threading
from typing import Any, Dict, List, Optional

from ..mca import var as mca_var
from ..utils import output
from ..utils.errors import ErrorCode, MPIError
from . import ess as ess_mod
from . import mesh as mesh_mod
from .state import JobState, ProcState, StateMachine

_log = output.stream("runtime")
_lock = threading.RLock()


class Runtime:
    """Process-global runtime instance (``ompi_mpi_state`` analogue)."""

    _instance: Optional["Runtime"] = None

    def __init__(self) -> None:
        self.job_state = StateMachine("job")
        self.proc_state = StateMachine("procs")
        self.mesh = None
        self.endpoints: List[mesh_mod.Endpoint] = []
        self.bootstrap: Dict[str, Any] = {}
        self.agent = None  # tpurun WorkerAgent (set by ess/tpurun)
        self.world = None
        self.self_comm = None
        self.initialized = False
        self.finalized = False
        # unified multi-controller world (tpurun): this process owns
        # world ranks [local_rank_offset, local_rank_offset+local_size)
        # and reaches every other process's ranks through the wire
        self.unified = False
        self.local_rank_offset = 0
        self.local_size = 0
        self.proc_spans: List[tuple] = []
        self.wire = None  # WireRouter when unified

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def current(cls) -> "Runtime":
        with _lock:
            if cls._instance is None:
                cls._instance = Runtime()
            return cls._instance

    @classmethod
    def is_initialized(cls) -> bool:
        with _lock:
            return cls._instance is not None and cls._instance.initialized

    def init(self, cli_args: Optional[List[str]] = None,
             devices=None, mesh_shape=None, axis_names=None) -> "Any":
        with _lock:
            if self.initialized:
                return self.world
            if self.finalized:
                raise MPIError(
                    ErrorCode.ERR_OTHER,
                    "runtime re-init after finalize is not supported "
                    "(matches MPI_Init-after-MPI_Finalize)",
                )

            # 1. core vars + CLI
            mesh_mod.register_vars()
            from .wire import register_vars as _wire_register_vars

            _wire_register_vars()  # wire transport cvars: visible to
            #                        tpu_info/CLI even in singleton mode
            from .progress import register_vars as _progress_vars

            _progress_vars()  # async progress engine cvars
            #                   (progress_thread / progress_poll_us)
            mca_var.register(
                "runtime_abort_on_error", "bool", True,
                "Abort the process on unhandled MPI errors "
                "(MPI_ERRORS_ARE_FATAL default)",
            )
            mca_var.register(
                "runtime_unified_world", "bool", True,
                "Under tpurun, form ONE COMM_WORLD spanning every "
                "worker process (cross-process ranks reachable through "
                "the wire router); false = each process's world spans "
                "only its local devices (pre-unification behavior)",
            )
            mca_var.register(
                "runtime_timing", "bool", False,
                "Report per-stage init timing after bring-up (the "
                "ompi_timing var, ompi_mpi_init.c:366-371,617-625)",
            )
            if cli_args:
                pairs = _parse_mca_cli(cli_args)
                mca_var.VARS.apply_cli(pairs)

            # observability plane hooks (cold path; one attr check when
            # off): re-derive the stall-watchdog gate now that CLI/env
            # cvars are final, and install the SIGUSR1/fatal-signal
            # flight-recorder dumps
            from .. import obs as _obs

            if _obs.enabled:
                from ..obs import watchdog as _obs_watchdog

                _obs_watchdog.refresh(True)
                _obs_watchdog.install_signal_handlers()

            self.job_state.activate(JobState.INIT)

            # 2. ESS bootstrap (identity + device discovery). Under
            # tpurun this runs the coordinator wire-up: OOB modex, tree
            # links, init barrier, heartbeats (ompi_mpi_init.c:630-642)
            ess = ess_mod.ESS_FRAMEWORK.select()
            self.bootstrap = ess.bootstrap()
            self.agent = self.bootstrap.get("agent")  # tpurun WorkerAgent
            self.job_state.activate(JobState.ALLOCATE, self.bootstrap)

            if self.agent is not None:
                # ULFM detection plane: TAG_PROC_FAILED epoch notices
                # and TAG_FT_REVOKE poison frames feed the process-
                # local failure picture the wire router's bounded
                # waits consult — armed before the first collective so
                # a failure during bring-up is already visible
                from ..ft import ulfm as _ulfm

                _ft = _ulfm.state()
                self.agent.start_ft_watcher(_ft.apply_notice,
                                            _ft.apply_revoke)

            if _obs.enabled and self.agent is not None:
                # estimate the clock offset NOW, not only at finalize:
                # a hung job killed mid-run leaves postmortems as its
                # only artifact, and without an offset their merged
                # timeline is garbage across controllers (finalize
                # re-estimates for the journal dump; drift over one
                # job is negligible next to OOB rtt)
                try:
                    off, rtt = self.agent.clock_sync()
                    _obs.set_clock(off, rtt)
                except Exception as e:
                    _log.verbose(1, f"obs clock sync skipped: {e}")
            if _obs.enabled:
                # arm the continuous pvar sampler (the fleet metrics
                # plane) — no-op unless obs_sample_interval > 0, and
                # the clock offset above is already in place so pushed
                # series points merge onto the HNP timeline
                try:
                    from ..obs import sampler as _obs_sampler

                    _obs_sampler.maybe_start(self)
                except Exception as e:
                    _log.verbose(1, f"obs sampler start skipped: {e}")
                # arm the online re-tuner on the sampler's tick hook
                # (no-op unless tune_online is set): sustained slow
                # links -> bounded micro-probe -> cvar-applied rule
                try:
                    from ..tuning import retune as _retune

                    _retune.maybe_start(self)
                except Exception as e:
                    _log.verbose(1, f"online retune arm skipped: {e}")

            # 3. mesh mapping
            self.mesh = mesh_mod.build_mesh(
                devices=devices or self.bootstrap["devices"],
                shape=mesh_shape,
                axis_names=axis_names,
            )
            self.job_state.activate(JobState.MAP, self.mesh)
            self.job_state.activate(JobState.VM_READY)

            # 4. modex (endpoint allgather) — PROCESS/NODE boundary in the
            # reference (ompi_mpi_init.c:630-642). Peer PROCESSES' host
            # identities come from their modex cards (run_modex only
            # knows this process's hostname). The card->endpoint overlay
            # is only meaningful under a REAL multi-controller runtime
            # (jax.distributed), where device.process_index enumerates
            # the jax processes and tpurun launches one process per
            # jax process (node i+1 <-> process i). Without
            # jax.distributed every device reports process_index 0, so
            # applying the overlay would stamp node 1's hostname onto
            # every endpoint — skip it and keep run_modex's honest
            # local-only host labels.
            self.endpoints = mesh_mod.run_modex(self.mesh)
            peer_cards = self.bootstrap.get("peer_cards") or []
            import jax as _jax

            unified = (
                self.agent is not None
                and len(peer_cards) > 1
                and bool(mca_var.get("runtime_unified_world", True))
                and _jax.process_count() == 1  # separate controllers
                and all("local_device_count" in c for c in peer_cards)
            )
            if unified:
                self._build_unified_world(peer_cards)
            elif (peer_cards and _jax.process_count() > 1
                    and len(peer_cards) == _jax.process_count()
                    and any("host" in c for c in peer_cards)):
                import dataclasses as _dc

                self.endpoints = [
                    _dc.replace(
                        ep, host=peer_cards[ep.process_index]["host"]
                    ) if peer_cards[ep.process_index].get("host") else ep
                    for ep in self.endpoints
                ]
            self.job_state.activate(JobState.RUNNING)

            # 5-6. communicators + per-comm coll selection
            from ..comm import world as comm_world

            self.world, self.self_comm = comm_world.create_world(self)
            self.job_state.activate(JobState.REGISTERED)

            # async progress engine: arm the dedicated thread when the
            # operator opted in (lazy posts also arm it; this makes the
            # opt-in effective from the first collective)
            from . import progress as _progress

            _progress.engine().ensure_thread()

            self.initialized = True
            _log.verbose(
                1,
                f"initialized: {len(self.endpoints)} ranks on "
                f"{self.mesh.devices.shape} mesh",
            )
            if mca_var.get("runtime_timing", False):
                self._report_init_timing()
            return self.world

    def _report_init_timing(self) -> None:
        """The ``ompi_timing`` report: per-stage durations from the
        job state machine's timestamped history (the reference prints
        coarse init-phase timings when the var is set,
        ``ompi_mpi_init.c:435-437,617-625``)."""
        hist = self.job_state.history()
        if len(hist) < 2:
            return
        total = (hist[-1][0] - hist[0][0]) * 1e3
        _log.info(f"init timing (total {total:.1f} ms):")
        for (t0, s0, _), (t1, _, _) in zip(hist, hist[1:]):
            name = self.job_state._fmt(s0)
            _log.info(f"  {name:<14} {(t1 - t0) * 1e3:8.1f} ms")

    def _build_unified_world(self, peer_cards: List[Dict]) -> None:
        """Form the union world: every process's devices become world
        ranks (process p owns a contiguous span), with peer-process
        ranks represented by endpoints synthesized from their modex
        cards — the ``add_procs``-over-all-peers step of
        ``ompi_mpi_init.c:759-786``. Cross-process pairs are reached
        through the wire router (shm handoff on one host, DCN staging
        across hosts), never by a fake ``device_put``."""
        import dataclasses as _dc

        from .wire import WireRouter

        my_pidx = int(self.bootstrap["process_index"])
        counts = [int(c["local_device_count"]) for c in peer_cards]
        local_eps = self.endpoints
        if counts[my_pidx] != len(local_eps):
            raise MPIError(
                ErrorCode.ERR_OTHER,
                f"unified world needs the full local device set: modex "
                f"card advertised {counts[my_pidx]} devices but the "
                f"mesh holds {len(local_eps)} (explicit device subsets "
                "are incompatible with runtime_unified_world)",
            )
        offsets = [0] * len(counts)
        for p in range(1, len(counts)):
            offsets[p] = offsets[p - 1] + counts[p - 1]
        endpoints: List[mesh_mod.Endpoint] = []
        for p, card in enumerate(peer_cards):
            if p == my_pidx:
                endpoints.extend(
                    _dc.replace(ep, rank=offsets[p] + ep.rank,
                                process_index=p)
                    for ep in local_eps
                )
            else:
                endpoints.extend(
                    mesh_mod.Endpoint(
                        rank=offsets[p] + li,
                        device_id=li,
                        process_index=p,
                        platform=str(card.get("platform", "unknown")),
                        device_kind="peer-process",
                        coords=(li,),
                        slice_index=0,
                        host=str(card.get("host", "")),
                    )
                    for li in range(counts[p])
                )
        self.endpoints = endpoints
        self.unified = True
        self.local_rank_offset = offsets[my_pidx]
        self.local_size = counts[my_pidx]
        self.proc_spans = [(offsets[p], counts[p])
                           for p in range(len(counts))]
        self.wire = WireRouter(self)
        _log.verbose(
            1,
            f"unified world: {sum(counts)} ranks over "
            f"{len(counts)} processes; local span "
            f"[{self.local_rank_offset}, "
            f"{self.local_rank_offset + self.local_size})",
        )

    def finalize(self) -> None:
        with _lock:
            if not self.initialized or self.finalized:
                return
            from .. import obs as _obs

            if _obs.enabled:
                # disarm the sampler FIRST (its final tick + push run
                # over the still-live HNP link), then the per-rank
                # journal + series dumps (obs_dump_dir) BEFORE the
                # agent closes: the clock-offset estimate in their
                # meta needs the live HNP link
                try:
                    from ..tuning import retune as _retune

                    _retune.stop()
                except Exception as e:
                    _log.verbose(1, f"online retune stop failed: {e}")
                try:
                    from ..obs import sampler as _obs_sampler

                    _obs_sampler.stop(final_push=True)
                except Exception as e:
                    _log.verbose(1, f"obs sampler stop failed: {e}")
                try:
                    from ..obs import export as _obs_export

                    _obs_export.maybe_dump_rank_journal(self)
                    _obs_export.maybe_dump_series(self)
                    _obs_export.maybe_dump_ledger(self)
                    _obs_export.maybe_dump_nativeev(self)
                except Exception as e:
                    _log.verbose(1, f"obs rank-journal dump failed: {e}")
            # stop the async progress engine BEFORE communicators are
            # torn down: a schedule running on the progress thread
            # still uses the comm registry and the wire
            from . import progress as _progress

            _progress.engine().shutdown()
            from ..comm import communicator as comm_mod
            from ..comm import dpm as dpm_mod

            dpm_mod.clear()
            comm_mod.clear_comm_registry()
            svc = getattr(self, "_win_service", None)
            if svc is not None:
                svc.stop()
                self._win_service = None
            if self.agent is not None:
                # report clean completion to the HNP (IOF_COMPLETE ->
                # TERMINATED flow of plm_types.h:113-151) and drop the
                # lifeline deliberately
                try:
                    self.agent.send_fin()
                except Exception:
                    pass
                self.agent.close()
                self.agent = None
            self.job_state.activate(JobState.TERMINATED)
            self.finalized = True
            self.initialized = False
            # keep the instance so a later init() hits the
            # re-init-after-finalize guard (MPI semantics) instead of
            # silently building a fresh runtime

    # -- queries -----------------------------------------------------------
    @property
    def world_size(self) -> int:
        return len(self.endpoints)


def _parse_mca_cli(argv: List[str]) -> List[tuple]:
    """Extract ``--mca key value`` pairs (orterun CLI analogue)."""
    pairs = []
    i = 0
    while i < len(argv):
        if argv[i] == "--mca" and i + 2 < len(argv):
            pairs.append((argv[i + 1], argv[i + 2]))
            i += 3
        else:
            i += 1
    return pairs


def init(cli_args: Optional[List[str]] = None, **kw):
    """Module-level MPI_Init analogue; returns COMM_WORLD."""
    return Runtime.current().init(cli_args=cli_args, **kw)


def finalize() -> None:
    rt = Runtime._instance
    if rt is not None:
        rt.finalize()


atexit.register(finalize)
