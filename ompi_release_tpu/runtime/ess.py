"""ESS — environment-specific bootstrap (``orte/mca/ess`` analogue).

How does this process learn its identity and device set? The reference
has one component per launch environment (env/singleton/pmi/slurm...,
``orte/mca/ess/``). Here:

  - ``singleton``: one controller process owning all locally-visible
    devices (the common JAX case; ``ess/singleton`` analogue).
  - ``distributed``: multi-controller via ``jax.distributed`` —
    coordinator address/rank from env (the ``ess/env``+``ess/pmi``
    analogue; the jax coordinator service replaces the orted tree).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from ..mca import component as mca_component
from ..mca import var as mca_var
from ..utils import output

_log = output.stream("ess")


def read_stdin_secret(stream) -> str:
    """One line of ``stream`` as the job secret (OMPITPU_SECRET_STDIN
    rsh handoff). An empty line / EOF means the launcher died or the
    pipe was misplumbed — that MUST fail the launch loudly: silently
    proceeding would disable auth on this endpoint and surface later
    as an inexplicable connect hang against the authenticated HNP."""
    from ..utils.errors import ErrorCode, MPIError

    secret = stream.readline().strip()
    if not secret:
        raise MPIError(
            ErrorCode.ERR_OTHER,
            "OMPITPU_SECRET_STDIN=1 but stdin closed before a job "
            "secret arrived (launcher died, or the rsh pipe was not "
            "plumbed) — refusing to start with auth silently disabled",
        )
    return secret


class SingletonEss(mca_component.Component):
    """Single-controller bootstrap: all visible devices, process 0."""

    NAME = "singleton"
    PRIORITY = 10

    def bootstrap(self):
        import jax

        return {
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "devices": jax.devices(),
            "local_devices": jax.local_devices(),
        }


class DistributedEss(mca_component.Component):
    """Multi-host bootstrap through the jax.distributed coordinator.

    Selected when coordinator env vars are present (the analogue of
    ess/env detecting mpirun's environment variables).
    """

    NAME = "distributed"
    PRIORITY = 50

    def register_vars(self) -> None:
        mca_var.register(
            "ess_distributed_coordinator", "str",
            os.environ.get("OMPITPU_COORDINATOR", ""),
            "host:port of the jax.distributed coordinator service",
        )
        mca_var.register(
            "ess_distributed_process_id", "int",
            int(os.environ.get("OMPITPU_PROCESS_ID", "-1")),
            "this controller's process id within the job (-1 = unset)",
        )
        mca_var.register(
            "ess_distributed_num_processes", "int",
            int(os.environ.get("OMPITPU_NUM_PROCESSES", "0")),
            "total controller processes in the job",
        )

    def query(self, ctx=None):
        if not mca_var.get("ess_distributed_coordinator"):
            return None  # not launched under a coordinator
        return (self.priority, self)

    def bootstrap(self):
        import jax

        coord = mca_var.get("ess_distributed_coordinator")
        pid = mca_var.get("ess_distributed_process_id")
        nprocs = mca_var.get("ess_distributed_num_processes")
        _log.verbose(1, f"jax.distributed.initialize({coord}, {nprocs}, {pid})")
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=nprocs if nprocs > 0 else None,
            process_id=pid if pid >= 0 else None,
        )
        return {
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "devices": jax.devices(),
            "local_devices": jax.local_devices(),
        }


class TpurunEss(mca_component.Component):
    """Bootstrap for processes launched by ``tpurun`` (the ess/env
    analogue: mpirun-launched procs detect the daemon's env vars,
    ``orte/mca/ess/env/ess_env_module.c:87``).

    Runs the FULL coordinator wire-up inside bring-up: JOIN + modex
    through the HNP, binomial tree link setup, the init barrier, and
    the heartbeat thread — so ``Runtime.init`` under tpurun flows
    through the OOB exactly like ``ompi_mpi_init.c:630-642,811`` flows
    through the daemon tree.
    """

    NAME = "tpurun"
    PRIORITY = 60  # above distributed: tpurun's env is more specific

    def register_vars(self) -> None:
        mca_var.register(
            "ess_tpurun_heartbeat_interval", "float", 0.5,
            "Seconds between worker heartbeats to the HNP "
            "(sensor_heartbeat.c:61 analogue)",
        )

    def query(self, ctx=None):
        if not os.environ.get("OMPITPU_HNP"):
            return None
        return (self.priority, self)

    def bootstrap(self):
        import jax

        from . import coordinator as coord

        host, port = os.environ["OMPITPU_HNP"].rsplit(":", 1)
        node_id = int(os.environ["OMPITPU_NODE_ID"])
        num_workers = int(os.environ["OMPITPU_NUM_NODES"])
        import socket

        if (os.environ.get("OMPITPU_SECRET_STDIN") == "1"
                and not os.environ.get("OMPITPU_JOB_SECRET")):
            # rsh launches ship the job secret on stdin (a command-line
            # env assignment would be world-readable via /proc); it
            # must land before the first endpoint is created
            import sys as _sys

            os.environ["OMPITPU_JOB_SECRET"] = \
                read_stdin_secret(_sys.stdin)
        agent = coord.WorkerAgent(node_id, host, int(port))
        card = {
            "node_id": node_id,
            "pid": os.getpid(),
            # shm-reachability identity. OMPITPU_HOST_ID overrides the
            # UTS hostname: two containers can SHARE a hostname while
            # having separate /dev/shm (shm handoffs between them would
            # fail), and conversely test rigs use it to exercise the
            # DCN staging path on one machine — the btl_tcp_if_include
            # style of deployment knob
            "host": os.environ.get("OMPITPU_HOST_ID")
                    or socket.gethostname(),
            "local_device_count": jax.local_device_count(),
            "platform": jax.local_devices()[0].platform,
        }
        try:
            # nativewire capability advertisement (ring token/geometry):
            # a probe failure just means the card stays portable-only
            from ..btl import nativewire as _nativewire

            card.update(_nativewire.modex_entry())
        except Exception:
            pass
        cards = agent.run_modex(card)  # launcher mode: workers only
        agent.setup_tree(num_workers + 1, cards)
        # FULL wire-up (superset of the tree edges): connect to every
        # lower-id peer so ANY worker pair holds a live OOB link — the
        # data plane the unified COMM_WORLD's cross-process transports
        # (runtime/wire.py) ride. The HIGHER id dials (same asymmetry
        # as tree links, where the child dials its parent); the lower
        # side's sends ride the accepted fd. The init barrier below
        # gates until every link is live.
        parent = coord.binomial_parent(node_id)
        from ..utils.errors import MPIError as _MPIError

        recovery = os.environ.get("OMPITPU_RECOVERY") == "1"
        for nid in range(1, node_id):
            if nid == parent:
                continue  # tree link already exists
            peer = cards[nid - 1]
            try:
                agent.ep.connect(nid, peer["oob_host"],
                                 int(peer["oob_port"]))
            except _MPIError:
                if not recovery:
                    # default policy: a dead peer address (typo'd
                    # hostfile, firewalled port) must fail the launch
                    # loudly, not surface later as a missing link
                    raise
                # resilient policy: the peer may have finished or be
                # mid-restart — the wire router raises a clear
                # ERR_UNREACH if this link is ever actually used
                _log.verbose(
                    1, f"wire-up: peer {nid} unreachable at "
                       f"{peer['oob_host']}:{peer['oob_port']} "
                       "(finished or restarting); continuing without "
                       "the link",
                )
        agent.barrier()  # every tree+wire edge live; init gate
        agent.start_heartbeats(
            float(mca_var.get("ess_tpurun_heartbeat_interval", 0.5))
        )
        _log.verbose(
            1, f"tpurun bootstrap: node {node_id}/{num_workers} wired"
        )
        return {
            "process_index": node_id - 1,
            "process_count": num_workers,
            "devices": jax.devices(),
            "local_devices": jax.local_devices(),
            "agent": agent,
            "peer_cards": cards,
        }


ESS_FRAMEWORK = mca_component.framework(
    "ess", "environment-specific bootstrap (orte/mca/ess analogue)"
)
ESS_FRAMEWORK.register(SingletonEss())
ESS_FRAMEWORK.register(DistributedEss())
ESS_FRAMEWORK.register(TpurunEss())
