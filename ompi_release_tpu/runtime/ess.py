"""ESS — environment-specific bootstrap (``orte/mca/ess`` analogue).

How does this process learn its identity and device set? The reference
has one component per launch environment (env/singleton/pmi/slurm...,
``orte/mca/ess/``). Here:

  - ``singleton``: one controller process owning all locally-visible
    devices (the common JAX case; ``ess/singleton`` analogue).
  - ``distributed``: multi-controller via ``jax.distributed`` —
    coordinator address/rank from env (the ``ess/env``+``ess/pmi``
    analogue; the jax coordinator service replaces the orted tree).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from ..mca import component as mca_component
from ..mca import var as mca_var
from ..utils import output

_log = output.stream("ess")


class SingletonEss(mca_component.Component):
    """Single-controller bootstrap: all visible devices, process 0."""

    NAME = "singleton"
    PRIORITY = 10

    def bootstrap(self):
        import jax

        return {
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "devices": jax.devices(),
            "local_devices": jax.local_devices(),
        }


class DistributedEss(mca_component.Component):
    """Multi-host bootstrap through the jax.distributed coordinator.

    Selected when coordinator env vars are present (the analogue of
    ess/env detecting mpirun's environment variables).
    """

    NAME = "distributed"
    PRIORITY = 50

    def register_vars(self) -> None:
        mca_var.register(
            "ess_distributed_coordinator", "str",
            os.environ.get("OMPITPU_COORDINATOR", ""),
            "host:port of the jax.distributed coordinator service",
        )
        mca_var.register(
            "ess_distributed_process_id", "int",
            int(os.environ.get("OMPITPU_PROCESS_ID", "-1")),
            "this controller's process id within the job (-1 = unset)",
        )
        mca_var.register(
            "ess_distributed_num_processes", "int",
            int(os.environ.get("OMPITPU_NUM_PROCESSES", "0")),
            "total controller processes in the job",
        )

    def query(self, ctx=None):
        if not mca_var.get("ess_distributed_coordinator"):
            return None  # not launched under a coordinator
        return (self.priority, self)

    def bootstrap(self):
        import jax

        coord = mca_var.get("ess_distributed_coordinator")
        pid = mca_var.get("ess_distributed_process_id")
        nprocs = mca_var.get("ess_distributed_num_processes")
        _log.verbose(1, f"jax.distributed.initialize({coord}, {nprocs}, {pid})")
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=nprocs if nprocs > 0 else None,
            process_id=pid if pid >= 0 else None,
        )
        return {
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "devices": jax.devices(),
            "local_devices": jax.local_devices(),
        }


ESS_FRAMEWORK = mca_component.framework(
    "ess", "environment-specific bootstrap (orte/mca/ess analogue)"
)
ESS_FRAMEWORK.register(SingletonEss())
ESS_FRAMEWORK.register(DistributedEss())
