"""Shared pubsub wire protocol — one implementation for every server.

The name-service protocol (seq-correlated publish/lookup/unpublish
frames over the OOB, parked lookups with client-supplied TTLs) is
served by TWO hosts: a tpurun job's HNP (``coordinator.py``, the
pubsub_orte role for the job's own workers) and the standalone
cross-job ``tpu-server`` (the orte-server role). Both instantiate
:class:`PubsubTable` and drive :func:`serve_once`; clients share
:func:`pubsub_rpc`. One wire format, one parking/pruning policy — a
protocol change lands in exactly one place.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Tuple

from ..native import DssBuffer
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("pubsub")

TAG_PUBLISH = 9       # client->server: publish service name
TAG_LOOKUP = 10       # client->server: lookup service name
TAG_PUBSUB_REPLY = 11  # server->client: response (seq-correlated)
TAG_UNPUBLISH = 12    # client->server: unpublish service name

SERVE_TAGS = (TAG_PUBLISH, TAG_LOOKUP, TAG_UNPUBLISH)


class PubEntry:
    """One published name: value + OWNER identity (the publishing
    client's node id — the handle evictions key on) + optional
    expiry. Owner/TTL are the multi-tenant hygiene additions: a dead
    tenant's stale names must never be looked up by the next tenant,
    so entries die with their owner's lifeline/lease or with their
    TTL, whichever comes first."""

    __slots__ = ("value", "owner", "expire_at")

    def __init__(self, value: str, owner: Optional[int] = None,
                 expire_at: Optional[float] = None) -> None:
        self.value = value
        self.owner = owner
        self.expire_at = expire_at

    def expired(self, now: float) -> bool:
        return self.expire_at is not None and now >= self.expire_at


class PubsubTable:
    """Server-side name table + parked lookups (pubsub_orte core)."""

    def __init__(self, ep) -> None:
        self.ep = ep
        self.names: Dict[str, PubEntry] = {}
        # service -> [(client_id, seq, expire_at)]
        self.waiters: Dict[str, List[Tuple[int, int, float]]] = {}
        #: guards names/waiters: the serve thread owns almost every
        #: access, but ``evict_owner`` is called cross-thread (the
        #: HNP's FT path on worker lifeline loss, a daemon eviction
        #: listener) and must not race prune()/handle() mid-mutation
        self._table_lock = threading.RLock()
        # per-instance so subclasses can serve extra RPCs (the
        # tpu_server metrics page) without widening every host
        self.serve_tags: List[int] = list(SERVE_TAGS)

    def _reply(self, nid: int, seq: int, ok: bool, value: str) -> None:
        frame = DssBuffer()
        frame.pack_int64(seq)
        frame.pack_int64(1 if ok else 0)
        frame.pack_string(value)
        try:
            self.ep.send(nid, TAG_PUBSUB_REPLY, frame.tobytes())
        except MPIError:
            _log.verbose(1, f"pubsub reply to {nid} failed")

    def prune(self) -> None:
        """Drop parked lookups whose client gave up (the lookup frame
        carries the client's deadline, so abandoned waiters cannot
        accumulate) AND published entries past their TTL — prune runs
        every serve iteration, so expiry is enforced continuously,
        not only at the next lookup."""
        now = time.monotonic()
        with self._table_lock:
            for service in list(self.waiters):
                alive = [w for w in self.waiters[service]
                         if w[2] > now]
                if alive:
                    self.waiters[service] = alive
                else:
                    del self.waiters[service]
            for service in [s for s, e in self.names.items()
                            if e.expired(now)]:
                del self.names[service]
                _log.verbose(1, f"pruned expired name '{service}'")

    def evict_owner(self, owner: int) -> List[str]:
        """Drop every name published by ``owner`` — the lifeline-loss
        / lease-expiry hook (HNP worker death, daemon tenant
        eviction). Returns the evicted service names. Parked waiters
        on those names stay parked: their own TTLs bound them, and a
        re-publish by a live owner still unparks them."""
        with self._table_lock:
            gone = [s for s, e in self.names.items()
                    if e.owner == owner]
            for service in gone:
                del self.names[service]
        if gone:
            _log.verbose(1, f"evicted {len(gone)} name(s) of dead "
                            f"owner {owner}: {gone}")
        return gone

    def publish_local(self, service: str, value: str,
                      owner: Optional[int] = None,
                      ttl_s: Optional[float] = None) -> bool:
        """Server-side publish (the daemon's own entries). False on a
        live duplicate."""
        now = time.monotonic()
        with self._table_lock:
            existing = self.names.get(service)
            if existing is not None and not existing.expired(now):
                return False
            self.names[service] = PubEntry(
                value, owner,
                now + float(ttl_s) if ttl_s is not None else None)
            unpark = self.waiters.pop(service, [])
        for wnid, wseq, _exp in unpark:
            self._reply(wnid, wseq, True, value)
        return True

    def handle(self, tag: int, src: int, raw: bytes) -> None:
        b = DssBuffer(raw)
        (seq,) = b.unpack_int64()
        service = b.unpack_string()
        now = time.monotonic()
        if tag == TAG_PUBLISH:
            port = b.unpack_string()
            # optional trailing TTL field (newer clients); absence —
            # an exhausted buffer — is the legacy no-TTL publish
            ttl_s = None
            try:
                ttl_ms = int(b.unpack_string())
                if ttl_ms > 0:
                    ttl_s = ttl_ms / 1000
            except (MPIError, ValueError):
                pass
            with self._table_lock:
                existing = self.names.get(service)
                if existing is not None and not existing.expired(now):
                    self._reply(src, seq, False, "already published")
                    return
                # the publisher's node id IS the owner identity:
                # evictions (owner lifeline loss, tenant lease
                # expiry) key on it
                self.names[service] = PubEntry(
                    port, src,
                    now + ttl_s if ttl_s is not None else None)
                unpark = self.waiters.pop(service, [])
            self._reply(src, seq, True, port)
            for wnid, wseq, _exp in unpark:
                self._reply(wnid, wseq, True, port)
        elif tag == TAG_UNPUBLISH:
            with self._table_lock:
                ok = self.names.pop(service, None) is not None
            self._reply(src, seq, ok, service)
        else:  # TAG_LOOKUP
            ttl_ms = int(b.unpack_string())
            with self._table_lock:
                entry = self.names.get(service)
                if entry is not None and entry.expired(now):
                    # lazy expiry backstop
                    self.names.pop(service, None)
                    entry = None
                if entry is None:
                    expire = time.monotonic() + ttl_ms / 1000
                    self.waiters.setdefault(service, []).append(
                        (src, seq, expire)
                    )
            if entry is not None:
                self._reply(src, seq, True, entry.value)

    def serve_once(self, timeout_ms: int = 50) -> None:
        """One serve iteration: prune, then drain one frame per tag.
        One malformed frame must not kill the service."""
        self.prune()
        for tag in self.serve_tags:
            try:
                src, _, raw = self.ep.recv(tag=tag,
                                           timeout_ms=timeout_ms)
            except MPIError:
                continue
            try:
                self.handle(tag, src, raw)
            except Exception as exc:
                _log.verbose(1, f"dropping bad pubsub frame from "
                                f"{src}: {exc}")

    def serve_loop(self, stop: threading.Event) -> None:
        while not stop.is_set():
            self.serve_once()


def pubsub_rpc(ep, lock: threading.Lock, seq_holder, tag: int,
               *fields: str, server_id: int = 0,
               timeout_ms: int = 10_000) -> Tuple[bool, str]:
    """Client side: send one request, wait for OUR seq's reply.

    Concurrent RPCs on one endpoint do NOT serialize behind each
    other: replies are demultiplexed by seq through a shared stash —
    one thread at a time plays receiver (condition-variable handoff),
    parks replies that belong to other outstanding RPCs, and wakes
    their owners. A publish issued while another thread's lookup is
    parked server-side therefore completes immediately (and typically
    unparks that very lookup) instead of waiting out its timeout.

    ``lock`` protects only seq allocation + the request send (frame
    ordering); ``seq_holder`` is any object with a mutable
    ``pubsub_seq`` int attribute."""
    with lock:
        # mux creation under the lock: two first-RPC threads racing an
        # unsynchronized check-then-set would mint two muxes and strand
        # one thread's replies in the orphaned stash
        state = getattr(ep, "_pubsub_mux", None)
        if state is None:
            state = ep._pubsub_mux = {
                "cond": threading.Condition(),
                "replies": {},      # seq -> (ok, value)
                "receiving": False,  # one thread owns the recv at a time
            }
        seq_holder.pubsub_seq = getattr(seq_holder, "pubsub_seq", 0) + 1
        seq = seq_holder.pubsub_seq
        frame = DssBuffer()
        frame.pack_int64(seq)
        for f in fields:
            frame.pack_string(f)
        ep.send(server_id, tag, frame.tobytes())
    cond = state["cond"]
    deadline = time.monotonic() + timeout_ms / 1000
    while True:
        with cond:
            if seq in state["replies"]:
                ok, value = state["replies"].pop(seq)
                return bool(ok), value
            left = deadline - time.monotonic()
            if left <= 0:
                raise MPIError(
                    ErrorCode.ERR_PENDING,
                    f"pubsub rpc seq={seq} timed out",
                )
            if state["receiving"]:
                # another thread is on the wire; it will park our
                # reply and wake us
                cond.wait(timeout=min(left, 0.5))
                continue
            state["receiving"] = True
        got_seq = None
        try:
            left_ms = max(1, int((deadline - time.monotonic()) * 1000))
            _, _, raw = ep.recv(tag=TAG_PUBSUB_REPLY,
                                timeout_ms=min(left_ms, 500))
            try:
                b = DssBuffer(raw)
                (got_seq,) = b.unpack_int64()
                (ok,) = b.unpack_int64()
                value = b.unpack_string()
            except Exception:
                # one garbled reply frame must cost only that frame —
                # never wedge the receiver handoff for the process
                _log.verbose(1, "dropping malformed pubsub reply")
                got_seq = None
        except MPIError:
            if time.monotonic() >= deadline:
                with cond:
                    state["receiving"] = False
                    cond.notify_all()
                raise MPIError(
                    ErrorCode.ERR_PENDING,
                    f"pubsub rpc seq={seq} timed out",
                )
        with cond:
            state["receiving"] = False
            if got_seq is not None:
                if got_seq == seq:
                    cond.notify_all()
                    return bool(ok), value
                # another outstanding RPC's reply: park it and wake
                # its owner; cap the stash so replies to long-dead
                # RPCs cannot accumulate
                state["replies"][int(got_seq)] = (int(ok), value)
                if len(state["replies"]) > 64:
                    state["replies"].pop(next(iter(state["replies"])))
            cond.notify_all()
