"""Cross-process data plane — the unified-COMM_WORLD wire router.

The reference's core runtime promise is that after launch every rank
reaches every rank through one API: ``ompi_mpi_init.c:759-786`` calls
``add_procs`` over *all* peers, and an ``MPI_Send`` crosses nodes
through ``btl/tcp`` (``btl_tcp_component.c:883-893``) with no
caller-visible difference from shared memory. Under ``tpurun`` each
worker process owns only its local jax devices, so cross-process
traffic cannot be a ``device_put`` — it rides the honest transports:
:class:`~..btl.components.ShmBtl` single-segment handoffs on the same
host, :class:`~..btl.components.DcnBtl` chunked OOB staging across
hosts. This router is the glue that lets the PML and the hierarchical
collectives use those transports *through the public API*:

- every worker holds a live OOB link to every peer (full wire-up runs
  during the ESS bootstrap, gated by the init barrier);
- p2p messages are an envelope frame (cid, src/dst comm ranks, user
  tag, sync flag, seq, delivery order) followed by the btl payload on
  a per-(destination, lane) channel tag — the receiving process drains
  its channels into the normal PML matching queues, so ordering and
  wildcards keep MPI semantics;
- collectives get per-communicator payload and control channels used
  by the ``hier`` coll component for the inter-process combine step.

**Pipelined wire transport** (the ob1 RNDV-pipeline role,
``pml_ob1_sendreq.c:785``): payloads above ``wire_pipeline_segsize``
cross as a stream of fixed-size fragments sliced straight off the
source buffer (memoryview, no monolithic ``tobytes()`` — see
``DcnBtl.staged_frames``), reassembled into a preallocated buffer at
each fragment's own offset on the receiver. ``wire_pipeline_segsize=0``
restores the exact legacy single-pass framing.

**Channel concurrency**: the old coarse ``("send", dst)`` /
``("drain", dst)`` locks serialized every tag behind one destination
stream — the head-of-line blocking the previous revision of this file
documented. Tags now hash onto ``wire_p2p_lanes`` per-destination
lanes, each with its own channel tag and lock, so independent tags and
comms no longer queue behind each other's large transfers. MPI's
non-overtaking rule survives lane reordering through a per-(sender
process, destination rank) delivery order stamped in the envelope: a
transfer may COMPLETE out of order, but messages enter the PML
matching queues in send order. ``wire_hol_wait_seconds`` times what is
left of the head-of-line wait.

Channel tags live far above ``USER_TAG_BASE`` so they can never shadow
the coordinator/pubsub control plane or hand-rolled staged transfers.

Thread model: driver-mode processes issue wire operations from the
main thread (plus completion threads polling acks and the nbc worker);
the ack set, sequence/order counters, reorder buffers, and the early
collective-transfer queue are lock-protected; payload channels rely on
the per-(src, tag) FIFO the OOB provides plus the shared stash in
``btl.components.stashed_recv``.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import obs as _obs
from ..obs import watchdog as _watchdog
from ..mca import pvar
from ..mca import var as mca_var
from ..native import DssBuffer
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("wire")

#: p2p envelope+payload channel: + lane stride + destination WORLD rank
WIRE_P2P_BASE = 1 << 20
#: ssend acknowledgements: + the original sender's WORLD rank
WIRE_ACK_BASE = 2 << 20
#: per-communicator collective payload channel: + cid
WIRE_COLL_BASE = 3 << 20
#: per-communicator collective control channel (barrier tokens): + cid
WIRE_CTL_BASE = 4 << 20

#: per-lane tag stride inside the p2p block: lane L of destination D is
#: ``WIRE_P2P_BASE + L * _LANE_STRIDE + D`` (lane 0 == the legacy tag)
_LANE_STRIDE = 1 << 17
_MAX_LANES = 8

_ENV_MAGIC = "WPM1"

#: sender time spent blocked behind another transfer's channel lock —
#: the head-of-line wait the per-(peer, tag-class) lanes exist to cut.
#: Module-level registration (the PR-1 zero-cost-counter class); the
#: uncontended path costs one try-acquire and never reads a clock.
_hol_wait = pvar.timer(
    "wire_hol_wait_seconds",
    "seconds senders spent waiting behind another transfer's wire "
    "channel lock (head-of-line wait)",
)

#: collective transfers the progress engine reaped into the
#: early-transfer queue off the caller (the opal_progress wire tick)
_coll_pumped = pvar.counter(
    "wire_coll_pumped",
    "collective transfers completed by the async progress engine's "
    "nonblocking wire pump (reaped before any reap parked on them)",
)

#: bounded-wait slice: every blocking collective/ctl wait re-checks
#: the ULFM failure picture (revoked cid, known-failed peers) at this
#: period, so a dead peer turns a would-be indefinite hang into
#: ERR_PROC_FAILED within one detection interval
_FT_SLICE_S = 0.1

_ft_singleton = None


def _ft():
    """The process-local ULFM state (lazy: ft.ulfm must not be pulled
    through the package __init__ — and its jax deps — at wire import
    time)."""
    global _ft_singleton
    if _ft_singleton is None:
        from ..ft import ulfm

        _ft_singleton = ulfm.state()
    return _ft_singleton


def _ft_split_awaiting(procs) -> Dict[str, List[int]]:
    """Watchdog postmortem annotation: known-failed peers are NAMED
    as failed instead of listed as merely 'awaiting'."""
    procs = list(procs)
    dead = set(_ft().dead_for(procs))
    return {
        "awaiting_procs": sorted(q for q in procs if q not in dead),
        "known_failed_procs": sorted(dead),
    }


def register_vars() -> None:
    from ..btl.components import register_pipeline_vars

    register_pipeline_vars()  # wire_pipeline_segsize / _depth
    mca_var.register(
        "wire_p2p_lanes", "int", 4,
        "Per-destination p2p channel lanes; user tags hash onto lanes "
        "so independent tags no longer serialize behind one "
        "destination stream (1 = the legacy single channel)",
    )
    mca_var.register(
        "wire_overlap_exchange", "bool", True,
        "Reap spanning-comm exchange receives in arrival order "
        "(posted-sends overlap) instead of fixed process order; false "
        "restores the sequential per-peer receive loop",
    )
    mca_var.register(
        "wire_coll_timeout_ms", "int", 60_000,
        "Default bound in milliseconds for blocking collective/ctl "
        "wire waits (coll_recv, coll_recv_any, ctl_recv, barrier "
        "tokens). Compiled-schedule waits and chaos tests tune this; "
        "explicit per-call timeouts still win",
    )
    # wire_qos_classes / wire_qos_class (the multi-tenant service
    # plane's lane classes + weighted-fair fragment scheduling) are
    # registered by service.qos — import-light, no jax
    from ..service import qos as _qos_vars

    _qos_vars.register_vars()


register_vars()  # idempotent; cvars must exist before the first router


class WireTuning:
    """One immutable snapshot of the wire's hot-path cvars, resolved
    through the registry ONCE and stamped with the registry write
    generation. Per-message sends used to pay a registry lock + dict
    lookup each for ``wire_p2p_lanes`` / ``wire_pipeline_depth`` /
    ``wire_pipeline_segsize``; the router now reads attributes off the
    current snapshot and re-resolves only when the generation moved —
    so a mid-job cvar write takes effect at the next snapshot refresh
    (and, for frozen schedule plans, at the next PLAN, which captures
    the snapshot at freeze time — never mid-schedule)."""

    __slots__ = ("gen", "lanes", "depth", "segsize", "coll_timeout_ms",
                 "qos_ranges", "qos_class", "arbiter")

    def __init__(self) -> None:
        self.gen = mca_var.VARS.generation
        self.lanes = max(1, min(_MAX_LANES,
                                int(mca_var.get("wire_p2p_lanes", 4)
                                    or 1)))
        self.depth = max(1, int(mca_var.get("wire_pipeline_depth", 4)
                                or 1))
        self.segsize = int(mca_var.get("wire_pipeline_segsize", 0) or 0)
        self.coll_timeout_ms = int(
            mca_var.get("wire_coll_timeout_ms", 60_000) or 60_000)
        # multi-tenant QoS (service plane): with wire_qos_classes
        # unset every field is None and no hot path changes — the
        # zero-config wire is the PR 3 wire
        spec = str(mca_var.get("wire_qos_classes", "") or "")
        self.qos_class = str(mca_var.get("wire_qos_class", "") or "")
        if spec:
            from ..service import qos as _qos

            self.qos_ranges = _qos.lane_ranges(_qos.parse_classes(spec),
                                               self.lanes)
            self.arbiter = _qos.arbiter_for(spec)
        else:
            self.qos_ranges = None
            self.arbiter = None


class ProcTopology:
    """Process/member layout of a communicator under the unified
    world — ONE derivation shared by the hier collectives, the wire
    windows, and two-phase collective IO (each previously re-derived
    it; a change to ownership mapping must land exactly once)."""

    __slots__ = ("router", "my_pidx", "owner", "procs", "members_of",
                 "local_ranks", "local_n", "peers")

    def __init__(self, comm) -> None:
        rt = comm.runtime
        self.router: "WireRouter" = rt.wire
        self.my_pidx = int(rt.bootstrap["process_index"])
        n = comm.size
        self.owner: List[int] = [
            self.router.owner_of(comm.group.world_rank(i))
            for i in range(n)
        ]
        self.procs: List[int] = sorted(set(self.owner))
        self.members_of: Dict[int, List[int]] = {
            p: [i for i in range(n) if self.owner[i] == p]
            for p in self.procs
        }
        self.local_ranks: List[int] = list(comm.local_comm_ranks)
        self.local_n = len(self.local_ranks)
        self.peers: List[int] = [p for p in self.procs
                                 if p != self.my_pidx]


def proc_topology(comm) -> ProcTopology:
    """Cached per-communicator topology (the derivation is O(size x
    procs) owner-span scans — pay it once per comm)."""
    topo = getattr(comm, "_proc_topology", None)
    if topo is None:
        topo = comm._proc_topology = ProcTopology(comm)
    return topo


class WireRouter:
    """Per-runtime cross-process router over the worker's OOB endpoint."""

    def __init__(self, runtime) -> None:
        from ..btl.components import DcnBtl, ShmBtl

        self.rt = runtime
        self.agent = runtime.agent
        self.ep = self.agent.ep
        self.cards: List[Dict[str, Any]] = runtime.bootstrap["peer_cards"]
        self.my_pidx: int = runtime.bootstrap["process_index"]
        # rank spans: process p owns world ranks [offset, offset+count)
        self.spans: List[Tuple[int, int]] = runtime.proc_spans
        self._shm = ShmBtl()
        self._dcn = DcnBtl()
        # the zero-copy native datapath (btl/nativewire): None when the
        # native library lacks the wire_*/shmring_* symbols or the
        # component is disabled — every routing site below then falls
        # back to the portable shm/dcn transports structurally
        from ..btl import nativewire as _nativewire

        self._nw = _nativewire.module_for(self.cards, self.my_pidx)
        self._seq = itertools.count(1)
        self._acks: set = set()
        self._ack_lock = threading.Lock()
        # per-channel locks, keyed ("send"|"drain", (dst_world, lane))
        # or ("deliver", dst_world): an envelope and its payload must
        # land back-to-back on one lane FIFO (send side) and be popped
        # as a unit (drain side) — concurrent threads on ONE lane would
        # interleave frames and corrupt the stream. Distinct lanes are
        # independent: that is the whole point.
        self._chan_locks: Dict[Tuple[str, Any], threading.Lock] = {}
        self._chan_guard = threading.Lock()
        # per-destination delivery order (sender side) and the
        # receiver's reorder state: completed-but-early messages wait
        # in _rx_hold until every lower-order message delivered, so
        # lane concurrency can never reorder PML matching
        self._order: Dict[int, int] = {}
        self._order_lock = threading.Lock()
        self._rx_hold: Dict[Tuple[int, int], Dict[int, tuple]] = {}
        self._rx_next: Dict[Tuple[int, int], int] = {}
        self._rx_lock = threading.Lock()
        # rotating first-lane offset per destination: a 1 ms
        # nonblocking poll pumps at most one lane, so successive polls
        # must start at different lanes or lanes past 0 would starve
        # (benign races: worst case two polls share a start lane)
        self._drain_rr: Dict[int, int] = {}
        # collective transfers completed by an any-source reap before
        # their round asked for them (a peer racing one round ahead):
        # (cid, src_pidx) -> FIFO of arrays
        self._coll_early: Dict[Tuple[int, int], List] = {}
        self._coll_early_lock = threading.Lock()
        #: cids whose progress-engine pump hit a mid-transfer failure:
        #: the channel stream is unrecoverable, so pumps stand down and
        #: the round's own reap surfaces the loud error
        self._pump_dead: set = set()
        #: per-cid pump backoff: an empty pump probe costs a ~1 ms
        #: blocking OOB recv (ep.pending() counts frames on EVERY tag,
        #: so unrelated p2p traffic defeats the cheap fast path) —
        #: after an empty probe the pump skips this cid briefly so a
        #: busy endpoint cannot turn the progress thread into a
        #: continuous blocking-recv loop
        self._pump_idle: Dict[int, float] = {}
        #: hot-path cvars resolved once at init (satellite of the
        #: compiled-schedule PR): refreshed only when the registry
        #: write generation moves — see WireTuning
        self._tuning = WireTuning()

    def tuning(self) -> WireTuning:
        """Current wire-tuning snapshot (generation-checked: one int
        compare on the hot path; a cvar write re-resolves lazily)."""
        t = self._tuning
        if t.gen != mca_var.VARS.generation:
            t = self._tuning = WireTuning()
        return t

    def refresh_tuning(self) -> WireTuning:
        """Force a fresh snapshot NOW (plan-freeze entry: a frozen
        schedule plan must capture post-write values even if the
        generation bookkeeping ever lagged)."""
        t = self._tuning = WireTuning()
        return t

    def _chan_lock(self, kind: str, key) -> threading.Lock:
        with self._chan_guard:
            lk = self._chan_locks.get((kind, key))
            if lk is None:
                lk = self._chan_locks[(kind, key)] = threading.Lock()
            return lk

    # -- identity ----------------------------------------------------------
    @staticmethod
    def _nid(pidx: int) -> int:
        return pidx + 1  # worker node ids are 1-based (0 is the HNP)

    def owner_of(self, world_rank: int) -> int:
        for p, (off, cnt) in enumerate(self.spans):
            if off <= world_rank < off + cnt:
                return p
        raise MPIError(ErrorCode.ERR_RANK,
                       f"world rank {world_rank} outside every span")

    def _btl_for(self, peer_pidx: int):
        """Transport choice, deterministic on BOTH sides: when both
        ends' modex cards advertise the native datapath, nativewire
        carries the payload (shm rings co-hosted, vectored sockets
        cross-host); otherwise same machine (modex card host identity)
        -> shm handoff, else DCN staging — exactly the per-peer
        eligibility add_procs computes from business cards
        (``btl.h:810-816``)."""
        nw = self._nw
        if nw is not None and nw.peer_capable(peer_pidx):
            return nw
        same_host = (
            self.cards[self.my_pidx].get("host")
            and self.cards[self.my_pidx].get("host")
            == self.cards[peer_pidx].get("host")
        )
        return self._shm if same_host else self._dcn

    # -- lanes -------------------------------------------------------------
    @staticmethod
    def _class_of(comm, t: WireTuning) -> Optional[str]:
        """The sender's QoS class for ``comm`` under tuning snapshot
        ``t``: the comm's stamped class (tenant comms) wins over the
        process-wide ``wire_qos_class`` cvar; None when QoS is off."""
        if t.qos_ranges is None:
            return None
        return getattr(comm, "_qos_class", None) or t.qos_class

    def _lane_of(self, user_tag: int, comm=None) -> int:
        """THE lane-selection rule (single definition — send and any
        future drain/debug site must agree), reading the
        generation-cached ``tuning()`` snapshot, never the registry.
        Under ``wire_qos_classes`` the comm's class selects its lane
        sub-range, so one class's transfers never queue behind
        another's channel lock; unknown/empty classes (and QoS off)
        ride the legacy full range."""
        t = self.tuning()
        if t.qos_ranges is not None:
            rng = t.qos_ranges.get(self._class_of(comm, t))
            if rng is not None:
                start, count = rng
                return start + int(user_tag) % count
        return int(user_tag) % t.lanes

    @staticmethod
    def _p2p_tag(dst_world: int, lane: int) -> int:
        if dst_world >= _LANE_STRIDE:
            raise MPIError(
                ErrorCode.ERR_INTERN,
                f"world rank {dst_world} exceeds the per-lane wire tag "
                f"space ({_LANE_STRIDE})",
            )
        return WIRE_P2P_BASE + lane * _LANE_STRIDE + dst_world

    # -- payload channel ---------------------------------------------------
    def _retry(self, fn, what: str, peer: Optional[int] = None,
               epoch0: int = 0):
        """First contact over an accepted fd can race the peer's
        announce processing on our reader thread (the same window
        recv_xcast retries around) — back off briefly before treating
        the link as dead. A peer the job epoch marks FAILED is not
        retried: the send fails fast with ERR_PROC_FAILED instead of
        burning the whole backoff against a corpse."""
        last = None
        for attempt in range(5):
            if peer is not None and attempt:
                _ft().check_peer(peer, what, epoch0)
            try:
                return fn()
            except MPIError as e:
                if e.code == ErrorCode.ERR_PROC_FAILED:
                    raise  # a confirmed process failure is not transient
                last = e
                time.sleep(0.05 * (attempt + 1))
        if peer is not None:
            _ft().check_peer(peer, what, epoch0)
        raise MPIError(ErrorCode.ERR_UNREACH,
                       f"{what} failed after retries: {last}")

    def _send_payload(self, peer_pidx: int, tag: int, arr,
                      epoch0: int = 0) -> None:
        btl = self._btl_for(peer_pidx)
        arr = np.asarray(arr)
        if btl is self._shm:
            self._retry(
                lambda: btl.send_shm(self.ep, self._nid(peer_pidx), tag,
                                     arr),
                f"shm handoff to process {peer_pidx}",
                peer=peer_pidx, epoch0=epoch0,
            )
        else:
            self._retry(
                lambda: btl.send_staged(self.ep, self._nid(peer_pidx),
                                        tag, arr),
                f"staged transfer to process {peer_pidx}",
                peer=peer_pidx, epoch0=epoch0,
            )

    def _recv_payload(self, tag: int, src_pidx: int,
                      timeout_ms: int = 30_000):
        btl = self._btl_for(src_pidx)
        if btl is self._shm:
            return btl.recv_shm(self.ep, tag, src=self._nid(src_pidx),
                                timeout_ms=timeout_ms)
        return btl.recv_staged(self.ep, tag, src=self._nid(src_pidx),
                               timeout_ms=timeout_ms)

    # -- p2p (the PML's cross-process route) -------------------------------
    def _next_order(self, dst_world: int) -> int:
        with self._order_lock:
            n = self._order.get(dst_world, 0) + 1
            self._order[dst_world] = n
            return n

    def send_p2p(self, comm, src_rank: int, dst_rank: int, user_tag: int,
                 data, sync: bool) -> int:
        """Envelope + payload to the process owning ``dst_rank``.
        Ranks in the envelope are COMM-local (matching happens against
        the destination comm's queues); the channel is keyed by the
        destination's WORLD rank plus the user tag's lane, so
        independent tags ride independent streams while every comm
        still shares the per-destination delivery order."""
        dst_world = comm.group.world_rank(dst_rank)
        peer = self.owner_of(dst_world)
        _ft().check_wait(comm.cid, (peer,), "p2p send",
                         epoch0=getattr(comm, "_ft_epoch0", 0))
        seq = next(self._seq)
        lane = self._lane_of(user_tag, comm)
        tag = self._p2p_tag(dst_world, lane)
        arr = np.asarray(data)
        rec = _obs.enabled  # capture once: flag may flip mid-send
        t0 = time.perf_counter() if rec else 0.0
        lock = self._chan_lock("send", (dst_world, lane))
        if not lock.acquire(blocking=False):
            # contended: another transfer owns this lane — time the
            # head-of-line wait (the uncontended path never reads a
            # clock, keeping the off-cost at one try-acquire)
            w0 = time.perf_counter()
            lock.acquire()
            _hol_wait.add(time.perf_counter() - w0)
        try:
            # order allocation and the envelope send are one atomic
            # step per destination: if the envelope never reaches the
            # wire, the slot is rolled back under the same lock, so a
            # failed send can never leave a permanent gap that strands
            # every later message in the receiver's reorder hold.
            # Envelopes are single small frames — cross-lane payloads
            # (the actual bytes) still stream concurrently below.
            with self._chan_lock("order", dst_world):
                order = self._next_order(dst_world)
                env = DssBuffer()
                env.pack_string(_ENV_MAGIC)
                env.pack_int64([comm.cid, src_rank, dst_rank,
                                int(user_tag), 1 if sync else 0, seq,
                                order])
                try:
                    self._retry(
                        lambda: self.ep.send(self._nid(peer), tag,
                                             env.tobytes()),
                        f"p2p envelope to process {peer}",
                    )
                except MPIError:
                    with self._order_lock:
                        # safe: no other thread can have allocated a
                        # later slot while we hold the order chan lock
                        self._order[dst_world] = order - 1
                    raise
            self._send_payload(peer, tag, arr,
                               epoch0=getattr(comm, "_ft_epoch0", 0))
        finally:
            lock.release()
        if rec and _obs.enabled:
            # flow id from (sender process, wire seq) — both already
            # ride the envelope, so the receiver derives the SAME id
            # with no wire-format change (the trace-context contract)
            _obs.record("wire_send", "wire", t0,
                        time.perf_counter() - t0,
                        nbytes=int(arr.nbytes), peer=dst_world,
                        comm_id=comm.cid,
                        flow=_obs.flow_id("p2p", self.my_pidx, seq),
                        flow_side="s")
        return seq

    def drain_p2p(self, dst_world_rank: int, timeout_ms: int = 50) -> bool:
        """Receive wire traffic destined to ``dst_world_rank`` and push
        completed messages into the owning communicator's PML matching
        queues, in per-sender send order. Returns True if at least one
        message was delivered.

        ``timeout_ms`` bounds only the wait for ENVELOPES; once one is
        popped, its payload is consumed to completion — the sender
        wrote it immediately behind the envelope on the same lane FIFO,
        so the stall is bounded by the in-flight transfer, not by user
        behavior (head-of-line now scoped to ONE lane: other tags'
        lanes stay drainable, by this thread on its next sweep or by a
        concurrent thread — busy lanes are skipped, never waited on).
        A sender dying between envelope and payload surfaces as a loud
        ERR_TRUNCATE here, never a silently dropped message.
        """
        if self._deliver_ready(dst_world_rank):
            return True
        # cheap empty-channel fast path for nonblocking progress
        # (imprecise: pending() counts frames on every tag, so other
        # traffic forces the short recv below — never misses a frame)
        if timeout_ms <= 1 and self.ep.pending() == 0:
            return False
        deadline = time.monotonic() + timeout_ms / 1000
        nlanes = self.tuning().lanes
        # lanes beyond the local cvar get ONE cheap probe per blocking
        # drain call: a sender configured with MORE lanes
        # (heterogeneous MCA env, or the cvar flipped mid-flight) must
        # never have its messages stranded on a tag we refuse to poll —
        # but the mismatch path must not tax every sweep either
        probe_extras = timeout_ms > 1 and nlanes < _MAX_LANES
        start = self._drain_rr.get(dst_world_rank, 0) % max(nlanes, 1)
        self._drain_rr[dst_world_rank] = start + 1
        first_sweep = True
        while True:
            pumped_any = False
            for i in range(_MAX_LANES):
                # rotate only the first sweep's order; later sweeps
                # are inside a blocking wait and cover every lane
                lane = (start + i) % nlanes if (first_sweep
                                                and i < nlanes) else i
                local = lane < nlanes
                if not local and not probe_extras:
                    continue
                if pumped_any and time.monotonic() >= deadline:
                    break  # bound nonblocking polls at ~one lane pump
                lk = self._chan_lock("drain", (dst_world_rank, lane))
                if not lk.acquire(blocking=False):
                    continue  # another thread is pumping this lane
                try:
                    pumped_any = True
                    left = deadline - time.monotonic()
                    # short per-lane envelope wait so one silent lane
                    # cannot eat the whole budget when others have
                    # frames queued; a single lane gets the full wait;
                    # extra (mismatch-tolerance) lanes get the minimum
                    if not local:
                        per = 0.001
                    elif nlanes == 1:
                        per = left
                    else:
                        per = min(left, 0.01)
                    self._pump_lane(dst_world_rank, lane,
                                    time.monotonic() + max(per, 0.001))
                finally:
                    lk.release()
                if self._deliver_ready(dst_world_rank):
                    return True
            probe_extras = False  # once per call is tolerance enough
            first_sweep = False
            if time.monotonic() >= deadline:
                return False
            if not pumped_any:
                # every lane is owned by another thread: yield instead
                # of spinning on try-acquires until the deadline
                time.sleep(0.001)

    def _pump_lane(self, dst_world: int, lane: int,
                   deadline: float) -> bool:
        """Pop one envelope (+ its payload, to completion) off one lane
        and park the completed message in the reorder buffer. Returns
        True if a frame was consumed. Caller holds the lane's drain
        lock."""
        from ..btl.components import stashed_recv

        tag = self._p2p_tag(dst_world, lane)
        try:
            src_nid, raw = stashed_recv(self.ep, None, tag, deadline)
        except MPIError:
            return False  # nothing pending within the timeout
        env = DssBuffer(raw)
        if env.unpack_string() != _ENV_MAGIC:
            _log.verbose(1, f"dropping non-envelope frame on p2p "
                            f"channel {tag}")
            return True
        cid, src_rank, dst_rank, user_tag, sync, seq, order = \
            env.unpack_int64(7)
        src_pidx = src_nid - 1
        rec = _obs.enabled  # capture once: flag may flip mid-recv
        t0 = time.perf_counter() if rec else 0.0
        try:
            data = self._recv_payload(tag, src_pidx)
        except MPIError as e:
            if e.code == ErrorCode.ERR_PROC_FAILED:
                # the transport already issued the typed ULFM verdict
                # (the shm ring's pid-liveness check is authoritative
                # on one host) — recovery policies key on the code, so
                # it must not be laundered into a generic truncation
                raise
            raise MPIError(
                ErrorCode.ERR_TRUNCATE,
                f"wire message from process {src_pidx} (comm cid "
                f"{cid}, src rank {src_rank}, tag {user_tag}) "
                "announced by its envelope but the payload never "
                f"completed — peer died mid-transfer? ({e})",
            )
        if rec and _obs.enabled:
            # the matching consumer span: same (sender process, seq)
            # flow id the sender stamped — tpu-doctor draws the arrow
            _obs.record("wire_recv", "wire", t0,
                        time.perf_counter() - t0,
                        nbytes=int(getattr(data, "nbytes", 0)),
                        peer=int(src_rank), comm_id=int(cid),
                        flow=_obs.flow_id("p2p", src_pidx, int(seq)),
                        flow_side="t")
        with self._rx_lock:
            self._rx_hold.setdefault((src_pidx, dst_world), {})[
                int(order)] = (int(cid), int(src_rank), int(dst_rank),
                               int(user_tag), int(sync), int(seq),
                               src_pidx, data)
        return True

    def _deliver_ready(self, dst_world: int) -> bool:
        """Deliver every reorder-buffer message whose per-sender order
        is next-expected. The deliver lock serializes PML insertion per
        destination so two drain threads can never swap send order."""
        if not self._rx_hold:  # racy-but-safe fast path (dict bool)
            return False
        delivered = False
        with self._chan_lock("deliver", dst_world):
            while True:
                ready = None
                with self._rx_lock:
                    for key in list(self._rx_hold):
                        if key[1] != dst_world:
                            continue
                        nxt = self._rx_next.get(key, 1)
                        hold = self._rx_hold[key]
                        if nxt in hold:
                            ready = hold.pop(nxt)
                            self._rx_next[key] = nxt + 1
                            if not hold:
                                del self._rx_hold[key]
                            break
                if ready is None:
                    return delivered
                self._deliver_one(ready)
                delivered = True

    def _deliver_one(self, msg: tuple) -> None:
        from ..comm.communicator import _comm_registry

        cid, src_rank, dst_rank, user_tag, sync, seq, src_pidx, data = msg
        comm = _comm_registry.get(int(cid))
        if comm is None:
            raise MPIError(
                ErrorCode.ERR_COMM,
                f"wire message for unknown cid {cid} (communicator "
                "creation order diverged across processes?)",
            )
        on_matched = None
        if sync:
            src_world = comm.group.world_rank(int(src_rank))

            def on_matched(_req, _p=src_pidx, _c=int(cid), _s=int(seq),
                           _w=src_world):
                self.send_ack(_p, _c, _s, _w)

        comm.pml._enqueue_wire(int(src_rank), int(dst_rank),
                               int(user_tag), data, on_matched=on_matched)

    # -- ssend acknowledgements --------------------------------------------
    def send_ack(self, peer_pidx: int, cid: int, seq: int,
                 sender_world_rank: int) -> None:
        b = DssBuffer()
        b.pack_int64([cid, seq])
        self._retry(
            lambda: self.ep.send(self._nid(peer_pidx),
                                 WIRE_ACK_BASE + sender_world_rank,
                                 b.tobytes()),
            f"ssend ack to process {peer_pidx}",
        )

    def poll_acks(self, sender_world_rank: int,
                  timeout_ms: int = 0) -> None:
        """Drain every available ack addressed to ``sender_world_rank``
        into the ack set (timeout_ms=0: near-nonblocking — an empty
        endpoint returns immediately via the pending() fast path; with
        unrelated frames queued the probe costs ~1 ms)."""
        tag = WIRE_ACK_BASE + sender_world_rank
        if timeout_ms <= 0 and self.ep.pending() == 0:
            return
        while True:
            try:
                _, _, raw = self.ep.recv(tag=tag,
                                         timeout_ms=max(1, timeout_ms))
            except MPIError:
                return
            cid, seq = DssBuffer(raw).unpack_int64(2)
            with self._ack_lock:
                self._acks.add((int(cid), int(seq)))
            timeout_ms = 0  # only the first recv may wait

    def has_ack(self, cid: int, seq: int) -> bool:
        with self._ack_lock:
            return (cid, seq) in self._acks

    def take_ack(self, cid: int, seq: int) -> bool:
        with self._ack_lock:
            if (cid, seq) in self._acks:
                self._acks.discard((cid, seq))
                return True
            return False

    # -- collective channels (used by the hier coll component) -------------
    @staticmethod
    def _coll_tag(comm) -> int:
        if comm.cid >= (1 << 20):
            raise MPIError(ErrorCode.ERR_INTERN,
                           f"cid {comm.cid} exceeds the wire tag space")
        return WIRE_COLL_BASE + comm.cid

    def _coll_early_pop(self, cid: int, src_pidx: int):
        with self._coll_early_lock:
            q = self._coll_early.get((cid, src_pidx))
            if q:
                arr = q.pop(0)
                if not q:
                    del self._coll_early[(cid, src_pidx)]
                return arr
        return None

    def coll_send(self, comm, peer_pidx: int, arr) -> None:
        epoch0 = getattr(comm, "_ft_epoch0", 0)
        _ft().check_wait(comm.cid, (peer_pidx,), "collective send",
                         epoch0=epoch0)
        self._send_payload(peer_pidx, self._coll_tag(comm), arr,
                           epoch0=epoch0)

    def coll_recv(self, comm, src_pidx: int,
                  timeout_ms: Optional[int] = None):
        early = self._coll_early_pop(comm.cid, src_pidx)
        if early is not None:
            return early
        if timeout_ms is None:  # wire_coll_timeout_ms cvar (tunable)
            timeout_ms = self.tuning().coll_timeout_ms
        # serialize against the progress engine's pump: two consumers
        # popping frames of ONE multi-frame transfer would split it.
        # The caller's timeout budget covers the lock wait too — a
        # pump mid-transfer must not silently extend a bounded reap.
        deadline = time.monotonic() + timeout_ms / 1000
        tag = self._coll_tag(comm)
        lk = self._chan_lock("collrx", comm.cid)
        if not lk.acquire(timeout=max(0.001,
                                      deadline - time.monotonic())):
            raise MPIError(
                ErrorCode.ERR_PENDING,
                f"collective receive from process {src_pidx} timed out "
                "waiting for the comm's wire channel (held by the "
                "progress pump or another reap)",
            )
        try:
            early = self._coll_early_pop(comm.cid, src_pidx)
            if early is not None:
                return early
            # bounded-slice wait for the FIRST frame; once one
            # landed, the transfer is committed to completion against
            # the caller's full deadline
            _, raw = self._sliced_recv(
                self._nid(src_pidx), tag, deadline, comm,
                lambda: (src_pidx,), "collective receive from",
                f"collective receive from process {src_pidx} timed "
                f"out after {timeout_ms} ms")
            return self._finish_checked(
                src_pidx, tag, raw, deadline,
                epoch0=getattr(comm, "_ft_epoch0", 0))
        finally:
            lk.release()

    def coll_pump(self, comm, budget: int = 8) -> int:
        """Nonblocking receive-side progress on ``comm``'s collective
        payload channel — the progress engine's wire tick: complete up
        to ``budget`` landed transfers into the early-transfer queue so
        the round's reap (or the round that raced ahead) finds them
        without parking. Skips out instantly when the endpoint is idle
        or a reap already owns the channel (a parked reap IS the
        progress for that channel). A pump only STARTS on a transfer
        whose first frame already landed; it may then ride out the
        transfer's in-flight tail (bounded by the sender's streaming —
        the opal_progress discipline: completing in-flight fragments
        IS the progress). A transfer that FAILS mid-pump (peer died)
        leaves the channel stream unrecoverable for any consumer, so
        the pump marks this cid poisoned and stands down — the round's
        own reap surfaces the loud ERR_TRUNCATE instead of every tick
        re-paying the timeout. The channel lock is held per TRANSFER,
        not across the whole budget, so a reap arriving mid-pump
        queues behind at most one in-flight tail."""
        from ..btl.components import stashed_recv

        if comm.cid in self._pump_dead or self.ep.pending() == 0:
            return 0
        if time.monotonic() < self._pump_idle.get(comm.cid, 0.0):
            return 0  # recent empty probe: let the backoff expire
        tag = self._coll_tag(comm)
        lk = self._chan_lock("collrx", comm.cid)
        n = 0
        while n < budget:
            if not lk.acquire(blocking=False):
                return n  # a reap owns the channel: it IS the progress
            try:
                try:
                    src_nid, raw = stashed_recv(
                        self.ep, None, tag, time.monotonic() + 0.001)
                except MPIError:
                    if n == 0:
                        self._pump_idle[comm.cid] = \
                            time.monotonic() + 0.005
                    return n  # nothing pending on this channel
                src = src_nid - 1
                try:
                    # the finish budget matches the reaps' 60 s default
                    # deliberately: a SHORTER pump deadline would strand
                    # the popped frames and fail a transfer the round's
                    # own reap budget would have absorbed
                    arr = self._finish_transfer(
                        src, tag, raw, time.monotonic() + 60.0)
                except MPIError:
                    self._pump_dead.add(comm.cid)
                    raise
                with self._coll_early_lock:
                    self._coll_early.setdefault(
                        (comm.cid, src), []).append(arr)
                _coll_pumped.add()
                n += 1
            finally:
                lk.release()
        return n

    def _peer_frames(self, peer: int, tag: int, arrs: List,
                     epoch0: int = 0, templates=None):
        """Side-effecting generator: each ``next()`` puts ONE wire
        frame of this peer's transfer queue on the OOB. DCN transfers
        above the pipeline segsize stream as zero-copy fragments; shm
        handoffs and legacy/small transfers count as one frame.
        ``templates`` (a frozen plan's per-array FrameTemplates, None
        entries = generic path) selects the precomposed-header send:
        no per-message cvar read or header packing."""
        btl = self._btl_for(peer)
        nid = self._nid(peer)
        for k, a in enumerate(arrs):
            tpl = templates[k] if templates is not None else None
            if btl is self._nw and btl is not None:
                # native datapath: the stream does its own sends (ring
                # writev / vectored sockets) with its own retry + typed
                # fault mapping; frames and yields stay 1:1 with the
                # portable stream so striping/QoS see the same shape
                for _ in btl.frame_stream(self.ep, peer, tag, a,
                                          tpl=tpl):
                    yield
                continue
            if tpl is not None and btl is self._dcn:
                for frame in self._dcn.planned_frames(a, tpl):
                    self._retry(
                        lambda f=frame: self.ep.send(nid, tag, f),
                        f"pipelined fragment to process {peer}",
                    )
                    yield
                continue
            seg = self._dcn.pipeline_segsize() if btl is self._dcn else 0
            if seg > 0:
                # pvar accounting happens inside staged_frames — the
                # one place that knows frames (shared with send_staged)
                for frame in self._dcn.staged_frames(a, segsize=seg):
                    self._retry(
                        lambda f=frame: self.ep.send(nid, tag, f),
                        f"pipelined fragment to process {peer}",
                    )
                    yield
            else:
                self._send_payload(peer, tag, a, epoch0=epoch0)
                yield

    def coll_send_all(self, comm, arrs_for: Dict[int, List]) -> None:
        """Post one exchange round's sends to EVERY peer, striping
        pipelined fragments round-robin across destinations in
        ``wire_pipeline_depth``-sized bursts — every peer's receive
        side starts reassembling while the round is still being sent,
        instead of peer P+1 waiting for peer P's full payload."""
        tag = self._coll_tag(comm)
        t = self.tuning()
        epoch0 = getattr(comm, "_ft_epoch0", 0)
        streams = [self._peer_frames(p, tag, arrs_for[p], epoch0)
                   for p in sorted(arrs_for) if arrs_for[p]]
        self._stripe(streams, t.depth, arbiter=t.arbiter,
                     cls=self._class_of(comm, t))

    def coll_send_planned(self, comm, rnd, sends: Dict[int, List]) -> None:
        """Steady-state round send from a frozen schedule plan
        (:mod:`coll.plan`): the round's peer list, per-peer templates
        (precomposed SGH2 headers + fragment offsets), striping depth
        and channel tag were all resolved at plan time — this path
        does ONE ULFM check for the round and then streams memoryview
        slices behind precomposed header bytes. Same frames, same
        striping discipline, same FIFO-per-peer ordering as
        :meth:`coll_send_all`."""
        epoch0 = getattr(comm, "_ft_epoch0", 0)
        _ft().check_wait(comm.cid, rnd.peers, "collective send",
                         epoch0=epoch0)
        streams = [
            self._peer_frames(p, rnd.tag, sends[p], epoch0,
                              templates=tpls)
            for p, tpls in rnd.peer_slots
        ]
        t = self.tuning()
        self._stripe(streams, rnd.depth, arbiter=t.arbiter,
                     cls=self._class_of(comm, t),
                     counts=getattr(rnd, "frame_counts", None))

    @staticmethod
    def _stripe(streams: List, depth: int, arbiter=None,
                cls: Optional[str] = None, counts=None) -> None:
        """Round-robin the per-peer frame generators in depth-sized
        bursts (the sliding in-flight window). With a QoS ``arbiter``
        (``wire_qos_classes`` set) every burst first passes the
        weighted-fair gate for this sender's class, so a bulk
        tenant's long fragment streams yield to a latency tenant's
        bursts at the class weight ratio instead of FIFO-hogging the
        endpoint.

        ``counts`` (frozen plans only): exact frames left per stream.
        A drained stream is dropped WITHOUT passing the gate — a
        solo-class short tail must not buy window it will never use —
        and a final partial burst is gated at its real cost, not the
        full depth."""
        if arbiter is not None:
            arbiter.enter(cls)
        try:
            remaining = list(counts) if counts is not None else None
            while streams:
                keep = []
                keep_left = []
                for j, it in enumerate(streams):
                    left = remaining[j] if remaining is not None \
                        else None
                    if left is not None and left <= 0:
                        continue  # exhausted: no gate, no next()
                    burst = depth if left is None \
                        else min(depth, left)
                    if arbiter is not None:
                        arbiter.gate(cls, cost=burst)
                    alive = True
                    done = 0
                    for _ in range(burst):
                        try:
                            next(it)
                        except StopIteration:
                            alive = False
                            break
                        done += 1
                    if left is not None:
                        left -= done
                        alive = alive and left > 0
                    if alive:
                        keep.append(it)
                        keep_left.append(left)
                streams = keep
                remaining = keep_left if remaining is not None \
                    else None
        finally:
            if arbiter is not None:
                arbiter.leave(cls)

    def coll_recv_any(self, comm, pending: Dict[int, int],
                      timeout_ms: Optional[int] = None):
        """Complete the NEXT transfer on ``comm``'s payload channel
        from whichever peer's frames arrive first; returns
        ``(src_pidx, array)``. ``pending`` maps peer -> messages still
        expected this round; a completed transfer from a peer with no
        outstanding count belongs to a FUTURE round (that peer raced
        ahead) and is queued for its own round's receive instead of
        being returned out of context. The default wait bound is the
        ``wire_coll_timeout_ms`` cvar."""
        if timeout_ms is None:
            timeout_ms = self.tuning().coll_timeout_ms
        for p in list(pending):
            if pending.get(p, 0) > 0:
                early = self._coll_early_pop(comm.cid, p)
                if early is not None:
                    return p, early
        tag = self._coll_tag(comm)
        deadline = time.monotonic() + timeout_ms / 1000
        tok = None
        if _watchdog.enabled:
            tok = _watchdog.arm(
                "coll_recv_any", comm_id=comm.cid,
                info=lambda p=pending: _ft_split_awaiting(
                    q for q, c in p.items() if c > 0),
            )
        # serialize against the progress engine's pump (coll_pump):
        # two consumers popping frames of one multi-frame transfer
        # would split it. A parked reap holding the lock is fine — it
        # IS the progress for this channel; the pump try-acquires and
        # skips. The lock wait itself is bounded by the caller's
        # deadline so a pump mid-transfer cannot extend a bounded reap.
        lk = self._chan_lock("collrx", comm.cid)
        try:
            if not lk.acquire(timeout=max(0.001,
                                          deadline - time.monotonic())):
                raise MPIError(
                    ErrorCode.ERR_PENDING,
                    f"collective any-source receive on {comm.name} "
                    "timed out waiting for the comm's wire channel",
                )
            try:
                while True:
                    # the pump may have reaped our transfer while we
                    # awaited the lock: early queue first, always
                    for p in list(pending):
                        if pending.get(p, 0) > 0:
                            early = self._coll_early_pop(comm.cid, p)
                            if early is not None:
                                return p, early
                    # bounded-slice wait (holding the channel lock,
                    # so the pump cannot add early transfers behind
                    # our back mid-wait)
                    src_nid, raw = self._sliced_recv(
                        None, tag, deadline, comm,
                        lambda: [q for q, c in pending.items()
                                 if c > 0],
                        "collective reap awaiting",
                        f"collective any-source receive on "
                        f"{comm.name} timed out")
                    src = src_nid - 1
                    arr = self._finish_checked(
                        src, tag, raw, deadline,
                        epoch0=getattr(comm, "_ft_epoch0", 0))
                    if pending.get(src, 0) > 0:
                        return src, arr
                    with self._coll_early_lock:
                        self._coll_early.setdefault((comm.cid, src),
                                                    []).append(arr)
            finally:
                lk.release()
        finally:
            if tok is not None:
                _watchdog.disarm(tok)

    def _finish_transfer(self, src_pidx: int, tag: int, first_raw,
                         deadline: float):
        """Complete one payload transfer whose first frame was already
        popped by an any-source peek."""
        btl = self._btl_for(src_pidx)
        left_ms = max(1, int((deadline - time.monotonic()) * 1000))
        first = (self._nid(src_pidx), first_raw)
        if btl is self._shm:
            return btl.recv_shm(self.ep, tag, src=self._nid(src_pidx),
                                timeout_ms=left_ms, first=first)
        return btl.recv_staged(self.ep, tag, src=self._nid(src_pidx),
                               timeout_ms=left_ms, first=first)

    def _sliced_recv(self, want_src, tag: int, deadline: float,
                     comm, peers_fn, what: str, timeout_msg: str):
        """THE bounded-slice wait shared by every blocking wire
        consumer (collective reaps, peer-specific receives, ctl
        tokens): each ~100 ms slice re-checks the ULFM failure
        picture — revoked cid, peers dead for this comm's birth
        epoch — so a dead peer or a revoke interrupts the wait with
        the typed error within one detection interval; deadline
        expiry raises ERR_PENDING with ``timeout_msg``. Returns the
        ``(src_nid, raw)`` of the first matching frame."""
        from ..btl.components import stashed_recv

        epoch0 = getattr(comm, "_ft_epoch0", 0)
        while True:
            _ft().check_wait(comm.cid, peers_fn(), what, epoch0=epoch0)
            left = deadline - time.monotonic()
            if left <= 0:
                raise MPIError(ErrorCode.ERR_PENDING, timeout_msg)
            try:
                return stashed_recv(
                    self.ep, want_src, tag,
                    time.monotonic() + min(left, _FT_SLICE_S))
            except MPIError as e:
                if e.code != ErrorCode.ERR_PENDING:
                    raise  # endpoint torn down: surface it
                # slice expired: re-check the picture and re-park

    def _finish_checked(self, src_pidx: int, tag: int, first_raw,
                        deadline: float, epoch0: int = 0):
        """`_finish_transfer` with the ULFM mapping: a transfer whose
        tail never completes because the SENDER is (or becomes) dead
        FOR THIS COMM (its failure episode started at/after the comm's
        birth epoch) surfaces as ERR_PROC_FAILED — the typed error
        recovery policies key on — instead of a generic truncation.
        The epoch comparison matters: a rejoined replacement's flaky
        transfer on a post-recovery comm must stay a flake, not be
        escalated into a (confirmed) process failure."""
        try:
            return self._finish_transfer(src_pidx, tag, first_raw,
                                         deadline)
        except MPIError as e:
            if _ft().dead_for((src_pidx,), epoch0):
                raise MPIError(
                    ErrorCode.ERR_PROC_FAILED,
                    f"collective transfer from process {src_pidx} "
                    f"broke off mid-stream and the job epoch "
                    f"({_ft().epoch}) marks that process failed ({e})",
                )
            raise

    def sentinel_exchange(self, comm, payload: bytes,
                          timeout_ms: Optional[int] = None) -> Dict[int, bytes]:
        """Collective contract sentinel piggyback path (obs_sentinel=2):
        exchange one small signature frame with every member process
        on the comm's ctl channel, strictly BEFORE the round's first
        payload frame. Safe to interleave with barrier tokens: every
        process performs this exchange in the same posting-order slot
        (the progress engine serializes collectives per comm), so the
        per-(src, tag) FIFO keeps signature frames ahead of the
        round's own ctl traffic — and a frame that still arrives out
        of protocol is a loud ERR_INTERN, never silently consumed as
        a token. Sends go out to every peer before any receive parks,
        so a desynced-but-present peer always answers (both sides
        detect the mismatch; neither hangs)."""
        from ..obs import sentinel as _sentinel

        topo = proc_topology(comm)
        for p in topo.peers:
            self.ctl_send(comm, p, _sentinel.SIG_MAGIC + payload)
        out: Dict[int, bytes] = {}
        for p in topo.peers:
            raw = self.ctl_recv(comm, p, timeout_ms=timeout_ms)
            if not raw.startswith(_sentinel.SIG_MAGIC):
                raise MPIError(
                    ErrorCode.ERR_INTERN,
                    f"sentinel exchange on {comm.name} popped a "
                    f"non-signature ctl frame from process {p} — "
                    "collective/ctl ordering diverged",
                )
            out[p] = raw[len(_sentinel.SIG_MAGIC):]
        return out

    def ctl_send(self, comm, peer_pidx: int, payload: bytes = b"") -> None:
        _ft().check_wait(comm.cid, (peer_pidx,), "ctl send",
                         epoch0=getattr(comm, "_ft_epoch0", 0))
        self._retry(
            lambda: self.ep.send(self._nid(peer_pidx),
                                 WIRE_CTL_BASE + comm.cid, payload),
            f"ctl token to process {peer_pidx}",
            peer=peer_pidx, epoch0=getattr(comm, "_ft_epoch0", 0),
        )

    def ctl_recv(self, comm, src_pidx: int,
                 timeout_ms: Optional[int] = None) -> bytes:
        if timeout_ms is None:  # wire_coll_timeout_ms cvar (tunable)
            timeout_ms = self.tuning().coll_timeout_ms
        tok = None
        if _watchdog.enabled:
            tok = _watchdog.arm(
                "barrier_token", comm_id=comm.cid, peer=src_pidx,
                info=lambda s=src_pidx: _ft_split_awaiting([s]))
        try:
            deadline = time.monotonic() + timeout_ms / 1000
            # bounded slices, exactly like the collective reaps: a
            # barrier/ctl wait on a dead peer (or a revoked comm) must
            # raise within one detection interval, not hang
            _, raw = self._sliced_recv(
                self._nid(src_pidx), WIRE_CTL_BASE + comm.cid,
                deadline, comm, lambda: (src_pidx,), "ctl wait on",
                f"ctl wait on process {src_pidx} timed out after "
                f"{timeout_ms} ms")
            return raw
        finally:
            if tok is not None:
                _watchdog.disarm(tok)

    def proc_barrier(self, comm, procs: List[int],
                     timeout_ms: Optional[int] = None) -> None:
        """Dissemination barrier among the participating processes
        (log2 rounds of token exchange on the comm's control channel)."""
        p = len(procs)
        if p <= 1:
            return
        me = procs.index(self.my_pidx)
        k = 1
        while k < p:
            self.ctl_send(comm, procs[(me + k) % p])
            self.ctl_recv(comm, procs[(me - k) % p],
                          timeout_ms=timeout_ms)
            k <<= 1
