"""Cross-process data plane — the unified-COMM_WORLD wire router.

The reference's core runtime promise is that after launch every rank
reaches every rank through one API: ``ompi_mpi_init.c:759-786`` calls
``add_procs`` over *all* peers, and an ``MPI_Send`` crosses nodes
through ``btl/tcp`` (``btl_tcp_component.c:883-893``) with no
caller-visible difference from shared memory. Under ``tpurun`` each
worker process owns only its local jax devices, so cross-process
traffic cannot be a ``device_put`` — it rides the honest transports:
:class:`~..btl.components.ShmBtl` single-segment handoffs on the same
host, :class:`~..btl.components.DcnBtl` chunked OOB staging across
hosts. This router is the glue that lets the PML and the hierarchical
collectives use those transports *through the public API*:

- every worker holds a live OOB link to every peer (full wire-up runs
  during the ESS bootstrap, gated by the init barrier);
- p2p messages are an envelope frame (cid, src/dst comm ranks, user
  tag, sync flag, seq) followed by the btl payload on a per-destination
  channel tag — the receiving process drains its channels into the
  normal PML matching queues, so ordering and wildcards keep MPI
  semantics;
- collectives get per-communicator payload and control channels used
  by the ``hier`` coll component for the inter-process combine step.

Channel tags live far above ``USER_TAG_BASE`` so they can never shadow
the coordinator/pubsub control plane or hand-rolled staged transfers.

Thread model: driver-mode processes issue wire operations from the
main thread (plus completion threads polling acks); the ack set and
sequence counter are lock-protected, payload channels rely on the
per-(src, tag) FIFO the OOB provides plus the shared stash in
``btl.components.stashed_recv``.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..native import DssBuffer
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("wire")

#: p2p envelope+payload channel: + destination WORLD rank
WIRE_P2P_BASE = 1 << 20
#: ssend acknowledgements: + the original sender's WORLD rank
WIRE_ACK_BASE = 2 << 20
#: per-communicator collective payload channel: + cid
WIRE_COLL_BASE = 3 << 20
#: per-communicator collective control channel (barrier tokens): + cid
WIRE_CTL_BASE = 4 << 20

_ENV_MAGIC = "WPM1"


class ProcTopology:
    """Process/member layout of a communicator under the unified
    world — ONE derivation shared by the hier collectives, the wire
    windows, and two-phase collective IO (each previously re-derived
    it; a change to ownership mapping must land exactly once)."""

    __slots__ = ("router", "my_pidx", "owner", "procs", "members_of",
                 "local_ranks", "local_n", "peers")

    def __init__(self, comm) -> None:
        rt = comm.runtime
        self.router: "WireRouter" = rt.wire
        self.my_pidx = int(rt.bootstrap["process_index"])
        n = comm.size
        self.owner: List[int] = [
            self.router.owner_of(comm.group.world_rank(i))
            for i in range(n)
        ]
        self.procs: List[int] = sorted(set(self.owner))
        self.members_of: Dict[int, List[int]] = {
            p: [i for i in range(n) if self.owner[i] == p]
            for p in self.procs
        }
        self.local_ranks: List[int] = list(comm.local_comm_ranks)
        self.local_n = len(self.local_ranks)
        self.peers: List[int] = [p for p in self.procs
                                 if p != self.my_pidx]


def proc_topology(comm) -> ProcTopology:
    """Cached per-communicator topology (the derivation is O(size x
    procs) owner-span scans — pay it once per comm)."""
    topo = getattr(comm, "_proc_topology", None)
    if topo is None:
        topo = comm._proc_topology = ProcTopology(comm)
    return topo


class WireRouter:
    """Per-runtime cross-process router over the worker's OOB endpoint."""

    def __init__(self, runtime) -> None:
        from ..btl.components import DcnBtl, ShmBtl

        self.rt = runtime
        self.agent = runtime.agent
        self.ep = self.agent.ep
        self.cards: List[Dict[str, Any]] = runtime.bootstrap["peer_cards"]
        self.my_pidx: int = runtime.bootstrap["process_index"]
        # rank spans: process p owns world ranks [offset, offset+count)
        self.spans: List[Tuple[int, int]] = runtime.proc_spans
        self._shm = ShmBtl()
        self._dcn = DcnBtl()
        self._seq = itertools.count(1)
        self._acks: set = set()
        self._ack_lock = threading.Lock()
        # per-destination-channel locks: an envelope and its payload
        # must land back-to-back on the channel FIFO (send side) and
        # be popped as a unit (drain side) — concurrent threads on one
        # channel would interleave frames and corrupt the stream
        self._chan_locks: Dict[Tuple[str, int], threading.Lock] = {}
        self._chan_guard = threading.Lock()

    def _chan_lock(self, kind: str, key: int) -> threading.Lock:
        with self._chan_guard:
            lk = self._chan_locks.get((kind, key))
            if lk is None:
                lk = self._chan_locks[(kind, key)] = threading.Lock()
            return lk

    # -- identity ----------------------------------------------------------
    @staticmethod
    def _nid(pidx: int) -> int:
        return pidx + 1  # worker node ids are 1-based (0 is the HNP)

    def owner_of(self, world_rank: int) -> int:
        for p, (off, cnt) in enumerate(self.spans):
            if off <= world_rank < off + cnt:
                return p
        raise MPIError(ErrorCode.ERR_RANK,
                       f"world rank {world_rank} outside every span")

    def _btl_for(self, peer_pidx: int):
        """Transport choice, deterministic on BOTH sides: same machine
        (modex card host identity) -> shm handoff, else DCN staging —
        exactly the per-peer eligibility add_procs computes from
        business cards (``btl.h:810-816``)."""
        same_host = (
            self.cards[self.my_pidx].get("host")
            and self.cards[self.my_pidx].get("host")
            == self.cards[peer_pidx].get("host")
        )
        return self._shm if same_host else self._dcn

    # -- payload channel ---------------------------------------------------
    def _retry(self, fn, what: str):
        """First contact over an accepted fd can race the peer's
        announce processing on our reader thread (the same window
        recv_xcast retries around) — back off briefly before treating
        the link as dead."""
        last = None
        for attempt in range(5):
            try:
                return fn()
            except MPIError as e:
                last = e
                time.sleep(0.05 * (attempt + 1))
        raise MPIError(ErrorCode.ERR_UNREACH,
                       f"{what} failed after retries: {last}")

    def _send_payload(self, peer_pidx: int, tag: int, arr) -> None:
        btl = self._btl_for(peer_pidx)
        arr = np.asarray(arr)
        if btl is self._shm:
            self._retry(
                lambda: btl.send_shm(self.ep, self._nid(peer_pidx), tag,
                                     arr),
                f"shm handoff to process {peer_pidx}",
            )
        else:
            self._retry(
                lambda: btl.send_staged(self.ep, self._nid(peer_pidx),
                                        tag, arr),
                f"staged transfer to process {peer_pidx}",
            )

    def _recv_payload(self, tag: int, src_pidx: int,
                      timeout_ms: int = 30_000):
        btl = self._btl_for(src_pidx)
        if btl is self._shm:
            return btl.recv_shm(self.ep, tag, src=self._nid(src_pidx),
                                timeout_ms=timeout_ms)
        return btl.recv_staged(self.ep, tag, src=self._nid(src_pidx),
                               timeout_ms=timeout_ms)

    # -- p2p (the PML's cross-process route) -------------------------------
    def send_p2p(self, comm, src_rank: int, dst_rank: int, user_tag: int,
                 data, sync: bool) -> int:
        """Envelope + payload to the process owning ``dst_rank``.
        Ranks in the envelope are COMM-local (matching happens against
        the destination comm's queues); the channel is keyed by the
        destination's WORLD rank so every comm shares one ordered
        stream per destination."""
        dst_world = comm.group.world_rank(dst_rank)
        peer = self.owner_of(dst_world)
        seq = next(self._seq)
        tag = WIRE_P2P_BASE + dst_world
        env = DssBuffer()
        env.pack_string(_ENV_MAGIC)
        env.pack_int64([comm.cid, src_rank, dst_rank, int(user_tag),
                        1 if sync else 0, seq])
        with self._chan_lock("send", dst_world):
            self._retry(
                lambda: self.ep.send(self._nid(peer), tag, env.tobytes()),
                f"p2p envelope to process {peer}",
            )
            self._send_payload(peer, tag, np.asarray(data))
        return seq

    def drain_p2p(self, dst_world_rank: int, timeout_ms: int = 50) -> bool:
        """Receive at most ONE wire message destined to
        ``dst_world_rank`` and push it into the owning communicator's
        PML matching queues. Returns True if a message was delivered.

        ``timeout_ms`` bounds only the wait for an ENVELOPE; once one
        is popped, its payload is consumed to completion — the sender
        wrote it immediately behind the envelope on the same FIFO, so
        the stall is bounded by the in-flight transfer, not by user
        behavior (head-of-line blocking per destination channel; a
        nonblocking probe can stall for the tail of a large in-flight
        message). A sender dying between envelope and payload surfaces
        as a loud ERR_TRUNCATE here, never a silently dropped message.
        """
        from ..btl.components import stashed_recv
        from ..comm.communicator import _comm_registry

        tag = WIRE_P2P_BASE + dst_world_rank
        # cheap empty-channel fast path for nonblocking progress
        # (imprecise: pending() counts frames on every tag, so other
        # traffic forces the short recv below — never misses a frame)
        if timeout_ms <= 1 and self.ep.pending() == 0:
            return False
        deadline = time.monotonic() + timeout_ms / 1000
        with self._chan_lock("drain", dst_world_rank):
            try:
                src_nid, raw = stashed_recv(self.ep, None, tag, deadline)
            except MPIError:
                return False  # nothing pending within the timeout
            env = DssBuffer(raw)
            if env.unpack_string() != _ENV_MAGIC:
                _log.verbose(1, f"dropping non-envelope frame on p2p "
                                f"channel {tag}")
                return False
            cid, src_rank, dst_rank, user_tag, sync, seq = \
                env.unpack_int64(6)
            src_pidx = src_nid - 1
            try:
                data = self._recv_payload(tag, src_pidx)
            except MPIError as e:
                raise MPIError(
                    ErrorCode.ERR_TRUNCATE,
                    f"wire message from process {src_pidx} (comm cid "
                    f"{cid}, src rank {src_rank}, tag {user_tag}) "
                    "announced by its envelope but the payload never "
                    f"completed — peer died mid-transfer? ({e})",
                )
        comm = _comm_registry.get(int(cid))
        if comm is None:
            raise MPIError(
                ErrorCode.ERR_COMM,
                f"wire message for unknown cid {cid} (communicator "
                "creation order diverged across processes?)",
            )
        on_matched = None
        if sync:
            src_world = comm.group.world_rank(int(src_rank))

            def on_matched(_req, _p=src_pidx, _c=int(cid), _s=int(seq),
                           _w=src_world):
                self.send_ack(_p, _c, _s, _w)

        comm.pml._enqueue_wire(int(src_rank), int(dst_rank),
                               int(user_tag), data, on_matched=on_matched)
        return True

    # -- ssend acknowledgements --------------------------------------------
    def send_ack(self, peer_pidx: int, cid: int, seq: int,
                 sender_world_rank: int) -> None:
        b = DssBuffer()
        b.pack_int64([cid, seq])
        self._retry(
            lambda: self.ep.send(self._nid(peer_pidx),
                                 WIRE_ACK_BASE + sender_world_rank,
                                 b.tobytes()),
            f"ssend ack to process {peer_pidx}",
        )

    def poll_acks(self, sender_world_rank: int,
                  timeout_ms: int = 0) -> None:
        """Drain every available ack addressed to ``sender_world_rank``
        into the ack set (timeout_ms=0: near-nonblocking — an empty
        endpoint returns immediately via the pending() fast path; with
        unrelated frames queued the probe costs ~1 ms)."""
        tag = WIRE_ACK_BASE + sender_world_rank
        if timeout_ms <= 0 and self.ep.pending() == 0:
            return
        while True:
            try:
                _, _, raw = self.ep.recv(tag=tag,
                                         timeout_ms=max(1, timeout_ms))
            except MPIError:
                return
            cid, seq = DssBuffer(raw).unpack_int64(2)
            with self._ack_lock:
                self._acks.add((int(cid), int(seq)))
            timeout_ms = 0  # only the first recv may wait

    def has_ack(self, cid: int, seq: int) -> bool:
        with self._ack_lock:
            return (cid, seq) in self._acks

    def take_ack(self, cid: int, seq: int) -> bool:
        with self._ack_lock:
            if (cid, seq) in self._acks:
                self._acks.discard((cid, seq))
                return True
            return False

    # -- collective channels (used by the hier coll component) -------------
    @staticmethod
    def _coll_tag(comm) -> int:
        if comm.cid >= (1 << 20):
            raise MPIError(ErrorCode.ERR_INTERN,
                           f"cid {comm.cid} exceeds the wire tag space")
        return WIRE_COLL_BASE + comm.cid

    def coll_send(self, comm, peer_pidx: int, arr) -> None:
        self._send_payload(peer_pidx, self._coll_tag(comm), arr)

    def coll_recv(self, comm, src_pidx: int, timeout_ms: int = 60_000):
        return self._recv_payload(self._coll_tag(comm), src_pidx,
                                  timeout_ms=timeout_ms)

    def ctl_send(self, comm, peer_pidx: int, payload: bytes = b"") -> None:
        self._retry(
            lambda: self.ep.send(self._nid(peer_pidx),
                                 WIRE_CTL_BASE + comm.cid, payload),
            f"ctl token to process {peer_pidx}",
        )

    def ctl_recv(self, comm, src_pidx: int,
                 timeout_ms: int = 60_000) -> bytes:
        from ..btl.components import stashed_recv

        deadline = time.monotonic() + timeout_ms / 1000
        _, raw = stashed_recv(self.ep, self._nid(src_pidx),
                              WIRE_CTL_BASE + comm.cid, deadline)
        return raw

    def proc_barrier(self, comm, procs: List[int],
                     timeout_ms: int = 60_000) -> None:
        """Dissemination barrier among the participating processes
        (log2 rounds of token exchange on the comm's control channel)."""
        p = len(procs)
        if p <= 1:
            return
        me = procs.index(self.my_pidx)
        k = 1
        while k < p:
            self.ctl_send(comm, procs[(me + k) % p])
            self.ctl_recv(comm, procs[(me - k) % p],
                          timeout_ms=timeout_ms)
            k <<= 1
