"""MPI_File API over host files — the ompio surface.

The surface of ``ompi/mca/io`` (open/close/read_at/write_at/
read_all/write_all/shared pointer/set_view) with ompio's component
split honored in miniature: fs = python file open/close per rank
handle, fbtl = individual pread/pwrite at explicit offsets, fcoll =
collective write_all/read_all where every rank's block lands at its
view offset (the two-phase exchange is unnecessary when each "rank"
writes a disjoint contiguous extent — the driver already holds the
aggregated blocks), sharedfp = an ordered shared file pointer.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Tuple

import numpy as np

from ..utils.errors import ErrorCode, MPIError

MODE_RDONLY = os.O_RDONLY
MODE_WRONLY = os.O_WRONLY
MODE_RDWR = os.O_RDWR
MODE_CREATE = os.O_CREAT


class File:
    """MPI_File analogue bound to a communicator."""

    def __init__(self, comm, path: str,
                 mode: int = MODE_RDWR | MODE_CREATE) -> None:
        self.comm = comm
        self.path = path
        try:
            self._fd = os.open(path, mode, 0o644)
        except OSError as e:
            raise MPIError(ErrorCode.ERR_FILE, f"open {path}: {e}")
        self._lock = threading.Lock()
        self._shared_ptr = 0  # sharedfp analogue
        # view: (displacement bytes, elementary dtype)
        self._disp = 0
        self._etype = np.dtype(np.uint8)
        self._closed = False

    # -- view (MPI_File_set_view) -----------------------------------------
    def set_view(self, disp: int = 0, etype=np.uint8) -> None:
        self._disp = int(disp)
        self._etype = np.dtype(etype)

    def _byte_offset(self, offset_elems: int) -> int:
        return self._disp + offset_elems * self._etype.itemsize

    def _check(self) -> None:
        if self._closed:
            raise MPIError(ErrorCode.ERR_FILE, f"{self.path} closed")

    # -- individual (fbtl) -------------------------------------------------
    def write_at(self, offset: int, data) -> int:
        """pwrite at an element offset in the current view."""
        self._check()
        buf = np.ascontiguousarray(np.asarray(data, self._etype))
        n = os.pwrite(self._fd, buf.tobytes(), self._byte_offset(offset))
        return n // self._etype.itemsize

    def read_at(self, offset: int, count: int) -> np.ndarray:
        self._check()
        raw = os.pread(
            self._fd, count * self._etype.itemsize,
            self._byte_offset(offset),
        )
        return np.frombuffer(raw, self._etype).copy()

    # -- collective (fcoll) ------------------------------------------------
    def write_at_all(self, offsets, blocks) -> int:
        """Collective write: rank i's block at element offset i
        (driver mode: per-rank lists). Disjoint contiguous extents per
        rank = the post-aggregation phase of fcoll/two_phase. The
        per-rank pwrites are issued concurrently (os.pwrite releases
        the GIL), matching the aggregators-write-in-parallel phase."""
        self._check()
        if len(offsets) != self.comm.size or len(blocks) != self.comm.size:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"need {self.comm.size} offsets/blocks (one per rank)",
            )
        with ThreadPoolExecutor(
            max_workers=min(self.comm.size, 16)
        ) as pool:
            total = sum(pool.map(
                lambda ob: self.write_at(ob[0], ob[1]),
                zip(offsets, blocks),
            ))
        self.comm.barrier()
        return total

    def read_at_all(self, offsets, counts):
        self._check()
        if len(offsets) != self.comm.size or len(counts) != self.comm.size:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"need {self.comm.size} offsets/counts (one per rank)",
            )
        with ThreadPoolExecutor(
            max_workers=min(self.comm.size, 16)
        ) as pool:
            out = list(pool.map(
                lambda oc: self.read_at(oc[0], oc[1]),
                zip(offsets, counts),
            ))
        self.comm.barrier()
        return out

    # -- shared file pointer (sharedfp) ------------------------------------
    def write_ordered(self, blocks) -> None:
        """Rank-ordered append at the shared pointer (sharedfp
        'ordered' semantics)."""
        self._check()
        with self._lock:
            for blk in blocks:
                buf = np.ascontiguousarray(np.asarray(blk, self._etype))
                os.pwrite(self._fd, buf.tobytes(),
                          self._byte_offset(self._shared_ptr))
                self._shared_ptr += buf.size

    def write_shared(self, data) -> int:
        """Append one buffer at the shared pointer (sharedfp
        non-ordered write: first-come placement) — one rank's
        write_ordered, sharing the placement logic."""
        buf = np.asarray(data, self._etype)
        self.write_ordered([buf])
        return int(buf.size)  # not a pointer diff: races with other
        #                       shared-pointer writers would misreport

    def read_shared(self, count: int) -> np.ndarray:
        self._check()
        with self._lock:
            out = self.read_at(self._shared_ptr, count)
            self._shared_ptr += count
        return out

    # -- admin -------------------------------------------------------------
    def size(self) -> int:
        self._check()
        return os.fstat(self._fd).st_size

    def preallocate(self, nbytes: int) -> None:
        self._check()
        os.ftruncate(self._fd, nbytes)

    def sync(self) -> None:
        self._check()
        os.fsync(self._fd)

    def close(self) -> None:
        if not self._closed:
            os.close(self._fd)
            self._closed = True

    @staticmethod
    def delete(path: str) -> None:
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass

    def __enter__(self) -> "File":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
