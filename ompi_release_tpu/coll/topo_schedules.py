"""Topology-aware inter-process schedules — multi-ring striping and
2D-torus decomposition for the spanning collectives.

The schedules in :mod:`.hier_schedules` treat every inter-process link
as uniform; the modex host identity knows better. This module adds the
schedule family that exploits it, in the same PURE form (driven only
through the exchange adapter, deterministic functions of
``(procs, me, sizes, host_of)`` — the lockstep parity harness and the
fleet simulator run them unmodified):

``multiring``  (allreduce)
    k concurrent rings over DISJOINT neighbor permutations (stride-s
    successor maps for k units s coprime to P — distinct strides give
    every process k distinct successors), the buffer striped k ways.
    Each round posts one chunk per ring, so a bandwidth-bound fabric
    sees ~k links driven in parallel where the single ring serialized
    one: same ~2n bytes per process, 2(P-1) rounds, k× ring bandwidth.

``torus2d``  (allreduce / allgather / bcast)
    ``topo.dims_create``-style factorization P = d0 × d1 with dim 0
    PINNED to intra-host links by the ``host_of`` grouping (uniform
    host groups of d0 processes across d1 hosts — :func:`torus_grid`
    returns None for ragged layouts and the schedules degrade to the
    flat ring). Allreduce: ring reduce-scatter along dim 0 (shm), ring
    allreduce of the 1/d0-sized partial along dim 1 (DCN), ring
    allgather along dim 0 — DCN carries ONLY the 1/d0-sized partials,
    exactly 2(d1-1)·ceil(ceil(n/d0)/d1) elements per process
    (:func:`torus_inter_bytes_per_rank`), a d0× cut of the flat ring's
    per-boundary-NIC bytes and strictly fewer total inter-host bytes
    (:func:`flat_ring_inter_bytes_total` gives the flat baseline the
    fleet tests compare closed-form). Allgather: dim-1 ring of own
    blocks (DCN moves single blocks), then a dim-0 multi-block ring
    (shm moves the aggregates). Bcast: binomial over one
    representative per host (DCN: d1-1 sends total), then binomial
    within each host (shm).

Reduction-order discipline is inherited: ``multiring``/``torus2d``
allreduce fold chunks in rotated order and pad with the op identity,
so they live in :data:`.hier_schedules.ORDER_WAIVING` — commutative
ops with an identity only, with the same forcing-raises /
rule-downgrades guard semantics the leader tier pinned.
"""

from __future__ import annotations

import math
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import obs as _obs
from ..mca import pvar
from . import hier_schedules as _hs
from .hier_schedules import _concat, _flat, _round

#: topology-aware schedule executions (one bump per completed run) —
#: the auditable "the topo family actually engaged" counter
_topo_runs = pvar.counter(
    "hier_topo_schedule_runs",
    "topology-aware (multi-ring / 2D-torus) spanning-schedule "
    "executions",
)

#: algorithm names this module serves (hier dispatch + the
#: leader-tier stand-aside check key off this)
TOPO_ALGS = ("multiring", "torus2d")


# ---------------------------------------------------------------------------
# grids, strides, closed forms
# ---------------------------------------------------------------------------

def torus_grid(procs: List[int], host_of: Dict[int, str]
               ) -> Optional[Tuple[int, int, List[List[int]]]]:
    """(d0, d1, groups) for a UNIFORM host layout of ``procs`` —
    groups (one per host, ordered by lowest member, members sorted by
    process index) of equal size d0 across d1 hosts — or None when the
    layout is ragged or spans a single host (no torus to exploit).
    Deterministic on every process: derived from the shared modex
    host map alone."""
    by_host: Dict[str, List[int]] = {}
    for p in procs:
        by_host.setdefault(host_of.get(p, f"proc-{p}"), []).append(p)
    groups = sorted((sorted(g) for g in by_host.values()),
                    key=lambda g: g[0])
    d1 = len(groups)
    if d1 < 2:
        return None
    d0 = len(groups[0])
    if any(len(g) != d0 for g in groups):
        return None  # ragged: no uniform torus
    return d0, d1, groups


def grid_dims(procs: List[int],
              host_of: Dict[int, str]) -> Optional[Tuple[int, int]]:
    """(d0, d1) of the uniform torus over ``procs``, or None — what
    ``pick(..., topo=)`` consumes."""
    g = torus_grid(procs, host_of)
    return (g[0], g[1]) if g else None


def ring_strides(P: int, k: int) -> List[int]:
    """Up to ``k`` stride values coprime to P (stride 1 first): each
    defines one single-cycle ring, and distinct strides give every
    process pairwise-distinct successors AND predecessors — the
    disjoint neighbor permutations multiring stripes across."""
    out = [s for s in range(1, P) if math.gcd(s, P) == 1]
    return out[:max(1, int(k))]


def torus_rounds(d0: int, d1: int) -> int:
    """Exchange rounds of the torus allreduce: dim-0 reduce-scatter +
    dim-1 ring allreduce + dim-0 allgather."""
    return 2 * (d0 - 1) + 2 * (d1 - 1)


def torus_inter_bytes_per_rank(n_elems: int, itemsize: int,
                               d0: int, d1: int) -> int:
    """Exact host-crossing send bytes per process of the torus
    allreduce: only the dim-1 ring allreduce of the 1/d0-sized partial
    crosses DCN — 2(d1-1) chunks of ceil(ceil(n/d0)/d1) elements."""
    per0 = max(1, -(-int(n_elems) // d0))
    per1 = max(1, -(-per0 // d1))
    return 2 * (d1 - 1) * per1 * int(itemsize)


def torus_inter_bytes_total(n_elems: int, itemsize: int,
                            d0: int, d1: int) -> int:
    return d0 * d1 * torus_inter_bytes_per_rank(n_elems, itemsize,
                                                d0, d1)


def flat_ring_inter_bytes_total(n_elems: int, itemsize: int,
                                P: int, hosts: int) -> int:
    """Exact host-crossing send bytes of the FLAT ring allreduce over
    contiguous equal host groups: the ring crosses hosts at exactly
    ``hosts`` boundary processes, each shipping every one of its
    2(P-1) chunks of ceil(n/P) elements across DCN. The closed-form
    baseline the torus variant is asserted strictly below (total) and
    ~d0× below (per boundary NIC)."""
    per = max(1, -(-int(n_elems) // P))
    return hosts * 2 * (P - 1) * per * int(itemsize)


# ---------------------------------------------------------------------------
# shared ring fragments
# ---------------------------------------------------------------------------

def _pad_flat(mine, slots: int, identity) -> Tuple[np.ndarray, int, int]:
    """(flat padded to per*slots elements, original length, per)."""
    flat = _flat(mine)
    L = flat.shape[0]
    per = max(1, -(-L // slots))
    if per * slots != L:
        flat = np.concatenate(
            [flat, np.full(per * slots - L, identity, flat.dtype)])
    elif not flat.flags.writeable:
        flat = flat.copy()
    return flat, L, per


def _ring_reduce_scatter(x, ring: List[int], mi: int,
                         chunks: List[np.ndarray], op: Callable) -> int:
    """In-place ring reduce-scatter over ``ring``: P-1 rounds, chunk
    fold order the fixed rotation (commutative ops only — callers sit
    behind the ORDER_WAIVING guard). Returns the chunk position this
    member owns fully reduced, (mi+1) % P."""
    P = len(ring)
    nxt, prv = ring[(mi + 1) % P], ring[(mi - 1) % P]
    for s in range(P - 1):
        cs = (mi - s) % P
        cr = (mi - s - 1) % P
        got = _round(x, {nxt: [chunks[cs]]}, {prv: 1})[prv][0]
        chunks[cr] = np.asarray(op(_flat(got), chunks[cr]))
    return (mi + 1) % P


def _allgather_ring_multi(x, ring: List[int], mi: int,
                          arrs: List[np.ndarray]) -> List[List[np.ndarray]]:
    """Ring allgather of a LIST of blocks per member (m messages per
    round, per-peer FIFO keeps list order). Returns per-position block
    lists in ring-position order."""
    P = len(ring)
    m = len(arrs)
    nxt, prv = ring[(mi + 1) % P], ring[(mi - 1) % P]
    blocks: Dict[int, List[np.ndarray]] = {
        mi: [np.asarray(a) for a in arrs]}
    for s in range(P - 1):
        cs = (mi - s) % P
        cr = (mi - s - 1) % P
        got = _round(x, {nxt: list(blocks[cs])}, {prv: m})
        blocks[cr] = [np.asarray(a) for a in got[prv]]
    return [blocks[i] for i in range(P)]


def _coords(grid: Tuple[int, int, List[List[int]]],
            me: int) -> Tuple[int, int]:
    """(intra position, group index) of ``me`` in the grid."""
    d0, d1, groups = grid
    for gj, g in enumerate(groups):
        if me in g:
            return g.index(me), gj
    raise ValueError(f"process {me} not in the torus grid")


# ---------------------------------------------------------------------------
# multi-ring striped allreduce
# ---------------------------------------------------------------------------

def allreduce_multiring(x, procs: List[int], me: int, mine,
                        op: Callable, identity, k: int = 4) -> np.ndarray:
    """k-ring striped allreduce: the buffer splits into k stripes,
    stripe j ring-reduce-scatter+allgathers over the stride-s_j ring,
    and every round posts all k stripes' chunks at once — k disjoint
    links driven in parallel per round. Degrades to the single ring
    when P admits fewer than 2 coprime strides. Commutative ops with
    an identity only (``pick`` enforces via ORDER_WAIVING)."""
    P = len(procs)
    if P == 1:
        return _flat(mine)
    strides = ring_strides(P, k)
    if len(strides) < 2:
        return _hs.allreduce_ring(x, procs, me, mine, op, identity)
    k = len(strides)
    rec = _obs.enabled
    t0 = _time.perf_counter() if rec else 0.0
    mi = procs.index(me)
    flat, L, per = _pad_flat(mine, k * P, identity)
    # chunks[j][c]: stripe j's chunk at ring position c
    chunks = [[flat[(j * P + c) * per:(j * P + c + 1) * per].copy()
               for c in range(P)] for j in range(k)]
    # my position on ring j: walking from 0 by stride s_j reaches mi
    # after (mi * s_j^-1) mod P steps; successor/predecessor are the
    # stride neighbors (pairwise distinct across rings)
    pos = [(mi * pow(s, -1, P)) % P for s in strides]
    nxt = [procs[(mi + s) % P] for s in strides]
    prv = [procs[(mi - s) % P] for s in strides]
    for s_ in range(P - 1):  # reduce-scatter, k rings per round
        sends = {nxt[j]: [chunks[j][(pos[j] - s_) % P]]
                 for j in range(k)}
        got = _round(x, sends, {prv[j]: 1 for j in range(k)})
        for j in range(k):
            cr = (pos[j] - s_ - 1) % P
            g = _flat(got[prv[j]][0])
            chunks[j][cr] = np.asarray(op(g, chunks[j][cr]))
    for s_ in range(P - 1):  # allgather of the reduced chunks
        sends = {nxt[j]: [chunks[j][(pos[j] + 1 - s_) % P]]
                 for j in range(k)}
        got = _round(x, sends, {prv[j]: 1 for j in range(k)})
        for j in range(k):
            cr = (pos[j] - s_) % P
            chunks[j][cr] = _flat(got[prv[j]][0])
    out = np.concatenate([chunks[j][c]
                          for j in range(k) for c in range(P)])[:L]
    _topo_runs.add()
    if rec and _obs.enabled:
        _obs.record("topo_allreduce_multiring", "hier", t0,
                    _time.perf_counter() - t0, nbytes=int(out.nbytes))
    return out


# ---------------------------------------------------------------------------
# 2D torus: allreduce / allgather / bcast
# ---------------------------------------------------------------------------

def allreduce_torus2d(x, procs: List[int], me: int, mine,
                      op: Callable, identity,
                      host_of: Dict[int, str]) -> np.ndarray:
    """2D-torus allreduce: reduce-scatter along the intra-host dim,
    ring allreduce of the 1/d0 partial along the inter-host dim, ring
    allgather back along the intra dim. DCN carries only the dim-1
    phase — :func:`torus_inter_bytes_per_rank` exactly. Falls back to
    the flat ring on ragged or single-host layouts (and on d0 == 1,
    where the torus IS the flat ring over hosts)."""
    grid = torus_grid(procs, host_of)
    if grid is None or grid[0] == 1:
        return _hs.allreduce_ring(x, procs, me, mine, op, identity)
    d0, d1, groups = grid
    rec = _obs.enabled
    t0 = _time.perf_counter() if rec else 0.0
    gi, gj = _coords(grid, me)
    group = groups[gj]
    column = [groups[j][gi] for j in range(d1)]
    flat, L, per0 = _pad_flat(mine, d0, identity)
    chunks = [flat[c * per0:(c + 1) * per0].copy() for c in range(d0)]
    own = _ring_reduce_scatter(x, group, gi, chunks, op)   # shm
    part = _hs.allreduce_ring(x, column, me, chunks[own],  # DCN
                              op, identity)
    got = _hs.allgather_ring(x, group, me, np.asarray(part))  # shm
    # intra position i owns chunk (i+1) % d0 after the reduce-scatter
    out = np.concatenate([_flat(got[(c - 1) % d0])
                          for c in range(d0)])[:L]
    _topo_runs.add()
    if rec and _obs.enabled:
        _obs.record("topo_allreduce_torus2d", "hier", t0,
                    _time.perf_counter() - t0, nbytes=int(out.nbytes))
    return out


def allgather_torus2d(x, procs: List[int], me: int, mine,
                      host_of: Dict[int, str]) -> List[np.ndarray]:
    """2D-torus allgather: ring allgather of single blocks along the
    inter-host dim (DCN moves (d1-1) blocks per process instead of a
    boundary NIC moving P-1), then a multi-block ring along the intra
    dim distributes the column aggregates over shm. Blocks may differ
    in shape (they ride the wire). Returns blocks in process-index
    order, exactly like :func:`.hier_schedules.allgather_ring`."""
    grid = torus_grid(procs, host_of)
    if grid is None:
        return _hs.allgather_ring(x, procs, me, mine)
    d0, d1, groups = grid
    rec = _obs.enabled
    t0 = _time.perf_counter() if rec else 0.0
    gi, gj = _coords(grid, me)
    column = [groups[j][gi] for j in range(d1)]
    col_blocks = _hs.allgather_ring(x, column, me, np.asarray(mine))
    group = groups[gj]
    if d0 > 1:
        rows = _allgather_ring_multi(x, group, gi, col_blocks)
    else:
        rows = [col_blocks]
    block_of: Dict[int, np.ndarray] = {}
    for i in range(d0):
        for j in range(d1):
            block_of[groups[j][i]] = np.asarray(rows[i][j])
    out = [block_of[p] for p in procs]
    _topo_runs.add()
    if rec and _obs.enabled:
        _obs.record("topo_allgather_torus2d", "hier", t0,
                    _time.perf_counter() - t0,
                    nbytes=sum(int(b.nbytes) for b in out))
    return out


def bcast_torus2d(x, procs: List[int], me: int, root: int, val,
                  host_of: Dict[int, str]):
    """2D-torus bcast: binomial over one representative per host (the
    root represents its own host), then binomial within each host —
    DCN carries exactly d1-1 copies total, shm the rest. ``val`` is
    read on the root only."""
    grid = torus_grid(procs, host_of)
    if grid is None:
        return _hs.bcast_binomial(x, procs, me, root, val)
    d0, d1, groups = grid
    rec = _obs.enabled
    t0 = _time.perf_counter() if rec else 0.0
    _, gj = _coords(grid, me)
    _, rj = _coords(grid, root)
    reps = sorted({root} | {groups[j][0] for j in range(d1)
                            if j != rj})
    if me in reps:
        val = _hs.bcast_binomial(x, reps, me, root, val)
    group = groups[gj]
    rep = root if gj == rj else groups[gj][0]
    if len(group) > 1:
        val = _hs.bcast_binomial(x, group, me, rep, val)
    val = np.asarray(val)
    _topo_runs.add()
    if rec and _obs.enabled:
        _obs.record("topo_bcast_torus2d", "hier", t0,
                    _time.perf_counter() - t0, nbytes=int(val.nbytes))
    return val
