"""Native plan execution — run frozen wire rounds end-to-end in C.

The reference's steady state walks posted descriptors inside opal
progress without re-entering any interpreter; our PR 13 plans and the
PR 17 native datapath still met in Python: every compiled fire paid
one ``PlannedXchg.exchange`` per round — per-fragment generator
``next()`` calls, per-arrival reap callbacks, fresh reassembly
buffers. This module lowers a whole frozen :class:`~.plan.WirePlan`
into a flat C descriptor table (``native/planexec.cc``) so a fire
becomes ONE ctypes call per ~100 ms slice: sends stripe through the
existing shm-ring writev / vectored-socket legs with the interpreted
path's exact FIFO-per-peer and depth discipline, receives land in a
per-plan preallocated reassembly pool reused across fires, and round
boundaries stamp into a timestamp block the obs ledger record
consumes unchanged.

How rounds >= 1 get their bytes without Python: at descriptor-compile
time the schedule body runs TWICE against a wire-free probe adapter,
each time over fresh random-byte inputs and random-byte synthetic
receives. Every later-round send payload is then located inside the
concatenation of (input regions | receive-pool regions) by unique
16-byte windows — a scatter-gather map of ``(region, offset, length)``
spans. Random bytes make any coincidental match astronomically
unlikely, and the two independently-seeded probes must infer the SAME
map or the plan stays on ``PlannedXchg``. The map is exact byte
provenance: at fire time C composes each send from live region bytes,
so the wire traffic is bitwise-identical to the interpreted path's
(the mixed-fleet contract — a peer without the .so interoperates
frame-for-frame).

Selection follows the MCA discipline: the ``coll_plan_native`` cvar
plus a capability check — native symbols present, every round peer on
the nativewire card, every send slot frame-templated, no QoS arbiter
— picks the C executor; anything else falls back to ``PlannedXchg``
unchanged. A fire that finds stashed/early frames or ring-lock
contention falls back for THAT fire only (``plan_native_fallbacks``).

ULFM: the executor polls a per-plan fault word and yields every
``slice_ms``; Python mirrors ``FtState`` into the word and runs
``check_wait`` between slices, so death/revocation surfaces as the
usual typed error within the detection interval.
"""
from __future__ import annotations

import os
import struct
import sys
import threading
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import obs as _obs
from ..mca import pvar
from ..mca import var as mca_var
from ..utils.errors import ErrorCode, MPIError

#: bytes held by the per-plan native reassembly pools (the
#: mpool/rcache analogue: sized from the frozen recv metadata at
#: descriptor-compile time, reused across fires)
_pool_bytes = pvar.counter(
    "plan_pool_bytes",
    "bytes preallocated in native plan-executor reassembly pools "
    "(sized from frozen recv metadata, reused across fires)",
)
_pool_hits = pvar.counter(
    "plan_pool_hits",
    "preallocated pool buffers served to native plan fires (each "
    "hit = one reassembly that allocated nothing)",
)
_native_fires = pvar.counter(
    "plan_native_fires",
    "frozen wire plans fired end-to-end by the C executor (one "
    "ctypes slice loop instead of per-round Python orchestration)",
)
_native_fallbacks = pvar.counter(
    "plan_native_fallbacks",
    "native-eligible fires that fell back to the interpreted "
    "PlannedXchg replay for one fire (stashed/early frames, "
    "ring-lock contention)",
)

_BLOB_MAGIC = 0x314345584C504F  # "OPLXEC1" little-endian
_BLOB_VERSION = 1
_WIN = 16        # provenance-window bytes: unique-match granularity
_SEP = 32        # random separator bytes between arena regions
_SLICE_MS = 100  # matches runtime.wire._FT_SLICE_S


class _ProbeFail(Exception):
    """Descriptor compile cannot prove byte provenance — the plan
    stays on the interpreted PlannedXchg replay (never an error)."""


class _Ineligible(Exception):
    """Selection gate said no (cvar off, mixed fleet, missing
    symbols, ...) — same graceful withdrawal as :class:`_ProbeFail`,
    but named so OMPITPU_PLAN_NATIVE_DEBUG reports the gate."""


def available() -> bool:
    """True when the loaded .so carries the planexec symbols."""
    try:
        from ..native import bindings as _b
        return bool(_b.planexec_symbols_available())
    except Exception:
        return False


def _as_np(a):
    return a if isinstance(a, np.ndarray) else np.asarray(a)


def _nbytes_of(shape, dtype_str) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n * int(np.dtype(dtype_str).itemsize)


# ---------------------------------------------------------------------------
# probe: run the schedule body wire-free over random bytes
# ---------------------------------------------------------------------------

class _ProbeXchg:
    """Wire-free exchange adapter for the provenance probe: verifies
    each round's structure against the frozen plan, captures the send
    payload bytes in stream order, and hands back the pre-generated
    random receive arrays (the future pool regions)."""

    __slots__ = ("plan", "pools", "i", "payloads")

    def __init__(self, plan, pools: Dict[Tuple[int, int], list]) -> None:
        self.plan = plan
        self.pools = pools
        self.i = 0
        #: per round: payload bytes per message, in (sorted peer,
        #: message-list) order — the blob's stream order
        self.payloads: List[List[bytes]] = []

    def exchange(self, sends: Dict[int, list],
                 recvs: Dict[int, int]) -> Dict[int, list]:
        plan = self.plan
        if self.i >= len(plan.rounds):
            raise _ProbeFail("probe ran more rounds than the plan")
        rnd = plan.rounds[self.i]
        sends_f = {p: [_as_np(a) for a in arrs]
                   for p, arrs in sends.items() if arrs}
        meta = tuple(
            (p, tuple((a.shape, str(a.dtype)) for a in sends_f[p]))
            for p in sorted(sends_f))
        recvs_t = tuple(sorted((int(p), int(c))
                               for p, c in recvs.items() if int(c) > 0))
        if meta != rnd.sends_meta or recvs_t != rnd.recvs_t:
            raise _ProbeFail("structure diverged under probe inputs")
        pay = []
        for p in sorted(sends_f):
            for a in sends_f[p]:
                pay.append(np.ascontiguousarray(a).tobytes())
        self.payloads.append(pay)
        got = {src: list(self.pools.get((self.i, src), ()))
               for src, _ in rnd.recvs_t}
        self.i += 1
        return got


def _rand_array(rng, shape, dtype_str) -> np.ndarray:
    dt = np.dtype(dtype_str)
    nb = _nbytes_of(shape, dtype_str)
    return np.frombuffer(bytearray(rng.bytes(nb)),
                         dtype=dt).reshape(shape)


def _probe_once(plan, m, fn: Callable, args: Tuple, kw: Dict,
                arg_idx: Tuple[int, ...], seed: int):
    """One wire-free run of the schedule body over random bytes.
    Returns (arg_arrays, pool_list, payloads-per-round)."""
    rng = np.random.default_rng(seed)
    pargs = list(args)
    arg_arrays = []
    for j in arg_idx:
        spec = _as_np(args[j])
        a = _rand_array(rng, spec.shape, str(spec.dtype))
        pargs[j] = a
        arg_arrays.append(a)
    pools: Dict[Tuple[int, int], list] = {}
    pool_list: List[np.ndarray] = []
    for i, rnd in enumerate(plan.rounds):
        for src, metas in rnd.recvs_meta:
            lst = [_rand_array(rng, shape, dt) for shape, dt in metas]
            pools[(i, src)] = lst
            pool_list.extend(lst)
    probe = _ProbeXchg(plan, pools)
    old = m._xchg
    m._xchg = probe
    try:
        # random bytes reinterpreted as floats are free to be NaN/inf
        # — only the structure and the raw payload bytes matter here
        with np.errstate(all="ignore"):
            fn(*pargs, **(kw or {}))
    finally:
        m._xchg = old
    if probe.i != len(plan.rounds):
        raise _ProbeFail("probe ran fewer rounds than the plan")
    return arg_arrays, pool_list, probe.payloads


def _build_arena(rng, arg_arrays, pool_list):
    """Concatenate every provenance source region with random
    separators. Returns (arena bytes, sorted region bounds) where a
    bound is (start, end, kind, idx): kind 0 = input region idx
    (positional — args occupy the first input slots), 1 = pool idx."""
    parts: List[bytes] = []
    bounds: List[Tuple[int, int, int, int]] = []
    pos = 0

    def _add(kind: int, idx: int, raw: bytes) -> None:
        nonlocal pos
        sep = rng.bytes(_SEP)
        parts.append(sep)
        pos += _SEP
        parts.append(raw)
        bounds.append((pos, pos + len(raw), kind, idx))
        pos += len(raw)

    for j, a in enumerate(arg_arrays):
        _add(0, j, a.tobytes())
    for k, a in enumerate(pool_list):
        _add(1, k, a.tobytes())
    parts.append(rng.bytes(_SEP))
    return b"".join(parts), bounds


def _region_at(bounds, off: int):
    """The region containing arena offset ``off`` (binary search), or
    None when it falls into a separator gap."""
    lo, hi = 0, len(bounds)
    while lo < hi:
        mid = (lo + hi) // 2
        if bounds[mid][0] <= off:
            lo = mid + 1
        else:
            hi = mid
    if lo == 0:
        return None
    b = bounds[lo - 1]
    return b if off < b[1] else None


def _match_payload(pay: bytes, arena: bytes, a_arr: np.ndarray,
                   bounds) -> Tuple[Tuple[int, int, int, int], ...]:
    """Greedy scatter-gather decomposition of one send payload over
    the arena: 16-byte windows anchor each span, vectorized compare
    extends it, region bounds clamp it. A window appearing in several
    regions (a round-0 send that aliases an argument, say) resolves
    DETERMINISTICALLY — longest matched span, then lowest arena
    offset — so both probe runs pick the same source; the cross-probe
    map-equality check in :func:`_infer_maps` is what proves the pick
    is structural, not a byte coincidence. Anything unprovable is a
    :class:`_ProbeFail` — fallback, never a guess."""
    n = len(pay)
    if n < _WIN:
        raise _ProbeFail("payload too small for provenance windows")
    p_arr = np.frombuffer(pay, dtype=np.uint8)
    segs: List[Tuple[int, int, int, int]] = []
    pos = 0
    while pos < n:
        if n - pos < _WIN:
            raise _ProbeFail("unmatchable payload tail")
        w = pay[pos:pos + _WIN]
        best = None  # (mlen, -off) maximized
        off = arena.find(w)
        if off < 0:
            raise _ProbeFail("payload bytes not found in any region")
        while off >= 0:
            reg = _region_at(bounds, off)
            if reg is not None and off + _WIN <= reg[1]:
                lim = min(n - pos, reg[1] - off)
                d = np.flatnonzero(
                    a_arr[off:off + lim] != p_arr[pos:pos + lim])
                mlen = int(d[0]) if d.size else lim
                if mlen >= _WIN and (best is None or mlen > best[0]):
                    best = (mlen, off, reg)
            off = arena.find(w, off + 1)
        if best is None:
            raise _ProbeFail("window matches no whole region span")
        mlen, off, reg = best
        start, _end, kind, idx = reg
        prev = segs[-1] if segs else None
        if (prev is not None and prev[0] == kind and prev[1] == idx
                and prev[2] + prev[3] == off - start):
            segs[-1] = (kind, idx, prev[2], prev[3] + mlen)
        else:
            segs.append((kind, idx, off - start, mlen))
        pos += mlen
    return tuple(segs)


def _infer_maps(plan, m, fn, args, kw, arg_idx):
    """Byte-provenance maps for every round >= 1 send message, proven
    identical across two independently-seeded probes."""
    results = []
    for seed in (0x5EED01 ^ (plan.cid & 0xFFFF),
                 0x5EED02 ^ (plan.cid & 0xFFFF)):
        arg_arrays, pool_list, payloads = _probe_once(
            plan, m, fn, args, kw, arg_idx, seed)
        # round-0 payload count has to match the stream order BEFORE
        # the arena is laid out: those payloads are input regions
        n0 = sum(len(a) for _, a in plan.rounds[0].sends_meta)
        if len(payloads[0]) != n0:
            raise _ProbeFail("round-0 message count diverged")
        rng = np.random.default_rng(seed ^ 0xA5A5A5)
        # provenance sources = args, then the round-0 send payloads
        # (same order as the C input-region table: a later round may
        # resend a locally-folded partial no argument ever held),
        # then every pool buffer
        inputs = list(arg_arrays) + [
            np.frombuffer(p, dtype=np.uint8) for p in payloads[0]]
        arena, bounds = _build_arena(rng, inputs, pool_list)
        a_arr = np.frombuffer(arena, dtype=np.uint8)
        maps: List[Optional[Tuple]] = [None]  # round 0 is identity
        for r in range(1, len(plan.rounds)):
            maps.append(tuple(_match_payload(p, arena, a_arr, bounds)
                              for p in payloads[r]))
        results.append(tuple(maps[1:]))
    if results[0] != results[1]:
        raise _ProbeFail("independent probes inferred different maps")
    return (None,) + results[0]


# ---------------------------------------------------------------------------
# descriptor compile: plan + maps -> flat C blob
# ---------------------------------------------------------------------------

def _align8(n: int) -> int:
    return (n + 7) & ~7


def build_blob(tag: int, input_lens, pool_sizes, peer_pidx,
               rounds) -> bytes:
    """Serialize the flat descriptor table ``planexec_create``
    consumes (all fields little-endian int64; byte fields carry an
    int64 length prefix). ``rounds`` entries are dicts with ``depth``,
    ``streams`` = [(peer_idx, [msg...])] where a send msg is
    (pre, mid, nbytes, nchunks, chunk, segs) and segs are
    (kind, idx, off, len); ``rsrcs`` = [(peer_idx, [recv msg...])]
    where a recv msg is (pool_idx, nbytes, nchunks, chunk, pre, mid).
    Exposed module-level so ``obs --selftest`` compiles a descriptor
    table device-free."""
    out = bytearray()

    def w(v: int) -> None:
        out.extend(struct.pack("<q", int(v)))

    def wb(b: bytes) -> None:
        w(len(b))
        out.extend(b)

    w(_BLOB_MAGIC)
    w(_BLOB_VERSION)
    w(tag)
    w(len(input_lens))
    for n in input_lens:
        w(n)
    off = 0
    offs = []
    for n in pool_sizes:
        offs.append(off)
        off = _align8(off + n)
    w(len(pool_sizes))
    for o, n in zip(offs, pool_sizes):
        w(o)
        w(n)
    w(off)  # pool_total
    w(len(peer_pidx))
    for p in peer_pidx:
        w(p)
    w(len(rounds))
    for rd in rounds:
        w(rd["depth"])
        w(len(rd["streams"]))
        for peer_idx, msgs in rd["streams"]:
            w(peer_idx)
            w(len(msgs))
            for pre, mid, nbytes, nchunks, chunk, segs in msgs:
                wb(pre)
                wb(mid)
                w(nbytes)
                w(nchunks)
                w(chunk)
                w(len(segs))
                for kind, idx, so, sl in segs:
                    w(kind)
                    w(idx)
                    w(so)
                    w(sl)
        w(len(rd["rsrcs"]))
        for peer_idx, msgs in rd["rsrcs"]:
            w(peer_idx)
            w(len(msgs))
            for pool_idx, nbytes, nchunks, chunk, pre, mid in msgs:
                w(pool_idx)
                w(nbytes)
                w(nchunks)
                w(chunk)
                wb(pre)
                wb(mid)
    return bytes(out)


class NativePlan:
    """One compiled-and-bound native executor: the C descriptor table
    handle, the fire-time layout (input specs, per-round pool
    placements), the ring/lock bindings, and precomputed pvar totals
    so the MPI_T series never dip when the C path engages."""

    __slots__ = (
        "gen", "px", "cid", "tag", "peers", "arg_idx", "arg_specs",
        "r0_specs", "pool_rounds", "timeout_ms", "ftword", "router",
        "rx_entries", "fire_locks", "send_msgs", "send_bytes",
        "recv_msgs", "recv_bytes", "send_frames", "recv_frames",
        "xfer_total", "pool_count", "pool_total",
    )

    def close(self) -> None:
        px, self.px = self.px, None
        if px is not None:
            try:
                px.close()
            except Exception:
                pass


def _sentinel_level() -> int:
    try:
        return int(mca_var.get("obs_sentinel", 0) or 0)
    except Exception:
        return 0


def try_compile(state, m, fn: Callable, args: Tuple,
                kw: Optional[Dict]):
    """Lower ``state.plan`` into a bound :class:`NativePlan`, or None
    when anything — cvar off, missing symbols, a non-native peer, an
    unprovable byte map — says the interpreted replay should keep the
    plan. Never raises: ineligibility is a selection outcome."""
    t0 = _time.perf_counter()
    try:
        return _compile(state, m, fn, args, kw or {}, t0)
    except Exception as e:
        if os.environ.get("OMPITPU_PLAN_NATIVE_DEBUG"):
            import traceback
            print(f"[native_exec] withdrew: {e!r}", file=sys.stderr)
            traceback.print_exc()
        return None


def _compile(state, m, fn, args, kw, t0):
    plan = state.plan
    if plan is None or not plan.rounds:
        raise _Ineligible("no frozen plan")
    if not bool(mca_var.get("coll_plan_native", True)):
        raise _Ineligible("coll_plan_native=0")
    if _sentinel_level() >= 2:
        # inline sentinel checking rides ctl frames interleaved with
        # the planned rounds — the C reap would stash them mid-fire
        raise _Ineligible("inline sentinel level >= 2")
    if not available():
        raise _Ineligible("planexec symbols absent")
    router = getattr(m, "router", None)
    nw = getattr(router, "_nw", None)
    if router is None or nw is None:
        raise _Ineligible("no nativewire btl")
    tuning = router.tuning()
    if tuning.arbiter is not None:
        # QoS arbiter owns pacing: stay interpreted
        raise _Ineligible("qos arbiter active")
    comm = state.comm

    # argument regions: every positional array arg is an input region
    arg_idx = []
    arg_specs = []
    for j, a in enumerate(args):
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            spec = _as_np(a)
            nb = int(spec.nbytes)
            if nb <= 0:
                raise _Ineligible("zero-byte array arg")
            arg_idx.append(j)
            arg_specs.append((tuple(spec.shape), str(spec.dtype), nb))
    for v in (kw or {}).values():
        if hasattr(v, "shape") and hasattr(v, "dtype"):
            raise _Ineligible("keyword array args not lowered")
    arg_idx = tuple(arg_idx)

    # capability + structure gate over every round
    send_peers = set()
    recv_srcs = set()
    for rnd in plan.rounds:
        metas = getattr(rnd, "recvs_meta", None)
        if metas is None:
            raise _Ineligible("plan has no arrival metas")
        by_src = dict(metas)
        for src, cnt in rnd.recvs_t:
            lst = by_src.get(src)
            if lst is None or len(lst) != cnt:
                raise _Ineligible("arrival metas disagree with recvs")
            recv_srcs.add(src)
            for shape, dt in lst:
                if _nbytes_of(shape, dt) <= 0:
                    raise _Ineligible("zero-byte receive")
        for (p, arrs), (_p2, tpls) in zip(rnd.sends_meta,
                                          rnd.peer_slots):
            send_peers.add(p)
            if len(arrs) != len(tpls) or any(t is None for t in tpls):
                raise _Ineligible("untemplated send slot")
    peers = tuple(sorted(send_peers | recv_srcs))
    if not peers:
        raise _Ineligible("no wire peers")
    for p in peers:
        if router._btl_for(p) is not nw:
            raise _Ineligible(f"peer {p} not on nativewire")
    # byte-provenance probe (two seeds, identical maps required)
    maps = _infer_maps(plan, m, fn, args, kw, arg_idx)

    seg = min(tuning.segsize, max(1, nw.max_send_size))
    from ..btl.components import plan_frame_template

    # input regions: args first, then round-0 send arrays in stream
    # order (later rounds may resend round-0 bytes that no arg holds)
    input_lens = [nb for _s, _d, nb in arg_specs]
    n_args = len(arg_specs)
    r0_specs = []
    for p, arrs in plan.rounds[0].sends_meta:
        for shape, dt in arrs:
            nb = _nbytes_of(shape, dt)
            r0_specs.append((p, tuple(shape), dt, nb))
            input_lens.append(nb)

    # pool layout: one buffer per (round, sorted src, message), at
    # the same 8-aligned cumulative offsets build_blob will emit
    pool_sizes: List[int] = []
    pool_round: List[int] = []
    pool_off = 0
    pool_rounds = []  # per round: [(src, [(idx, off, shape, dt, nb)])]
    for i, rnd in enumerate(plan.rounds):
        per_src = []
        for src, metas in sorted(dict(rnd.recvs_meta).items()):
            lst = []
            for shape, dt in metas:
                nb = _nbytes_of(shape, dt)
                lst.append((len(pool_sizes), pool_off, tuple(shape),
                            np.dtype(dt), nb))
                pool_sizes.append(nb)
                pool_round.append(i)
                pool_off = _align8(pool_off + nb)
            per_src.append((src, lst))
        pool_rounds.append(per_src)

    peer_index = {p: i for i, p in enumerate(peers)}
    send_msgs = send_bytes = send_frames = 0
    recv_msgs = recv_bytes = recv_frames = 0
    rounds_desc = []
    for i, rnd in enumerate(plan.rounds):
        streams = []
        flat = 0  # message index within the round, stream order
        r0_base = n_args
        for (p, arrs), (_p2, tpls) in zip(rnd.sends_meta,
                                          rnd.peer_slots):
            msgs = []
            for k, ((shape, dt), tpl) in enumerate(zip(arrs, tpls)):
                nb = _nbytes_of(shape, dt)
                if i == 0:
                    segs = ((0, r0_base + flat, 0, nb),)
                else:
                    segs = maps[i][flat]
                    tot = 0
                    for kind, idx, _so, sl in segs:
                        tot += sl
                        if kind == 1 and pool_round[idx] >= i:
                            # provenance from a not-yet-filled pool
                            # buffer can only be coincidence
                            raise _ProbeFail("acausal provenance")
                    if tot != nb:
                        raise _ProbeFail("map does not cover payload")
                msgs.append((tpl.pre, tpl.mid, nb, int(tpl.nchunks),
                             int(tpl.chunk), segs))
                send_msgs += 1
                send_bytes += nb
                send_frames += int(tpl.nchunks) + 1
                flat += 1
            streams.append((peer_index[p], msgs))
        rsrcs = []
        for src, lst in pool_rounds[i]:
            msgs = []
            for pool_idx, _off, shape, dt, nb in lst:
                tpl = plan_frame_template(shape, dt, seg)
                msgs.append((pool_idx, nb, int(tpl.nchunks),
                             int(tpl.chunk), tpl.pre, tpl.mid))
                recv_msgs += 1
                recv_bytes += nb
                recv_frames += int(tpl.nchunks) + 1
            rsrcs.append((peer_index[src], msgs))
        rounds_desc.append({"depth": int(rnd.depth),
                            "streams": streams, "rsrcs": rsrcs})

    blob = build_blob(plan.rounds[0].tag, input_lens, pool_sizes,
                      peers, rounds_desc)
    from ..native import bindings as _b
    px = _b.PlanExec(blob)

    # bind the live endpoint + ring handles once (rings exist after
    # the recording fire; a missing tx ring means the socket leg)
    handles = nw.plan_endpoints(plan.rounds[0].tag,
                                sorted(send_peers),
                                sorted(recv_srcs))
    tx_h, rx_h, rx_entries, fire_locks = [], [], {}, []
    for p in peers:
        tx, rx = handles[p]
        tx_h.append(tx[0]._h if tx is not None else None)
        rx_h.append(rx[0]._h if rx is not None else None)
        if tx is not None:
            fire_locks.append((p, 0, tx[1]))
        if rx is not None:
            fire_locks.append((p, 1, rx[1]))
            rx_entries[p] = rx
    import ctypes
    word = (ctypes.c_int64 * 1)(0)
    px.bind(router.ep._h, router._nid(m.my_pidx),
            [router._nid(p) for p in peers], tx_h, rx_h)
    px.set_ftword(word)

    npl = NativePlan()
    npl.gen = plan.gen
    npl.px = px
    npl.cid = comm.cid
    npl.tag = plan.rounds[0].tag
    npl.peers = peers
    npl.arg_idx = arg_idx
    npl.arg_specs = tuple(arg_specs)
    npl.r0_specs = tuple(r0_specs)
    npl.pool_rounds = pool_rounds
    npl.timeout_ms = plan.timeout_ms
    npl.ftword = word
    npl.router = router
    npl.rx_entries = rx_entries
    npl.fire_locks = sorted(fire_locks, key=lambda e: (e[0], e[1]))
    npl.send_msgs = send_msgs
    npl.send_bytes = send_bytes
    npl.recv_msgs = recv_msgs
    npl.recv_bytes = recv_bytes
    npl.send_frames = send_frames
    npl.recv_frames = recv_frames
    npl.xfer_total = max(1, send_msgs)
    npl.pool_count = len(pool_sizes)
    npl.pool_total = px.pool_total
    _pool_bytes.add(npl.pool_total)
    if _obs.enabled:
        _obs.record("plan_native_compile", "plan", t0,
                    _time.perf_counter() - t0, comm_id=comm.cid)
    return npl


# ---------------------------------------------------------------------------
# fire: the per-replay exchange adapter
# ---------------------------------------------------------------------------

class NativeXchg:
    """Exchange adapter that fires the WHOLE plan C-side on its first
    round: round-0 sends come verbatim from the arrays the schedule
    just passed, later rounds compose from the proven byte-provenance
    maps, receives reassemble into the plan pool. Rounds >= 1 only
    verify structure and hand back pool copies. Any per-fire safety
    veto (stashed frames, lock contention) delegates the entire fire
    to a fresh :class:`~.plan.PlannedXchg` — same plan, same bytes."""

    __slots__ = ("m", "plan", "np", "i", "ts", "args", "_delegate",
                 "_pool", "_c_wait")

    def __init__(self, module, plan, npl: NativePlan,
                 args: Tuple) -> None:
        self.m = module
        self.plan = plan
        self.np = npl
        self.i = 0
        self.ts: Optional[List[float]] = None
        self.args = args
        self._delegate = None
        self._pool = None
        #: seconds spent blocked in the C slice loop during the last
        #: exchange — wire-transport time, subtracted from the
        #: orchestration self-report (the ctypes entry/exit and pool
        #: copies are Python orchestration; the descriptor walk isn't)
        self._c_wait = 0.0

    def _mismatch(self, detail: str) -> MPIError:
        return MPIError(
            ErrorCode.ERR_INTERN,
            f"compiled schedule plan diverged mid-run on "
            f"{self.m.comm.name} (round {self.i}): {detail}. The "
            "schedule no longer matches its frozen plan — rebuild "
            "the persistent request (or re-issue the collective) "
            "after changing schedule-selection cvars",
        )

    def exchange(self, sends: Dict[int, list],
                 recvs: Dict[int, int]) -> Dict[int, list]:
        if self._delegate is not None:
            return self._delegate.exchange(sends, recvs)
        t0 = _time.perf_counter()
        self._c_wait = 0.0
        try:
            return self._exchange(sends, recvs)
        finally:
            if self._delegate is None:
                # a fire that fell back mid-call accounted itself
                # through the delegate's PlannedXchg.exchange
                from . import driver as _driver
                _driver.orch_add(
                    _time.perf_counter() - t0 - self._c_wait)

    def _exchange(self, sends: Dict[int, list],
                  recvs: Dict[int, int]) -> Dict[int, list]:
        plan = self.plan
        if self.i >= len(plan.rounds):
            raise self._mismatch("more rounds than the plan recorded")
        rnd = plan.rounds[self.i]
        sends_f = {p: [_as_np(a) for a in arrs]
                   for p, arrs in sends.items() if arrs}
        meta = tuple(
            (p, tuple((a.shape, str(a.dtype)) for a in sends_f[p]))
            for p in sorted(sends_f))
        rl = {int(p): int(c) for p, c in recvs.items() if int(c) > 0}
        if meta != rnd.sends_meta or rl != rnd.recvs:
            raise self._mismatch(
                f"sends/recvs {meta}/{rl} != frozen "
                f"{rnd.sends_meta}/{rnd.recvs}")
        if self.i == 0 and not self._fire(sends_f):
            _native_fallbacks.add()
            from .plan import PlannedXchg
            dg = PlannedXchg(self.m, plan)
            dg.ts = self.ts
            self._delegate = dg
            return dg.exchange(sends, recvs)
        got = self._materialize(self.i)
        self.i += 1
        return got

    # -- fire-time plumbing ------------------------------------------------
    def _contig(self, a: np.ndarray) -> np.ndarray:
        if a.flags.c_contiguous:
            return a
        from ..btl.nativewire import _fallback_copies
        _fallback_copies.add()
        return np.ascontiguousarray(a)

    def _inputs(self, sends_f) -> Optional[List[np.ndarray]]:
        npl = self.np
        out = []
        for j, (shape, dt, _nb) in zip(npl.arg_idx, npl.arg_specs):
            a = self._contig(_as_np(self.args[j]))
            if tuple(a.shape) != shape or str(a.dtype) != dt:
                return None
            out.append(a)
        flat: List[np.ndarray] = []
        for p in sorted(sends_f):
            flat.extend(sends_f[p])
        if len(flat) != len(npl.r0_specs):
            return None
        for a, (_p, shape, dt, _nb) in zip(flat, npl.r0_specs):
            out.append(self._contig(a))
        return out

    def _clean_channel(self) -> bool:
        """True when no stashed/early frame could race the C reap."""
        npl = self.np
        router = npl.router
        cid = npl.cid
        with router._coll_early_lock:
            for (c, _src), q in router._coll_early.items():
                if c == cid and q:
                    return False
        from ..btl.components import _ep_stash
        stash, lock = _ep_stash(router.ep)
        with lock:
            for p in npl.peers:
                if stash.get((router._nid(p), npl.tag)):
                    return False
        return True

    def _fire(self, sends_f) -> bool:
        npl = self.np
        m = self.m
        router = npl.router
        inputs = self._inputs(sends_f)
        if inputs is None:
            return False
        comm = m.comm
        epoch0 = getattr(comm, "_ft_epoch0", 0)
        from ..runtime.wire import _ft
        held: List[threading.Lock] = []
        chan = router._chan_lock("collrx", npl.cid)
        if not chan.acquire(blocking=False):
            return False
        held.append(chan)
        fired = False
        t0 = _time.perf_counter()
        try:
            for _p, _kind, lk in npl.fire_locks:
                if not lk.acquire(blocking=False):
                    return False
                held.append(lk)
            if not self._clean_channel():
                return False
            for _src, (_ring, _lk, rstash) in npl.rx_entries.items():
                if rstash.get(npl.tag):
                    return False
            _ft().check_wait(npl.cid, npl.peers, "native plan fire",
                             epoch0=epoch0)
            from ..btl import components as _btlc
            base = next(_btlc._xfer_ids)
            for _ in range(npl.xfer_total - 1):
                next(_btlc._xfer_ids)
            npl.ftword[0] = 0
            px = npl.px
            if px.fire_begin(inputs, base, npl.timeout_ms) != 0:
                return False
            fired = True
            self._run(px, npl, epoch0)
            self._harvest(px, npl, t0)
            return True
        finally:
            if fired:
                # the rx entry locks are still held here — the
                # restash below needs them
                self._drain_stash(npl)
            for lk in reversed(held):
                lk.release()

    def _run(self, px, npl: NativePlan, epoch0: int) -> None:
        from ..obs import watchdog as _watchdog
        from ..runtime.wire import _ft
        tok = None
        if _watchdog.enabled:
            tok = _watchdog.arm(
                "native_plan_fire", comm_id=npl.cid,
                info=lambda n=npl: {"peers": list(n.peers),
                                    "rounds": len(n.pool_rounds)})
        t_w = _time.perf_counter()
        try:
            while True:
                rc = px.fire_step(_SLICE_MS)
                if rc == px.RC_DONE:
                    return
                if rc in (px.RC_AGAIN, px.RC_FTSTOP):
                    # the detection interval: mirror FtState into the
                    # fault word, surface death/revocation typed
                    try:
                        _ft().check_wait(npl.cid, npl.peers,
                                         "native plan fire",
                                         epoch0=epoch0)
                    except MPIError:
                        npl.ftword[0] = 1
                        raise
                    continue
                self._raise_rc(px, npl, rc)
        finally:
            self._c_wait = _time.perf_counter() - t_w
            if tok is not None:
                _watchdog.disarm(tok)

    def _raise_rc(self, px, npl: NativePlan, rc: int) -> None:
        if rc == px.RC_PEERDEAD:
            pidx = px.err_peer()  # the C side stores the pidx
            raise MPIError(
                ErrorCode.ERR_PROC_FAILED,
                f"native plan fire on {self.m.comm.name} depends on "
                f"process {pidx}, which the wire reports dead "
                f"(round {px.err_round()})",
            )
        if rc == px.RC_TIMEOUT:
            raise MPIError(
                ErrorCode.ERR_PENDING,
                f"native plan fire on {self.m.comm.name} timed out "
                f"after {npl.timeout_ms} ms (round {px.err_round()})",
            )
        if rc == px.RC_DIVERGED:
            raise self._mismatch(
                "an inbound header did not match the frozen frame "
                "template (peer re-planned or cvars differ across "
                "ranks)")
        if rc == px.RC_TRUNCATED:
            raise MPIError(
                ErrorCode.ERR_TRUNCATE,
                "native plan fire: reassembled payload failed its "
                f"CRC (round {px.err_round()})",
            )
        raise MPIError(ErrorCode.ERR_INTERN,
                       f"native plan executor returned rc {rc}")

    def _drain_stash(self, npl: NativePlan) -> None:
        """Re-inject frames the C reap popped but does not own into
        the shared Python stashes (kind 0 = endpoint frame, kind 1 =
        ring record) — the portable consumers find them exactly where
        the interpreted path would have stashed them."""
        px = npl.px
        try:
            entries = px.drain_stash()
        except Exception:
            return
        if not entries:
            return
        from ..btl.components import _ep_stash
        from ..btl.nativewire import _fallback_copies
        router = npl.router
        for kind, pidx, tag, raw in entries:
            if kind == 1 and pidx in npl.rx_entries:
                _ring, _lk, rstash = npl.rx_entries[pidx]
                # caller already holds the rx entry lock
                rstash.setdefault(tag, []).append(raw)
                _fallback_copies.add()  # the one restash copy
            else:
                stash, lock = _ep_stash(router.ep)
                with lock:
                    stash.setdefault((router._nid(pidx), tag),
                                     []).append(raw)

    def _harvest(self, px, npl: NativePlan, t0: float) -> None:
        self._pool = px.pool_view()
        if self.ts is not None:
            self.ts[:] = px.round_ts()
        # pvar continuity: the C fire IS these sends/recvs — MPI_T
        # series must not dip when the native executor engages.
        # Frame counts mirror the interpreted path exactly: chunk
        # pvars count fragments (not headers), _native_frames counts
        # send fragments.
        from . import hier as _hier
        _hier._inter_msgs_sent.add(npl.send_msgs)
        _hier._inter_bytes.add(npl.send_bytes)
        _hier._inter_msgs_recvd.add(npl.recv_msgs)
        from ..btl import nativewire as _nw
        _nw._native_bytes.add(npl.send_bytes + npl.recv_bytes)
        _nw._native_frames.add(npl.send_frames - npl.send_msgs)
        _nw._zero_copy_strict.add(npl.send_bytes + npl.recv_bytes)
        btl = npl.router._nw
        if btl is not None:
            btl.staged_chunks_pvar.add(
                (npl.send_frames - npl.send_msgs)
                + (npl.recv_frames - npl.recv_msgs))
            btl.staged_bytes_pvar.add(npl.send_bytes + npl.recv_bytes)
        _pool_hits.add(npl.pool_count)
        _native_fires.add()
        if _obs.enabled:
            _obs.record("plan_native_fire", "plan", t0,
                        _time.perf_counter() - t0, comm_id=npl.cid)

    def _materialize(self, r: int) -> Dict[int, list]:
        npl = self.np
        pool = self._pool
        got: Dict[int, list] = {}
        for src, lst in npl.pool_rounds[r]:
            arrs = []
            for _pool_idx, off, shape, dt, nb in lst:
                a = np.empty(shape, dtype=dt)
                a.reshape(-1).view(np.uint8)[:] = pool[off:off + nb]
                arrs.append(a)
            got[src] = arrs
        return got
