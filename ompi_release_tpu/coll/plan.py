"""Compiled whole-schedule collectives — frozen :class:`SchedulePlan`s
fired with zero per-round Python work (ROADMAP item 6).

The reference pays its per-collective decision and segmentation cost
once, in C; this reproduction paid it in Python on EVERY call — the
tuned pickers' cvar reads, the per-dispatch body-lambda tables and
cache-key builds in ``coll/components.py``, per-frame header packing
in ``btl/components.py``, and per-message ``mca_var.get`` lookups in
``runtime/wire.py``. This module freezes all of it at plan time:

in-process (device) collectives
    The MPI-4 persistent ``*_init`` path — and, in steady state,
    blocking and i-family calls with a previously-seen signature —
    fire ONE cached compiled XLA program per plan signature. The
    first (capturing) run goes through the full interpreted dispatch;
    :mod:`coll.driver` records the program handle plus the exact
    input/output objects, and identity of those objects against the
    collective's own argument and return value PROVES the dispatch
    was pre/post-processing-free, i.e. the program alone IS the
    collective. Every later fire is ``prog(jnp.asarray(buffer))`` —
    no decision logic, no cvar reads, no cache-key tuples. Bitwise
    parity with the interpreted path is structural: the fired program
    object is the very one the interpreted path compiled and ran.

spanning (wire) collectives
    The first run of a schedule records its ROUND STRUCTURE (peer
    lists, per-round send shapes/dtypes and receive counts) through a
    :class:`RoundRecorder` wrapped around the hier exchange adapter;
    :func:`freeze_wire_plan` then resolves the wire tuning cvars ONCE
    and precomposes every round's SGH2 frame headers and fragment
    offsets (:class:`~..btl.components.FrameTemplate`). Steady-state
    fires replay through :class:`PlannedXchg`: one ULFM check per
    round, memoryview slicing behind precomposed header bytes, the
    arrival-order reap — no per-message dict lookups, tag math, or
    header packing. The wire bytes are byte-identical to the
    interpreted path's, so results are bitwise-identical and the
    receive side needs no changes; FT slicing (PR 9) and sentinel
    hashing (PR 10 — one signature per collective, noted at posting)
    are untouched.

Invalidation: every plan is stamped with the MCA registry's write
GENERATION. Any cvar write bumps it, so the next fire quietly
re-captures with the new values — a mid-job tuning write takes effect
at the next plan, never mid-schedule. A schedule that still diverges
from its frozen plan mid-run (structure mismatch) is a loud typed
error naming the fix, never a silently wrong frame.

Observability is a property of the steady state, not a mode that
replaces it: an observed run KEEPS firing frozen plans. Each observed
compiled fire appends one fixed-size binary record — plan id, posting
seq, fire start/end, and one clock read per planned wire round — to
the plan-relative flight recorder (:mod:`~..obs.ledger`), which
registered the plan's full round/flow structure once at freeze time;
``tpu-doctor`` expands the records back into synthetic spans with the
interpreted path's exact flow ids. The ``obs_trace_sample`` cvar runs
1-in-N observed fires through the fully interpreted path for
ground-truth deep traces (the frozen plan survives), and inline
sentinel checking (level 2) rides the planned path over ctl frames —
neither tracing nor contract checking de-optimizes the hot path.

Scope guards: plans engage only for the fixed-signature collective
families (``_PLANNABLE``), and only when the call signature is
hashable metadata (:func:`signature_of` returns None for ragged
v-variants and pair ops, which stay interpreted).

pvars: ``coll_compiled_cache_hits`` (1 = fired a frozen plan, 0 = a
capturing run froze one; sum/count = steady-state hit ratio, printed
by ``obs --selftest``) — identical with obs on and off, the satellite
contract tpu_top's compiled-fire ratio column reads. Orchestration
time is witnessed by the driver's ``coll_orchestration_seconds``
timer, which both legs feed.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import obs as _obs
from ..mca import pvar
from ..mca import var as mca_var
from ..obs import ledger as _ledger
from ..obs import watchdog as _watchdog
from ..utils.errors import ErrorCode, MPIError

#: plan-cache outcome per plannable collective fire: 1 = a frozen plan
#: fired (compiled program / planned wire rounds), 0 = a capturing run
#: built one. sum/count = the steady-state hit ratio.
_compiled_hits = pvar.aggregate(
    "coll_compiled_cache_hits",
    "compiled-schedule plan-cache outcome per fire (1=fired frozen "
    "plan, 0=capturing run froze one); sum/count = hit ratio",
)
_wire_rounds_frozen = pvar.counter(
    "coll_wire_rounds_frozen",
    "schedule rounds captured into frozen wire plans (peer lists, "
    "frame headers, fragment offsets precomposed at plan time)",
)


def register_vars() -> None:
    mca_var.register(
        "coll_compiled", "bool", True,
        "Fire frozen schedule plans (one compiled XLA program / "
        "precomposed wire rounds per plan signature) for persistent, "
        "blocking, and i-family collectives in steady state; false "
        "restores the fully interpreted per-call dispatch",
    )
    mca_var.register(
        "coll_plan_native", "bool", True,
        "Fire eligible frozen wire plans through the native C plan "
        "executor (one ctypes slice loop walks every round: striped "
        "sends, pooled reassembly, FT fault-word polling). Requires "
        "the native .so and a nativewire card on every round peer; "
        "anything else — and false — replays through the interpreted "
        "PlannedXchg path, bitwise-identical",
    )
    mca_var.register(
        "obs_trace_sample", "int", 0,
        "With obs on, run every Nth compiled-plan fire through the "
        "fully interpreted path for a ground-truth deep trace (full "
        "span/flow record); 0 = never — compiled fires are always "
        "flight-recorded in the obs ledger. Set identically on every "
        "rank (fire counters advance in lockstep)",
    )


register_vars()  # idempotent; the cvar must exist before first dispatch

#: collective families with fixed call signatures whose schedules are
#: deterministic functions of (comm, shapes, op, root) — the plannable
#: set. Ragged v-variants ship data-dependent structure; barrier has
#: no payload to plan; submit()'s arbitrary serialized callables may
#: carry side effects a re-fire would skip.
_PLANNABLE = frozenset({
    "allreduce", "bcast", "allgather", "reduce", "gather", "scatter",
    "reduce_scatter_block", "reduce_scatter", "alltoall", "scan",
    "exscan",
})

# lazy heavyweight imports (driver pulls jax): resolved once at first
# device dispatch so the wire-plan/metadata half of this module stays
# importable device-free (obs --selftest, the fleet-sim tests)
_driver = None
_jnp = None

#: (gen, enabled, overlap, trace_sample) snapshot of the
#: coll_compiled / wire_overlap_exchange / obs_trace_sample cvars —
#: re-resolved only when the registry write generation moves
_conf = (-1, True, True, 0)

_lock = threading.Lock()
#: (cid, signature) -> device-plan entry {"gen", "prog"|"bad"}
_device_plans: Dict[Tuple[int, Tuple], Dict[str, Any]] = {}
#: (cid, signature) -> SpanningPlanState
_span_states: Dict[Tuple[int, Tuple], "SpanningPlanState"] = {}


def _lazy_driver():
    global _driver, _jnp
    if _driver is None:
        import jax.numpy as jnp

        from . import driver

        _driver, _jnp = driver, jnp
    return _driver


def _refresh_conf() -> Tuple[int, bool, bool, int]:
    global _conf
    gen = mca_var.VARS.generation
    if _conf[0] != gen:
        _conf = (gen, bool(mca_var.get("coll_compiled", True)),
                 bool(mca_var.get("wire_overlap_exchange", True)),
                 int(mca_var.get("obs_trace_sample", 0) or 0))
    return _conf


def _enabled() -> bool:
    return _refresh_conf()[1]


def _overlap_on() -> bool:
    # the planned replay path IS the striped/overlapped send path;
    # an operator's wire_overlap_exchange=False opt-out (serialize
    # sends, e.g. around a flaky fabric) must keep spanning fires
    # fully interpreted, where _XchgAdapter honors the flag
    return _refresh_conf()[2]


def _trace_sample() -> int:
    return _refresh_conf()[3]


#: live planned replays, keyed by plan-state identity: the watchdog's
#: "frozen_plans" contributor names the plan (id, signature, round
#: index) a rank is stuck inside, instead of just raw wire waits.
#: Mutated only under an ``_obs.enabled`` gate (postmortems only fire
#: with obs on), so the unobserved hot path never touches it.
_active_replays: Dict[int, Tuple["SpanningPlanState",
                                 "PlannedXchg"]] = {}


def _frozen_plans_snapshot() -> Dict[str, Any]:
    out = []
    for st, px in list(_active_replays.values()):
        plan = px.plan
        out.append({
            "plan": plan.ledger_id, "name": st.name,
            "comm": getattr(st.comm, "name", "?"), "cid": plan.cid,
            "signature": _ledger._sig_summary(st.sig),
            "round": px.i, "rounds_total": len(plan.rounds),
        })
    return {"active_replays": out, **cache_stats()}


_watchdog.add_contributor("frozen_plans", _frozen_plans_snapshot)


def _sig_nbytes(sig: Tuple) -> int:
    """Payload bytes of a plan signature's first array argument (the
    flight recorder's per-fire byte accounting for device plans)."""
    for d in sig[1:]:
        if isinstance(d, tuple) and d and d[0] == "arr":
            n = 1
            for s in d[1]:
                n *= int(s)
            try:
                return n * int(np.dtype(d[2]).itemsize)
            except TypeError:
                return 0
    return 0


def clear_comm(cid: int) -> None:
    """Drop every frozen plan of one communicator (comm free / the
    explicit-cid rebuild path: a reused cid must never fire a dead
    comm's programs)."""
    with _lock:
        for d in (_device_plans, _span_states):
            for key in [k for k in d if k[0] == cid]:
                d.pop(key, None)


def cache_stats() -> Dict[str, int]:
    """Operator-visible plan-cache counters (obs --selftest leg)."""
    st = _compiled_hits.read()
    return {
        "device_plans": len(_device_plans),
        "spanning_plans": len(_span_states),
        "fires": int(st["count"]),
        "hits": int(st["sum"]),
    }


def _reset_for_tests() -> None:
    with _lock:
        _device_plans.clear()
        _span_states.clear()
        _active_replays.clear()


# ---------------------------------------------------------------------------
# plan signatures: hashable metadata of one collective call
# ---------------------------------------------------------------------------

def _arg_desc(a) -> Optional[Tuple]:
    shape = getattr(a, "shape", None)
    if shape is not None and hasattr(a, "dtype"):
        return ("arr", tuple(int(d) for d in shape), str(a.dtype))
    if a is None or isinstance(a, (bool, int, float, str)):
        return ("v", a)
    if hasattr(a, "commutative") and hasattr(a, "name"):
        # an Op: the (frozen, hashable) op itself is the key — two ops
        # sharing a name but different fns must not share a program,
        # and holding the object (not its id) keeps it alive so a
        # recycled address can never alias a dead op's frozen program
        try:
            hash(a)
        except TypeError:
            return None
        return ("op", a)
    if isinstance(a, (list, tuple)):
        if all(isinstance(v, (bool, int, float)) for v in a):
            return ("seq", tuple(a))
        return None  # ragged buffer lists: not plannable
    return None


#: public name: osc/plan reuses the same descriptor rules for RMA
#: epoch signatures — identical Op-OBJECT keying and array metadata,
#: so the two planes can never drift on what is plannable
arg_desc = _arg_desc


def signature_of(name: str, args: Tuple,
                 kw: Optional[Dict]) -> Optional[Tuple]:
    """Hashable plan signature of one collective call, or None when
    the call is not plannable (ragged buffers, pair-op tuples,
    exotic kwargs)."""
    sig: List[Any] = [name]
    for a in args:
        d = _arg_desc(a)
        if d is None:
            return None
        sig.append(d)
    for k in sorted(kw or ()):
        d = _arg_desc(kw[k])
        if d is None:
            return None
        sig.append((k, d))
    return tuple(sig)


# ---------------------------------------------------------------------------
# in-process: one compiled XLA program per plan signature
# ---------------------------------------------------------------------------

def dispatch(comm, name: str, fn: Callable, args: Tuple,
             kw: Optional[Dict] = None,
             sig_box: Optional[list] = None) -> Any:
    """THE in-process collective dispatch: fire the signature's frozen
    compiled program when one exists (steady state — no decision
    logic, no cvar reads), else run the interpreted path under
    capture and freeze the program it dispatched. Falls back to plain
    interpreted execution whenever obs is on (full span record), the
    family is unplannable, or the capture proved the dispatch did
    pre/post-processing the program alone cannot replay.
    ``sig_box``: a persistent request's one-element signature memo —
    the arguments are bound at ``*_init``, so ``start()`` skips even
    the signature build."""
    t0 = _time.perf_counter()
    if name not in _PLANNABLE:
        return fn(comm, *args, **(kw or {}))
    if not _enabled():
        # fully interpreted (coll_compiled=0): still re-base the
        # orchestration timer at THIS entry so the interpreted and
        # compiled legs of the steady_state bench time the same span
        d = _lazy_driver()
        d.orch_mark(t0)
        try:
            return fn(comm, *args, **(kw or {}))
        finally:
            d.orch_clear()
    if sig_box is not None and sig_box:
        sig = sig_box[0]
    else:
        sig = signature_of(name, args, kw)
        if sig_box is not None:
            sig_box.append(sig)
    if sig is None:
        return fn(comm, *args, **(kw or {}))
    gen = mca_var.VARS.generation
    key = (comm.cid, sig)
    e = _device_plans.get(key)
    if e is not None and e["gen"] == gen:
        prog = e.get("prog")
        if prog is not None:
            # the steady state — observed or not. An observed fire is
            # flight-recorded (one fixed-size ledger record, no span
            # objects); obs_trace_sample=N diverts every Nth observed
            # fire through the interpreted path for a ground-truth
            # deep trace, plan intact.
            obs_on = _obs.enabled
            if obs_on:
                n = _trace_sample()
                if n > 0:
                    f = e["fires"] = e.get("fires", 0) + 1
                    if f % n == 0:
                        d = _lazy_driver()
                        d.orch_mark(t0)
                        try:
                            return fn(comm, *args, **(kw or {}))
                        finally:
                            d.orch_clear()
            d = _lazy_driver()
            # pvar continuity: a frozen-plan fire IS an invocation and
            # a (deeper) plan-cache hit — MPI_T series must not dip
            # when the steady state engages
            d._invoke_count.add()
            d._plan_cache.observe(1.0)
            if comm.cid >= 0:
                # runtime-internal comms (the hier shadow) fire plans
                # too, but only USER-visible collectives count in the
                # hit ratio — the sentinel's negative-cid rule
                _compiled_hits.observe(1)
            # timer closes BEFORE the buffer conversion + launch,
            # exactly where run_sharded closes it on the interpreted
            # leg — the two legs time the identical span
            d._orch.add(_time.perf_counter() - t0)
            if not obs_on:
                return prog(_jnp.asarray(args[0]))
            out = prog(_jnp.asarray(args[0]))
            lid = e.get("lid")
            if lid is None:
                lid = e["lid"] = _ledger.register_device_plan(
                    comm.cid, name, _sig_nbytes(sig), sig)
            _ledger.record_fire(_ledger.KIND_DEVICE, lid, comm.cid,
                                t0, _time.perf_counter())
            return out
        if "bad" in e:
            return fn(comm, *args, **(kw or {}))
    # capture attempt: interpreted run with program-dispatch recording
    d = _lazy_driver()
    d.orch_mark(t0)  # the timer covers the decision path too
    cap = d.begin_capture()
    try:
        out = fn(comm, *args, **(kw or {}))
    finally:
        d.end_capture()
        d.orch_clear()
    entry: Dict[str, Any] = {"gen": gen}
    if (len(cap) == 1 and cap[0]["out"] is out
            and cap[0]["x"] is args[0] and not cap[0]["extra"]):
        entry["prog"] = cap[0]["prog"]
        if comm.cid >= 0:
            _compiled_hits.observe(0)
        if _obs.enabled:
            _obs.record("plan_capture_" + name, "plan", t0,
                        _time.perf_counter() - t0, comm_id=comm.cid)
    else:
        entry["bad"] = True
    with _lock:
        _device_plans[key] = entry
    return out


# ---------------------------------------------------------------------------
# spanning: record the round structure, freeze the wire frames
# ---------------------------------------------------------------------------

#: module-level alias so tests can monkeypatch-count conversions:
#: the planned replay path must NOT pay np.asarray for inputs that
#: already are ndarrays (the overwhelmingly common steady state)
_np_asarray = np.asarray


def _as_nd(a):
    return a if isinstance(a, np.ndarray) else _np_asarray(a)


def _round_meta(sends: Dict[int, list]) -> Tuple:
    return tuple(
        (p, tuple((a.shape, str(a.dtype))
                  for a in map(_as_nd, sends[p])))
        for p in sorted(sends) if sends[p]
    )


class RoundRecorder:
    """Exchange-adapter wrapper: delegates every round to the real
    transport and records its structure — (peer, shape, dtype) per
    send, receive counts per peer, and the per-source arrival
    shapes/dtypes (the native executor's reassembly-pool layout;
    per-source order is deterministic: the wire is FIFO per peer).
    Works over the production :class:`~.hier._XchgAdapter` and the
    fleet simulator's ``FleetXchg`` alike (anything honoring the
    exchange contract)."""

    __slots__ = ("inner", "rounds", "recv_metas")

    def __init__(self, inner) -> None:
        self.inner = inner
        self.rounds: List[Tuple[Tuple, Tuple]] = []
        self.recv_metas: List[Tuple] = []

    def exchange(self, sends: Dict[int, list],
                 recvs: Dict[int, int]) -> Dict[int, list]:
        got = self.inner.exchange(sends, recvs)
        self.rounds.append((
            _round_meta(sends),
            tuple(sorted((int(p), int(c)) for p, c in recvs.items()
                         if int(c) > 0)),
        ))
        self.recv_metas.append(tuple(sorted(
            (int(src), tuple((_as_nd(a).shape, str(_as_nd(a).dtype))
                             for a in arrs))
            for src, arrs in got.items() if arrs)))
        return got


class WireRound:
    """One frozen schedule round: verification metadata plus the
    resolved send slots (peer -> per-message FrameTemplates or None
    for shm/legacy sends), channel tag, and striping depth.

    ``recvs_meta`` (per-source arrival shapes/dtypes) sizes the
    native executor's reassembly pool; ``frame_counts`` (frames per
    peer stream, header included) lets the striper skip QoS gating on
    exhausted streams. Both default None: manually-built rounds and
    pre-upgrade plans replay exactly as before."""

    __slots__ = ("sends_meta", "recvs_t", "recvs", "peers",
                 "peer_slots", "tag", "depth", "recvs_meta",
                 "frame_counts")

    def __init__(self, sends_meta: Tuple, recvs_t: Tuple, peer_slots,
                 tag: int, depth: int, recvs_meta: Optional[Tuple] = None,
                 frame_counts: Optional[Tuple] = None) -> None:
        self.sends_meta = sends_meta
        self.recvs_t = recvs_t
        self.recvs = dict(recvs_t)
        self.peers = tuple(p for p, _ in sends_meta)
        self.peer_slots = peer_slots
        self.tag = tag
        self.depth = depth
        self.recvs_meta = recvs_meta
        self.frame_counts = frame_counts


class WirePlan:
    """Frozen wire schedule: every round's structure and precomposed
    frames (the segsize they were built from is baked into each
    :class:`~..btl.components.FrameTemplate`), plus the plan-time
    ``wire_coll_timeout_ms`` snapshot replay waits are bounded by."""

    __slots__ = ("gen", "cid", "rounds", "timeout_ms", "ledger_id")

    def __init__(self, gen: int, cid: int, rounds: List[WireRound],
                 timeout_ms: int) -> None:
        self.gen = gen
        self.cid = cid
        self.rounds = rounds
        self.timeout_ms = timeout_ms
        #: flight-recorder plan id — registered lazily at the first
        #: OBSERVED fire (obs/ledger holds the frozen round/flow
        #: structure; fires then append fixed-size records only)
        self.ledger_id: Optional[int] = None


def freeze_wire_plan(comm, recorded: List[Tuple[Tuple, Tuple]],
                     gen: int,
                     recv_metas: Optional[List[Tuple]] = None,
                     ) -> Optional[WirePlan]:
    """Resolve one recorded round structure into a frozen
    :class:`WirePlan`: wire tuning cvars snapshot once (the satellite
    contract — a mid-job cvar write lands here, at the NEXT plan),
    SGH2 headers and fragment offsets precomposed per send slot.

    ``recv_metas`` (parallel to ``recorded``, the recorder's
    per-source arrival shapes/dtypes) is optional: plans frozen
    without it stay fully replayable, they just never graduate to the
    native executor (which needs arrival metas to size its pool)."""
    router = getattr(comm.runtime, "wire", None)
    if router is None:
        return None
    from ..btl import components as _btl

    tuning = router.refresh_tuning()
    tag = router._coll_tag(comm)
    rounds: List[WireRound] = []
    for i, item in enumerate(recorded):
        sends_meta, recvs_t = item[0], item[1]
        recvs_meta = (recv_metas[i] if recv_metas is not None
                      and i < len(recv_metas) else None)
        peer_slots = []
        frame_counts = []
        for p, arrs in sends_meta:
            tpls = []
            for shape, dtype in arrs:
                tpl = None
                btl = router._btl_for(p)
                # every segsize-framed transport precomposes: dcn's
                # interpreted SGH2 stream and nativewire's
                # scatter-gather stream share the FrameTemplate (the
                # byte-identity authority), each clamped to its OWN
                # max frame size cvar
                if tuning.segsize > 0 and (
                        btl is router._dcn
                        or (router._nw is not None
                            and btl is router._nw)):
                    seg = min(tuning.segsize,
                              max(1, btl.max_send_size))
                    tpl = _btl.plan_frame_template(shape, dtype, seg)
                tpls.append(tpl)
            peer_slots.append((p, tuple(tpls)))
            # frames a stream will emit: header + fragments for a
            # templated message, one frame otherwise — exact, so the
            # striper can drop a drained stream without gating it
            frame_counts.append(sum(
                (int(t.nchunks) + 1) if t is not None else 1
                for t in tpls))
        rounds.append(WireRound(sends_meta, recvs_t, tuple(peer_slots),
                                tag, tuning.depth,
                                recvs_meta=recvs_meta,
                                frame_counts=tuple(frame_counts)))
    _wire_rounds_frozen.add(len(rounds))
    return WirePlan(gen, comm.cid, rounds, tuning.coll_timeout_ms)


class PlannedXchg:
    """Exchange adapter replaying a frozen :class:`WirePlan`: each
    round verifies its structure against the plan (cheap tuple
    compare), then sends through the precomposed frame path and reaps
    in arrival order. Divergence is a loud typed error — frames from
    a wrong header would corrupt the peer's reassembly."""

    __slots__ = ("m", "plan", "i", "ts")

    def __init__(self, module, plan: WirePlan) -> None:
        self.m = module
        self.plan = plan
        self.i = 0
        #: round-end clock reads for the flight recorder (one
        #: perf_counter per planned round); None = unobserved fire,
        #: zero clock reads
        self.ts: Optional[List[float]] = None

    def _mismatch(self, detail: str) -> MPIError:
        return MPIError(
            ErrorCode.ERR_INTERN,
            f"compiled schedule plan diverged mid-run on "
            f"{self.m.comm.name} (round {self.i}): {detail}. The "
            "schedule no longer matches its frozen plan — rebuild the "
            "persistent request (or re-issue the collective) after "
            "changing schedule-selection cvars",
        )

    def exchange(self, sends: Dict[int, list],
                 recvs: Dict[int, int]) -> Dict[int, list]:
        # the whole replay round is Python orchestration (posting,
        # striping, reap polling) — self-report it so the steady-state
        # orchestration split sees the replay loop the native executor
        # exists to eliminate
        t0 = _time.perf_counter()
        try:
            return self._exchange(sends, recvs)
        finally:
            _lazy_driver().orch_add(_time.perf_counter() - t0)

    def _exchange(self, sends: Dict[int, list],
                  recvs: Dict[int, int]) -> Dict[int, list]:
        plan = self.plan
        if self.i >= len(plan.rounds):
            raise self._mismatch("more rounds than the plan recorded")
        rnd = plan.rounds[self.i]
        self.i += 1
        # comparison forms were precomputed at freeze time
        # (rnd.sends_meta / rnd.recvs): no re-sort of the recv list,
        # no np.asarray for inputs that already are ndarrays, and the
        # metadata tuple is built from the once-converted arrays
        sends_f = {p: [_as_nd(a) for a in arrs]
                   for p, arrs in sends.items() if arrs}
        meta = tuple(
            (p, tuple((a.shape, str(a.dtype)) for a in sends_f[p]))
            for p in sorted(sends_f))
        recvs_l = {int(p): int(c)
                   for p, c in recvs.items() if int(c) > 0}
        if meta != rnd.sends_meta or recvs_l != rnd.recvs:
            raise self._mismatch(
                f"sends/recvs {meta}/{recvs_l} != frozen "
                f"{rnd.sends_meta}/{rnd.recvs}")
        m = self.m
        if sends_f:
            m._send_all_planned(rnd, sends_f)
        got: Dict[int, list] = {p: [] for p in rnd.recvs}
        if rnd.recvs:
            # record=False: the flight recorder owns this fire's
            # span/flow story (expanded from the plan structure at
            # doctor time) — per-arrival journal spans here would
            # duplicate the synthetic ones and advance the hier
            # flow-k counters the expansion re-derives from zero
            m._reap(dict(rnd.recvs),
                    lambda src, arr: got[src].append(arr),
                    plan.timeout_ms, record=False)
        ts = self.ts
        if ts is not None:
            ts.append(_time.perf_counter())
        return got


class SpanningPlanState:
    """Per-(cid, signature) frozen-wire-plan holder: first fire
    records and freezes, later fires replay; a registry write
    generation bump quietly re-records (cvar writes take effect at
    the next plan, never mid-schedule)."""

    __slots__ = ("comm", "name", "plan", "sig", "fires",
                 "sentinel_tpl", "native")

    def __init__(self, comm, name: str, sig: Optional[Tuple] = None
                 ) -> None:
        self.comm = comm
        self.name = name
        self.plan: Optional[WirePlan] = None
        #: the plan lowered into the C executor (coll/native_exec) —
        #: None when ineligible; lives and dies with ``plan``
        self.native = None
        self.sig = sig
        #: observed-fire counter driving obs_trace_sample (advances in
        #: lockstep across ranks: collectives are, by definition,
        #: fired the same number of times everywhere)
        self.fires = 0
        #: (key, InlineFrameTemplate) cache — sentinel level 2's
        #: precomposed ctl-frame payload for this plan's call shape
        self.sentinel_tpl: Optional[Tuple] = None

    def _drop_native(self) -> None:
        nx, self.native = self.native, None
        if nx is not None:
            try:
                nx.close()
            except Exception:
                pass

    def run(self, fn: Callable, args: Tuple,
            kw: Optional[Dict]) -> Any:
        kw = kw or {}
        m = getattr(self.comm, "_hier_module", None)
        if m is None or not _enabled() or not _overlap_on():
            return fn(*args, **kw)
        gen = mca_var.VARS.generation
        plan = self.plan
        if plan is not None and plan.gen != gen:
            plan = self.plan = None  # cvars moved: re-plan
            self._drop_native()
        old = m._xchg
        if plan is None:
            # recording rides the fully-interpreted transport (spans,
            # flow ids, pvars untouched) — the recorder only watches
            t0 = _time.perf_counter()
            rec = RoundRecorder(old)
            m._xchg = rec
            try:
                out = fn(*args, **kw)
            finally:
                m._xchg = old
            self.plan = freeze_wire_plan(self.comm, rec.rounds, gen)
            if (self.plan is not None
                    and len(self.plan.rounds) == len(rec.recv_metas)):
                # graft the recorder's arrival metas onto the frozen
                # rounds: only the native executor reads them (pool
                # sizing), interpreted replay never looks
                for rnd, rmeta in zip(self.plan.rounds,
                                      rec.recv_metas):
                    try:
                        rnd.recvs_meta = rmeta
                    except (AttributeError, TypeError):
                        break
            if self.plan is not None:
                _compiled_hits.observe(0)
                if _obs.enabled:
                    _obs.record("plan_freeze_" + self.name, "plan",
                                t0, _time.perf_counter() - t0,
                                comm_id=self.comm.cid)
                # lower the fresh plan into the C executor (two
                # wire-free probe runs + descriptor compile + ring
                # bind); None = ineligible, replay stays interpreted
                from . import native_exec as _native
                self.native = _native.try_compile(
                    self, m, fn, args, kw)
            return out
        rec = _obs.enabled
        if rec:
            n = _trace_sample()
            self.fires += 1
            if n > 0 and self.fires % n == 0:
                # ground-truth deep trace: every Nth observed fire
                # runs fully interpreted (complete span/flow record);
                # the frozen plan survives for the next fire
                return fn(*args, **kw)
        nx = self.native
        if nx is not None and nx.gen == plan.gen:
            from . import native_exec as _native
            px = _native.NativeXchg(m, plan, nx, args)
        else:
            px = PlannedXchg(m, plan)
        t0 = 0.0
        if rec:
            if plan.ledger_id is None:
                plan.ledger_id = _ledger.register_spanning_plan(
                    self.comm.cid, self.name, m.my_pidx, plan.rounds,
                    self.sig)
            px.ts = []
            _active_replays[id(self)] = (self, px)
            t0 = _time.perf_counter()
        m._xchg = px
        try:
            out = fn(*args, **kw)
        except BaseException:
            # ANY replay failure — structure divergence, an FT error
            # mid-round — drops the frozen plan so the next fire
            # re-records instead of replaying the same stale rounds
            # forever (the divergence error's own advice, "re-issue
            # the collective", must actually work)
            self.plan = None
            self._drop_native()
            raise
        finally:
            m._xchg = old
            if rec:
                _active_replays.pop(id(self), None)
        _compiled_hits.observe(1)
        if rec and _obs.enabled:
            # one fixed-size binary record; round0 is the hier round
            # counter _wrap advanced for this fire (synchronized
            # across ranks under obs), the flow-id base the doctor's
            # expansion shares with the interpreted path
            _ledger.record_fire(_ledger.KIND_SPANNING, plan.ledger_id,
                                self.comm.cid, t0,
                                _time.perf_counter(),
                                round0=m._round, round_ts=px.ts)
        return out


def spanning_state_for(comm, name: str, args: Tuple,
                       kw: Optional[Dict]) -> Optional[SpanningPlanState]:
    """The comm's plan state for this call signature (None = not
    plannable: ragged buffers, non-deterministic families)."""
    if name not in _PLANNABLE:
        return None
    sig = signature_of(name, args, kw)
    if sig is None:
        return None
    key = (comm.cid, sig)
    st = _span_states.get(key)
    if st is None:
        with _lock:
            st = _span_states.setdefault(
                key, SpanningPlanState(comm, name, sig))
    return st


def spanning_wrap(state: Optional[SpanningPlanState],
                  fn: Callable) -> Callable:
    """Wrap one schedule body so its execution (on whichever thread
    the progress engine runs it) records/replays through ``state``."""
    if state is None:
        return fn
    return lambda *a, **k: state.run(fn, a, k)
