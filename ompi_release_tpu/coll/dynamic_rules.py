"""tuned dynamic rule files — operator-supplied decision tables
(``ompi/mca/coll/tuned/coll_tuned_dynamic_file.c`` +
``coll_tuned_dynamic_rules.c`` analogue).

The reference lets an operator replace tuned's compiled-in decision
constants with a rule file mapping (collective, communicator size,
message size) to an algorithm, selected with
``--mca coll_tuned_use_dynamic_rules 1 --mca
coll_tuned_dynamic_rules_filename FILE``.  Same feature here, with a
readable line format instead of the reference's positional numeric
one::

    # collective  min_comm_size  min_msg_bytes  algorithm
    allreduce     0              0              recursive_doubling
    allreduce     0              1048576        ring
    alltoall      8              0              pairwise

The LAST line whose ``min_comm_size <= comm.size`` and
``min_msg_bytes <= message bytes`` wins (file order = increasing
specificity, mirroring the reference's nested size tables).  An
algorithm of ``auto`` falls through to the fixed decision constants.

``min_msg_bytes`` is measured in each collective's OWN decision
unit — the same size its fixed decision rule tests, exactly like the
reference (each ``*_intra_dec_fixed`` computes its own
dsize/block_dsize/total_dsize):

======== =================================================
allreduce  bytes per rank (``block_dsize``)
bcast      bytes per rank
reduce     bytes per rank
gather     bytes per rank (the per-rank block the root collects)
scatter    bytes per DESTINATION BLOCK (per-rank / n)
allgather  TOTAL bytes across the comm (``total_dsize``,
           coll_tuned_decision_fixed.c:535)
alltoall   bytes per DESTINATION BLOCK (``block_dsize``,
           coll_tuned_decision_fixed.c:122 — per-rank / n)
======== =================================================

For reduce, a rule naming ``binomial`` on a NONCOMMUTATIVE op is
upgraded to ``in_order_binary`` (binomial's root-relative vranks
rotate operand order; a config file cannot waive MPI semantics).

Precedence inside the tuned component: operator forcing
(``coll_tuned_<op>_algorithm``) > dynamic rules > fixed constants —
the reference's order (forcing checked first in
``coll_tuned_<op>_intra_dec_dynamic``, falling back to the rule
table, then to the fixed decisions).

Unknown collectives or algorithms fail at LOAD time with the file and
line number: a typo'd rule silently reverting to defaults would defeat
the operator's tuning run.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

from ..mca import var as mca_var
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.Stream("coll")

#: collective name -> algorithms a rule may name (filled by
#: components.py at import; kept here to avoid a cycle)
RULE_COLLECTIVES: Dict[str, Tuple[str, ...]] = {}

# (path, mtime_ns, size) -> parsed rules; a rewritten file is
# re-parsed, an unchanged one costs a stat per lookup.  mtime_ns +
# size (not float mtime): some filesystems round mtime to 1 s, so a
# rewrite landing within the same second as the first parse would
# otherwise keep serving stale rules.  Collectives may run from
# multiple threads; _cache_lock guards every _cache access.
_cache: Dict[Tuple[str, int, int], Dict[str, List[Tuple[int, int, str]]]] = {}
_cache_lock = threading.Lock()


def load_rules(path: str) -> Dict[str, List[Tuple[int, int, str]]]:
    """Parse a rule file into {collective: [(min_n, min_bytes, alg)]}
    preserving file order."""
    try:
        lines = open(path).read().splitlines()
    except OSError as e:
        raise MPIError(ErrorCode.ERR_FILE,
                       f"cannot read dynamic rules file {path}: {e}")
    rules: Dict[str, List[Tuple[int, int, str]]] = {}
    for lineno, line in enumerate(lines, 1):
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        if len(parts) != 4:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"{path}:{lineno}: expected 'collective min_comm_size "
                f"min_msg_bytes algorithm', got '{line}'",
            )
        coll, n_s, bytes_s, alg = parts
        if coll not in RULE_COLLECTIVES:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"{path}:{lineno}: unknown collective '{coll}' "
                f"(rule-capable: {', '.join(sorted(RULE_COLLECTIVES))})",
            )
        try:
            min_n, min_bytes = int(n_s), int(bytes_s)
        except ValueError:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"{path}:{lineno}: sizes must be integers in '{line}'",
            )
        if min_n < 0 or min_bytes < 0:
            raise MPIError(ErrorCode.ERR_ARG,
                           f"{path}:{lineno}: sizes must be >= 0")
        if alg not in RULE_COLLECTIVES[coll]:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"{path}:{lineno}: unknown {coll} algorithm '{alg}' "
                f"(choices: {', '.join(RULE_COLLECTIVES[coll])})",
            )
        rules.setdefault(coll, []).append((min_n, min_bytes, alg))
    return rules


def lookup(coll: str, comm_size: int, msg_bytes: int) -> Optional[str]:
    """The algorithm the operator's rule file picks for this call, or
    None (no file configured / no matching rule / rule says auto)."""
    if not mca_var.get("coll_tuned_use_dynamic_rules", False):
        return None
    path = mca_var.get("coll_tuned_dynamic_rules_filename", "")
    if not path:
        return None
    try:
        st = os.stat(path)
        key = (path, st.st_mtime_ns, st.st_size)
    except OSError as e:
        # the file vanished MID-RUN (scratch-dir cleanup): keep
        # serving the last successfully parsed copy rather than
        # turning a config deletion into a crash inside the
        # collective hot path; only a file that never parsed is fatal
        with _cache_lock:
            rules_for_path = next(
                (r for (p, _, _), r in _cache.items() if p == path), None
            )
        if rules_for_path is None:
            raise MPIError(ErrorCode.ERR_FILE,
                           f"dynamic rules file {path} unreadable: {e}")
        _log.verbose(1, f"dynamic rules file {path} vanished; "
                        "keeping the last parsed rules")
        key = None
    if key is not None:
        with _cache_lock:
            rules_for_path = _cache.get(key)
        if rules_for_path is None:
            # parse BEFORE dropping the old copy (and outside the
            # lock: load_rules may raise on a mid-run rewrite with a
            # syntax error, and the last-good rules must stay cached
            # so deleting the broken file falls back to them)
            parsed = load_rules(path)
            with _cache_lock:
                _cache.clear()  # at most one live file; drop stale keys
                _cache[key] = parsed
            rules_for_path = parsed
    picked: Optional[str] = None
    for min_n, min_bytes, alg in rules_for_path.get(coll, ()):
        if comm_size >= min_n and msg_bytes >= min_bytes:
            picked = alg
    if picked == "auto":
        return None
    if picked is not None:
        _log.verbose(3, f"dynamic rule: {coll} n={comm_size} "
                        f"bytes={msg_bytes} -> {picked}")
    return picked
