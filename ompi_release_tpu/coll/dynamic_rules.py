"""tuned dynamic rule files — operator-supplied decision tables
(``ompi/mca/coll/tuned/coll_tuned_dynamic_file.c`` +
``coll_tuned_dynamic_rules.c`` analogue).

The reference lets an operator replace tuned's compiled-in decision
constants with a rule file mapping (collective, communicator size,
message size) to an algorithm, selected with
``--mca coll_tuned_use_dynamic_rules 1 --mca
coll_tuned_dynamic_rules_filename FILE``.  Same feature here, with a
readable line format instead of the reference's positional numeric
one::

    # collective  min_comm_size  min_msg_bytes  algorithm  [segsize]
    allreduce     0              0              recursive_doubling
    allreduce     0              1048576        ring       262144
    alltoall      8              0              pairwise

The LAST line whose ``min_comm_size <= comm.size`` and
``min_msg_bytes <= message bytes`` wins (file order = increasing
specificity, mirroring the reference's nested size tables).  An
algorithm of ``auto`` falls through to the fixed decision constants.

The optional fifth column, ``segsize``, is the pipeline segment size
in bytes for that rule (``coll_tuned_<op>_segmentsize`` analogue,
consumed by :mod:`coll.pipeline`): pipeline-capable algorithms (ring
allreduce, binomial bcast/reduce) split messages into
``ceil(bytes / segsize)`` double-buffered segments.  ``auto`` (or an
omitted column) defers to the ``coll_pipeline_segsize`` cvar; ``0``
disables pipelining for calls matching the rule.  Size suffixes are
accepted (``256K``, ``1M``).  ``tpu-tune --segsizes`` sweeps this
column and emits measured values (:mod:`tools.tpu_tune`).
:func:`lookup_segsize` answers the segsize query with the same
last-match-wins semantics as :func:`lookup`.

``min_msg_bytes`` is measured in each collective's OWN decision
unit — the same size its fixed decision rule tests, exactly like the
reference (each ``*_intra_dec_fixed`` computes its own
dsize/block_dsize/total_dsize):

======== =================================================
allreduce  bytes per rank (``block_dsize``)
bcast      bytes per rank
reduce     bytes per rank
gather     bytes per rank (the per-rank block the root collects)
scatter    bytes per DESTINATION BLOCK (per-rank / n)
allgather  TOTAL bytes across the comm (``total_dsize``,
           coll_tuned_decision_fixed.c:535)
alltoall   bytes per DESTINATION BLOCK (``block_dsize``,
           coll_tuned_decision_fixed.c:122 — per-rank / n)
======== =================================================

For reduce, a rule naming ``binomial`` on a NONCOMMUTATIVE op is
upgraded to ``in_order_binary`` (binomial's root-relative vranks
rotate operand order; a config file cannot waive MPI semantics).

``hier_<collective>`` rules select the INTER-process schedule of
spanning collectives (:mod:`coll.hier_schedules`): there
``min_comm_size`` matches the PROCESS count of the spanning comm, and
``min_msg_bytes`` the inter decision unit (partial/block bytes;
allgather: total bytes; alltoall: per-pair chunk bytes). A
``hier_allreduce`` rule naming ``ring``/``rabenseifner`` for a
non-commutative or identity-less op is downgraded to
``recursive_doubling`` — the same cannot-waive-semantics guard.

Precedence inside the tuned component: operator forcing
(``coll_tuned_<op>_algorithm``) > dynamic rules > fixed constants —
the reference's order (forcing checked first in
``coll_tuned_<op>_intra_dec_dynamic``, falling back to the rule
table, then to the fixed decisions).

Unknown collectives or algorithms fail at LOAD time with the file and
line number: a typo'd rule silently reverting to defaults would defeat
the operator's tuning run.

Rule files may carry an optional topology-fingerprint header stanza::

    # fingerprint: hosts=8;ppn=8;links=shm+dcn;P=64
    # version: 2

— parsed (malformed stanzas fail at load time), exposed through
:func:`load_rules_doc` / :func:`rules_source`, and used by the tuning
database (:mod:`..tuning.db`) to key versioned entries. When
``coll_tuning_db_dir`` is set and NO explicit rules filename is, the
best-matching database entry for the job's topology fingerprint is
selected automatically at comm construction; precedence is unchanged
(forcing > rules — explicit file > DB entry — > fixed constants).
Files without the stanza keep the exact legacy semantics.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

from ..mca import var as mca_var
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.Stream("coll")

#: collective name -> algorithms a rule may name (filled by
#: components.py at import; kept here to avoid a cycle)
RULE_COLLECTIVES: Dict[str, Tuple[str, ...]] = {
    # parallel/tree planned whole-tree passes register here directly
    # (no algorithm module to cycle with): min_comm_size is the
    # participant count, min_msg_bytes the TOTAL tree bytes, and the
    # 5th (segsize) column the fused bucket capacity in bytes;
    # "per_leaf" pins bucketing off. Emitted by tpu-tune
    # --tree-buckets, consumed by parallel.tree.resolve_bucket_bytes.
    "tree_buckets": ("auto", "fused", "per_leaf"),
}

# (path, mtime_ns, size) -> (parsed rules, header meta); a rewritten
# file is re-parsed, an unchanged one costs a stat per lookup.
# mtime_ns + size (not float mtime): some filesystems round mtime to
# 1 s, so a rewrite landing within the same second as the first parse
# would otherwise keep serving stale rules.  Collectives may run from
# multiple threads; _cache_lock guards every _cache access.
_cache: Dict[Tuple[str, int, int], Tuple[Dict, Dict]] = {}
_cache_lock = threading.Lock()


def load_rules_doc(path: str) -> Tuple[
        Dict[str, List[Tuple[int, int, str, Optional[int]]]], Dict]:
    """Parse a rule file into ``(rules, meta)``: rules is
    {collective: [(min_n, min_bytes, alg, segsize)]} preserving file
    order (``segsize`` None when the fifth column is absent or
    ``auto``); meta carries the optional topology-fingerprint header
    stanza — ``{"fingerprint": canonical str | None, "version":
    int | None}``. The stanza is PARSED, not skipped as a comment: a
    malformed ``# fingerprint:`` line fails at load time (a tuning-db
    entry with an unreadable key would be silently unselectable).
    Files without the stanza keep the exact legacy semantics."""
    try:
        lines = open(path).read().splitlines()
    except OSError as e:
        raise MPIError(ErrorCode.ERR_FILE,
                       f"cannot read dynamic rules file {path}: {e}")
    from ..tuning import db as _tuning_db

    rules: Dict[str, List[Tuple[int, int, str, Optional[int]]]] = {}
    meta: Dict = {"fingerprint": None, "version": None}
    for lineno, line in enumerate(lines, 1):
        m = _tuning_db.FP_LINE_RE.match(line)
        if m:
            try:
                fp = _tuning_db.Fingerprint.parse(m.group(1))
            except ValueError as e:
                raise MPIError(ErrorCode.ERR_ARG,
                               f"{path}:{lineno}: {e}")
            meta["fingerprint"] = fp.canon()
            continue
        m = _tuning_db.VERSION_LINE_RE.match(line)
        if m:
            meta["version"] = int(m.group(1))
            continue
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split()
        if len(parts) not in (4, 5):
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"{path}:{lineno}: expected 'collective min_comm_size "
                f"min_msg_bytes algorithm [segsize]', got '{line}'",
            )
        coll, n_s, bytes_s, alg = parts[:4]
        if coll not in RULE_COLLECTIVES:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"{path}:{lineno}: unknown collective '{coll}' "
                f"(rule-capable: {', '.join(sorted(RULE_COLLECTIVES))})",
            )
        try:
            min_n, min_bytes = int(n_s), int(bytes_s)
        except ValueError:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"{path}:{lineno}: sizes must be integers in '{line}'",
            )
        if min_n < 0 or min_bytes < 0:
            raise MPIError(ErrorCode.ERR_ARG,
                           f"{path}:{lineno}: sizes must be >= 0")
        if alg not in RULE_COLLECTIVES[coll]:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"{path}:{lineno}: unknown {coll} algorithm '{alg}' "
                f"(choices: {', '.join(RULE_COLLECTIVES[coll])})",
            )
        segsize: Optional[int] = None
        if len(parts) == 5 and parts[4] != "auto":
            try:
                segsize = mca_var.parse_size(parts[4])
            except ValueError:
                raise MPIError(
                    ErrorCode.ERR_ARG,
                    f"{path}:{lineno}: segsize must be bytes (suffixes "
                    f"K/M/G ok) or 'auto', got '{parts[4]}'",
                )
        rules.setdefault(coll, []).append((min_n, min_bytes, alg, segsize))
    return rules, meta


def load_rules(path: str) -> Dict[str, List[Tuple[int, int, str,
                                                  Optional[int]]]]:
    """Back-compat view of :func:`load_rules_doc`: the rule table
    alone."""
    return load_rules_doc(path)[0]


def _db_selected_path() -> Optional[str]:
    """The tuning database's entry for the active topology
    fingerprint, or None (no ``coll_tuning_db_dir`` configured / no
    matching entry — fall through to the fixed constants, exactly as
    if no file were named)."""
    if not mca_var.get("coll_tuning_db_dir", ""):
        return None
    from ..tuning import db as _tuning_db

    return _tuning_db.select_rules_path()


def _active_doc() -> Tuple[Optional[Dict], Optional[Dict],
                           Optional[str], str]:
    """(rules, meta, path, mode) of the currently configured rule
    table; (None, None, None, "off") when dynamic rules are off or
    nothing is configured. The explicit filename outranks the
    database (an operator pinning ONE file means that file); the
    stat-based cache and the vanished-mid-run fallback are as before."""
    if not mca_var.get("coll_tuned_use_dynamic_rules", False):
        return None, None, None, "off"
    path = mca_var.get("coll_tuned_dynamic_rules_filename", "")
    mode = "file"
    if not path:
        path = _db_selected_path()
        mode = "db"
        if not path:
            return None, None, None, "off"
    try:
        st = os.stat(path)
        key = (path, st.st_mtime_ns, st.st_size)
    except OSError as e:
        # the file vanished MID-RUN (scratch-dir cleanup): keep
        # serving the last successfully parsed copy rather than
        # turning a config deletion into a crash inside the
        # collective hot path; only a file that never parsed is fatal
        with _cache_lock:
            doc = next(
                (d for (p, _, _), d in _cache.items() if p == path), None
            )
        if doc is None:
            raise MPIError(ErrorCode.ERR_FILE,
                           f"dynamic rules file {path} unreadable: {e}")
        _log.verbose(1, f"dynamic rules file {path} vanished; "
                        "keeping the last parsed rules")
        key = None
    if key is not None:
        with _cache_lock:
            doc = _cache.get(key)
        if doc is None:
            # parse BEFORE dropping the old copy (and outside the
            # lock: load_rules may raise on a mid-run rewrite with a
            # syntax error, and the last-good rules must stay cached
            # so deleting the broken file falls back to them)
            parsed = load_rules_doc(path)
            with _cache_lock:
                _cache.clear()  # at most one live file; drop stale keys
                _cache[key] = parsed
            doc = parsed
    return doc[0], doc[1], path, mode


def _active_rules() -> Optional[Dict[str, List[Tuple[int, int, str,
                                                     Optional[int]]]]]:
    return _active_doc()[0]


def rules_source() -> Dict[str, Optional[str]]:
    """Where the live rule table comes from — what ``obs --selftest``
    and tpu-doctor print: ``{"mode": "off" | "file" | "db", "path",
    "fingerprint"}`` (fingerprint = the loaded file's stamped header,
    None for legacy files)."""
    rules, meta, path, mode = _active_doc()
    return {"mode": mode, "path": path,
            "fingerprint": (meta or {}).get("fingerprint")}


def lookup(coll: str, comm_size: int, msg_bytes: int) -> Optional[str]:
    """The algorithm the operator's rule file picks for this call, or
    None (no file configured / no matching rule / rule says auto)."""
    rules = _active_rules()
    if rules is None:
        return None
    picked: Optional[str] = None
    for min_n, min_bytes, alg, _segsize in rules.get(coll, ()):
        if comm_size >= min_n and msg_bytes >= min_bytes:
            picked = alg
    if picked == "auto":
        return None
    if picked is not None:
        _log.verbose(3, f"dynamic rule: {coll} n={comm_size} "
                        f"bytes={msg_bytes} -> {picked}")
    return picked


def lookup_segsize(coll: str, comm_size: int,
                   msg_bytes: int) -> Optional[int]:
    """The pipeline segment size the rule file picks for this call, or
    None (no file / no matching rule / rule says auto) — the caller
    (``coll/pipeline.py``) falls back to the ``coll_pipeline_segsize``
    cvar. Last matching rule wins, same as :func:`lookup`."""
    rules = _active_rules()
    if rules is None:
        return None
    picked: Optional[int] = None
    for min_n, min_bytes, _alg, segsize in rules.get(coll, ()):
        if comm_size >= min_n and msg_bytes >= min_bytes:
            picked = segsize
    if picked is not None:
        _log.verbose(3, f"dynamic rule: {coll} n={comm_size} "
                        f"bytes={msg_bytes} -> segsize={picked}")
    return picked
