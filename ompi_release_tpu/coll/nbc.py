"""Nonblocking & persistent collectives — the ``coll/libnbc`` analogue.

The reference implements ``MPI_Iallreduce``-class operations as round
schedules advanced by the progress engine (``ompi/mca/coll/libnbc/
nbc.c``: build the schedule, return a handle, progress rounds off the
caller) and MPI-4 persistent collectives (``MPI_Allreduce_init``) as a
schedule built ONCE and fired by ``MPI_Start`` many times. This module
is that layer for the TPU runtime, split by communicator kind:

in-process comms
    XLA async dispatch IS the progress engine: the compiled program is
    the round schedule, dispatch returns future arrays, and
    :func:`async_request` wraps them in a Request whose readiness is
    the arrays' readiness. The request is registered with the
    progress engine's poll list so a tick (or the progress thread)
    completes it off the caller.

spanning comms (``tpurun`` multi-process worlds)
    The hier collective's wire exchanges block, so the whole round
    schedule becomes a :class:`~runtime.progress.ScheduledOp` posted to
    the :mod:`runtime.progress` engine. Dispatch never touches the
    wire (and performs no ``block_until_ready``); execution happens in
    posting order — at ``wait()`` on the caller (polling mode) or off
    the caller on the progress thread (``progress_thread`` cvar). Each
    op carries a wire pump so engine ticks reap the comm's completed
    transfers into the router's early-transfer queue while the
    schedule is still queued or mid-round.

Blocking spanning collectives are expressed through the SAME machinery
— :func:`run_blocking` posts the schedule and waits it — so there is
exactly one round-advancing code path (the old per-comm worker
executor is gone). Persistent collectives build their plan once at
``*_init`` (the dispatch closure: resolved c_coll entry, op object,
bound buffers, memoized plan signature) and ``Request.start()``
re-fires it against the CURRENT buffer contents, the MPI persistent
buffer-reuse contract — through :mod:`coll.plan`'s frozen schedule
plans: in-process starts launch ONE cached compiled XLA program,
spanning starts replay precomposed wire rounds (peer lists, frame
headers, fragment offsets resolved at plan time). Blocking and
i-family collectives ride the same per-(cid, signature) plan cache.

Bitwise parity is structural: the nonblocking path runs the identical
collective function the blocking path runs, only later and possibly on
another thread — same schedules, same exact-order folds, same
non-commutative discipline.
"""

from __future__ import annotations

import time as _time
from typing import Any, Callable, Dict, Optional, Tuple

from .. import obs as _obs
from ..mca import pvar
from ..obs import sentinel as _sentinel
from ..request.request import Request
from ..runtime import progress as _progress
from ..utils.errors import ErrorCode, MPIError
from . import plan as _plan

_ops_posted = pvar.counter(
    "nbc_ops_posted",
    "nonblocking/blocking collective schedules posted to the progress "
    "engine (spanning comms)",
)
_persistent_starts = pvar.counter(
    "nbc_persistent_starts",
    "persistent-collective start() fires (plans built once at *_init)",
)
# the SAME registered timer coll/driver feeds (registration is
# idempotent): here it covers the spanning POSTING prelude — sentinel
# note, op construction, engine enqueue — the Python-orchestration
# segment before the schedule/wire takes over
_orch = pvar.timer(
    "coll_orchestration_seconds",
    "Python orchestration seconds on the collective dispatch path "
    "(decision, planning, validation, posting — before the compiled "
    "program or wire transport takes over)",
)


def _comm_key(comm) -> Tuple[str, int]:
    return ("comm", comm.cid)


def _make_pump(comm) -> Callable[[], int]:
    """The op's receive-side wire tick: reap completed collective
    transfers on this comm's payload channel into the router's
    early-transfer queue (a no-op once the comm is freed)."""

    def pump() -> int:
        router = getattr(comm.runtime, "wire", None)
        if router is None or getattr(comm, "_freed", False):
            return 0
        return router.coll_pump(comm)

    return pump


def _make_op(comm, name: str, fn: Callable, args: Tuple,
             kw: Optional[Dict]) -> _progress.ScheduledOp:
    return _progress.ScheduledOp(
        _comm_key(comm), name, fn, cid=comm.cid, args=args,
        kw=kw or {}, pump=_make_pump(comm),
    )


def _post(comm, op: _progress.ScheduledOp) -> _progress.ScheduledOp:
    """Hand one fully-wired op to the engine. Completion callbacks
    MUST be attached before this call: with the progress thread on,
    the schedule can run to completion the instant it is posted."""
    _ops_posted.add()
    rec = _obs.enabled  # capture once: flag may flip mid-post
    t0 = _time.perf_counter() if rec else 0.0
    _progress.engine().post(op)
    if rec and _obs.enabled:
        _obs.record("nbc_post", "nbc", t0, _time.perf_counter() - t0,
                    comm_id=comm.cid)
    return op


def _op_request(op: _progress.ScheduledOp) -> Request:
    """Bind one NOT-YET-POSTED schedule to a Request (the callback is
    attached here, before the engine can run the op): test() advances
    the engine one bounded step toward this op (and surfaces a
    schedule error), wait() drives the engine's posting-order drain,
    completion carries the schedule's result."""
    eng = _progress.engine()

    def prog(_r, _op=op, _eng=eng) -> None:
        _eng.advance_toward(_op)
        if _op.done.is_set() and _op.error is not None:
            raise _op.error

    def block(_op=op, _eng=eng) -> None:
        _eng.wait(_op)  # raises the schedule's error

    req = Request(progress_fn=prog, block_fn=block)
    # expose the schedule handle: per-pass consumers (parallel/tree's
    # hidden-time accounting) read its t_start/t_done/t_first_wait
    req._sched_op = op

    def finish(o, _req=req) -> None:
        if o.error is None:
            _req.complete(value=o.result)

    op.callbacks.append(finish)
    return req


def _inline_tpl(state, sig):
    """Sentinel level 2's precomposed ctl-frame payload, cached on
    the frozen-plan state (one JSON encode per plan signature, not
    per fire) — None when the call is unplannable or unsigned, where
    wrap_inline falls back to the per-fire encoding."""
    if state is None or sig is None:
        return None
    key = (sig.canon, sig.site)
    tpl = state.sentinel_tpl
    if tpl is None or tpl[0] != key:
        state.sentinel_tpl = tpl = (
            key, _sentinel.InlineFrameTemplate(sig.canon, sig.site))
    return tpl[1]


def _resolve(comm, name: str) -> Callable:
    fn = comm.c_coll.get(name)
    if fn is None:
        raise MPIError(
            ErrorCode.ERR_INTERN,
            f"no {name} implementation installed on {comm.name}",
        )
    return fn


# ---------------------------------------------------------------------------
# in-process: XLA async dispatch wrapped as a Request
# ---------------------------------------------------------------------------

def async_request(value) -> Request:
    """Wrap already-dispatched (future) arrays as a Request and hand it
    to the engine's poll list, so completion happens at the next tick —
    caller's or the progress thread's — instead of only at test()."""
    import jax

    arrs = [a for a in jax.tree.leaves(value) if hasattr(a, "is_ready")]
    req = Request(
        ready_fn=lambda: all(a.is_ready() for a in arrs),
        block_fn=lambda: jax.block_until_ready(value),
    )
    req.value = value
    _progress.engine().add_poll(req)
    return req


# ---------------------------------------------------------------------------
# public entry points (Communicator delegates here)
# ---------------------------------------------------------------------------

def _nested_inline(comm, fn, args, kw) -> Optional[Request]:
    """An i-collective issued from INSIDE a running schedule on the
    same comm cannot queue: the outer op owns the queue head until it
    completes, so the nested op could never be claimed and waiting it
    would hang. MPI permits a nonblocking op to complete at
    initiation — run it inline (sequential on this thread, so frames
    cannot interleave; the old per-comm-worker path did the same) and
    return an already-complete Request. None when not nested."""
    cur = _progress.engine().executing()
    if cur is None or cur.key != _comm_key(comm):
        return None
    req = Request()
    req.complete(value=fn(*args, **(kw or {})))
    return req


def icoll(comm, name: str, args: Tuple, kw: Optional[Dict] = None
          ) -> Request:
    """Nonblocking collective: dispatch returns before completion for
    every family (no ``block_until_ready`` on the dispatch path)."""
    t0 = _time.perf_counter()
    comm._check_usable()
    fn = _resolve(comm, name)
    # contract sentinel: the call signature is derived at POSTING time
    # (the user frame is on the stack, the per-comm posting seq is
    # this slot); inline verification, if any, runs at execution
    sig = _sentinel.note(comm, name, args, kw) if _sentinel.enabled \
        else None
    if not comm.spans_processes:
        # steady state: a previously-seen signature fires its frozen
        # compiled program through coll/plan instead of re-running the
        # interpreted decision path
        return async_request(
            _plan.dispatch(comm, name, fn, tuple(args), kw))
    nested = _nested_inline(comm, fn, (comm,) + tuple(args), kw)
    if nested is not None:
        return nested
    state = _plan.spanning_state_for(comm, name, args, kw)
    if sig is not None:
        fn = _sentinel.wrap_inline(comm, sig, fn,
                                   _inline_tpl(state, sig))
    run = _plan.spanning_wrap(state, fn)
    op = _make_op(comm, name, run, (comm,) + tuple(args), kw)
    req = _op_request(op)  # callback wired BEFORE the engine sees it
    _post(comm, op)
    _orch.add(_time.perf_counter() - t0)
    return req


def run_blocking(comm, name: str, fn: Callable, args: Tuple,
                 kw: Optional[Dict] = None) -> Any:
    """A blocking spanning collective = fire the NBC schedule + wait —
    the one round-advancing code path. A collective nested inside a
    running schedule on the SAME comm (two-phase IO's closing barrier)
    runs inline on the executing thread — sequential, so frames on the
    comm's channel cannot interleave and the outer op still owns the
    queue head. A nested call onto a DIFFERENT comm posts through that
    comm's queue like any other (the engine's claim rule is the one
    arbiter of who runs on a channel — an inline run could race a
    progress-thread/kick claim of another schedule on the same cid);
    the drain ledger skips ops running beneath this thread, so the
    nested wait cannot self-deadlock on its own outer op."""
    t0 = _time.perf_counter()
    eng = _progress.engine()
    cur = eng.executing()
    if cur is not None and cur.key == _comm_key(comm):
        return fn(*args, **(kw or {}))
    # the sentinel notes against the USER-FACING args (args[0] is the
    # comm for c_coll entries; note() strips it), and the plan state
    # keys on the same signature the i-family/persistent paths use
    user_args = args[1:] if args and args[0] is comm else args
    state = _plan.spanning_state_for(comm, name, user_args, kw)
    if _sentinel.enabled:
        sig = _sentinel.note(comm, name, user_args, kw)
        if sig is not None:
            fn = _sentinel.wrap_inline(comm, sig, fn,
                                       _inline_tpl(state, sig))
    run = _plan.spanning_wrap(state, fn)
    op = _make_op(comm, name, run, args, kw)
    _post(comm, op)
    _orch.add(_time.perf_counter() - t0)
    return eng.wait(op)


def submit(comm, name: str, fn: Callable, args: Tuple,
           kw: Optional[Dict] = None) -> Request:
    """Nonblocking run of an arbitrary collective-ordered callable on
    the comm's schedule queue (the nonblocking collective-IO path):
    keeps posting order with every other collective on the comm."""
    comm._check_usable()
    nested = _nested_inline(comm, fn, args, kw)
    if nested is not None:
        return nested
    if _sentinel.enabled:
        sig = _sentinel.note(comm, name, args, kw)
        if sig is not None:
            fn = _sentinel.wrap_inline(comm, sig, fn)
    op = _make_op(comm, name, fn, args, kw)
    req = _op_request(op)
    _post(comm, op)
    return req


def drain_comm(comm) -> None:
    """Complete every outstanding schedule on ``comm`` in posting
    order (comm free path: peers participate in the queued
    collectives, so they must run, not vanish)."""
    _progress.engine().drain_key(_comm_key(comm))


# ---------------------------------------------------------------------------
# persistent collectives (MPI_Allreduce_init / MPI_Start)
# ---------------------------------------------------------------------------

def persistent(comm, name: str, args: Tuple, kw: Optional[Dict] = None
               ) -> Request:
    """Build the plan ONCE, fire it per start(): the c_coll entry and
    argument binding resolve now; each ``Request.start()`` re-fires the
    plan against the bound buffers' CURRENT contents (MPI persistent
    buffer reuse) without blocking — a fresh schedule posts to the
    engine (spanning) or a fresh async dispatch launches (in-process,
    where the compiled program cached at first fire IS the plan)."""
    comm._check_usable()
    kw = kw or {}
    if name == "barrier" and not comm.spans_processes:
        ifn = comm.c_coll.get("ibarrier")

        def fire() -> Request:
            if ifn is not None:
                if _sentinel.enabled:
                    _sentinel.note(comm, "barrier")
                return async_request(ifn(comm))
            # provider thread fallback runs comm.barrier(), whose
            # _coll wrapper notes the signature itself — noting here
            # too would double-count the one collective
            return comm.ibarrier()
    else:
        fn = _resolve(comm, name)
        if comm.spans_processes:
            # the frozen wire plan is built ONCE per (cid, signature):
            # the first start() records the round structure, every
            # later start() replays precomposed frames (coll/plan)
            state = _plan.spanning_state_for(comm, name, args, kw)

            def fire() -> Request:
                t0 = _time.perf_counter()
                # each start() is one collective round: it takes its
                # own posting-seq slot in the comm's signature chain
                run = fn
                if _sentinel.enabled:
                    sig = _sentinel.note(comm, name, args, kw)
                    if sig is not None:
                        run = _sentinel.wrap_inline(
                            comm, sig, fn, _inline_tpl(state, sig))
                run = _plan.spanning_wrap(state, run)
                op = _make_op(comm, name, run, (comm,) + tuple(args),
                              kw)
                inner = _op_request(op)
                _post(comm, op)
                _orch.add(_time.perf_counter() - t0)
                return inner
        else:
            sig_box: list = []  # signature computed once, not per start

            def fire() -> Request:
                if _sentinel.enabled:
                    _sentinel.note(comm, name, args, kw)
                # start() fires the signature's frozen compiled
                # program (the MPI-4 "plan built once" promise made
                # literal: one XLA program per plan, cached across
                # starts via coll/plan)
                return async_request(
                    _plan.dispatch(comm, name, fn, tuple(args), kw,
                                   sig_box=sig_box))

    def start(req) -> None:
        _persistent_starts.add()
        req._inner = fire()

    def prog(r) -> None:
        inner = getattr(r, "_inner", None)
        if inner is None:
            return
        done, _st = inner.test()
        if done and not r.is_complete:
            r.complete(value=inner.value, status=inner.status)

    req = Request(progress_fn=prog, persistent_start=start)

    def block() -> None:
        inner = req._inner
        st = inner.wait()
        req.complete(value=inner.value, status=st)

    req._block_fn = block
    req._inner = None
    return req
