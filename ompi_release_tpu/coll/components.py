"""coll components: ``xla`` (compiler-scheduled), ``tuned`` (named
algorithms + decision rules), ``basic`` (linear reference), ``self``
(size-1 fast path).

Priorities mirror the reference's layering logic: the hardware-offload
component outranks tuned outranks basic (reference: fca/hcoll > tuned 30
> basic 10), and ``self`` claims only size-1 communicators
(``ompi/mca/coll/self``).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..mca import component as mca_component
from ..mca import var as mca_var
from ..ops.op import Op
from ..utils import output
from . import dynamic_rules, hier_schedules, pipeline, spmd  # noqa: F401
from .base import COLL_FRAMEWORK
from .driver import run_sharded

_log = output.stream("coll")

AXIS = "rank"  # every comm submesh uses this axis name


def _per_rank_bytes(x) -> int:
    per_rank = x[0] if hasattr(x, "shape") else x
    return int(per_rank.size * per_rank.dtype.itemsize)


def _resolve_op(op: Op, x) -> Op:
    """Accelerated-kernel resolution for the local-reduction step of a
    hand-scheduled algorithm (the ``ompi/mca/op`` select): the pallas
    component claims large contiguous f32/bf16 SUMs, everything else
    stays on the XLA combiner. Resolution returns a DISTINCT op object
    (``sum[pallas]``), so the compiled-program cache keys — which embed
    the op itself — never mix the two kernels."""
    from ..ops import op as op_mod

    if op.is_pair_op or not hasattr(x, "dtype"):
        return op
    return op_mod.resolve(op, x.dtype, _per_rank_bytes(x))


# ---------------------------------------------------------------------------
# xla component — lower straight to XLA collectives
# ---------------------------------------------------------------------------

class _XlaModule:
    """Collectives as single fused XLA ops; the compiler plans the ICI
    schedule. This is the default data plane (BASELINE.json coll/xla)."""

    def __init__(self, comm) -> None:
        self.comm = comm

    def fns(self) -> Dict[str, Callable]:
        return {
            "allreduce": self.allreduce,
            "reduce": self.reduce,
            "bcast": self.bcast,
            "allgather": self.allgather,
            "gather": self.gather,
            "scatter": self.scatter,
            "reduce_scatter_block": self.reduce_scatter_block,
            "alltoall": self.alltoall,
            "scan": self.scan,
            "exscan": self.exscan,
            "barrier": self.barrier,
            "ibarrier": self.ibarrier,
            "alltoallv": self.alltoallv,
            "allgatherv": self.allgatherv,
            "gatherv": self.gatherv,
            "scatterv": self.scatterv,
            "reduce_scatter": self.reduce_scatter,
        }

    # each driver fn: key identifies the compiled program; all static
    # parameters must be part of the key — the op as an OBJECT (frozen,
    # hashable): keying by name would hand a same-named user op another
    # op's baked-in combiner
    def allreduce(self, comm, x, op: Op):
        if op.is_pair_op:
            vals, idxs = x
            return run_sharded(
                comm, ("xla", "allreduce_pair", op),
                lambda v, i: spmd.allreduce_pair_lax(v, i, op, AXIS),
                vals, extra_arrays=(idxs,),
            )
        return run_sharded(
            comm, ("xla", "allreduce", op),
            lambda xb: spmd.allreduce_lax(xb, op, AXIS), x,
        )

    def reduce(self, comm, x, op: Op, root: int):
        if op.is_pair_op:
            # MPI_Reduce with MINLOC/MAXLOC — THE canonical pair-op
            # call (global extremum + its location at the root)
            vals, idxs = x

            def pair_body(vb, ib):
                rv, ri = spmd.allreduce_pair_lax(vb, ib, op, AXIS)
                rank = lax.axis_index(AXIS)
                return (jnp.where(rank == root, rv, jnp.zeros_like(rv)),
                        jnp.where(rank == root, ri, jnp.zeros_like(ri)))

            return run_sharded(
                comm, ("xla", "reduce_pair", op, root),
                pair_body, vals, extra_arrays=(idxs,),
            )

        def body(xb):
            red = spmd.allreduce_lax(xb, op, AXIS)
            rank = lax.axis_index(AXIS)
            return jnp.where(rank == root, red, jnp.zeros_like(red))

        return run_sharded(comm, ("xla", "reduce", op, root), body, x)

    def bcast(self, comm, x, root: int):
        return run_sharded(
            comm, ("xla", "bcast", root),
            lambda xb: spmd.bcast_masked_psum(xb, xb.dtype, AXIS, root), x,
        )

    def allgather(self, comm, x):
        def body(xb):
            g = lax.all_gather(xb, AXIS, axis=0)  # (n, ...)
            return g.reshape((-1,) + g.shape[2:])

        return run_sharded(comm, ("xla", "allgather"), body, x)

    def gather(self, comm, x, root: int):
        return run_sharded(
            comm, ("xla", "gather", root),
            lambda xb: spmd.gather_linear(xb, AXIS, comm.size, root), x,
        )

    def scatter(self, comm, x, root: int):
        # x: root's slice holds n chunks back-to-back
        return run_sharded(
            comm, ("xla", "scatter", root),
            lambda xb: spmd.scatter_linear(xb, AXIS, comm.size, root), x,
        )

    def reduce_scatter_block(self, comm, x, op: Op):
        n = comm.size
        if op.is_pair_op:
            vals, idxs = x

            def pair_body(vb, ib):
                rv, ri = spmd.allreduce_pair_lax(vb, ib, op, AXIS)
                rank = lax.axis_index(AXIS)
                cv = rv.reshape((n, -1) + rv.shape[1:])
                ci = ri.reshape((n, -1) + ri.shape[1:])
                return (jnp.take(cv, rank, axis=0),
                        jnp.take(ci, rank, axis=0))

            return run_sharded(
                comm, ("xla", "rsb_pair", op),
                pair_body, vals, extra_arrays=(idxs,),
            )
        return run_sharded(
            comm, ("xla", "reduce_scatter_block", op),
            lambda xb: spmd.reduce_scatter_lax(xb, op, AXIS, n), x,
        )

    def alltoall(self, comm, x):
        n = comm.size

        def body(xb):
            blocks = xb.reshape((n, -1) + xb.shape[1:])
            out = spmd.alltoall_lax(blocks, AXIS, n)
            return out.reshape(xb.shape)

        return run_sharded(comm, ("xla", "alltoall"), body, x)

    def scan(self, comm, x, op: Op, *, exclusive: bool = False):
        n = comm.size
        if op.is_pair_op:
            # MPI_Scan with MINLOC/MAXLOC: associative_scan runs the
            # pair combiner over the gathered (value, index) pytree;
            # the rank-0 exscan slice is zeros (MPI leaves it
            # undefined)
            vals, idxs = x

            def pair_body(vb, ib):
                gv = lax.all_gather(vb, AXIS, axis=0)
                gi = lax.all_gather(ib, AXIS, axis=0)
                sv, si = lax.associative_scan(op, (gv, gi), axis=0)
                rank = lax.axis_index(AXIS)
                if exclusive:
                    pv = jnp.take(sv, jnp.maximum(rank - 1, 0), axis=0)
                    pi = jnp.take(si, jnp.maximum(rank - 1, 0), axis=0)
                    return (jnp.where(rank == 0, jnp.zeros_like(pv), pv),
                            jnp.where(rank == 0, jnp.zeros_like(pi), pi))
                return (jnp.take(sv, rank, axis=0),
                        jnp.take(si, rank, axis=0))

            return run_sharded(
                comm, ("xla", "scan_pair", op, exclusive),
                pair_body, vals, extra_arrays=(idxs,),
            )
        # the gather-based scan stages the WHOLE comm's buffers on
        # every rank (O(n * size) memory): past the limit, decline so
        # the chain falls to tuned's recursive-doubling scan, which
        # keeps per-rank memory O(size)
        if _per_rank_bytes(x) > int(mca_var.get(
                "coll_xla_scan_gather_limit", 1 << 20)):
            return None

        def body(xb):
            g = lax.all_gather(xb, AXIS, axis=0)  # (n, ...)
            s = lax.associative_scan(op, g, axis=0)
            rank = lax.axis_index(AXIS)
            if exclusive:
                prev = jnp.take(
                    s, jnp.maximum(rank - 1, 0), axis=0
                )
                return jnp.where(
                    rank == 0, jnp.zeros_like(prev), prev
                )
            return jnp.take(s, rank, axis=0)

        return run_sharded(
            comm, ("xla", "scan", op, exclusive), body, x
        )

    def exscan(self, comm, x, op: Op):
        return self.scan(comm, x, op, exclusive=True)

    def barrier(self, comm):
        jax.block_until_ready(self.ibarrier(comm))

    def ibarrier(self, comm):
        """Nonblocking barrier: dispatch the compiled barrier program
        and return its (future) output WITHOUT blocking — the libnbc
        round schedule (``nbc.c``) is the compiled program itself and
        XLA's async dispatch is the progress engine. The caller wraps
        the result in a Request whose readiness is the array's."""
        return run_sharded(
            comm, ("xla", "barrier"),
            lambda xb: spmd.barrier_psum(AXIS) + xb,
            jnp.zeros((comm.size,), jnp.int32),
        )

    # -- v-variants (padded lax kernels, counts at the driver edge) --------
    def alltoallv(self, comm, sendbufs, sendcounts):
        from . import vcoll

        return vcoll.alltoallv(comm, sendbufs, sendcounts, kernel="lax")

    def allgatherv(self, comm, sendbufs):
        from . import vcoll

        return vcoll.allgatherv(comm, sendbufs, kernel="lax")

    def gatherv(self, comm, sendbufs, root: int):
        from . import vcoll

        return vcoll.gatherv(comm, sendbufs, root, kernel="lax")

    def scatterv(self, comm, sendbuf, counts, root: int):
        from . import vcoll

        return vcoll.scatterv(comm, sendbuf, counts, root)

    def reduce_scatter(self, comm, x, recvcounts, op: Op):
        from . import vcoll

        return vcoll.reduce_scatter(comm, x, recvcounts, op, kernel="lax")


class XlaCollComponent(mca_component.Component):
    NAME = "xla"
    PRIORITY = 100

    def register_vars(self) -> None:
        mca_var.register(
            "coll_xla_scan_gather_limit", "size", 1 << 20,
            "Per-rank bytes above which the xla scan/exscan (all_gather"
            " + associative_scan, O(n*size) staged per rank) defers to "
            "tuned's recursive-doubling scan",
        )

    def query(self, ctx=None):
        if ctx is None:
            return (self.priority, self)
        if getattr(ctx, "spans_processes", False):
            return None  # cross-process comms belong to coll/hier
        return (self.priority, _XlaModule(ctx))


# ---------------------------------------------------------------------------
# tuned component — named algorithms + fixed decision rules
# ---------------------------------------------------------------------------

ALLREDUCE_ALGORITHMS = (
    # mirror of the enum coll_tuned_allreduce.c:46-54
    "auto", "basic_linear", "nonoverlapping", "recursive_doubling",
    "ring", "segmented_ring",
)
BCAST_ALGORITHMS = (
    # coll_tuned_bcast.c menu; split_bintree maps to binary_tree (the
    # split-halves+exchange trick optimizes bidirectional link use,
    # which the XLA scheduler owns on a compiled program); basic_linear
    # is masked_psum's one-shot
    "auto", "binomial", "binary_tree", "chain", "pipeline",
    "masked_psum",
)
ALLGATHER_ALGORITHMS = (
    # mirror of coll_tuned_allgather.c's menu (two_procs is subsumed
    # by bruck at n=2 — one round, identical exchange; the
    # even-n neighbor_exchange large-message case maps to ring, whose
    # structure IS the neighbor pass — substitutions documented in
    # the decision fn)
    "auto", "ring", "bruck", "recursive_doubling", "lax",
)
ALLTOALL_ALGORITHMS = (
    # coll_tuned_alltoall.c menu: basic_linear (all exchanges posted
    # at once = the one-shot fused lax.all_to_all here; two_procs is
    # its n=2 case), bruck (log-phase store-and-forward), pairwise
    "auto", "pairwise", "bruck", "basic_linear", "lax",
)
# coll_tuned_{gather,scatter}.c menus; both linear_sync branches map
# to linear (the sync round-trip protects an eager receiver from
# overrun — no analogue in a compiled SPMD exchange)
GATHER_ALGORITHMS = ("auto", "binomial", "linear")
SCATTER_ALGORITHMS = ("auto", "binomial", "linear")
# coll_tuned_reduce.c menu: binomial (commutative; the segmented
# binomial/pipeline picks keep its structure — segmentation is the
# compiler's domain in a compiled program), in_order_binary
# (noncommutative-safe contiguous-rank-range tree), linear (strict
# left fold)
REDUCE_ALGORITHMS = ("auto", "binomial", "in_order_binary", "linear")

# the collectives a dynamic rule file may target, with their legal
# algorithm names (consumed by coll/dynamic_rules.py at load time)
dynamic_rules.RULE_COLLECTIVES.update({
    "allreduce": ALLREDUCE_ALGORITHMS,
    "bcast": BCAST_ALGORITHMS,
    "allgather": ALLGATHER_ALGORITHMS,
    "alltoall": ALLTOALL_ALGORITHMS,
    "reduce": REDUCE_ALGORITHMS,
    "gather": GATHER_ALGORITHMS,
    "scatter": SCATTER_ALGORITHMS,
})
# (the hier_<coll> namespaces — the INTER-process schedules of
# spanning collectives — register themselves in coll/hier_schedules,
# which imports standalone; see hier_schedules.ALGORITHMS)


class _TunedModule:
    """Hand-written ppermute schedules with tuned's decision rules.

    Decision constants are the reference's
    (``coll_tuned_decision_fixed.c:51-83``): <10 kB → recursive
    doubling; commutative && count > comm_size → ring, segmented ring
    past comm_size × 1 MiB; otherwise nonoverlapping.
    """

    def __init__(self, comm) -> None:
        self.comm = comm

    def fns(self) -> Dict[str, Callable]:
        return {
            "allreduce": self.allreduce,
            "bcast": self.bcast,
            "reduce": self.reduce,
            "allgather": self.allgather,
            "gather": self.gather,
            "scatter": self.scatter,
            "reduce_scatter_block": self.reduce_scatter_block,
            "alltoall": self.alltoall,
            "scan": self.scan,
            "exscan": self.exscan,
            "barrier": self.barrier,
            "alltoallv": self.alltoallv,
            "allgatherv": self.allgatherv,
            "gatherv": self.gatherv,
            "scatterv": self.scatterv,
            "reduce_scatter": self.reduce_scatter,
        }

    # -- allreduce --------------------------------------------------------
    def _pick_allreduce(self, x, op: Op) -> str:
        forced = mca_var.get("coll_tuned_allreduce_algorithm", "auto")
        if forced != "auto":
            return forced
        n = self.comm.size
        count = x[0].size
        block_dsize = _per_rank_bytes(x)
        dyn = dynamic_rules.lookup("allreduce", n, block_dsize)
        if dyn is not None:
            if dyn in ("ring", "segmented_ring") and (
                    not op.commutative or op.identity is None):
                # a rule file cannot waive MPI semantics (same guard
                # as reduce below): ring's reduce-scatter folds chunks
                # in rotating ring order and pads with the identity —
                # downgrade to the rank-ordered fallback
                dyn = "nonoverlapping"
            return dyn
        if block_dsize < mca_var.get("coll_tuned_small_message", 10000):
            return "recursive_doubling"
        if op.commutative and count > n and op.identity is not None:
            seg = mca_var.get("coll_tuned_segment_size", 1 << 20)
            if n * seg >= block_dsize:
                return "ring"
            return "segmented_ring"
        return "nonoverlapping"

    def allreduce(self, comm, x, op: Op):
        if op.is_pair_op:
            return None  # pair ops stay with xla's gather path
        alg = self._pick_allreduce(x, op)
        if alg in ("ring", "segmented_ring") and (
                not op.commutative or op.identity is None):
            # mirrors reduce()'s order-invariant enforcement: the fixed
            # constants never pick ring here and a dynamic rule is
            # downgraded in the picker, so this catches operator forcing
            from ..utils.errors import ErrorCode, MPIError

            raise MPIError(
                ErrorCode.ERR_ARG,
                "ring allreduce folds chunks in rotating ring order and "
                "pads with the op identity; use nonoverlapping or "
                "recursive_doubling for this op",
            )
        op = _resolve_op(op, x)  # accelerated local-reduction kernel
        n = comm.size
        segsize = mca_var.get("coll_tuned_segment_size", 1 << 20)
        seg_elems = max(1, segsize // x.dtype.itemsize)
        bodies = {
            "basic_linear": lambda xb: spmd.allreduce_basic_linear(
                xb, op, AXIS, n
            ),
            "nonoverlapping": lambda xb: spmd.allreduce_nonoverlapping(
                xb, op, AXIS, n
            ),
            "recursive_doubling": lambda xb: spmd.allreduce_recursive_doubling(
                xb, op, AXIS, n
            ),
            "ring": lambda xb: spmd.allreduce_ring(xb, op, AXIS, n),
            "segmented_ring": lambda xb: spmd.allreduce_segmented_ring(
                xb, op, AXIS, n, seg_elems
            ),
        }
        if alg == "ring":
            # pipelined segmentation (coll/pipeline.py): above the
            # segsize the ring runs as double-buffered column segments
            # of the same chunk matrix — bitwise-identical to the
            # monolithic ring, keyed by segment count in the plan cache
            block_dsize = _per_rank_bytes(x)
            nseg = pipeline.segment_count("allreduce", n, block_dsize)
            if nseg > 1:
                _log.verbose(3, f"{comm.name}: tuned allreduce -> "
                                f"ring pipelined x{nseg}")
                return pipeline.run_pipelined(
                    comm, ("tuned", "allreduce", "ring", op),
                    lambda xb: pipeline.allreduce_ring_pipelined(
                        xb, op, AXIS, n, nseg),
                    x, nseg=nseg, nbytes=block_dsize,
                    opname="allreduce",
                )
        _log.verbose(3, f"{comm.name}: tuned allreduce -> {alg}")
        # the segment size is baked into the compiled program, so it
        # must be part of the cache key or later var changes would be
        # silently ignored
        key = ("tuned", "allreduce", alg, op) + (
            (seg_elems,) if alg == "segmented_ring" else ()
        )
        return run_sharded(comm, key, bodies[alg], x)

    # -- others -----------------------------------------------------------
    def _pick_bcast(self, x) -> tuple:
        """coll_tuned_decision_fixed.c bcast_intra_dec_fixed: < 2048 B
        -> binomial; < 370728 B -> split_bintree@1k (binary_tree
        here); larger -> pipeline with the segment size chosen by the
        reference's regression lines (128/64/16/8 KiB as the comm
        grows relative to a_pXX * msg + b_pXX). Returns
        (algorithm, segment_bytes)."""
        forced = mca_var.get("coll_tuned_bcast_algorithm", "auto")
        if forced != "auto":
            return forced, int(mca_var.get(
                "coll_tuned_bcast_segment_size", 128 << 10))
        n = self.comm.size
        msg = _per_rank_bytes(x)
        dyn = dynamic_rules.lookup("bcast", n, msg)
        if dyn is not None:
            return dyn, int(mca_var.get(
                "coll_tuned_bcast_segment_size", 128 << 10))
        if msg < 2048:
            return "binomial", 0
        if msg < 370728:
            return "binary_tree", 1 << 10
        if n < 1.6134e-6 * msg + 2.1102:   # a_p128/b_p128
            return "pipeline", 128 << 10
        if n < 13:
            return "binary_tree", 8 << 10
        if n < 2.3679e-6 * msg + 1.1787:   # a_p64/b_p64
            return "pipeline", 64 << 10
        if n < 3.2118e-6 * msg + 8.7936:   # a_p16/b_p16
            return "pipeline", 16 << 10
        return "pipeline", 8 << 10

    def bcast(self, comm, x, root: int):
        alg, segbytes = self._pick_bcast(x)
        n = comm.size
        # floor at one element: a misconfigured segment size of 0
        # must degrade to per-element streaming, not a negative-pad
        # reshape crash inside the kernel
        seg_elems = max(1, segbytes // x.dtype.itemsize) \
            if hasattr(x, "dtype") else 1
        bodies = {
            "binomial": lambda xb: spmd.bcast_binomial(xb, AXIS, n, root),
            "binary_tree": lambda xb: spmd.bcast_binary_tree(
                xb, AXIS, n, root),
            "chain": lambda xb: spmd.bcast_chain(xb, AXIS, n, root),
            "pipeline": lambda xb: spmd.bcast_pipeline(
                xb, AXIS, n, root, seg_elems),
            "masked_psum": lambda xb: spmd.bcast_masked_psum(
                xb, xb.dtype, AXIS, root),
        }
        if alg == "binomial" and hasattr(x, "dtype"):
            # segmented binomial bcast (coll/pipeline.py): trivially
            # bitwise-equal (no reduction); segments double-buffer
            # down the tree
            msg = _per_rank_bytes(x)
            nseg = pipeline.segment_count("bcast", n, msg)
            if nseg > 1:
                return pipeline.run_pipelined(
                    comm, ("tuned", "bcast", "binomial", root),
                    lambda xb: pipeline.bcast_binomial_pipelined(
                        xb, AXIS, n, root, nseg),
                    x, nseg=nseg, nbytes=msg, opname="bcast",
                )
        # the segment size is baked into the compiled pipeline
        key = ("tuned", "bcast", alg, root) + (
            (seg_elems,) if alg == "pipeline" else ()
        )
        return run_sharded(comm, key, bodies[alg], x)

    def _pick_reduce(self, x, op: Op) -> str:
        """coll_tuned_decision_fixed.c reduce_intra_dec_fixed:
        noncommutative -> linear when small (< 12 ranks and < 2 kB)
        else in_order_binary; commutative -> linear for tiny
        (< 8 ranks, < 512 B), binomial otherwise (the reference's
        segmented binomial/pipeline picks keep binomial's structure —
        segmentation is the compiler's scheduling domain here)."""
        forced = mca_var.get("coll_tuned_reduce_algorithm", "auto")
        if forced != "auto":
            return forced
        n = self.comm.size
        msg = _per_rank_bytes(x)
        dyn = dynamic_rules.lookup("reduce", n, msg)
        if dyn is not None:
            if not op.commutative and dyn == "binomial":
                dyn = "in_order_binary"  # rule may not break order
            return dyn
        if not op.commutative:
            if n < 12 and msg < 2048:
                return "linear"
            return "in_order_binary"
        if n < 8 and msg < 512:
            return "linear"
        return "binomial"

    def reduce(self, comm, x, op: Op, root: int):
        if op.is_pair_op:
            return None  # pair ops stay with xla's gather path
        n = comm.size
        alg = self._pick_reduce(x, op)
        if alg == "binomial" and not op.commutative:
            from ..utils.errors import ErrorCode, MPIError

            raise MPIError(
                ErrorCode.ERR_ARG,
                "binomial reduce rotates operand order by root; use "
                "in_order_binary or linear for a noncommutative op",
            )
        op = _resolve_op(op, x)

        def binom(xb):
            red = spmd.reduce_binomial(xb, op, AXIS, n, root)
            rank = lax.axis_index(AXIS)
            return jnp.where(rank == root, red, jnp.zeros_like(red))

        bodies = {
            "binomial": binom,
            "in_order_binary": lambda xb: spmd.reduce_in_order_binary(
                xb, op, AXIS, n, root),
            "linear": lambda xb: spmd.reduce_linear(
                xb, op, AXIS, n, root),
        }
        if alg == "binomial":
            # segmented binomial reduce (coll/pipeline.py): the tree's
            # per-element combine order ignores element position, so
            # the segmented result is bitwise-identical
            msg = _per_rank_bytes(x)
            nseg = pipeline.segment_count("reduce", n, msg)
            if nseg > 1:
                def pipe_binom(xb):
                    red = pipeline.reduce_binomial_pipelined(
                        xb, op, AXIS, n, root, nseg)
                    rank = lax.axis_index(AXIS)
                    return jnp.where(rank == root, red,
                                     jnp.zeros_like(red))

                return pipeline.run_pipelined(
                    comm, ("tuned", "reduce", "binomial", op, root),
                    pipe_binom, x, nseg=nseg, nbytes=msg,
                    opname="reduce",
                )
        return run_sharded(comm, ("tuned", "reduce", alg, op, root),
                           bodies[alg], x)

    def _pick_allgather(self, x) -> str:
        """coll_tuned_decision_fixed.c:537-567: total < 50 kB ->
        recursive doubling (power-of-two n) else bruck; larger ->
        ring. (The reference's large/even-n pick, neighbor_exchange,
        maps to ring here — ring's step IS the neighbor pass; its
        n==2 special case, two_procs, is bruck's one round.)"""
        forced = mca_var.get("coll_tuned_allgather_algorithm", "auto")
        if forced != "auto":
            return forced
        n = self.comm.size
        total = _per_rank_bytes(x) * n
        dyn = dynamic_rules.lookup("allgather", n, total)
        if dyn is not None:
            return dyn
        if total < mca_var.get("coll_tuned_allgather_small_total",
                               50_000):
            return "recursive_doubling" if n & (n - 1) == 0 else "bruck"
        return "ring"

    def allgather(self, comm, x):
        alg = self._pick_allgather(x)
        n = comm.size
        if alg not in ALLGATHER_ALGORITHMS or alg == "auto":
            from ..utils.errors import ErrorCode, MPIError

            raise MPIError(
                ErrorCode.ERR_ARG,
                f"unknown allgather algorithm '{alg}' "
                f"(choices: {ALLGATHER_ALGORITHMS})",
            )
        if alg == "recursive_doubling" and n & (n - 1):
            from ..utils.errors import ErrorCode, MPIError

            raise MPIError(
                ErrorCode.ERR_ARG,
                f"recursive_doubling allgather needs power-of-two "
                f"ranks (got {n}); use bruck",
            )

        def flat(fn):
            def body(xb):
                g = fn(xb)
                return g.reshape((-1,) + g.shape[2:])
            return body

        bodies = {
            "ring": flat(lambda xb: spmd.allgather_ring(xb, AXIS, n)),
            "bruck": flat(lambda xb: spmd.allgather_bruck(xb, AXIS, n)),
            "recursive_doubling": flat(
                lambda xb: spmd.allgather_recursive_doubling(xb, AXIS, n)
            ),
            "lax": flat(lambda xb: spmd.allgather_lax(xb, AXIS)),
        }
        return run_sharded(comm, ("tuned", "allgather", alg),
                           bodies[alg], x)

    def reduce_scatter_block(self, comm, x, op: Op):
        n = comm.size
        if not op.commutative:
            return None
        op = _resolve_op(op, x)

        # reduce_scatter_ring blocks the flat per-rank buffer itself
        def body(xb):
            return spmd.reduce_scatter_ring(xb, op, AXIS, n)

        return run_sharded(
            comm, ("tuned", "reduce_scatter_block", op), body, x
        )

    # -- gather / scatter (coll_tuned_{gather,scatter}.c) -----------------
    def _pick_gather(self, x) -> str:
        """coll_tuned_decision_fixed.c:677-734: block > 6000 B ->
        linear (the reference's two linear_SYNC branches — the sync
        round-trip protects an eager receiver from overrun, which a
        compiled SPMD exchange has no analogue of, so both map to
        linear here, documented); n > 60, or n > 10 with block
        < 1024 B -> binomial; else basic linear."""
        forced = mca_var.get("coll_tuned_gather_algorithm", "auto")
        if forced != "auto":
            return forced
        n = self.comm.size
        block = _per_rank_bytes(x)
        dyn = dynamic_rules.lookup("gather", n, block)
        if dyn is not None:
            return dyn
        if block > 6000:
            return "linear"
        if n > 60 or (n > 10 and block < 1024):
            return "binomial"
        return "linear"

    def gather(self, comm, x, root: int):
        alg = self._pick_gather(x)
        n = comm.size
        if alg == "binomial":
            body = lambda xb: spmd.gather_binomial(xb, AXIS, n, root)
        else:
            body = lambda xb: spmd.gather_linear(xb, AXIS, n, root)
        return run_sharded(comm, ("tuned", "gather", alg, root), body, x)

    def _pick_scatter(self, x) -> str:
        """coll_tuned_decision_fixed.c:744-770: n > 10 with block
        < 300 B -> binomial; else basic linear. Block size is the
        per-destination chunk of root's buffer."""
        forced = mca_var.get("coll_tuned_scatter_algorithm", "auto")
        if forced != "auto":
            return forced
        n = self.comm.size
        block = _per_rank_bytes(x) // max(1, n)
        dyn = dynamic_rules.lookup("scatter", n, block)
        if dyn is not None:
            return dyn
        return "binomial" if (n > 10 and block < 300) else "linear"

    def scatter(self, comm, x, root: int):
        n = comm.size
        alg = self._pick_scatter(x)
        if alg == "binomial":
            body = lambda xb: spmd.scatter_binomial(xb, AXIS, n, root)
        else:
            body = lambda xb: spmd.scatter_linear(xb, AXIS, n, root)
        return run_sharded(comm, ("tuned", "scatter", alg, root),
                           body, x)

    def _pick_alltoall(self, x) -> str:
        """coll_tuned_decision_fixed.c:124-133: per-destination block
        < 200 B at n > 12 -> bruck; block < 3000 B -> basic_linear;
        else pairwise."""
        forced = mca_var.get("coll_tuned_alltoall_algorithm", "auto")
        if forced != "auto":
            return forced
        n = self.comm.size
        block = _per_rank_bytes(x) // max(1, n)
        dyn = dynamic_rules.lookup("alltoall", n, block)
        if dyn is not None:
            return dyn
        if block < 200 and n > 12:
            return "bruck"
        if block < 3000:
            return "basic_linear"
        return "pairwise"

    def alltoall(self, comm, x):
        alg = self._pick_alltoall(x)
        if alg not in ALLTOALL_ALGORITHMS:
            from ..utils.errors import ErrorCode, MPIError

            raise MPIError(
                ErrorCode.ERR_ARG,
                f"unknown alltoall algorithm '{alg}' "
                f"(choices: {ALLTOALL_ALGORITHMS})",
            )
        n = comm.size
        fn = {
            "lax": spmd.alltoall_lax,
            "basic_linear": spmd.alltoall_lax,  # one-shot posted set
            "bruck": spmd.alltoall_bruck,
            "pairwise": spmd.alltoall_pairwise,
        }[alg]

        def body(xb):
            blocks = xb.reshape((n, -1) + xb.shape[1:])
            return fn(blocks, AXIS, n).reshape(xb.shape)

        return run_sharded(comm, ("tuned", "alltoall", alg), body, x)

    def scan(self, comm, x, op: Op):
        if op.is_pair_op:
            return None  # pair scans stay with xla's gather path
        n = comm.size
        return run_sharded(
            comm, ("tuned", "scan", op),
            lambda xb: spmd.scan_recursive_doubling(xb, op, AXIS, n), x,
        )

    def exscan(self, comm, x, op: Op):
        if op.is_pair_op:
            return None  # pair scans stay with xla's gather path
        n = comm.size
        return run_sharded(
            comm, ("tuned", "exscan", op),
            lambda xb: spmd.scan_recursive_doubling(
                xb, op, AXIS, n, exclusive=True
            ), x,
        )

    def barrier(self, comm):
        out = run_sharded(
            comm, ("tuned", "barrier"),
            lambda xb: spmd.barrier_psum(AXIS) + xb,
            jnp.zeros((comm.size,), jnp.int32),
        )
        jax.block_until_ready(out)

    # -- v-variants: tuned's hand schedules on the padded kernels ----------
    def alltoallv(self, comm, sendbufs, sendcounts):
        from . import vcoll

        return vcoll.alltoallv(comm, sendbufs, sendcounts,
                               kernel="pairwise")

    def allgatherv(self, comm, sendbufs):
        from . import vcoll

        return vcoll.allgatherv(comm, sendbufs, kernel="ring")

    def gatherv(self, comm, sendbufs, root: int):
        from . import vcoll

        return vcoll.gatherv(comm, sendbufs, root, kernel="ring")

    def scatterv(self, comm, sendbuf, counts, root: int):
        from . import vcoll

        return vcoll.scatterv(comm, sendbuf, counts, root)

    def reduce_scatter(self, comm, x, recvcounts, op: Op):
        if not op.commutative or op.identity is None:
            return None  # xla's allreduce+slice path handles these
        from . import vcoll

        return vcoll.reduce_scatter(comm, x, recvcounts, op, kernel="ring")


class TunedCollComponent(mca_component.Component):
    NAME = "tuned"
    PRIORITY = 50

    def register_vars(self) -> None:
        mca_var.register(
            "coll_tuned_allreduce_algorithm", "enum", "auto",
            "Force a specific allreduce algorithm",
            choices=ALLREDUCE_ALGORITHMS,
        )
        mca_var.register(
            "coll_tuned_bcast_algorithm", "enum", "auto",
            "Force a specific bcast algorithm", choices=BCAST_ALGORITHMS,
        )
        mca_var.register(
            "coll_tuned_allgather_algorithm", "enum", "auto",
            "Force a specific allgather algorithm",
            choices=ALLGATHER_ALGORITHMS,
        )
        mca_var.register(
            "coll_tuned_alltoall_algorithm", "enum", "auto",
            "Force a specific alltoall algorithm",
            choices=ALLTOALL_ALGORITHMS,
        )
        mca_var.register(
            "coll_tuned_small_message", "size", 10000,
            "Below this many bytes per rank, allreduce uses recursive "
            "doubling (coll_tuned_decision_fixed.c:51)",
        )
        mca_var.register(
            "coll_tuned_segment_size", "size", 1 << 20,
            "Ring segment size (coll_tuned_decision_fixed.c:71)",
        )
        mca_var.register(
            "coll_tuned_reduce_algorithm", "enum", "auto",
            "Force a specific reduce algorithm",
            choices=REDUCE_ALGORITHMS,
        )
        mca_var.register(
            "coll_tuned_bcast_segment_size", "size", 128 << 10,
            "Segment size for a FORCED pipeline bcast (auto mode uses "
            "the reference's regression-picked 8-128 KiB)",
        )
        mca_var.register(
            "coll_tuned_gather_algorithm", "enum", "auto",
            "Force a specific gather algorithm",
            choices=GATHER_ALGORITHMS,
        )
        mca_var.register(
            "coll_tuned_scatter_algorithm", "enum", "auto",
            "Force a specific scatter algorithm",
            choices=SCATTER_ALGORITHMS,
        )
        mca_var.register(
            "coll_tuned_allgather_small_total", "size", 50_000,
            "Below this many TOTAL bytes, allgather uses recursive "
            "doubling (power-of-two ranks) or bruck "
            "(coll_tuned_decision_fixed.c:544-559)",
        )
        mca_var.register(
            "coll_tuned_use_dynamic_rules", "bool", False,
            "Consult the dynamic rule file between operator forcing "
            "and the fixed decision constants "
            "(coll_tuned_dynamic_file.c)",
        )
        mca_var.register(
            "coll_tuned_dynamic_rules_filename", "str", "",
            "Rule file: 'collective min_comm_size min_msg_bytes "
            "algorithm' lines, last match wins (see "
            "coll/dynamic_rules.py)",
        )

    def query(self, ctx=None):
        if ctx is None:
            return (self.priority, self)
        if getattr(ctx, "spans_processes", False):
            return None  # cross-process comms belong to coll/hier
        return (self.priority, _TunedModule(ctx))


# ---------------------------------------------------------------------------
# basic component — linear/log reference algorithms (always correct)
# ---------------------------------------------------------------------------

class _BasicModule:
    """Linear algorithms (``ompi/mca/coll/basic``): the correctness
    yardstick. (tuned's reduce also handles non-commutative ops now,
    via in_order_binary/linear — this module remains the
    always-correct fallback, not the only safe path.)"""

    def __init__(self, comm) -> None:
        self.comm = comm

    def fns(self) -> Dict[str, Callable]:
        return {
            "allreduce": self.allreduce,
            "reduce": self.reduce,
            "scatter": self.scatter,
            "gather": self.gather,
        }

    def allreduce(self, comm, x, op: Op):
        if op.is_pair_op:
            return None
        n = comm.size
        op = _resolve_op(op, x)
        return run_sharded(
            comm, ("basic", "allreduce", op),
            lambda xb: spmd.allreduce_basic_linear(xb, op, AXIS, n), x,
        )

    def reduce(self, comm, x, op: Op, root: int):
        n = comm.size
        op = _resolve_op(op, x)

        def body(xb):
            red = spmd.allreduce_basic_linear(xb, op, AXIS, n)
            rank = lax.axis_index(AXIS)
            return jnp.where(rank == root, red, jnp.zeros_like(red))

        return run_sharded(comm, ("basic", "reduce", op, root), body, x)

    def scatter(self, comm, x, root: int):
        n = comm.size

        def body(xb):
            full = spmd.bcast_masked_psum(xb, xb.dtype, AXIS, root)
            chunks = full.reshape((n, -1) + full.shape[1:])
            rank = lax.axis_index(AXIS)
            return jnp.take(chunks, rank, axis=0)

        return run_sharded(comm, ("basic", "scatter", root), body, x)

    def gather(self, comm, x, root: int):
        def body(xb):
            g = lax.all_gather(xb, AXIS, axis=0)
            g = g.reshape((-1,) + g.shape[2:])
            rank = lax.axis_index(AXIS)
            return jnp.where(rank == root, g, jnp.zeros_like(g))

        return run_sharded(comm, ("basic", "gather", root), body, x)


class BasicCollComponent(mca_component.Component):
    NAME = "basic"
    PRIORITY = 10

    def query(self, ctx=None):
        if ctx is None:
            return (self.priority, self)
        if getattr(ctx, "spans_processes", False):
            return None  # cross-process comms belong to coll/hier
        return (self.priority, _BasicModule(ctx))


# ---------------------------------------------------------------------------
# self component — size-1 communicators never touch the mesh
# ---------------------------------------------------------------------------

class _SelfModule:
    def __init__(self, comm) -> None:
        self.comm = comm

    def fns(self) -> Dict[str, Callable]:
        import numpy as _np

        def identity(comm, x, *a, **k):
            return jnp.asarray(x)

        def allreduce(comm, x, op):
            return jnp.asarray(x)

        return {
            "allreduce": allreduce,
            "reduce": lambda comm, x, op, root: jnp.asarray(x),
            "bcast": lambda comm, x, root: jnp.asarray(x),
            "allgather": identity,
            "gather": lambda comm, x, root: jnp.asarray(x),
            "scatter": lambda comm, x, root: jnp.asarray(x),
            "reduce_scatter_block": lambda comm, x, op: jnp.asarray(x),
            "alltoall": identity,
            "scan": lambda comm, x, op: jnp.asarray(x),
            "exscan": lambda comm, x, op: jnp.zeros_like(jnp.asarray(x)),
            "barrier": lambda comm: None,
            # v-variants on one rank: local identities, but with the
            # SAME validation + 1-D flattening contract as the vcoll
            # path so callers see identical shapes on any comm size
            "alltoallv": self._alltoallv,
            "allgatherv": self._allgatherv,
            "gatherv": lambda comm, bufs, root: self._allgatherv(comm, bufs),
            "scatterv": self._scatterv,
            "reduce_scatter": self._reduce_scatter,
        }

    @staticmethod
    def _alltoallv(comm, bufs, counts):
        from . import vcoll

        b = vcoll._as_1d_arrays(bufs, 1, "alltoallv")
        c = vcoll._counts_matrix(counts, 1)
        if b[0].shape[0] != int(c[0, 0]):
            from ..utils.errors import ErrorCode, MPIError

            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"alltoallv buffer has {b[0].shape[0]} elements, count "
                f"is {int(c[0, 0])}",
            )
        return [jnp.asarray(b[0])]

    @staticmethod
    def _allgatherv(comm, bufs):
        from . import vcoll

        return jnp.asarray(vcoll._as_1d_arrays(bufs, 1, "allgatherv")[0])

    @staticmethod
    def _scatterv(comm, buf, counts, root):
        import numpy as _np

        from ..utils.errors import ErrorCode, MPIError

        if root != 0:
            raise MPIError(ErrorCode.ERR_ROOT, f"bad root {root}")
        flat = _np.asarray(buf).reshape(-1)
        counts = [int(k) for k in counts]
        if len(counts) != 1 or flat.shape[0] != counts[0]:
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"scatterv needs 1 count matching the buffer length",
            )
        return [jnp.asarray(flat)]

    @staticmethod
    def _reduce_scatter(comm, x, counts, op):
        import numpy as _np

        from ..utils.errors import ErrorCode, MPIError

        flat = _np.asarray(x).reshape(-1)
        counts = [int(k) for k in counts]
        if len(counts) != 1 or flat.shape[0] != counts[0]:
            raise MPIError(
                ErrorCode.ERR_COUNT,
                "reduce_scatter on a self comm needs x of shape "
                "(1, recvcounts[0])",
            )
        return [jnp.asarray(flat)]


class SelfCollComponent(mca_component.Component):
    NAME = "self"
    PRIORITY = 0

    def query(self, ctx=None):
        if ctx is None:
            return (self.priority, self)
        if getattr(ctx, "spans_processes", False):
            return None  # a size-1 spanning comm has no local member
        if ctx.size == 1:
            return (1000, _SelfModule(ctx))  # claim size-1 comms outright
        return None


# ---------------------------------------------------------------------------
# ml component — hierarchical two-level collectives (ml/bcol/sbgp)
# ---------------------------------------------------------------------------

def _discover_hierarchy(comm) -> Optional[tuple]:
    """sbgp-style subgroup discovery: split the comm's ranks into fast
    domains (same host process / slice — ``ompi/mca/sbgp`` socket/UMA
    grouping). Returns (inter, intra) when ranks form equal-size
    contiguous groups, else None. The ``coll_ml_local_size`` variable
    overrides discovery (for CI, where every virtual device shares one
    process)."""
    forced = int(mca_var.get("coll_ml_local_size", 0))
    n = comm.size
    if forced > 1:
        return (n // forced, forced) if n % forced == 0 else None
    eps = {e.rank: e for e in comm.runtime.endpoints}
    keys = []
    for i in range(n):
        e = eps.get(comm.group.world_rank(i))
        if e is None:
            return None
        keys.append((e.process_index, e.slice_index))
    groups: Dict[tuple, list] = {}
    for i, k in enumerate(keys):
        groups.setdefault(k, []).append(i)
    sizes = {len(v) for v in groups.values()}
    if len(groups) < 2 or len(sizes) != 1:
        return None
    intra = sizes.pop()
    if intra < 2:
        return None
    # groups must be contiguous rank blocks for the 2-D factorization
    for members in groups.values():
        if members != list(range(members[0], members[0] + intra)):
            return None
    return (len(groups), intra)


class _MlModule:
    """Two-level algorithms over the (node, local) decomposition."""

    def __init__(self, comm, inter: int, intra: int) -> None:
        self.comm = comm
        self.inter = inter
        self.intra = intra

    def fns(self) -> Dict[str, Callable]:
        return {
            "allreduce": self.allreduce,
            "reduce": self.reduce,
            "bcast": self.bcast,
            "allgather": self.allgather,
            "reduce_scatter_block": self.reduce_scatter_block,
            "alltoall": self.alltoall,
            "barrier": self.barrier,
        }

    def _reducible(self, op: Op) -> bool:
        return not (op.is_pair_op or op.identity is None
                    or not op.commutative)

    def allreduce(self, comm, x, op: Op):
        if not self._reducible(op):
            return None  # defer to lower-priority providers
        from .driver import run_sharded2d

        op = _resolve_op(op, x)
        body = lambda xb: spmd.allreduce_two_level(
            xb, op, "local", "node", self.intra
        )
        return run_sharded2d(
            comm, ("ml", "allreduce", op, self.inter, self.intra),
            body, x, inter=self.inter, intra=self.intra,
        )

    def reduce(self, comm, x, op: Op, root: int):
        if not self._reducible(op):
            return None
        from .driver import run_sharded2d

        op = _resolve_op(op, x)
        body = lambda xb: spmd.reduce_two_level(
            xb, op, "local", "node", root, self.intra
        )
        return run_sharded2d(
            comm, ("ml", "reduce", op, root, self.inter, self.intra),
            body, x, inter=self.inter, intra=self.intra,
        )

    def allgather(self, comm, x):
        from .driver import run_sharded2d

        def body(xb):
            g = spmd.allgather_two_level(xb, "local", "node")
            return g.reshape((-1,) + g.shape[2:])

        return run_sharded2d(
            comm, ("ml", "allgather", self.inter, self.intra),
            body, x, inter=self.inter, intra=self.intra,
        )

    def reduce_scatter_block(self, comm, x, op: Op):
        if not self._reducible(op):
            return None
        from .driver import run_sharded2d

        op = _resolve_op(op, x)
        n = comm.size
        body = lambda xb: spmd.reduce_scatter_two_level(
            xb, op, "local", "node", self.intra, n
        )
        return run_sharded2d(
            comm,
            ("ml", "reduce_scatter_block", op, self.inter,
             self.intra),
            body, x, inter=self.inter, intra=self.intra,
        )

    def alltoall(self, comm, x):
        from .driver import run_sharded2d

        n = comm.size

        def body(xb):
            blocks = xb.reshape((n, -1) + xb.shape[1:])
            out = spmd.alltoall_two_level(
                blocks, "local", "node", self.intra, self.inter
            )
            return out.reshape(xb.shape)

        return run_sharded2d(
            comm, ("ml", "alltoall", self.inter, self.intra),
            body, x, inter=self.inter, intra=self.intra,
        )

    def bcast(self, comm, x, root: int):
        from .driver import run_sharded2d

        body = lambda xb: spmd.bcast_two_level(
            xb, "local", "node", root, self.intra
        )
        return run_sharded2d(
            comm, ("ml", "bcast", root, self.inter, self.intra),
            body, x, inter=self.inter, intra=self.intra,
        )

    def barrier(self, comm):
        from .driver import run_sharded2d

        out = run_sharded2d(
            comm, ("ml", "barrier", self.inter, self.intra),
            lambda xb: spmd.barrier_psum("local")
            + spmd.barrier_psum("node") + xb,
            jnp.zeros((comm.size,), jnp.int32),
            inter=self.inter, intra=self.intra,
        )
        jax.block_until_ready(out)


class MlCollComponent(mca_component.Component):
    """Hierarchical collectives; wins only when selected (coll=ml) or
    its priority is raised, and declines comms with no hierarchy."""

    NAME = "ml"
    PRIORITY = 40

    def register_vars(self) -> None:
        mca_var.register(
            "coll_ml_local_size", "int", 0,
            "Force the fast-domain (intra) size for hierarchical "
            "collectives; 0 = discover from endpoint process/slice ids",
        )

    def query(self, ctx=None):
        if ctx is None:
            return (self.priority, self)
        if getattr(ctx, "spans_processes", False):
            return None  # cross-process comms belong to coll/hier
        h = _discover_hierarchy(ctx)
        if h is None:
            return None
        return (self.priority, _MlModule(ctx, *h))


from .hier import HierCollComponent  # noqa: E402  (registration order)

COLL_FRAMEWORK.register(XlaCollComponent())
COLL_FRAMEWORK.register(TunedCollComponent())
COLL_FRAMEWORK.register(MlCollComponent())
COLL_FRAMEWORK.register(BasicCollComponent())
COLL_FRAMEWORK.register(SelfCollComponent())
COLL_FRAMEWORK.register(HierCollComponent())
