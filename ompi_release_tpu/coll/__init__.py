"""Collectives: SPMD kernels, host driver, framework + components."""

from . import spmd
from .base import COLL_FRAMEWORK, OP_NAMES, comm_select

__all__ = ["spmd", "COLL_FRAMEWORK", "OP_NAMES", "comm_select"]
