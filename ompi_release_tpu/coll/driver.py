"""Host-side collective driver: persistent compiled programs per comm.

Wraps the SPMD kernels (``coll/spmd.py``) into MPI-semantic host calls:
inputs/outputs carry a leading ``size`` axis (slice i = rank i's
buffer). Each (comm, operation, algorithm) pair gets ONE persistent
jitted ``shard_map`` program, cached on the communicator — re-invoking
with the same shapes never retraces (the "no per-call retrace"
requirement from SURVEY §6's north star).
"""

from __future__ import annotations

import threading
import time as _time
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..utils import jaxcompat as _jaxcompat

_jaxcompat.install()  # jax.shard_map on 0.4.x jaxlibs

from .. import obs as _obs
from ..mca import pvar
from ..obs import skew as _skew

_invoke_count = pvar.counter(
    "coll_invocations", "host-driver collective invocations"
)
_compile_count = pvar.counter(
    "coll_programs_compiled", "distinct compiled collective programs"
)
# per-invocation plan-cache outcome: observe(1) on a cache hit,
# observe(0) on a compile — so sum/count IS the hit ratio
# (coll_programs_compiled vs coll_invocations, as one AGGREGATE)
_plan_cache = pvar.aggregate(
    "coll_plan_cache_hits",
    "plan-cache outcome per driver invocation (1=hit, 0=compile); "
    "sum/count = hit ratio",
)
#: Python time on the collective DISPATCH path — everything between a
#: collective's dispatch entry and the moment the compiled program (or
#: the wire transport) takes over: decision logic, plan/cache lookups,
#: validation, schedule posting. THE witness for the interpreted-vs-
#: compiled steady-state claim (bench.py ``steady_state``): the delta
#: of this timer across a run isolates orchestration from device/wire
#: time. Two clock reads per dispatch — measurement, not policy.
_orch = pvar.timer(
    "coll_orchestration_seconds",
    "Python orchestration seconds on the collective dispatch path "
    "(decision, planning, validation, posting — before the compiled "
    "program or wire transport takes over)",
)

#: capture/attribution state for :mod:`coll.plan` (the compiled
#: whole-schedule layer): ``entries`` records each program dispatch
#: (prog handle, input object, output object) while a capture is
#: active; ``t0`` re-bases the orchestration timer at the OUTER
#: dispatch entry so interpreted and compiled fires time the same span.
_capture_tls = threading.local()


def begin_capture() -> list:
    """Arm program-dispatch capture on this thread; returns the live
    entry list (one dict per ``run_sharded`` program launch)."""
    entries: list = []
    _capture_tls.entries = entries
    return entries


def end_capture() -> None:
    _capture_tls.entries = None


def orch_mark(t0: float) -> None:
    """Re-base the next ``run_sharded`` orchestration interval at
    ``t0`` (the outer dispatch entry), so the timer covers the
    component decision path too, not just the driver prologue."""
    _capture_tls.t0 = t0


def orch_clear() -> None:
    _capture_tls.t0 = None


def orch_add(dt: float) -> None:
    """Credit ``dt`` seconds of Python orchestration directly. The
    wire-replay adapters (PlannedXchg's per-round Python loop, the
    native executor's ctypes entry/exit + pool copies) run BETWEEN
    driver dispatches, where the ``run_sharded`` interval can't see
    them — they self-report here so ``coll_orchestration_seconds``
    keeps meaning "Python time before the compiled program or wire
    transport takes over" on every leg of the steady state."""
    if dt > 0.0:
        _orch.add(dt)


def _orch_t0(default: float) -> float:
    t0 = getattr(_capture_tls, "t0", None)
    if t0 is None:
        return default
    _capture_tls.t0 = None  # one-shot: consumed by this dispatch
    return t0


def _op_name(key: Tuple) -> str:
    """Collective-op label from a program-cache key — keys are
    (component, op, ...) tuples by convention throughout coll/."""
    if isinstance(key, tuple) and len(key) > 1 and isinstance(key[1], str):
        return key[1]
    return str(key[0]) if isinstance(key, tuple) and key else str(key)


def _arr_nbytes(x) -> int:
    try:
        return int(x.size) * int(x.dtype.itemsize)
    except (AttributeError, TypeError):
        return 0


def _program_cache(comm) -> Dict[Tuple, Callable]:
    cache = getattr(comm, "_coll_programs", None)
    if cache is None:
        cache = {}
        comm._coll_programs = cache
    return cache


def run_sharded2d(comm, key: Tuple, body: Callable, x, *,
                  inter: int, intra: int) -> Any:
    """Like run_sharded but over a 2-D (node, local) factorization of
    the comm's ranks: rank r = node r//intra, local r%intra (the sbgp
    subgrouping). Used by hierarchical (ml) algorithms."""
    import numpy as _np
    from jax.sharding import Mesh

    t_in = _time.perf_counter()
    _invoke_count.add()
    tok = (_skew.begin(_op_name(key), getattr(comm, "cid", -1))
           if _obs.enabled else None)
    if x.shape[0] != comm.size or inter * intra != comm.size:
        from ..utils.errors import ErrorCode, MPIError

        raise MPIError(
            ErrorCode.ERR_COUNT,
            f"2-D driver needs leading axis == size ({comm.size}) and "
            f"inter*intra == size (got {inter}x{intra})",
        )
    cache = _program_cache(comm)
    prog = cache.get(key)
    _plan_cache.observe(0.0 if prog is None else 1.0)
    if prog is None:
        _compile_count.add()
        devs = _np.asarray(
            list(comm.submesh.devices.reshape(-1)), dtype=object
        ).reshape(inter, intra)
        mesh2d = Mesh(devs, ("node", "local"))

        def wrapper(xb):
            return body(xb[0])[None]

        prog = jax.jit(
            jax.shard_map(
                wrapper, mesh=mesh2d,
                in_specs=P(("node", "local")),
                out_specs=P(("node", "local")),
            )
        )
        cache[key] = prog
    _orch.add(_time.perf_counter() - t_in)
    if tok is None:
        return prog(jnp.asarray(x))
    _skew.body(tok)
    out = prog(jnp.asarray(x))
    _skew.end(tok, _arr_nbytes(x))
    return out


def _local_rank_count(comm) -> int:
    """Ranks of this comm whose device is addressable by THIS
    controller (jax.distributed multi-controller SPMD mode)."""
    pidx = jax.process_index()
    return sum(
        1 for d in comm.submesh.devices.reshape(-1)
        if int(getattr(d, "process_index", 0)) == pidx
    )


def run_sharded_spmd(comm, key: Tuple, body: Callable, local_x) -> Any:
    """Multi-controller SPMD mode (``jax.distributed``): every
    controller passes only ITS ranks' leading-axis slices; the global
    array is assembled from the per-process shards, ONE compiled
    program runs SPMD across all controllers (XLA's cross-host
    collectives ride ICI/DCN), and each controller receives its local
    shard of the result back. This is the collective path the
    single-controller driver cannot provide under ``jax.distributed``
    — the leading-rank-axis array never materializes on one host."""
    import numpy as _np
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as _P

    t_in = _time.perf_counter()
    _invoke_count.add()
    tok = (_skew.begin(_op_name(key), getattr(comm, "cid", -1))
           if _obs.enabled else None)
    mesh = comm.submesh
    sharding = NamedSharding(mesh, _P("rank"))
    local_x = _np.asarray(local_x)
    global_shape = (comm.size,) + local_x.shape[1:]
    garr = jax.make_array_from_process_local_data(
        sharding, local_x, global_shape
    )
    cache = _program_cache(comm)
    prog = cache.get(key)
    _plan_cache.observe(0.0 if prog is None else 1.0)
    if prog is None:
        _compile_count.add()

        def wrapper(xb):
            out = body(xb[0])
            return jax.tree.map(lambda a: a[None], out)

        prog = jax.jit(
            jax.shard_map(wrapper, mesh=mesh, in_specs=P("rank"),
                          out_specs=P("rank"))
        )
        cache[key] = prog
    _orch.add(_time.perf_counter() - t_in)
    if tok is not None:
        _skew.body(tok)
    out = prog(garr)
    if tok is not None:
        _skew.end(tok, _arr_nbytes(local_x))

    def to_local(a):
        shards = sorted(a.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        return _np.concatenate([_np.asarray(s.data) for s in shards],
                               axis=0)

    return jax.tree.map(to_local, out)


def _check_no_narrowing(arr) -> None:
    """MPI_DOUBLE is not MPI_FLOAT: with jax_enable_x64 off (the JAX
    default), ``jnp.asarray`` silently narrows 64-bit host buffers to
    32 bits — a reduction over them would return plausible-but-wrong
    values. Refuse loudly; with x64 enabled the widths pass through
    and this is a no-op."""
    dt = getattr(arr, "dtype", None)
    if dt is None:
        return
    try:
        jt = jax.dtypes.canonicalize_dtype(dt)  # pure metadata, no
    except TypeError:                           # dispatch on the hot path
        return  # non-canonicalizable dtypes fail later with their own error
    if np.dtype(jt).itemsize < np.dtype(dt).itemsize:
        from ..utils.errors import ErrorCode, MPIError

        raise MPIError(
            ErrorCode.ERR_TYPE,
            f"{np.dtype(dt).name} buffer would be silently narrowed "
            f"to {np.dtype(jt).name} (jax_enable_x64 is off) — enable "
            "x64 (jax.config.update('jax_enable_x64', True)) or cast "
            "the buffer explicitly",
        )


def run_sharded(comm, key: Tuple, body: Callable, x, *,
                extra_arrays: Tuple = ()) -> Any:
    """Run ``body(block, *extra_blocks)`` under shard_map over the comm's
    1-D ``rank`` axis. ``x`` has leading axis == comm.size; every extra
    array is sharded the same way. Result keeps the leading rank axis.

    Under a ``jax.distributed`` multi-controller runtime, a buffer
    whose leading axis matches this controller's LOCAL rank count is
    dispatched through :func:`run_sharded_spmd` (per-process shards in,
    per-process shards out) — the single-controller convention cannot
    apply there because no controller holds every rank's slice.
    """
    t_in = _orch_t0(_time.perf_counter())
    _invoke_count.add()
    tok = (_skew.begin(_op_name(key), getattr(comm, "cid", -1))
           if _obs.enabled else None)
    if getattr(comm, "spans_processes", False):
        from ..utils.errors import ErrorCode, MPIError

        # the submesh covers only LOCAL members on a spanning comm:
        # compiling over it with comm.size rows would silently place
        # remote ranks' slices on local devices (wrong results, no
        # error). Everything with a cross-process implementation
        # dispatches through coll/hier or the wire — reaching this
        # compiled in-process path is a capability boundary.
        raise MPIError(
            ErrorCode.ERR_NOT_AVAILABLE,
            f"compiled in-process collective invoked on {comm.name}, "
            "which spans controller processes — this operation has no "
            "cross-process implementation; run it on a process-local "
            "sub-communicator (split_type_shared)",
        )
    if not hasattr(x, "shape"):
        from ..utils.errors import ErrorCode, MPIError

        raise MPIError(
            ErrorCode.ERR_TYPE,
            "driver-mode collectives take a single array with a leading "
            "rank axis; pair-op (value, index) tuples are supported by "
            "allreduce/reduce/reduce_scatter_block/scan/exscan "
            "(MINLOC/MAXLOC)",
        )
    if x.shape[0] != comm.size:
        from ..utils.errors import ErrorCode, MPIError

        if (jax.process_count() > 1 and not extra_arrays
                and x.shape[0] == _local_rank_count(comm)):
            _invoke_count.add(-1)  # the spmd entry counts this call
            return run_sharded_spmd(comm, key, body, x)
        raise MPIError(
            ErrorCode.ERR_COUNT,
            f"driver-mode buffer leading axis {x.shape[0]} != comm size "
            f"{comm.size} (one slice per rank)",
        )
    for arr in (x,) + tuple(extra_arrays):
        _check_no_narrowing(arr)
    cache = _program_cache(comm)
    prog = cache.get(key)
    _plan_cache.observe(0.0 if prog is None else 1.0)
    if prog is None:
        _compile_count.add()
        mesh = comm.submesh
        n_extra = len(extra_arrays)

        def wrapper(xb, *eb):
            out = body(xb[0], *[e[0] for e in eb])
            return jax.tree.map(lambda a: a[None], out)

        prog = jax.jit(
            jax.shard_map(
                wrapper,
                mesh=mesh,
                in_specs=tuple([P("rank")] * (1 + n_extra)),
                out_specs=P("rank"),
            )
        )
        cache[key] = prog
    cap = getattr(_capture_tls, "entries", None)
    if cap is not None:
        # coll/plan capture: record the program handle plus the exact
        # input/output OBJECTS — identity against the collective's own
        # argument and return value proves the dispatch was pre/post-
        # processing-free, i.e. safe to re-fire as the program alone
        cap.append({"prog": prog, "x": x, "extra": bool(extra_arrays),
                    "out": None})
    _orch.add(_time.perf_counter() - t_in)
    if tok is None:
        out = prog(jnp.asarray(x),
                   *[jnp.asarray(e) for e in extra_arrays])
    else:
        # skew emit point: wait = arrival -> program launch (cache
        # lookup / compile / validation), body = the dispatch itself
        _skew.body(tok)
        out = prog(jnp.asarray(x), *[jnp.asarray(e) for e in extra_arrays])
        _skew.end(tok, _arr_nbytes(x))
    if cap is not None:
        cap[-1]["out"] = out
    return out
