"""Scheduled inter-process algorithms for the spanning (hier)
collectives — the ``coll/tuned`` algorithm menu recast for the
process-combine step of ``coll/hier.py``.

Every function here is a PURE schedule: it speaks to the wire only
through an exchange adapter (one call per schedule round, posting all
of the round's sends before reaping its receives), so the same code is
driven by the real :class:`~.hier._HierModule` transport in a
``tpurun`` job and by the lockstep in-memory simulator the parity
tests use. Schedules are deterministic functions of
``(procs, me, sizes)`` alone — both sides of every message compute the
identical round plan, which is what keeps the PR-4 trace-context
contract intact (flow ids derive from per-pair message indices that
advance in lockstep) and what lets packed multi-block payloads be
split without shipping any layout metadata.

Algorithm menu (``pick`` resolves forcing > dynamic rules > fixed
decision constants, the tuned precedence):

==========  ==========================================================
allreduce   ``linear`` (all-pairs partial exchange, the historic
            path), ``recursive_doubling`` (doubling-distance Bruck
            allgather of partials + a LOCAL fold in process-index
            order — ceil(log2 P) messages, bitwise-identical to
            linear for every op including non-commutative ones),
            ``ring`` (ring reduce-scatter + ring allgather, ~2n bytes
            per process), ``rabenseifner`` (recursive-halving
            reduce-scatter + recursive-doubling allgather; power-of-
            two process counts, else it degrades to ring),
            ``multiring`` / ``torus2d`` (topology-aware striped /
            2D-torus variants, :mod:`coll.topo_schedules`)
bcast       ``linear``, ``binomial`` (ceil(log2 P)-depth tree),
            ``torus2d`` (host-representative tree, DCN ships d1-1
            copies)
reduce      ``linear`` (direct partial gather to the root's owner),
            ``binomial`` (tree gather of per-process partials; the
            fold happens ONCE at the root in process-index order, so
            both are bitwise-identical to each other and safe for
            non-commutative ops)
allgather   ``linear``, ``bruck`` (log rounds, packed doubling
            payloads), ``ring`` (neighbor-only passes)
alltoall    ``linear``, ``bruck`` (log rounds, store-and-forward),
            ``pairwise`` (P-1 rounds, send to me+k / recv from me-k)
gather      ``linear``, ``binomial``
scatter     ``linear``, ``binomial``
==========  ==========================================================

Reduction-order discipline (the coll/tuned rule): ``ring`` and
``rabenseifner`` fold chunks in rotated/halving order and pad with the
op identity, so they are only ever selected for commutative ops with
an identity; a dynamic rule naming them for anything else is silently
downgraded to ``recursive_doubling`` (a config file cannot waive MPI
semantics), while operator FORCING via ``hier_inter_algorithm`` raises
loudly. Everything else preserves the exact process-index fold order
of the linear path and is bitwise-identical to it.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import obs as _obs
from ..mca import pvar
from ..mca import var as mca_var
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("coll")

#: schedule rounds executed (one exchange call = one round) — the
#: auditable counterpart of the O(P^2) -> O(log P) round-count claim
_sched_rounds = pvar.counter(
    "hier_schedule_rounds",
    "inter-process schedule rounds executed by spanning collectives",
)

#: collective -> algorithms a ``hier_<coll>`` dynamic rule may name
#: (registered into dynamic_rules.RULE_COLLECTIVES by coll/components)
ALGORITHMS: Dict[str, tuple] = {
    "allreduce": ("auto", "linear", "recursive_doubling", "ring",
                  "rabenseifner", "multiring", "torus2d"),
    "bcast": ("auto", "linear", "binomial", "torus2d"),
    "reduce": ("auto", "linear", "binomial"),
    "allgather": ("auto", "linear", "bruck", "ring", "torus2d"),
    "alltoall": ("auto", "linear", "bruck", "pairwise"),
    "gather": ("auto", "linear", "binomial"),
    "scatter": ("auto", "linear", "binomial"),
}

#: allreduce algorithms that reorder the fold and pad with the
#: identity (the topology-aware variants stripe/decompose the buffer,
#: so they inherit the exact same commutative-only guard semantics)
ORDER_WAIVING = ("ring", "rabenseifner", "multiring", "torus2d")


def _register_rule_namespaces() -> None:
    """``hier_<coll>`` dynamic-rule namespaces (min_comm_size matches
    the PROCESS count; min_msg_bytes the inter decision unit — see
    :func:`pick`). Registered here, not in components.py, so a rule
    file naming them parses wherever this module is importable."""
    from . import dynamic_rules

    dynamic_rules.RULE_COLLECTIVES.update({
        f"hier_{coll}": algs for coll, algs in ALGORITHMS.items()
    })


_register_rule_namespaces()


def register_vars() -> None:
    mca_var.register(
        "hier_inter_algorithm", "str", "auto",
        "Force one inter-process schedule for spanning collectives "
        "(hier). Applied to every collective whose menu contains the "
        "name; others keep auto selection. See "
        "coll/hier_schedules.ALGORITHMS for the menus.",
    )
    mca_var.register(
        "hier_small_message", "size", 65536,
        "Inter-message bytes below which latency-bound schedules win "
        "the fixed decision (allreduce recursive_doubling, "
        "reduce/gather/scatter binomial, alltoall bruck)",
    )
    mca_var.register(
        "hier_bruck_cutoff", "size", 262144,
        "Total allgather bytes below which the fixed decision picks "
        "bruck's packed log-round schedule over the linear exchange",
    )
    mca_var.register(
        "hier_leader_tier", "bool", True,
        "Host-aware leader tier for spanning allreduce-combines and "
        "bcast: co-hosted processes combine/fan out over shm first, "
        "one leader per host crosses DCN (coll/ml subgrouping). "
        "Active only when the job spans >1 host with >1 process on "
        "some host; commutative ops only.",
    )
    mca_var.register(
        "hier_topo_schedules", "bool", True,
        "Let the fixed decision constants pick the topology-aware "
        "schedules (2D-torus allreduce/allgather/bcast) when the job "
        "spans a uniform multi-host grid — DCN then carries only the "
        "1/dim0-sized partials. False restores the flat decisions; "
        "forcing and dynamic rules can still name the variants.",
    )
    mca_var.register(
        "hier_multiring_k", "int", 4,
        "Ring count for the multiring striped allreduce (disjoint "
        "stride-coprime neighbor permutations; the effective count is "
        "capped by the units available mod P). Selected via forcing "
        "or a hier_allreduce dynamic rule naming 'multiring'.",
    )


register_vars()  # idempotent; cvars must exist before the first pick


# ---------------------------------------------------------------------------
# selection: forcing > dynamic rules > fixed decision constants
# ---------------------------------------------------------------------------

def _topo_ok(topo: Optional[tuple]) -> bool:
    """A (d0, d1) uniform grid worth exploiting: both dims non-trivial
    and the operator has not opted out."""
    return (topo is not None and int(topo[0]) > 1 and int(topo[1]) > 1
            and bool(mca_var.get("hier_topo_schedules", True)))


def pick(coll: str, nprocs: int, nbytes: int, *,
         commutative: bool = True, has_identity: bool = True,
         pair_op: bool = False,
         topo: Optional[tuple] = None) -> str:
    """The inter algorithm for this call. ``nprocs`` is the PROCESS
    count of the spanning comm (what a ``hier_<coll>`` rule's
    min_comm_size column matches against — the inter step never sees
    ranks), ``nbytes`` the collective's inter decision unit
    (allreduce/reduce/bcast/gather/scatter: one partial/block's bytes;
    allgather: total bytes across processes; alltoall: bytes per
    destination-process block). MINLOC/MAXLOC calls pass ``pair_op``:
    the chunked schedules have no (value, index) variant, so an
    order-waiving pick quietly becomes ``recursive_doubling`` even
    when forced — whereas forcing ring/rabenseifner for a
    NON-COMMUTATIVE op is a semantics violation and raises. ``topo``
    is the comm's uniform (d0, d1) host grid or None: the fixed
    decision prefers the 2D-torus variants when one exists (DCN
    carries 1/d0-sized partials), gated by ``hier_topo_schedules``."""
    from . import dynamic_rules

    menu = ALGORITHMS[coll]
    forced = mca_var.get("hier_inter_algorithm", "auto")
    if forced and forced != "auto":
        if forced in menu:
            if coll == "allreduce" and forced in ORDER_WAIVING:
                if pair_op:
                    _log.verbose(
                        3, f"hier_inter_algorithm={forced}: no pair-op "
                           "variant; recursive_doubling applies")
                    return "recursive_doubling"
                if not (commutative and has_identity):
                    raise MPIError(
                        ErrorCode.ERR_ARG,
                        f"hier_inter_algorithm={forced}: {forced} "
                        "allreduce folds chunks in rotated order and "
                        "pads with the op identity; use "
                        "recursive_doubling or linear for this op",
                    )
            return forced
        _log.verbose(
            3, f"hier_inter_algorithm={forced} has no {coll} variant; "
               f"auto selection applies")
    dyn = dynamic_rules.lookup(f"hier_{coll}", nprocs, nbytes)
    if dyn is not None:
        if coll == "allreduce" and dyn in ORDER_WAIVING \
                and not (commutative and has_identity and not pair_op):
            # same guard as coll/tuned: a rule file cannot waive MPI
            # semantics — downgrade to the exact-order fallback
            dyn = "recursive_doubling"
        return dyn
    # fixed decision constants
    small = int(mca_var.get("hier_small_message", 65536))
    if coll == "allreduce":
        # pair_op checked here too: a user Op CAN carry is_pair_op
        # together with an identity, and the chunked schedules have no
        # (value, index) variant regardless
        if nbytes < small or pair_op \
                or not (commutative and has_identity):
            return "recursive_doubling"
        if _topo_ok(topo):
            return "torus2d"
        return "rabenseifner" if nprocs & (nprocs - 1) == 0 else "ring"
    if coll == "bcast":
        # the torus bcast's DCN cost is d1-1 copies at log-depth for
        # any size, strictly below the flat binomial's host-oblivious
        # edge set — no size threshold needed
        return "torus2d" if _topo_ok(topo) else "binomial"
    if coll in ("reduce", "gather", "scatter"):
        return "binomial" if nbytes < small else "linear"
    if coll == "allgather":
        cutoff = int(mca_var.get("hier_bruck_cutoff", 262144))
        if nbytes < cutoff:
            return "bruck"
        return "torus2d" if _topo_ok(topo) else "linear"
    if coll == "alltoall":
        return "bruck" if nbytes < small else "pairwise"
    return "linear"


# ---------------------------------------------------------------------------
# round plumbing
# ---------------------------------------------------------------------------

def _round(x, sends: Dict[int, List[np.ndarray]],
           recvs: Dict[int, int]) -> Dict[int, List[np.ndarray]]:
    """One schedule round: post every send, reap every receive. The
    adapter owns transport, pvars, flow ids, and the watchdog wait
    registry; this wrapper adds the round counter and (gated) a
    round-granularity span."""
    _sched_rounds.add()
    rec = _obs.enabled
    t0 = _time.perf_counter() if rec else 0.0
    got = x.exchange(sends, recvs)
    if rec and _obs.enabled:
        _obs.record(
            "hier_sched_round", "hier", t0, _time.perf_counter() - t0,
            nbytes=sum(int(np.asarray(a).nbytes)
                       for arrs in sends.values() for a in arrs),
        )
    return got


def _flat(a) -> np.ndarray:
    a = np.asarray(a)
    return np.ascontiguousarray(a).reshape(-1)


def _concat(arrs: Sequence[np.ndarray], dtype) -> np.ndarray:
    arrs = [np.asarray(a).reshape(-1) for a in arrs]
    if not arrs:
        return np.zeros((0,), dtype)
    if len(arrs) == 1:
        return arrs[0]
    return np.concatenate(arrs)


def round_exchange(x, sends: Dict[int, List[np.ndarray]],
                   recvs: Dict[int, int]) -> Dict[int, List[np.ndarray]]:
    """Public round entry for schedule fragments that live OUTSIDE
    this module (the hier leader tier's fan-in/fan-out stages, the
    direct reduce gather): same counter/span accounting as every
    in-module round, so ``hier_schedule_rounds`` reflects every
    participant of every schedule."""
    return _round(x, sends, recvs)


def linear_exchange(x, procs: List[int], me: int,
                    payload) -> Dict[int, np.ndarray]:
    """The historic all-pairs exchange as ONE schedule round: send
    ``payload`` to every peer, receive one message back from each.
    Returns {peer: array}."""
    peers = [p for p in procs if p != me]
    got = _round(x, {p: [payload] for p in peers},
                 {p: 1 for p in peers})
    return {p: np.asarray(got[p][0]) for p in peers}


# ---------------------------------------------------------------------------
# allgather family (also the partial-exchange engine for allreduce's
# recursive_doubling and the row exchange behind scan/exscan)
# ---------------------------------------------------------------------------

def allgather_bruck(x, procs: List[int], me: int, mine,
                    counts: Sequence[int]) -> List[np.ndarray]:
    """Doubling-distance (Bruck) allgather of one flat block per
    process: ceil(log2 P) rounds, ONE packed payload per round (both
    sides derive the block split from ``counts``, indexed by process
    POSITION). Returns the P flat blocks in process-index order."""
    P = len(procs)
    mi = procs.index(me)
    mine = _flat(mine)
    blocks: Dict[int, np.ndarray] = {mi: mine}
    have = 1
    while have < P:
        n = min(have, P - have)
        dst = procs[(mi - have) % P]
        src = procs[(mi + have) % P]
        payload = _concat([blocks[(mi + t) % P] for t in range(n)],
                          mine.dtype)
        got = _flat(_round(x, {dst: [payload]}, {src: 1})[src][0])
        off = 0
        for t in range(n):
            j = (mi + have + t) % P
            c = int(counts[j])
            blocks[j] = got[off:off + c]
            off += c
        have += n
    return [blocks[i] for i in range(P)]


def allgather_ring(x, procs: List[int], me: int,
                   mine) -> List[np.ndarray]:
    """Neighbor-only ring allgather: P-1 rounds, each passing one
    whole block to the next process (shapes ride the wire, so blocks
    may differ in shape). Returns blocks in process-index order."""
    P = len(procs)
    mi = procs.index(me)
    nxt, prv = procs[(mi + 1) % P], procs[(mi - 1) % P]
    blocks: Dict[int, np.ndarray] = {mi: np.asarray(mine)}
    for s in range(P - 1):
        cs = (mi - s) % P
        cr = (mi - s - 1) % P
        got = _round(x, {nxt: [blocks[cs]]}, {prv: 1})[prv][0]
        blocks[cr] = np.asarray(got)
    return [blocks[i] for i in range(P)]


# ---------------------------------------------------------------------------
# allreduce: ring and Rabenseifner (reduce-scatter + allgather)
# ---------------------------------------------------------------------------

def _pad_chunks(mine, P: int, identity) -> tuple:
    flat = _flat(mine)
    L = flat.shape[0]
    per = max(1, -(-L // P))
    if per * P != L:
        flat = np.concatenate(
            [flat, np.full(per * P - L, identity, flat.dtype)])
    elif not flat.flags.writeable:  # jax-backed views are read-only;
        flat = flat.copy()          # rabenseifner accumulates in place
    return flat, L, per


def allreduce_ring(x, procs: List[int], me: int, mine,
                   op: Callable, identity) -> np.ndarray:
    """Ring reduce-scatter + ring allgather: per-process inter bytes
    drop from (P-1)*n to ~2n. Chunk c's fold order is the fixed
    rotation (c, c+1, ..., c-1) — deterministic and identical on every
    process/run, commutative ops only (``pick`` enforces)."""
    P = len(procs)
    mi = procs.index(me)
    flat, L, per = _pad_chunks(mine, P, identity)
    chunks = [flat[j * per:(j + 1) * per].copy() for j in range(P)]
    nxt, prv = procs[(mi + 1) % P], procs[(mi - 1) % P]
    for s in range(P - 1):  # reduce-scatter
        cs = (mi - s) % P
        cr = (mi - s - 1) % P
        got = _round(x, {nxt: [chunks[cs]]}, {prv: 1})[prv][0]
        # operand order is fixed: the travelling accumulator (earlier
        # ring positions) on the left, my partial on the right
        chunks[cr] = np.asarray(op(_flat(got), chunks[cr]))
    for s in range(P - 1):  # allgather of the reduced chunks
        cs = (mi + 1 - s) % P
        cr = (mi - s) % P
        got = _round(x, {nxt: [chunks[cs]]}, {prv: 1})[prv][0]
        chunks[cr] = _flat(got)
    return np.concatenate(chunks)[:L]


def allreduce_rabenseifner(x, procs: List[int], me: int, mine,
                           op: Callable, identity) -> np.ndarray:
    """Recursive-halving reduce-scatter + recursive-doubling
    allgather (Rabenseifner): ~2n bytes in ceil(2 log2 P) rounds.
    Power-of-two process counts only — callers degrade to
    :func:`allreduce_ring` otherwise. The halving fold keeps a fixed
    operand order (lower process positions left), deterministic across
    ranks and runs; commutative ops only."""
    P = len(procs)
    if P & (P - 1):
        return allreduce_ring(x, procs, me, mine, op, identity)
    mi = procs.index(me)
    flat, L, per = _pad_chunks(mine, P, identity)
    lo, hi = 0, P  # chunk-position range I still accumulate
    d = P // 2
    while d >= 1:  # recursive halving reduce-scatter
        partner = procs[mi ^ d]
        half = (hi - lo) // 2
        if mi & d:
            keep, send = (lo + half, hi), (lo, lo + half)
        else:
            keep, send = (lo, lo + half), (lo + half, hi)
        payload = flat[send[0] * per:send[1] * per]
        got = _flat(_round(x, {partner: [payload]},
                           {partner: 1})[partner][0])
        seg = flat[keep[0] * per:keep[1] * per]
        # fixed operand order: the lower-position accumulator left
        merged = op(got, seg) if mi & d else op(seg, got)
        flat[keep[0] * per:keep[1] * per] = np.asarray(merged)
        lo, hi = keep
        d //= 2
    d = 1
    blk = mi  # owned chunk position (== mi: bits selected top-down)
    while d < P:  # recursive doubling allgather
        partner = procs[mi ^ d]
        plo = blk ^ d
        payload = flat[blk * per:(blk + d) * per]
        got = _flat(_round(x, {partner: [payload]},
                           {partner: 1})[partner][0])
        flat[plo * per:(plo + d) * per] = got
        blk = min(blk, plo)
        d *= 2
    return flat[:L]


# ---------------------------------------------------------------------------
# binomial trees: bcast / gather / scatter (vranks relative to root)
# ---------------------------------------------------------------------------

def bcast_binomial(x, procs: List[int], me: int, root: int, val):
    """Binomial-tree bcast: ceil(log2 P) depth, the root sends exactly
    ceil(log2 P) messages (vs P-1 linear). ``val`` is read on the root
    only; every process returns the broadcast array."""
    P = len(procs)
    mi = procs.index(me)
    ri = procs.index(root)
    vr = (mi - ri) % P
    mask = 1
    while mask < P:
        if vr & mask:
            src = procs[((vr - mask) + ri) % P]
            val = _round(x, {}, {src: 1})[src][0]
            break
        mask <<= 1
    val = np.asarray(val)
    mask >>= 1
    sends: Dict[int, List[np.ndarray]] = {}
    while mask > 0:
        if vr + mask < P:
            dst = procs[((vr + mask) + ri) % P]
            sends[dst] = [val]
        mask >>= 1
    if sends:
        _round(x, sends, {})
    return val


def _subtree(vr: int, mask: int, P: int) -> int:
    """Size of the binomial subtree rooted at vrank ``vr`` when it
    reports at distance ``mask`` (contiguous vranks [vr, vr+size))."""
    return min(mask, P - vr)


def gather_binomial(x, procs: List[int], me: int, root: int, mine,
                    counts: Sequence[int]) -> Optional[List[np.ndarray]]:
    """Binomial-tree gather of one flat block per process to the root:
    every non-root sends exactly ONE packed message (its subtree's
    blocks, vrank-ascending), the root receives ceil(log2 P). Returns
    the P flat blocks in process-index order at the root, None
    elsewhere. ``counts`` is indexed by process POSITION."""
    P = len(procs)
    mi = procs.index(me)
    ri = procs.index(root)
    vr = (mi - ri) % P

    def vcount(v: int) -> int:
        return int(counts[(v + ri) % P])

    held: Dict[int, np.ndarray] = {vr: _flat(mine)}
    mask = 1
    while mask < P:
        if vr & mask:
            parent = procs[((vr - mask) + ri) % P]
            payload = _concat([held[v] for v in sorted(held)],
                              held[vr].dtype)
            _round(x, {parent: [payload]}, {})
            return None
        child = vr + mask
        if child < P:
            src = procs[(child + ri) % P]
            got = _flat(_round(x, {}, {src: 1})[src][0])
            off = 0
            for v in range(child, child + _subtree(child, mask, P)):
                c = vcount(v)
                held[v] = got[off:off + c]
                off += c
        mask <<= 1
    return [held[(i - ri) % P] for i in range(P)]


def scatter_binomial(x, procs: List[int], me: int, root: int,
                     chunks: Optional[List[np.ndarray]],
                     weights: Sequence[int],
                     meta: Optional[np.ndarray] = None) -> tuple:
    """Binomial-tree scatter: the root ships each child its whole
    subtree's chunks in one packed message (plus a small ``meta``
    array forwarded verbatim — the caller's shape header, since
    non-roots must not read the buffer); intermediates peel their own
    span and forward. ``chunks`` (root only) and the returned flat
    chunk are indexed by process POSITION; per-position lengths are
    ``weights[i] * unit`` with ``unit`` inferred from the received
    payload — ``weights`` must be positive and identical everywhere.
    Returns ``(my_flat_chunk, meta)``."""
    P = len(procs)
    mi = procs.index(me)
    ri = procs.index(root)
    vr = (mi - ri) % P

    def vweight(v: int) -> int:
        return int(weights[(v + ri) % P])

    held: Dict[int, np.ndarray] = {}
    mask = 1
    if vr == 0:
        meta = np.asarray([] if meta is None else meta, np.int64)
        for v in range(P):
            held[v] = _flat(chunks[(v + ri) % P])
        while mask < P:
            mask <<= 1
    else:
        while mask < P:
            if vr & mask:
                src = procs[((vr - mask) + ri) % P]
                got = _round(x, {}, {src: 2})[src]
                meta = np.asarray(got[0], np.int64)
                flat = _flat(got[1])
                span = list(range(vr, vr + _subtree(vr, mask, P)))
                wsum = sum(vweight(v) for v in span)
                if wsum <= 0 or flat.shape[0] % wsum:
                    raise MPIError(
                        ErrorCode.ERR_TRUNCATE,
                        f"binomial scatter: payload of {flat.shape[0]} "
                        f"elements does not divide across subtree "
                        f"weights {wsum}",
                    )
                unit = flat.shape[0] // wsum
                off = 0
                for v in span:
                    c = vweight(v) * unit
                    held[v] = flat[off:off + c]
                    off += c
                break
            mask <<= 1
    mask >>= 1
    while mask > 0:
        child = vr + mask
        if child < P:
            dst = procs[(child + ri) % P]
            span = range(child, child + _subtree(child, mask, P))
            payload = _concat([held[v] for v in span], held[vr].dtype)
            _round(x, {dst: [meta, payload]}, {})
        mask >>= 1
    return held[vr], meta


# ---------------------------------------------------------------------------
# alltoall: pairwise exchange and Bruck store-and-forward
# ---------------------------------------------------------------------------

def alltoall_pairwise(x, procs: List[int], me: int,
                      payload_for: Dict[int, np.ndarray]
                      ) -> Dict[int, np.ndarray]:
    """P-1 rounds; round k sends my block to procs[mi+k] and receives
    from procs[mi-k] — the coll_tuned pairwise schedule that bounds
    per-round concurrency for large messages. Payloads are the same
    per-peer aggregates the linear path ships."""
    P = len(procs)
    mi = procs.index(me)
    got: Dict[int, np.ndarray] = {}
    for s in range(1, P):
        dst = procs[(mi + s) % P]
        src = procs[(mi - s) % P]
        r = _round(x, {dst: [payload_for[dst]]}, {src: 1})
        got[src] = np.asarray(r[src][0])
    return got


def alltoall_bruck(x, procs: List[int], me: int,
                   mine: List[np.ndarray],
                   pair_counts) -> List[Optional[np.ndarray]]:
    """Bruck alltoall: ceil(log2 P) rounds of store-and-forward, one
    packed payload each. ``mine[j]`` is my flat block destined to
    position j; ``pair_counts[o][j]`` the flat length of the (origin
    o, destination j) block — every process computes the identical
    slot plan from it, so payloads need no framing. Returns received
    flat blocks by SOURCE position (my own position is None: the local
    block never leaves the process)."""
    P = len(procs)
    mi = procs.index(me)
    dtype = np.asarray(mine[(mi + 1) % P] if P > 1 else mine[mi]).dtype
    # slot t holds the block whose (dest - origin) displacement is t;
    # before round k (distance d=2^k) the slot's content at process p
    # originated at p - (t & (d-1)) — both sides derive sizes from that
    slot: Dict[int, np.ndarray] = {
        t: _flat(mine[(mi + t) % P]) for t in range(1, P)
    }
    d = 1
    while d < P:
        ts = [t for t in range(1, P) if t & d]
        dst = procs[(mi + d) % P]
        src = procs[(mi - d) % P]
        payload = _concat([slot[t] for t in ts], dtype)
        got = _flat(_round(x, {dst: [payload]}, {src: 1})[src][0])
        off = 0
        for t in ts:
            o = (mi - d - (t & (d - 1))) % P
            j = (o + t) % P
            c = int(pair_counts[o][j])
            slot[t] = got[off:off + c]
            off += c
        if off != got.shape[0]:
            raise MPIError(
                ErrorCode.ERR_TRUNCATE,
                f"bruck alltoall round d={d}: payload from process "
                f"{src} has {got.shape[0]} elements, the shared count "
                f"plan implies {off} — mismatched counts across "
                "processes?",
            )
        d <<= 1
    out: List[Optional[np.ndarray]] = [None] * P
    for t in range(1, P):
        out[(mi - t) % P] = slot[t]
    return out
