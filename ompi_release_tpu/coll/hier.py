"""coll/hier — collectives for communicators that SPAN controller
processes (the unified COMM_WORLD of ``tpurun -n P``).

Two-level compose, the ``coll/ml`` shape (``ompi/mca/coll/ml`` with
bcol/sbgp subgrouping) re-cast for the TPU runtime:

  intra  this process's members: ONE compiled XLA collective over the
         local submesh (a shadow communicator reuses the whole normal
         coll stack — xla/tuned selection, persistent programs);
  inter  the process-combine step over the wire router — shm segment
         handoffs on one host, chunked DCN staging across hosts
         (``runtime/wire.py``), never a fake device_put.

Driver-mode contract on a spanning communicator: buffers carry one
leading-axis slice per LOCAL member (this process's members of the
comm, in comm-rank order) — the per-process shard of the single-
controller convention. Results keep that local leading axis;
"identical on every rank" results are replicated across it.

Reduction order: local partials use the selected local algorithm's
order; the inter step combines partials in process-index order — the
same fixed-order tree discipline the parity harness pins for the
in-process algorithms.

The inter step is SCHEDULED (:mod:`coll.hier_schedules`): recursive
doubling for small allreduce, ring/Rabenseifner reduce-scatter +
allgather for large allreduce (~2n inter bytes per process instead of
(P-1)*n), binomial trees for bcast/reduce/gather/scatter, Bruck for
small allgather/alltoall with pairwise exchange above the cutoff, and
a ``linear`` all-pairs exchange kept as the baseline (and for the
ragged v-variants, whose sizes are not globally derivable). Selection
follows the tuned precedence — ``hier_inter_algorithm`` forcing >
``hier_<coll>`` dynamic rules (PR-2 machinery; min_comm_size matches
the PROCESS count) > fixed decision constants — and every schedule
combines in a fixed, process-index-derived order identical across
ranks and runs, falling back to exact-order schedules for
non-commutative ops. A host-aware LEADER TIER (``hier_leader_tier``,
the coll/ml subgrouping shape) activates when the job spans hosts:
co-hosted processes combine/fan out over shm handoffs first and one
leader per host crosses DCN. The pvars ``hier_inter_bytes`` /
``hier_inter_msgs_sent`` / ``hier_inter_msgs_recvd`` count exactly
what crossed a process boundary so both the two-level byte reduction
and the O(P^2) -> O(log P) message-count claim are auditable.

Exchange overlap (``wire_overlap_exchange``, default on): every round
posts ALL its sends first — striped across peers in pipelined fragment
bursts by ``WireRouter.coll_send_all`` — then reaps receives in
ARRIVAL order (``coll_recv_any``), so one slow peer no longer blocks
the reap of peers whose data already landed, the failure mode of the
old fixed-process-order ``self._recv(p)`` loops. Per-peer FIFO order
still holds (the OOB guarantees it), so multi-message rounds keep
their member ordering.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from .. import obs as _obs
from ..mca import component as mca_component
from ..mca import pvar
from ..mca import var as mca_var
from ..obs import watchdog as _watchdog
from ..ops.op import Op
from ..utils import output
from ..utils.errors import ErrorCode, MPIError
from . import hier_schedules as _hs
from . import topo_schedules as _topo

_log = output.stream("coll")

_inter_bytes = pvar.counter(
    "hier_inter_bytes",
    "bytes crossing a controller-process boundary in hier collectives "
    "(SENT side)",
)
_inter_msgs_sent = pvar.counter(
    "hier_inter_msgs_sent",
    "inter-process messages SENT by hier collectives",
)
_inter_msgs_recvd = pvar.counter(
    "hier_inter_msgs_recvd",
    "inter-process messages RECEIVED by hier collectives",
)
# MPI_T-compat alias: the old ambiguous counter bumped on both sides
# (one logical message counted twice per process); it lives on as a
# read-only sum so existing tooling keeps a continuous series while
# the split pvars make the O(P^2) -> O(log P) claim auditable.
_inter_msgs = pvar.PVARS.register(
    "hier_inter_msgs", pvar.PvarClass.COUNTER,
    "inter-process messages in hier collectives (alias: sent + recvd)",
    getter=lambda: _inter_msgs_sent.read() + _inter_msgs_recvd.read(),
)
_leader_combines = pvar.counter(
    "hier_leader_combines",
    "host-leader-tier combines performed by spanning collectives",
)

#: current spanning-collective round per comm cid, maintained only
#: while obs is enabled: {"op", "round", "awaiting_procs",
#: "awaiting_ranks"}. THE answer to "the job is stuck — who is waiting
#: in what?": the flight recorder dumps this table verbatim.
_round_state: Dict[int, Dict] = {}


def _hier_rounds_snapshot() -> Dict[str, Dict]:
    return {str(cid): dict(st) for cid, st in list(_round_state.items())}


_watchdog.add_contributor("hier_rounds", _hier_rounds_snapshot)


class _XchgAdapter:
    """The round transport :mod:`coll.hier_schedules` drives: one call
    posts ALL of a schedule round's sends (striped/pipelined by
    ``coll_send_all`` under ``wire_overlap_exchange``), then reaps the
    round's receives in arrival order. Every byte flows through the
    module's instrumented ``_send/_send_all/_recv/_reap`` touchpoints,
    so pvar accounting, ``(cid, round, pair, k)`` flow ids, and the
    watchdog wait registry (``awaiting_info`` names exactly the
    tree/ring neighbors still pending) are identical to the linear
    path's — the PR-4 observability contract survives every schedule."""

    __slots__ = ("m",)

    def __init__(self, module: "_HierModule") -> None:
        self.m = module

    def exchange(self, sends: Dict[int, list],
                 recvs: Dict[int, int]) -> Dict[int, list]:
        m = self.m
        sends = {p: [np.asarray(a) for a in arrs]
                 for p, arrs in sends.items() if arrs}
        recvs = {p: int(c) for p, c in recvs.items() if c > 0}
        got: Dict[int, list] = {p: [] for p in recvs}
        if m._overlap():
            if sends:
                m._send_all(sends)
            if recvs:
                m._reap(dict(recvs),
                        lambda src, arr: got[src].append(arr))
            return got
        for p in sorted(sends):
            for a in sends[p]:
                m._send(p, a)
        for p in sorted(recvs):
            for _ in range(recvs[p]):
                got[p].append(m._recv(p))
        return got


class _HierModule:
    """Two-level collectives over (process, local-member) subgroups."""

    def __init__(self, comm) -> None:
        from ..comm.communicator import Communicator
        from ..comm.group import Group

        self.comm = comm
        rt = comm.runtime
        from ..runtime.wire import proc_topology

        t = proc_topology(comm)  # the one shared layout derivation
        self.router = t.router
        self.my_pidx = t.my_pidx
        self.owner = t.owner
        self.procs = t.procs
        self.members_of = t.members_of
        self.local_ranks = t.local_ranks
        self.local_n = t.local_n
        # shadow communicator over the LOCAL members: the intra level,
        # with the full normal coll stack (the bcol analogue).
        # internal=True: shadow creation happens only on processes with
        # local members, so it must not consume a global cid — that
        # counter has to stay SPMD-synchronized for wire addressing
        self.shadow = Communicator(
            rt, Group([comm.group.world_rank(i) for i in self.local_ranks]),
            name=f"{comm.name}.local", internal=True,
        )
        # the shadow lives exactly as long as its owner: freeing the
        # spanning comm frees it (no registry leak per create/free)
        comm._on_free = tuple(getattr(comm, "_on_free", ())) + (
            self.shadow.free,
        )
        # trace context (maintained only while obs is on): a
        # process-synchronized round counter plus per-(src, dst) message
        # indices within the round. Both sides of every inter-process
        # message derive the SAME flow id from (cid, round, pair, k) —
        # collective call order is identical on every process (MPI's
        # own rule) and per-peer FIFO keeps k aligned, so journals join
        # into flow arrows with no wire-format change. Requires obs
        # enabled on every rank (same MCA env under tpurun).
        self._round = 0
        self._flow_k: Dict[tuple, int] = {}
        # host-aware leader tier (the coll/ml sbgp shape): group the
        # participating processes by the SAME modex-card host identity
        # the router's transport choice consults (_btl_for), so the
        # leader fan-in/fan-out stages ride shm exactly when the
        # transports do. Leader = lowest process index on the host.
        cards = self.router.cards
        self.host_of: Dict[int, str] = {
            p: str(cards[p].get("host") or f"proc-{p}")
            for p in self.procs
        }
        self.host_groups: Dict[str, List[int]] = {}
        for p in self.procs:
            self.host_groups.setdefault(self.host_of[p], []).append(p)
        self.leader_of: Dict[int, int] = {
            p: min(self.host_groups[self.host_of[p]]) for p in self.procs
        }
        self.leaders: List[int] = sorted(
            min(g) for g in self.host_groups.values())
        # uniform (d0, d1) host grid, if one exists: what the fixed
        # decision's torus pick and the topo schedules key off
        self.torus_dims = _topo.grid_dims(self.procs, self.host_of)
        # publish the topology fingerprint the tuning database selects
        # rule files by — (hosts, procs-per-host, link classes, P).
        # force=False: the WIDEST comm (the world) owns the global
        # selection; a narrower subcomm must not displace it
        from ..tuning import db as _tuning_db

        _tuning_db.set_active(
            _tuning_db.fingerprint_for(self.host_of, len(self.procs)),
            force=False)
        self._xchg = _XchgAdapter(self)
        # handle for coll/plan's frozen-schedule record/replay: the
        # plan layer swaps _xchg for the duration of ONE schedule run
        # (ops on a comm are engine-serialized, so the swap is
        # race-free) — it needs the module, which only closures hold
        comm._hier_module = self

    # -- plumbing ----------------------------------------------------------
    @property
    def peers(self) -> List[int]:
        return [p for p in self.procs if p != self.my_pidx]

    @staticmethod
    def _overlap() -> bool:
        return bool(mca_var.get("wire_overlap_exchange", True))

    # -- trace context / round bookkeeping ---------------------------------
    def _flow(self, src_p: int, dst_p: int) -> int:
        """Flow id of the NEXT message src_p -> dst_p this round (call
        only under an ``_obs.enabled`` gate: the k counters must
        advance in lockstep on both sides)."""
        key = (src_p, dst_p)
        k = self._flow_k.get(key, 0)
        self._flow_k[key] = k + 1
        return _obs.flow_id("hier", self.comm.cid, self._round,
                            src_p, dst_p, k)

    def _round_begin(self, name: str) -> float:
        self._round += 1
        self._flow_k = {}
        _round_state[self.comm.cid] = {
            "op": name, "round": self._round, "comm": self.comm.name,
            "awaiting_procs": [], "awaiting_ranks": [],
        }
        return _time.perf_counter()

    def _round_end(self, name: str, t0: float) -> None:
        _round_state.pop(self.comm.cid, None)
        if _obs.enabled:
            _obs.record(name, "coll", t0, _time.perf_counter() - t0,
                        comm_id=self.comm.cid)

    def _awaiting_info(self, pending: Dict[int, int]) -> Callable:
        """Watchdog info resolver: who has NOT arrived, as processes
        AND world ranks — resolved at dump time so it reflects
        arrivals since arming, and mirrored into the round-state table
        the flight recorder dumps."""

        def resolve() -> Dict[str, list]:
            procs = sorted(p for p, c in pending.items() if c > 0)
            ranks = sorted(
                self.comm.group.world_rank(i)
                for p in procs for i in self.members_of.get(p, ())
            )
            st = _round_state.get(self.comm.cid)
            if st is not None:
                st["awaiting_procs"] = procs
                st["awaiting_ranks"] = ranks
            return {"awaiting_procs": procs, "awaiting_ranks": ranks}

        return resolve

    def _stalled_op(self) -> str:
        st = _round_state.get(self.comm.cid)
        return st["op"] if st else "hier"

    # -- transport touchpoints ---------------------------------------------
    def _send(self, peer: int, arr) -> None:
        arr = np.asarray(arr)
        rec = _obs.enabled  # capture once: flag may flip mid-send
        t0 = _time.perf_counter() if rec else 0.0
        self.router.coll_send(self.comm, peer, arr)
        _inter_msgs_sent.add()
        _inter_bytes.add(int(arr.nbytes))
        if rec and _obs.enabled:
            _obs.record("hier_send", "hier", t0,
                        _time.perf_counter() - t0,
                        nbytes=int(arr.nbytes), peer=peer,
                        comm_id=self.comm.cid,
                        flow=self._flow(self.my_pidx, peer),
                        flow_side="s")

    def _recv(self, peer: int):
        rec = _obs.enabled
        t0 = _time.perf_counter() if rec else 0.0
        tok = None
        if _watchdog.enabled:
            tok = _watchdog.arm(self._stalled_op(),
                                comm_id=self.comm.cid, peer=peer,
                                info=self._awaiting_info({peer: 1}))
        try:
            out = np.asarray(self.router.coll_recv(self.comm, peer))
        finally:
            if tok is not None:
                _watchdog.disarm(tok)
        _inter_msgs_recvd.add()
        if rec and _obs.enabled:
            _obs.record("hier_recv", "hier", t0,
                        _time.perf_counter() - t0,
                        nbytes=int(out.nbytes), peer=peer,
                        comm_id=self.comm.cid,
                        flow=self._flow(peer, self.my_pidx),
                        flow_side="t")
        return out

    def _send_all(self, sends: Dict[int, list]) -> None:
        """Post one round's sends to every peer, striped across
        destinations in pipelined fragment bursts (same pvar
        accounting as per-peer :meth:`_send`)."""
        rec = _obs.enabled
        t0 = _time.perf_counter() if rec else 0.0
        self.router.coll_send_all(self.comm, sends)
        dt = (_time.perf_counter() - t0) if rec else 0.0
        if rec and _obs.enabled:
            # the burst's duration lives on ONE aggregate span; the
            # per-message producer spans below are INSTANTS at the
            # burst start — coll_send_all stripes internally, so no
            # per-message completion time exists, and stamping every
            # message with the burst-end time would put flow-arrow
            # origins AFTER receivers consumed the early fragments
            # (negative latencies in the merged trace). The post time
            # is the causally safe bound.
            _obs.record("hier_send_all", "hier", t0, dt,
                        nbytes=sum(int(a.nbytes) for arrs in
                                   sends.values() for a in arrs),
                        comm_id=self.comm.cid)
        for p, arrs in sends.items():
            for a in arrs:
                _inter_msgs_sent.add()
                _inter_bytes.add(int(a.nbytes))
                if rec and _obs.enabled:
                    # one producer span per message: k advances in list
                    # order, the same order coll_send_all puts each
                    # peer's messages on its FIFO
                    _obs.record("hier_send", "hier", t0, 0.0,
                                nbytes=int(a.nbytes), peer=p,
                                comm_id=self.comm.cid,
                                flow=self._flow(self.my_pidx, p),
                                flow_side="s")

    def _send_all_planned(self, rnd, sends: Dict[int, list]) -> None:
        """Steady-state planned round send (coll/plan frozen
        schedules): channel tag, striping depth, and per-message frame
        headers were precomposed at plan time, so this path is ONE
        ULFM check + memoryview slicing behind precomposed header
        bytes. Inter-process pvar accounting matches :meth:`_send_all`
        exactly; per-message spans are NOT journaled here — observed
        replays append one fixed-size record per fire to the obs
        ledger, and tpu-doctor expands it against the frozen plan
        structure into the same flow-id spans the interpreted path
        emits."""
        self.router.coll_send_planned(self.comm, rnd, sends)
        for arrs in sends.values():
            for a in arrs:
                _inter_msgs_sent.add()
                _inter_bytes.add(int(a.nbytes))

    def _reap(self, pending: Dict[int, int],
              on_arrival: Callable[[int, np.ndarray], None],
              timeout_ms: Optional[int] = None,
              record: bool = True) -> None:
        """Reap ``pending[p]`` messages per peer in ARRIVAL order —
        a slow peer never blocks the reap of one whose data already
        landed (the posted-sends overlap the module docstring pins).
        ``timeout_ms``: explicit wait bound (frozen-plan replays pass
        their plan-time snapshot); None = the live cvar.
        ``record=False`` (frozen-plan replays): skip per-arrival span
        emission and the flow-k advance — the obs ledger's expansion
        re-derives both from the frozen plan structure, and journal
        spans here would double them."""
        left = sum(pending.values())
        tok = None
        if _watchdog.enabled:
            tok = _watchdog.arm(self._stalled_op(),
                                comm_id=self.comm.cid,
                                info=self._awaiting_info(pending))
        try:
            while left:
                rec = record and _obs.enabled
                t0 = _time.perf_counter() if rec else 0.0
                src, arr = self.router.coll_recv_any(self.comm, pending,
                                                     timeout_ms)
                if tok is not None:
                    # progress resets the stall clock (and re-arms a
                    # wait that already dumped): a slow but ARRIVING
                    # round is not a stall, and false dumps would burn
                    # the MAX_STALL_DUMPS budget the real hang needs
                    tok.t0 = _time.perf_counter()
                    tok.dumped = False
                _inter_msgs_recvd.add()
                pending[src] -= 1
                left -= 1
                arr = np.asarray(arr)
                if rec and _obs.enabled:
                    _obs.record("hier_recv", "hier", t0,
                                _time.perf_counter() - t0,
                                nbytes=int(arr.nbytes), peer=src,
                                comm_id=self.comm.cid,
                                flow=self._flow(src, self.my_pidx),
                                flow_side="t")
                on_arrival(src, arr)
        finally:
            if tok is not None:
                _watchdog.disarm(tok)

    def _exchange(self, arrs_for: Dict[int, list]) -> Dict[int, list]:
        """Linear inter-process exchange: send every peer its arrays,
        then receive the same count back from each peer (all sends
        land before any recv parks — deadlock-free for the linear
        pattern). One thin shim over the exchange adapter — the SINGLE
        round-advancing code path, shared with every schedule — which
        owns the overlap/sequential split (``wire_overlap_exchange``)
        and all pvar/flow/watchdog accounting."""
        sends = {p: [np.asarray(a) for a in arrs_for.get(p, [])]
                 for p in self.peers}
        got = self._xchg.exchange(
            sends, {p: len(sends[p]) for p in self.peers})
        return {p: got.get(p, []) for p in self.peers}

    def _check_local_axis(self, x, what: str) -> None:
        if not hasattr(x, "shape") or x.ndim == 0 \
                or x.shape[0] != self.local_n:
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"{what} on spanning {self.comm.name}: buffers carry "
                f"one slice per LOCAL member ({self.local_n}), got "
                f"shape {getattr(x, 'shape', None)}",
            )
        # same refusal as the compiled driver edge: hier's local
        # partials and jnp conversions would otherwise silently narrow
        # 64-bit buffers with x64 off — and behavior would even differ
        # by process layout (a 1-member process skips the shadow comm)
        from .driver import _check_no_narrowing

        _check_no_narrowing(x)

    def _local_partial(self, x, op: Op):
        """Reduce this process's member slices to one partial."""
        if op.is_pair_op:
            vals, idxs = x
            self._check_local_axis(vals, "pair allreduce")
            if self.local_n == 1:
                return (jnp.asarray(vals[0]), jnp.asarray(idxs[0]))
            out_v, out_i = self.shadow.allreduce((vals, idxs), op)
            return (out_v[0], out_i[0])
        self._check_local_axis(x, "reduce")
        if self.local_n == 1:
            return jnp.asarray(x[0])
        return self.shadow.allreduce(x, op)[0]

    # -- partial packing / combine dispatch --------------------------------
    def _note_alg(self, alg: str) -> None:
        """Record the selected schedule in the round-state table the
        flight recorder dumps (postmortems name op, round AND alg)."""
        if not _obs.enabled:
            return
        st = _round_state.get(self.comm.cid)
        if st is not None:
            st["alg"] = alg

    @staticmethod
    def _pack_pair(pv: np.ndarray, pi: np.ndarray) -> np.ndarray:
        """One contiguous wire payload for a MINLOC/MAXLOC (value,
        index) partial: both sides know the shapes/dtypes from their
        own partial, so the split point ships no metadata — one
        message per peer per step instead of two (half the
        ``hier_inter_msgs_sent`` and per-message framing)."""
        pv = np.ascontiguousarray(pv)
        pi = np.ascontiguousarray(pi)
        return np.concatenate([pv.reshape(-1).view(np.uint8),
                               pi.reshape(-1).view(np.uint8)])

    @staticmethod
    def _unpack_pair(buf: np.ndarray, like_v: np.ndarray,
                     like_i: np.ndarray):
        buf = np.ascontiguousarray(np.asarray(buf)).view(np.uint8)
        nv = int(like_v.nbytes)
        v = buf[:nv].view(like_v.dtype).reshape(like_v.shape)
        i = buf[nv:].view(like_i.dtype).reshape(like_i.shape)
        return v, i

    def _pack_partial(self, partial, op: Op) -> np.ndarray:
        if op.is_pair_op:
            return self._pack_pair(np.asarray(partial[0]),
                                   np.asarray(partial[1]))
        return np.asarray(partial)

    def _unpack_partial(self, buf, like, op: Op):
        # `like` is read for shape/dtype/nbytes only — attributes jax
        # arrays expose directly; never np.asarray it here (that would
        # force a device fetch of the unchanged partial per peer)
        if op.is_pair_op:
            v, i = self._unpack_pair(buf, like[0], like[1])
            return (jnp.asarray(v), jnp.asarray(i))
        return jnp.asarray(np.asarray(buf).reshape(like.shape))

    @staticmethod
    def _fold(parts: list, op: Op):
        acc = parts[0]
        for nxt in parts[1:]:
            acc = op(acc, nxt)
        return acc

    def _fold_flats(self, procs: List[int], flats: Dict[int, object],
                    partial, op: Op):
        """Fold per-process packed partials in PROCESS-INDEX order —
        the one combine sequence every exact-order schedule shares.
        ``flats`` maps pidx -> packed payload for every peer; this
        process contributes ``partial`` directly (never re-unpacked)."""
        me = self.my_pidx
        parts = [partial if p == me
                 else self._unpack_partial(flats[p], partial, op)
                 for p in procs]
        if not op.is_pair_op:
            parts = [jnp.asarray(t) for t in parts]
        return self._fold(parts, op)

    def _leader_tier_active(self, op: Optional[Op] = None) -> bool:
        """Leader tier applies when the comm spans >1 host AND some
        host holds >1 process (else grouping is the flat set); the
        per-host fold regroups the combine order, so reductions keep
        it for commutative ops only."""
        if len(self.leaders) <= 1 or len(self.leaders) == len(self.procs):
            return False
        if op is not None and not op.commutative:
            return False
        return bool(mca_var.get("hier_leader_tier", True))

    def _pick_allreduce(self, procs: List[int], nbytes: int,
                        op: Op) -> str:
        """The inter allreduce pick for ``procs`` — one call site so
        the leader-tier stand-aside and the combine itself can never
        disagree. The topo hint describes THIS process set (the
        leader set is one-per-host, so its grid is never uniform)."""
        dims = self.torus_dims if procs is self.procs \
            else _topo.grid_dims(procs, self.host_of)
        return _hs.pick(
            "allreduce", len(procs), nbytes,
            commutative=op.commutative,
            has_identity=op.identity is not None,
            pair_op=op.is_pair_op, topo=dims,
        )

    def _combine_partials(self, partial, op: Op):
        """Inter-process combine of per-process partials; identical on
        every process (fixed, process-index-derived order per
        schedule)."""
        if len(self.procs) == 1:
            if op.is_pair_op:
                return (jnp.asarray(partial[0]), jnp.asarray(partial[1]))
            return jnp.asarray(partial)
        if self._leader_tier_active(op):
            # a topology-aware pick over the FULL process set is
            # host-aware itself: the leader tier stands aside instead
            # of regrouping the torus/multiring schedule away. The
            # pack+pick feed straight into _combine_flat when it runs
            # — never computed twice on this hot path.
            packed = self._pack_partial(partial, op)
            alg = self._pick_allreduce(self.procs, int(packed.nbytes),
                                       op)
            if alg not in _topo.TOPO_ALGS:
                return self._combine_leader(partial, op)
            return self._combine_flat(self.procs, partial, op,
                                      packed=packed, alg=alg)
        return self._combine_flat(self.procs, partial, op)

    def _combine_flat(self, procs: List[int], partial, op: Op,
                      packed=None, alg: Optional[str] = None):
        """Run the selected allreduce schedule over ``procs`` (the
        whole process set, or the leader set under the leader tier).
        ``packed``/``alg`` let a caller that already packed and picked
        (the leader-tier stand-aside) hand both through."""
        P = len(procs)
        if P == 1:
            if op.is_pair_op:
                return (jnp.asarray(partial[0]), jnp.asarray(partial[1]))
            return jnp.asarray(partial)
        if packed is None:
            packed = self._pack_partial(partial, op)
        if alg is None:
            alg = self._pick_allreduce(procs, int(packed.nbytes), op)
        self._note_alg(alg)
        me = self.my_pidx
        if alg in _hs.ORDER_WAIVING:
            arr = np.asarray(partial)
            npop = lambda a, b: np.asarray(op(a, b))  # noqa: E731
            ident = op.identity_for(arr.dtype)
            if alg == "multiring":
                out = _topo.allreduce_multiring(
                    self._xchg, procs, me, arr, npop, ident,
                    int(mca_var.get("hier_multiring_k", 4)))
            elif alg == "torus2d":
                out = _topo.allreduce_torus2d(
                    self._xchg, procs, me, arr, npop, ident,
                    self.host_of)
            else:
                fn = (_hs.allreduce_ring if alg == "ring"
                      else _hs.allreduce_rabenseifner)
                out = fn(self._xchg, procs, me, arr, npop, ident)
            return jnp.asarray(np.asarray(out).reshape(arr.shape))
        if alg == "recursive_doubling":
            flats = _hs.allgather_bruck(
                self._xchg, procs, me, packed,
                [int(packed.size)] * P)
            return self._fold_flats(
                procs, dict(zip(procs, flats)), partial, op)
        # linear: the all-pairs exchange baseline (one packed message
        # per peer; pair ops no longer ship two)
        got = _hs.linear_exchange(self._xchg, procs, me, packed)
        return self._fold_flats(procs, got, partial, op)

    def _combine_leader(self, partial, op: Op):
        """Host-aware two-stage combine: co-hosted processes fold at
        their host leader (shm), leaders run the selected schedule
        across hosts (DCN), results fan back out. Fold order is fixed:
        host members in process-index order, then hosts in leader-
        index order — identical on every rank and run."""
        me = self.my_pidx
        lead = self.leader_of[me]
        if lead != me:
            _hs.round_exchange(
                self._xchg, {lead: [self._pack_partial(partial, op)]}, {})
            got = _hs.round_exchange(self._xchg, {}, {lead: 1})[lead][0]
            return self._unpack_partial(got, partial, op)
        _leader_combines.add()
        members = self.host_groups[self.host_of[me]]  # sorted (pidx)
        parts = {me: partial}
        others = [p for p in members if p != me]
        if others:
            got = _hs.round_exchange(self._xchg, {},
                                     {p: 1 for p in others})
            for p in others:
                parts[p] = self._unpack_partial(got[p][0], partial, op)
        acc = self._fold([parts[p] for p in members], op)
        total = self._combine_flat(self.leaders, acc, op)
        if others:
            tp = self._pack_partial(total, op)
            _hs.round_exchange(self._xchg, {p: [tp] for p in others}, {})
        return total

    def _bcast_local_axis(self, value):
        value = jnp.asarray(value)
        return jnp.broadcast_to(
            value[None], (self.local_n,) + value.shape
        )

    @staticmethod
    def _cat(parts: list) -> np.ndarray:
        """Concatenate per-rank slices the way all_gather+reshape does
        (0-d slices stack into a vector)."""
        parts = [np.asarray(p) for p in parts]
        if parts[0].ndim == 0:
            return np.stack(parts)
        return np.concatenate(parts, axis=0)

    # -- operation table ---------------------------------------------------
    def _wrap(self, name: str, fn: Callable) -> Callable:
        """Round instrumentation around one table entry: when obs is
        off this is ONE attribute check and a tail call; when on, it
        advances the synchronized round counter, publishes the round
        state the flight recorder dumps, and journals the whole op as
        a coll-layer span (what the doctor's skew report rounds on)."""

        def run(comm, *args, **kw):
            if not _obs.enabled:
                return fn(comm, *args, **kw)
            t0 = self._round_begin(name)
            try:
                return fn(comm, *args, **kw)
            finally:
                self._round_end(name, t0)

        return run

    def fns(self) -> Dict[str, Callable]:
        return {name: self._wrap(name, fn)
                for name, fn in self._table().items()}

    def _table(self) -> Dict[str, Callable]:
        return {
            "allreduce": self.allreduce,
            "reduce": self.reduce,
            "bcast": self.bcast,
            "allgather": self.allgather,
            "gather": self.gather,
            "scatter": self.scatter,
            "reduce_scatter_block": self.reduce_scatter_block,
            "alltoall": self.alltoall,
            "scan": self.scan,
            "exscan": self.exscan,
            "barrier": self.barrier,
            "alltoallv": self.alltoallv,
            "allgatherv": self.allgatherv,
            "gatherv": self.gatherv,
            "scatterv": self.scatterv,
            "reduce_scatter": self.reduce_scatter,
        }

    # -- reductions --------------------------------------------------------
    def allreduce(self, comm, x, op: Op):
        total = self._combine_partials(self._local_partial(x, op), op)
        if op.is_pair_op:
            tv, ti = total
            return (self._bcast_local_axis(tv),
                    self._bcast_local_axis(ti))
        return self._bcast_local_axis(total)

    def reduce(self, comm, x, op: Op, root: int):
        """Gather per-process partials to the root's owner — binomial
        tree (one packed send per non-root, ceil(log2 P) receives at
        the root) or direct linear sends — then ONE fold there in
        process-index order: bitwise-identical to the historic
        combine-everywhere path (same fold order) at a fraction of the
        messages, and exact for non-commutative ops. The result is
        masked to the root's slice (zeros elsewhere, the xla rooted-
        reduce convention)."""
        partial = self._local_partial(x, op)
        owner = self.owner[root]
        me = self.my_pidx
        P = len(self.procs)
        packed = self._pack_partial(partial, op)
        alg = _hs.pick("reduce", P, int(packed.nbytes)) if P > 1 \
            else "linear"
        self._note_alg(alg)
        flats = None
        if P == 1:
            total = partial
        elif alg == "binomial":
            flats = _hs.gather_binomial(
                self._xchg, self.procs, me, owner, packed,
                [int(packed.size)] * P)
        elif me != owner:
            _hs.round_exchange(self._xchg, {owner: [packed]}, {})
        else:
            got = _hs.round_exchange(
                self._xchg, {}, {p: 1 for p in self.procs if p != me})
            flats = [packed if p == me else got[p][0]
                     for p in self.procs]
        if flats is not None:
            total = self._fold_flats(
                self.procs, dict(zip(self.procs, flats)), partial, op)
        elif P > 1 and me != owner:
            total = None  # recv buffer undefined off-root (zeros)

        def place(t):
            out = np.zeros((self.local_n,) + np.asarray(t).shape,
                           np.asarray(t).dtype)
            if total is not None and root in self.local_ranks:
                out[self.local_ranks.index(root)] = np.asarray(t)
            return jnp.asarray(out)

        if op.is_pair_op:
            like = partial if total is None else total
            return (place(like[0]), place(like[1]))
        return place(partial if total is None else total)

    def reduce_scatter_block(self, comm, x, op: Op):
        n = comm.size

        def chunked(total: np.ndarray) -> np.ndarray:
            if total.shape[0] % n:
                raise MPIError(
                    ErrorCode.ERR_COUNT,
                    f"reduce_scatter_block buffer length "
                    f"{total.shape[0]} not divisible by comm size {n}",
                )
            chunks = total.reshape((n, -1) + total.shape[1:])
            out = np.stack([chunks[r] for r in self.local_ranks])
            return out.reshape((self.local_n, -1) + total.shape[1:])

        total = self._combine_partials(self._local_partial(x, op), op)
        if op.is_pair_op:
            tv, ti = total
            return (jnp.asarray(chunked(np.asarray(tv))),
                    jnp.asarray(chunked(np.asarray(ti))))
        return jnp.asarray(chunked(np.asarray(total)))

    # -- data movement -----------------------------------------------------
    def bcast(self, comm, x, root: int):
        owner = self.owner[root]
        me = self.my_pidx
        if owner == me:
            self._check_local_axis(x, "bcast")
            val = np.asarray(x[self.local_ranks.index(root)])
        else:
            val = None
        # every rank passes an x of the same per-slice shape (the
        # driver-mode SPMD convention), so the decision byte count is
        # derivable symmetrically off-root too
        xa = np.asarray(x)
        slice_bytes = int(xa.nbytes // xa.shape[0]) if xa.ndim else 0
        alg = _hs.pick("bcast", len(self.procs), slice_bytes,
                       topo=self.torus_dims)
        self._note_alg(alg)
        if alg == "torus2d" and len(self.procs) > 1:
            # host-aware by construction: the torus bcast subsumes the
            # leader tier's fan-out (one DCN copy per host)
            val = _topo.bcast_torus2d(self._xchg, self.procs, me,
                                      owner, val, self.host_of)
        elif alg == "binomial" and len(self.procs) > 1:
            if self._leader_tier_active():
                val = self._bcast_leader(owner, val)
            else:
                val = _hs.bcast_binomial(self._xchg, self.procs, me,
                                         owner, val)
        elif owner == me:
            self._xchg.exchange({p: [val] for p in self.peers}, {})
        else:
            val = self._xchg.exchange({}, {owner: 1})[owner][0]
        return self._bcast_local_axis(val)

    def _bcast_leader(self, owner: int, val):
        """Leader-tier bcast: binomial over {owner + other hosts'
        leaders} crosses DCN, then each of those fans out to its
        co-hosted processes over shm (the owner serves its own host —
        including that host's nominal leader)."""
        me = self.my_pidx
        host = self.host_of
        bset = sorted({owner} | {l for l in self.leaders
                                 if host[l] != host[owner]})
        if me in bset:
            val = _hs.bcast_binomial(self._xchg, bset, me, owner, val)
            fan = [p for p in self.host_groups[host[me]] if p != me]
            if fan:
                _hs.round_exchange(
                    self._xchg, {p: [np.asarray(val)] for p in fan}, {})
            return val
        src = owner if host[me] == host[owner] else self.leader_of[me]
        return np.asarray(
            _hs.round_exchange(self._xchg, {}, {src: 1})[src][0])

    def _gather_block_rows(self,
                           block: np.ndarray) -> Dict[int, np.ndarray]:
        """Every rank's slice via the selected allgather schedule over
        per-process blocks (one (local_n, chunk...) block each);
        returns {comm rank: row}."""
        me = self.my_pidx
        P = len(self.procs)
        chunk_shape = block.shape[1:]
        chunk_elems = int(np.prod(chunk_shape, dtype=np.int64)) \
            if chunk_shape else 1
        total_bytes = int(self.comm.size * chunk_elems * block.itemsize)
        alg = _hs.pick("allgather", P, total_bytes,
                       topo=self.torus_dims) if P > 1 else "linear"
        self._note_alg(alg)
        blocks: Dict[int, np.ndarray] = {}
        if P == 1 or alg == "linear":
            got = self._exchange({p: [block] for p in self.peers})
            for p in self.procs:
                blocks[p] = block if p == me else np.asarray(got[p][0])
        elif alg == "torus2d":
            parts = _topo.allgather_torus2d(self._xchg, self.procs,
                                            me, block, self.host_of)
            for i, p in enumerate(self.procs):
                blocks[p] = np.asarray(parts[i])
        elif alg == "bruck":
            counts = [len(self.members_of[p]) * chunk_elems
                      for p in self.procs]
            flats = _hs.allgather_bruck(
                self._xchg, self.procs, me,
                np.ascontiguousarray(block).reshape(-1), counts)
            for i, p in enumerate(self.procs):
                blocks[p] = np.asarray(flats[i]).reshape(
                    (len(self.members_of[p]),) + chunk_shape)
        else:  # ring: neighbor-only passes, shapes ride the wire
            parts = _hs.allgather_ring(self._xchg, self.procs, me, block)
            for i, p in enumerate(self.procs):
                blocks[p] = np.asarray(parts[i])
        rows: Dict[int, np.ndarray] = {}
        for p in self.procs:
            pblock = blocks[p]
            for pos, r in enumerate(self.members_of[p]):
                rows[r] = pblock[pos]
        return rows

    def allgather(self, comm, x):
        self._check_local_axis(x, "allgather")
        block = np.asarray(x)  # (local_n, chunk...)
        rows = self._gather_block_rows(block)
        full = self._cat([rows[r] for r in range(comm.size)])
        return self._bcast_local_axis(full)

    def gather(self, comm, x, root: int):
        self._check_local_axis(x, "gather")
        owner = self.owner[root]
        me = self.my_pidx
        P = len(self.procs)
        block = np.asarray(x)
        full_shape = (comm.size * block.shape[1],) + block.shape[2:] \
            if block.ndim > 1 else (comm.size,)
        chunk_shape = block.shape[1:]
        chunk_elems = int(np.prod(chunk_shape, dtype=np.int64)) \
            if chunk_shape else 1
        slice_bytes = int(chunk_elems * block.itemsize)
        alg = _hs.pick("gather", P, slice_bytes) if P > 1 else "linear"
        self._note_alg(alg)
        rows: Dict[int, np.ndarray] = {}
        if alg == "binomial" and P > 1:
            counts = [len(self.members_of[p]) * chunk_elems
                      for p in self.procs]
            flats = _hs.gather_binomial(
                self._xchg, self.procs, me, owner,
                np.ascontiguousarray(block).reshape(-1), counts)
            if flats is None:
                return jnp.zeros((self.local_n,) + full_shape,
                                 block.dtype)
            for i, p in enumerate(self.procs):
                pblock = np.asarray(flats[i]).reshape(
                    (len(self.members_of[p]),) + chunk_shape)
                for pos, r in enumerate(self.members_of[p]):
                    rows[r] = pblock[pos]
        else:
            if owner != me:
                self._xchg.exchange({owner: [block]}, {})
                return jnp.zeros((self.local_n,) + full_shape,
                                 block.dtype)
            for pos, r in enumerate(self.members_of[me]):
                rows[r] = block[pos]
            got = self._xchg.exchange({}, {p: 1 for p in self.peers})
            for p in self.peers:
                pblock = np.asarray(got[p][0])
                for pos, r in enumerate(self.members_of[p]):
                    rows[r] = pblock[pos]
        full = self._cat([rows[r] for r in range(comm.size)])
        out = np.zeros((self.local_n,) + full.shape, full.dtype)
        out[self.local_ranks.index(root)] = full
        return jnp.asarray(out)

    def scatter(self, comm, x, root: int):
        n = comm.size
        owner = self.owner[root]
        me = self.my_pidx
        P = len(self.procs)
        # MPI reads the buffer on the root only, so non-roots cannot
        # know the message size — the schedule decision must still be
        # identical everywhere, so it is taken at bytes=0 (forcing and
        # zero-threshold rules apply; size-split rules cannot)
        alg = _hs.pick("scatter", P, 0) if P > 1 else "linear"
        self._note_alg(alg)
        chunks = None
        if owner == me:
            self._check_local_axis(x, "scatter")
            full = np.asarray(x[self.local_ranks.index(root)])
            if full.shape[0] % n:
                raise MPIError(
                    ErrorCode.ERR_COUNT,
                    f"scatter buffer length {full.shape[0]} not "
                    f"divisible by comm size {n}",
                )
            chunks = full.reshape((n, -1) + full.shape[1:])
        if alg == "binomial" and P > 1:
            weights = [len(self.members_of[p]) for p in self.procs]
            per_pos = meta = None
            if owner == me:
                per_pos = [np.ascontiguousarray(
                    chunks[self.members_of[p]]).reshape(-1)
                    for p in self.procs]
                meta = np.asarray(chunks.shape[1:], np.int64)
            flat, meta = _hs.scatter_binomial(self._xchg, self.procs,
                                              me, owner, per_pos,
                                              weights, meta)
            if owner == me:
                mine = chunks[self.members_of[me]]
            else:
                # the forwarded meta header carries the per-rank chunk
                # shape MPI lets only the root read
                shape = (self.local_n,) + tuple(int(s) for s in meta)
                mine = np.asarray(flat).reshape(shape)
        elif owner == me:
            self._xchg.exchange({p: [chunks[self.members_of[p]]]
                                 for p in self.peers}, {})
            mine = chunks[self.members_of[me]]
        else:
            # (local_n, chunk...)
            mine = self._xchg.exchange({}, {owner: 1})[owner][0]
        return jnp.asarray(mine)

    def alltoall(self, comm, x):
        self._check_local_axis(x, "alltoall")
        n = comm.size
        block = np.asarray(x)
        if block.shape[1] % n:
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"alltoall buffer length {block.shape[1]} not divisible "
                f"by comm size {n}",
            )
        c = block.shape[1] // n
        # chunks[a, j]: local member a's chunk destined to comm rank j
        chunks = block.reshape((self.local_n, n, c) + block.shape[2:])
        P = len(self.procs)
        me = self.my_pidx
        trail = int(np.prod(block.shape[2:], dtype=np.int64)) \
            if block.ndim > 2 else 1
        # decision unit = one rank-pair chunk's bytes (block_dsize,
        # coll_tuned_decision_fixed.c:122) — identical on every process
        alg = _hs.pick("alltoall", P, int(c * trail * block.itemsize)) \
            if P > 1 else "linear"
        self._note_alg(alg)
        recv_block: Dict[int, np.ndarray] = {}
        if P == 1:
            pass
        elif alg == "bruck":
            mlen = [len(self.members_of[p]) for p in self.procs]
            cf = c * trail
            pair_counts = [[mlen[o] * mlen[j] * cf for j in range(P)]
                           for o in range(P)]
            mine = [np.ascontiguousarray(
                chunks[:, self.members_of[p]]).reshape(-1)
                for p in self.procs]
            res = _hs.alltoall_bruck(self._xchg, self.procs, me, mine,
                                     pair_counts)
            for i, p in enumerate(self.procs):
                if p == me:
                    continue
                recv_block[p] = np.asarray(res[i]).reshape(
                    (mlen[i], self.local_n, c) + block.shape[2:])
        elif alg == "pairwise":
            payload_for = {p: np.ascontiguousarray(
                chunks[:, self.members_of[p]]) for p in self.peers}
            got = _hs.alltoall_pairwise(self._xchg, self.procs, me,
                                        payload_for)
            recv_block = {p: np.asarray(a) for p, a in got.items()}
        else:  # linear: every peer's aggregate posted at once
            got = self._exchange({p: [chunks[:, self.members_of[p]]]
                                  for p in self.peers})
            recv_block = {p: np.asarray(got[p][0]) for p in self.peers}
        out = np.empty_like(chunks)
        # local block: out[b, i] = in[a, j] for local members i->j
        for a, i in enumerate(self.local_ranks):
            for b, j in enumerate(self.local_ranks):
                out[b, i] = chunks[a, j]
        for p in self.peers:
            r = recv_block[p]  # [a, b]: p's member a -> my member b
            for a, i in enumerate(self.members_of[p]):
                for b in range(self.local_n):
                    out[b, i] = r[a, b]
        return jnp.asarray(out.reshape(block.shape))

    # -- v-variant collectives (ragged; lists indexed by LOCAL member) -----
    # Spanning-comm analogue of coll/vcoll.py's driver-mode convention:
    # rank-dependent inputs/outputs are Python lists with one entry per
    # LOCAL member in comm-rank order; identical-everywhere results are
    # returned once. Counts arguments are GLOBAL (the full matrix /
    # per-rank vector on every process), matching MPI's requirement
    # that every caller supplies the complete picture.

    def _ragged_local(self, bufs, what: str) -> List[np.ndarray]:
        if len(bufs) != self.local_n:
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"{what} on spanning {self.comm.name}: pass one buffer "
                f"per LOCAL member ({self.local_n}), got {len(bufs)}",
            )
        out = [np.asarray(b).reshape(-1) for b in bufs]
        dtypes = {a.dtype for a in out}
        if len(dtypes) != 1:
            raise MPIError(
                ErrorCode.ERR_TYPE,
                f"{what} buffers must share one dtype, got "
                f"{sorted(map(str, dtypes))}",
            )
        from .driver import _check_no_narrowing

        if out:
            _check_no_narrowing(out[0])
        return out

    def alltoallv(self, comm, sendbufs, sendcounts):
        """Pairwise exchange, process-aggregated
        (``coll_tuned_alltoallv.c:148`` sends rank-pairwise over the
        PML; here every process sends ONE aggregated message per peer
        process — its members' chunks for that peer's members — since
        both sides derive the sub-layout from the shared count
        matrix). ``sendcounts`` is the full (n, n) matrix; returns
        ``recv[b]`` = source-order concatenation for local member b."""
        n = comm.size
        c = np.asarray(sendcounts, dtype=np.int64)
        if c.shape != (n, n) or (c < 0).any():
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"alltoallv needs a non-negative ({n},{n}) count "
                f"matrix, got {getattr(c, 'shape', None)}",
            )
        bufs = self._ragged_local(sendbufs, "alltoallv")
        dtype = bufs[0].dtype
        offs = np.concatenate(
            [np.zeros((n, 1), np.int64), np.cumsum(c, axis=1)], axis=1
        )
        for pos, i in enumerate(self.local_ranks):
            if bufs[pos].shape[0] != int(c[i].sum()):
                raise MPIError(
                    ErrorCode.ERR_COUNT,
                    f"alltoallv rank {i}: buffer has "
                    f"{bufs[pos].shape[0]} elements, counts sum to "
                    f"{int(c[i].sum())}",
                )

        def chunk(pos: int, i: int, j: int) -> np.ndarray:
            return bufs[pos][offs[i, j]:offs[i, j] + int(c[i, j])]

        sends = {}
        for p in self.peers:
            parts = [chunk(pos, i, j)
                     for pos, i in enumerate(self.local_ranks)
                     for j in self.members_of[p]]
            sends[p] = [np.concatenate(parts) if parts
                        else np.zeros((0,), dtype)]
        got = self._exchange(sends)
        from_peer: Dict[tuple, np.ndarray] = {}
        for p in self.peers:
            msg = np.asarray(got[p][0])
            off = 0
            for i in self.members_of[p]:
                for j in self.local_ranks:
                    k = int(c[i, j])
                    from_peer[(i, j)] = msg[off:off + k]
                    off += k
            if off != msg.shape[0]:
                raise MPIError(
                    ErrorCode.ERR_TRUNCATE,
                    f"alltoallv message from process {p} has "
                    f"{msg.shape[0]} elements, count matrix implies "
                    f"{off} — mismatched sendcounts across processes?",
                )
        recv = []
        for pos, j in enumerate(self.local_ranks):
            parts = [
                chunk(self.local_ranks.index(i), i, j)
                if self.owner[i] == self.my_pidx else from_peer[(i, j)]
                for i in range(n)
            ]
            recv.append(jnp.asarray(np.concatenate(parts) if parts
                                    else np.zeros((0,), dtype)))
        return recv

    def _gather_rows(self, bufs: List[np.ndarray]) -> Dict[int, np.ndarray]:
        """Every rank's ragged buffer: send each LOCAL member's buffer
        as its own message (shapes ride the wire, so no count
        pre-exchange), receive each peer's members' in comm-rank
        order (per-peer FIFO keeps member order under arrival-order
        reaping)."""
        rows: Dict[int, np.ndarray] = {
            r: bufs[pos] for pos, r in enumerate(self.local_ranks)
        }
        got = self._xchg.exchange(
            {p: list(bufs) for p in self.peers},
            {p: len(self.members_of[p]) for p in self.peers})
        for p in self.peers:
            # per-peer FIFO keeps member order under arrival reaping
            for r, arr in zip(self.members_of[p], got[p]):
                rows[r] = np.asarray(arr)
        return rows

    def allgatherv(self, comm, sendbufs):
        """Rank-order concatenation of ragged buffers; identical on
        every rank, returned once (the vcoll convention)."""
        bufs = self._ragged_local(sendbufs, "allgatherv")
        rows = self._gather_rows(bufs)
        return jnp.asarray(
            np.concatenate([rows[r] for r in range(comm.size)])
        )

    def gatherv(self, comm, sendbufs, root: int):
        """Linear gather to the root's owner process
        (``coll_base_gatherv`` linear variant): non-owner processes
        send their members' buffers and return None (MPI leaves the
        recv buffer undefined off-root); the owner returns the
        rank-order concatenation."""
        n = comm.size
        if not 0 <= root < n:
            raise MPIError(ErrorCode.ERR_ROOT, f"bad root {root}")
        bufs = self._ragged_local(sendbufs, "gatherv")
        owner = self.owner[root]
        if owner != self.my_pidx:
            self._xchg.exchange({owner: list(bufs)}, {})
            from .base import NO_RESULT

            return NO_RESULT  # recv buffer undefined off-root
        rows: Dict[int, np.ndarray] = {
            r: bufs[pos] for pos, r in enumerate(self.local_ranks)
        }
        got = self._xchg.exchange(
            {}, {p: len(self.members_of[p]) for p in self.peers})
        for p in self.peers:
            for r, arr in zip(self.members_of[p], got[p]):
                rows[r] = np.asarray(arr)
        return jnp.asarray(np.concatenate([rows[r] for r in range(n)]))

    def scatterv(self, comm, sendbuf, counts, root: int):
        """Root's owner splits ``sendbuf`` by ``counts`` and ships each
        remote rank's chunk to its owner; returns one array per LOCAL
        member. ``sendbuf`` is read only on the owner process."""
        n = comm.size
        if not 0 <= root < n:
            raise MPIError(ErrorCode.ERR_ROOT, f"bad root {root}")
        counts = [int(k) for k in counts]
        if len(counts) != n or any(k < 0 for k in counts):
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"scatterv needs {n} non-negative counts, got {counts}",
            )
        owner = self.owner[root]
        if owner != self.my_pidx:
            got = self._xchg.exchange({}, {owner: self.local_n})
            return [jnp.asarray(a) for a in got[owner]]
        buf = np.asarray(sendbuf).reshape(-1)
        from .driver import _check_no_narrowing

        _check_no_narrowing(buf)
        if buf.shape[0] != sum(counts):
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"scatterv root buffer has {buf.shape[0]} elements, "
                f"counts sum to {sum(counts)}",
            )
        offs = np.concatenate([[0], np.cumsum(counts)])
        chunks = [buf[offs[j]:offs[j] + counts[j]] for j in range(n)]
        self._xchg.exchange({p: [chunks[j] for j in self.members_of[p]]
                             for p in self.peers}, {})
        return [jnp.asarray(chunks[j]) for j in self.local_ranks]

    def reduce_scatter(self, comm, x, recvcounts, op: Op):
        """General MPI_Reduce_scatter: combine (local partial, then
        process-index-order inter combine — the allreduce discipline),
        each rank keeps its ``recvcounts[i]``-length segment. ``x`` is
        (local_n, total); returns one array per LOCAL member."""
        n = comm.size
        recvcounts = [int(k) for k in recvcounts]
        if len(recvcounts) != n or any(k < 0 for k in recvcounts):
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"reduce_scatter needs {n} non-negative counts",
            )
        total = sum(recvcounts)
        if op.is_pair_op:
            vals, idxs = x
            self._check_local_axis(vals, "reduce_scatter")
            vals = np.asarray(vals)
            if vals.reshape(self.local_n, -1).shape[1] != total:
                raise MPIError(
                    ErrorCode.ERR_COUNT,
                    f"reduce_scatter needs values shaped "
                    f"({self.local_n}, {total}), got {vals.shape}",
                )
            tv, ti = self._combine_partials(
                self._local_partial((vals, idxs), op), op
            )
            tv, ti = np.asarray(tv).reshape(-1), np.asarray(ti).reshape(-1)
            offs = np.concatenate([[0], np.cumsum(recvcounts)])
            return [
                (jnp.asarray(tv[offs[r]:offs[r] + recvcounts[r]]),
                 jnp.asarray(ti[offs[r]:offs[r] + recvcounts[r]]))
                for r in self.local_ranks
            ]
        x = np.asarray(x)
        from .driver import _check_no_narrowing

        _check_no_narrowing(x)  # BEFORE the jnp conversion below
        if x.shape[0] != self.local_n \
                or x.reshape(self.local_n, -1).shape[1] != total:
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"reduce_scatter needs x shaped ({self.local_n}, "
                f"{total}), got {x.shape}",
            )
        x = x.reshape(self.local_n, total)
        red = np.asarray(self._combine_partials(
            self._local_partial(jnp.asarray(x), op), op
        ))
        offs = np.concatenate([[0], np.cumsum(recvcounts)])
        return [jnp.asarray(red[offs[r]:offs[r] + recvcounts[r]])
                for r in self.local_ranks]

    # -- prefix scans ------------------------------------------------------
    def _full_rows(self, x) -> Dict[int, np.ndarray]:
        """Every rank's slice, via the selected allgather schedule."""
        return self._gather_block_rows(np.asarray(x))

    def _scan_impl(self, comm, x, op: Op, exclusive: bool):
        if op.is_pair_op:
            # MINLOC/MAXLOC scans: fold the gathered (value, index)
            # rows with the pair combiner in rank order; the rank-0
            # exscan slice is zeros (MPI leaves it undefined)
            vals, idxs = x
            self._check_local_axis(vals, "scan")
            vrows = self._full_rows(vals)
            irows = self._full_rows(idxs)
            outv, outi = [], []
            for r in self.local_ranks:
                end = r if exclusive else r + 1
                if end == 0:
                    outv.append(np.zeros_like(vrows[0]))
                    outi.append(np.zeros_like(irows[0]))
                    continue
                acc = (jnp.asarray(vrows[0]), jnp.asarray(irows[0]))
                for j in range(1, end):
                    acc = op(acc, (jnp.asarray(vrows[j]),
                                   jnp.asarray(irows[j])))
                outv.append(np.asarray(acc[0]))
                outi.append(np.asarray(acc[1]))
            return (jnp.asarray(np.stack(outv)),
                    jnp.asarray(np.stack(outi)))
        self._check_local_axis(x, "scan")
        rows = self._full_rows(x)
        out = []
        for r in self.local_ranks:
            if exclusive:
                if r == 0:
                    out.append(np.zeros_like(rows[0]))
                    continue
                acc = jnp.asarray(rows[0])
                for j in range(1, r):
                    acc = op(acc, jnp.asarray(rows[j]))
            else:
                acc = jnp.asarray(rows[0])
                for j in range(1, r + 1):
                    acc = op(acc, jnp.asarray(rows[j]))
            out.append(np.asarray(acc))
        return jnp.asarray(np.stack(out))

    def scan(self, comm, x, op: Op):
        return self._scan_impl(comm, x, op, exclusive=False)

    def exscan(self, comm, x, op: Op):
        return self._scan_impl(comm, x, op, exclusive=True)

    # -- synchronization ---------------------------------------------------
    def barrier(self, comm):
        if self.local_n > 1:
            self.shadow.barrier()
        self.router.proc_barrier(self.comm, self.procs)


class HierCollComponent(mca_component.Component):
    """Claims exactly the communicators no in-process component can
    serve: those spanning controller processes."""

    NAME = "hier"
    PRIORITY = 150

    def query(self, ctx=None):
        if ctx is None:
            return (self.priority, self)
        if not getattr(ctx, "spans_processes", False):
            return None
        if getattr(ctx.runtime, "wire", None) is None:
            return None  # no router: nothing can serve this comm
        return (self.priority, _HierModule(ctx))
