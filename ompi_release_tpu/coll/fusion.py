"""Small-message fusion — a Horovod-fusion-buffer / BTL-send-coalescing
analogue for host-driver collectives.

The reference's small-message wins come from coalescing: the BTL packs
many small sends into one wire frame, and Horovod's fusion buffer packs
many small gradient allreduces into one device collective, amortizing
the per-collective dispatch latency. This module is that engine for
the driver path: concurrent small collectives on the same
``(comm, op, dtype)`` pack into ONE flat fused buffer and issue as ONE
device collective.

Contract
--------
- Tensors whose per-rank payload is below the ``coll_fusion_threshold``
  cvar queue in the communicator's :class:`FusionBuffer`
  (``comm.fusion_buffer()``); larger ones dispatch immediately.
- A queue drains on: explicit :meth:`FusionBuffer.flush`, a handle's
  :meth:`FusedHandle.result` (correctness never waits on policy),
  pending bytes exceeding ``coll_fusion_buffer_bytes``, or the oldest
  pending tensor aging past ``coll_fusion_max_delay_us`` (checked at
  every submission — the max-delay bound, no progress thread needed).
- Packing reuses :func:`plan_buckets`, the same greedy same-dtype
  planner ``parallel/dp.py`` uses for SPMD gradient bucketing — one
  definition of the fusion decision at both layers.

pvars: ``coll_fusion_batched`` (tensors coalesced), ``coll_fusion_flushes``
(fused device collectives issued), ``coll_fusion_bytes_saved`` (payload
bytes that rode an already-issued collective instead of their own) —
all module-level zero-cost counters; journal spans are gated on
``obs.enabled`` so the hot path stays one attribute check when
observability is off.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from .. import obs as _obs
from ..mca import pvar
from ..mca import var as mca_var
from ..utils.errors import ErrorCode, MPIError

_batched = pvar.counter(
    "coll_fusion_batched", "tensors coalesced into fused collectives"
)
_flushes = pvar.counter(
    "coll_fusion_flushes", "fused device collectives issued"
)
_bytes_saved = pvar.counter(
    "coll_fusion_bytes_saved",
    "payload bytes that rode a fused collective instead of issuing "
    "their own (bytes beyond the first tensor of each flush)",
)


def register_vars() -> None:
    mca_var.register(
        "coll_fusion_threshold", "size", 64 * 1024,
        "Per-rank bytes below which a collective is eligible for "
        "fusion (Horovod fusion-buffer / BTL coalescing analogue); "
        "0 disables fusion (everything dispatches immediately)",
    )
    mca_var.register(
        "coll_fusion_buffer_bytes", "size", 4 * 1024 * 1024,
        "Pending-bytes capacity of the fusion buffer: a submission "
        "pushing past this flushes the queue",
    )
    mca_var.register(
        "coll_fusion_max_delay_us", "int", 2000,
        "Oldest pending tensor's max age in microseconds: a "
        "submission finding older pendings flushes them first "
        "(the fusion latency bound)",
    )


register_vars()  # idempotent; cvars must exist before first buffer


def plan_buckets(items: Iterable[Tuple[Any, int, Any]],
                 capacity: int) -> List[List[Any]]:
    """Greedy in-order fusion planning, shared by the SPMD gradient
    bucketer (``parallel/dp.py``) and :class:`FusionBuffer`.

    ``items`` yields ``(tag, nbytes, group_key)``; a bucket closes when
    adding the next item would exceed ``capacity`` or its ``group_key``
    (dtype) differs from the bucket's. Returns the list of buckets as
    lists of tags, order preserved. An item alone larger than
    ``capacity`` still gets a bucket (it must go somewhere)."""
    buckets: List[List[Any]] = []
    cur: List[Any] = []
    cur_bytes = 0
    cur_key = None
    for tag, nbytes, key in items:
        if cur and (cur_bytes + nbytes > capacity or key != cur_key):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(tag)
        cur_bytes += nbytes
        cur_key = key
    if cur:
        buckets.append(cur)
    return buckets


class FusedHandle:
    """Future for one tensor submitted to a :class:`FusionBuffer`.
    ``result()`` returns the reduced array, flushing the buffer first
    if this tensor is still pending."""

    __slots__ = ("_buffer", "_value", "_error", "_event")

    def __init__(self, buffer: Optional["FusionBuffer"],
                 value: Any = None, done: bool = False) -> None:
        self._buffer = buffer
        self._value = value
        self._error: Optional[BaseException] = None
        self._event = threading.Event()
        if done:
            self._event.set()

    @property
    def done(self) -> bool:
        return self._event.is_set()

    def _complete(self, value: Any) -> None:
        self._value = value
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()

    def result(self) -> Any:
        if not self._event.is_set():
            # a concurrent flush may have claimed this tensor's queue
            # already (flush() swaps queues out under the lock and
            # completes handles outside it) — our own flush() is then
            # a no-op and the EVENT, not the flush return, is the
            # completion signal
            self._buffer.flush()
            self._event.wait()
        if self._error is not None:
            raise self._error
        return self._value


class _Pending:
    __slots__ = ("handle", "x", "shape", "nbytes", "t_submit")

    def __init__(self, handle: FusedHandle, x, shape, nbytes: int) -> None:
        self.handle = handle
        self.x = x
        self.shape = shape
        self.nbytes = nbytes
        self.t_submit = time.perf_counter()


class FusionBuffer:
    """Per-communicator fusion buffer for driver-mode collectives.

    Thread-safe: submissions and flushes serialize on one lock; the
    device collectives themselves run outside it (the comm's own
    dispatch handles concurrency)."""

    def __init__(self, comm, *, threshold: Optional[int] = None,
                 capacity: Optional[int] = None,
                 max_delay_us: Optional[int] = None) -> None:
        self.comm = comm
        self._threshold = threshold
        self._capacity = capacity
        self._max_delay_us = max_delay_us
        self._lock = threading.Lock()
        # (op, dtype_str) -> [_Pending]; keyed by the op OBJECT so two
        # same-named ops with different combiners never share a queue
        # (and the key itself carries the op for flush)
        self._queues: Dict[Tuple[Any, str], List[_Pending]] = {}
        self._pending_bytes = 0  # running total (capacity check is O(1))

    # -- config (cvars re-read per call so runtime tuning applies) ---------
    def threshold(self) -> int:
        if self._threshold is not None:
            return self._threshold
        return int(mca_var.get("coll_fusion_threshold", 64 * 1024))

    def capacity(self) -> int:
        if self._capacity is not None:
            return self._capacity
        return int(mca_var.get("coll_fusion_buffer_bytes", 4 * 1024 * 1024))

    def max_delay_s(self) -> float:
        us = (self._max_delay_us if self._max_delay_us is not None
              else int(mca_var.get("coll_fusion_max_delay_us", 2000)))
        return us / 1e6

    def pending(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    # -- submission --------------------------------------------------------
    def allreduce(self, x, op=None) -> FusedHandle:
        """Submit a driver-mode allreduce (leading axis = comm.size).
        Below the fusion threshold the tensor queues for coalescing;
        at/above it (or for pair ops, which have no flat packing) it
        dispatches immediately."""
        from .. import ops as ops_mod

        op = op or ops_mod.SUM
        if op.is_pair_op or isinstance(x, tuple):
            return FusedHandle(None, self.comm.allreduce(x, op), True)
        arr = np.asarray(x)
        if arr.ndim < 1 or arr.shape[0] != self.comm.size:
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"fused allreduce needs a driver-mode buffer with "
                f"leading axis == comm size {self.comm.size}, got "
                f"shape {arr.shape}",
            )
        per_rank = int(arr[0].size) * int(arr.dtype.itemsize)
        thresh = self.threshold()
        if thresh <= 0 or per_rank >= thresh:
            return FusedHandle(None, self.comm.allreduce(arr, op), True)

        handle = FusedHandle(self)
        now = time.perf_counter()
        max_delay = self.max_delay_s()
        with self._lock:
            expired = any(
                now - q[0].t_submit > max_delay
                for q in self._queues.values() if q
            )
        if expired:
            # the latency bound: older pendings flush BEFORE the new
            # tensor queues, so no tensor waits past max_delay + one
            # submission gap
            self.flush()
        key = (op, str(arr.dtype))
        with self._lock:
            self._queues.setdefault(key, []).append(
                _Pending(handle, arr.reshape(self.comm.size, -1),
                         arr.shape, per_rank)
            )
            self._pending_bytes += per_rank
            over = self._pending_bytes > self.capacity()
        if over:
            self.flush()
        return handle

    # -- drain -------------------------------------------------------------
    def flush(self) -> int:
        """Issue every pending queue as fused device collectives;
        returns how many collectives were issued."""
        with self._lock:
            queues = self._queues
            self._queues = {}
            self._pending_bytes = 0
        issued = 0
        t0 = time.perf_counter()
        fused_bytes = 0
        claimed = [p for q in queues.values() for p in q]
        try:
            for key, pendings in queues.items():
                if not pendings:
                    continue
                op = key[0]
                # plan_buckets gives an oversize item its own bucket,
                # so the cvar capacity needs no inflation here
                buckets = plan_buckets(
                    ((p, p.nbytes, key) for p in pendings),
                    self.capacity(),
                )
                for bucket in buckets:
                    issued += 1
                    _flushes.add()
                    _batched.add(len(bucket))
                    _bytes_saved.add(sum(p.nbytes for p in bucket[1:]))
                    fused_bytes += sum(p.nbytes for p in bucket)
                    if len(bucket) == 1:
                        p = bucket[0]
                        p.handle._complete(
                            self.comm.allreduce(p.x.reshape(p.shape), op)
                        )
                        continue
                    flat = np.concatenate([p.x for p in bucket], axis=1)
                    red = self.comm.allreduce(flat, op)
                    off = 0
                    for p in bucket:
                        width = p.x.shape[1]
                        p.handle._complete(
                            red[:, off:off + width].reshape(p.shape)
                        )
                        off += width
        except BaseException as e:
            # the queues were already claimed: handles that will never
            # complete must fail loudly, not leave result() blocked
            for p in claimed:
                if not p.handle.done:
                    p.handle._fail(e)
            raise
        if issued and _obs.enabled:
            _obs.record("fusion_flush", "fusion", t0,
                        time.perf_counter() - t0, nbytes=fused_bytes,
                        comm_id=getattr(self.comm, "cid", -1))
        return issued
