"""v-variant collectives: per-rank counts with static-shape kernels.

The reference implements MPI_Alltoallv/Allgatherv/Gatherv/Scatterv and
general MPI_Reduce_scatter as count/displacement-driven send/recv loops
(``ompi/mca/coll/tuned/coll_tuned_alltoallv.c``, ``coll_base``
linear variants). XLA needs static shapes, so the TPU-native design
splits each v-collective in two:

  driver edge (here, host numpy)   ragged per-rank buffers <-> one
                                   padded rectangular array (pad to the
                                   max count; op identity as filler)
  compiled kernel (coll/spmd.py)   the equal-block collective on the
                                   padded array — one persistent
                                   program per (n, cmax, dtype), counts
                                   NOT baked in

so arbitrary count matrices reuse one compiled program per padded
shape: changing counts changes only the edge slicing, never triggers a
retrace (the "no per-call retrace" north-star requirement applies to
varying ragged workloads too — this is why counts live at the edge).

Driver-mode conventions (matching ``comm/communicator.py``):
rank-dependent inputs/outputs are Python lists indexed by rank (ragged
lengths make a leading-axis array impossible); results identical on
every rank are returned once.
"""

from __future__ import annotations

import time as _time
from typing import List, Sequence

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..ops.op import Op
from ..utils.errors import ErrorCode, MPIError
from . import spmd
from .driver import run_sharded

AXIS = "rank"

from .. import obs as _obs  # noqa: E402
from ..mca import pvar as _pvar  # noqa: E402

_padded_elems = _pvar.counter(
    "vcoll_alltoallv_padded_elems",
    "elements moved by the padded alltoallv kernel",
)
_overflow_elems = _pvar.counter(
    "vcoll_alltoallv_overflow_elems",
    "hot-pair tail elements delivered host-side at the driver edge "
    "(skew mitigation; these bypass the kernel)",
)


def _as_1d_arrays(bufs, n: int, what: str) -> List[np.ndarray]:
    if len(bufs) != n:
        raise MPIError(
            ErrorCode.ERR_COUNT,
            f"{what} needs one buffer per rank ({n}), got {len(bufs)}",
        )
    out = [np.asarray(b).reshape(-1) for b in bufs]
    dtypes = {a.dtype for a in out}
    if len(dtypes) != 1:
        raise MPIError(
            ErrorCode.ERR_TYPE,
            f"{what} buffers must share one dtype, got {sorted(map(str, dtypes))}",
        )
    if out:
        # check the ORIGINAL dtype here: the padded staging array is
        # jnp-converted before run_sharded's own narrowing check can
        # see the user's 64-bit buffer
        from .driver import _check_no_narrowing

        _check_no_narrowing(out[0])
    return out


def _counts_matrix(counts, n: int) -> np.ndarray:
    c = np.asarray(counts, dtype=np.int64)
    if c.shape != (n, n) or (c < 0).any():
        raise MPIError(
            ErrorCode.ERR_COUNT,
            f"need a non-negative ({n},{n}) count matrix, got {c.shape}",
        )
    return c


# ---------------------------------------------------------------------------
# alltoallv
# ---------------------------------------------------------------------------

def _skew_cap(c: np.ndarray) -> int:
    """Padding cap for a skewed count matrix.

    The padded kernel moves n·n·cmax elements regardless of counts, so
    ONE hot (rank, rank) pair makes every pair pay cmax. When cmax
    exceeds ``coll_alltoallv_skew_factor`` × the median nonzero count,
    the kernel's pad is capped at the 90th-percentile count and the
    few hot pairs' tails travel pairwise instead (the reference's
    linear send/recv loop pays per-pair counts natively; this hybrid
    recovers that property for the outliers while the bulk stays one
    compiled program)."""
    from ..mca import var as mca_var

    nz = c[c > 0]
    if nz.size <= 1:
        return int(c.max()) if c.size else 1
    cmax = int(nz.max())
    factor = int(mca_var.get("coll_alltoallv_skew_factor", 4))
    med = max(1, int(np.median(nz)))
    if factor > 0 and cmax > factor * med:
        return max(1, int(np.quantile(nz, 0.9)))
    return cmax


def alltoallv(comm, sendbufs: Sequence, sendcounts, *,
              kernel: str = "lax") -> List:
    """Every rank sends ``sendcounts[i][j]`` elements to rank j.

    ``sendbufs[i]`` = rank i's send buffer: the chunks for ranks
    0..n-1 back to back (MPI's sdispls are implicit/contiguous; pass
    pre-sliced data for the general displacement case). Returns
    ``recv[i]`` = concatenation of chunks from ranks 0..n-1 in source
    order — exactly MPI_Alltoallv's receive layout.

    Skewed count matrices are mitigated (see :func:`_skew_cap`): the
    padded kernel's cap is bounded at a count quantile and hot pairs'
    overflow tails are delivered host-side at the driver edge
    (numpy slices concatenated into the receive buffers — they never
    traverse a kernel or transport), accounted in the
    ``vcoll_alltoallv_overflow_elems`` pvar.
    """
    rec = _obs.enabled  # capture once: flag may flip mid-call
    t_edge = _time.perf_counter() if rec else 0.0
    n = comm.size
    bufs = _as_1d_arrays(sendbufs, n, "alltoallv")
    c = _counts_matrix(sendcounts, n)
    for i in range(n):
        if bufs[i].shape[0] != int(c[i].sum()):
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"alltoallv rank {i}: buffer has {bufs[i].shape[0]} "
                f"elements, counts sum to {int(c[i].sum())}",
            )
    cap = _skew_cap(c)
    dtype = bufs[0].dtype
    base_c = np.minimum(c, cap)
    padded = np.zeros((n, n, cap), dtype=dtype)
    offs = np.concatenate(
        [np.zeros((n, 1), np.int64), np.cumsum(c, axis=1)], axis=1
    )
    overflow: dict = {}
    overflow_elems = 0
    for i in range(n):
        for j in range(n):
            k = int(c[i, j])
            kb = int(base_c[i, j])
            if kb:
                padded[i, j, :kb] = bufs[i][offs[i, j]:offs[i, j] + kb]
            if k > kb:  # hot pair: tail travels pairwise
                overflow[(i, j)] = bufs[i][offs[i, j] + kb:offs[i, j] + k]
                overflow_elems += k - kb

    body = (spmd.alltoall_lax if kernel == "lax"
            else spmd.alltoall_pairwise)
    out = run_sharded(
        comm, (kernel, "alltoallv", n, cap, str(dtype)),
        lambda xb: body(xb, AXIS, n), jnp.asarray(padded),
    )
    _padded_elems.add(n * n * cap)
    _overflow_elems.add(overflow_elems)
    out = np.asarray(out)  # (n, n, cap); out[i, j] = chunk j -> i
    recv = []
    for i in range(n):
        parts = []
        for j in range(n):
            kb = int(base_c[j, i])
            part = out[i, j, :kb]
            tail = overflow.get((j, i))
            if tail is not None:
                part = np.concatenate([part, tail])
            parts.append(part)
        recv.append(jnp.asarray(np.concatenate(parts) if parts
                                else np.zeros((0,), dtype)))
    if rec:
        # whole-edge span (pad + kernel + overflow delivery); the
        # kernel's own coll-layer span nests inside it in the trace
        _obs.record(
            "alltoallv", "vcoll", t_edge, _time.perf_counter() - t_edge,
            nbytes=int((n * n * cap + overflow_elems) * dtype.itemsize),
            comm_id=comm.cid,
        )
    return recv


# ---------------------------------------------------------------------------
# allgatherv / gatherv
# ---------------------------------------------------------------------------

def allgatherv(comm, sendbufs: Sequence, *, kernel: str = "lax"):
    """Concatenate every rank's (ragged) buffer in rank order; the
    result is identical on all ranks, returned once."""
    rec = _obs.enabled
    t_edge = _time.perf_counter() if rec else 0.0
    n = comm.size
    bufs = _as_1d_arrays(sendbufs, n, "allgatherv")
    counts = [b.shape[0] for b in bufs]
    cmax = max(1, max(counts))
    dtype = bufs[0].dtype
    padded = np.zeros((n, cmax), dtype=dtype)
    for i, b in enumerate(bufs):
        padded[i, : counts[i]] = b

    if kernel == "ring":
        body = lambda xb: spmd.allgather_ring(xb, AXIS, n)
    else:
        body = lambda xb: lax.all_gather(xb, AXIS, axis=0)
    out = run_sharded(
        comm, (kernel, "allgatherv", n, cmax, str(dtype)), body,
        jnp.asarray(padded),
    )
    # (n, n, cmax): row r is rank r's gathered copy; all rows identical
    # — fetch only rank 0's shard, not n replicated copies
    g = np.asarray(out[0])
    result = jnp.asarray(
        np.concatenate([g[i, : counts[i]] for i in range(n)])
    )
    if rec:
        _obs.record("allgatherv", "vcoll", t_edge,
                    _time.perf_counter() - t_edge,
                    nbytes=int(n * cmax * dtype.itemsize),
                    comm_id=comm.cid)
    return result


def gatherv(comm, sendbufs: Sequence, root: int, *, kernel: str = "lax"):
    """Root receives the rank-order concatenation (other ranks' recv
    buffers are undefined in MPI).

    Root-respecting cost model: the reference's gatherv is LINEAR —
    non-root ranks send exactly their own buffer and only root receives
    (``coll_base_gatherv`` linear variant); no rank pays an allgather.
    Driver mode's analogue of "root receives rank i's message" is a
    host-side read of each rank's (already rank-local) buffer, so the
    correct implementation is edge concatenation with a completion
    barrier — NO compiled all-to-all-style collective, and no
    per-rank O(total) receive buffers. ``kernel`` is accepted for API
    symmetry with :func:`allgatherv` but unused.
    """
    n = comm.size
    if not 0 <= root < n:
        raise MPIError(ErrorCode.ERR_ROOT, f"bad root {root}")
    bufs = _as_1d_arrays(sendbufs, n, "gatherv")
    comm.barrier()
    return jnp.asarray(np.concatenate(bufs))


# ---------------------------------------------------------------------------
# scatterv
# ---------------------------------------------------------------------------

def scatterv(comm, sendbuf, counts: Sequence[int], root: int) -> List:
    """Root's buffer split into ``counts[i]`` elements for rank i."""
    n = comm.size
    if not 0 <= root < n:
        raise MPIError(ErrorCode.ERR_ROOT, f"bad root {root}")
    counts = [int(k) for k in counts]
    if len(counts) != n or any(k < 0 for k in counts):
        raise MPIError(
            ErrorCode.ERR_COUNT,
            f"scatterv needs {n} non-negative counts, got {counts}",
        )
    buf = np.asarray(sendbuf).reshape(-1)
    if buf.shape[0] != sum(counts):
        raise MPIError(
            ErrorCode.ERR_COUNT,
            f"scatterv root buffer has {buf.shape[0]} elements, counts "
            f"sum to {sum(counts)}",
        )
    cmax = max(1, max(counts) if counts else 1)
    dtype = buf.dtype
    # only root's slice carries data (bcast-masked under the hood)
    padded = np.zeros((n, n, cmax), dtype=dtype)
    off = 0
    for j, k in enumerate(counts):
        padded[root, j, :k] = buf[off:off + k]
        off += k

    def body(xb):
        full = spmd.bcast_masked_psum(xb, xb.dtype, AXIS, root)
        rank = lax.axis_index(AXIS)
        return jnp.take(full, rank, axis=0)

    out = run_sharded(
        comm, ("xla", "scatterv", n, cmax, str(dtype), root), body,
        jnp.asarray(padded),
    )
    out = np.asarray(out)  # (n, cmax)
    return [jnp.asarray(out[i, : counts[i]]) for i in range(n)]


# ---------------------------------------------------------------------------
# reduce_scatter (general, per-rank counts)
# ---------------------------------------------------------------------------

def reduce_scatter(comm, x, recvcounts: Sequence[int], op: Op, *,
                   kernel: str = "lax") -> List:
    """General MPI_Reduce_scatter: reduce the full buffer, rank i keeps
    the segment of length ``recvcounts[i]``.

    ``x``: (size, total) — per-rank contribution rows,
    total = sum(recvcounts). Returns one array per rank. MINLOC/MAXLOC
    pairs are accepted: ``x = (values, indices)`` and each returned
    segment is a (values, indices) pair.
    """
    n = comm.size
    recvcounts = [int(k) for k in recvcounts]
    if len(recvcounts) != n or any(k < 0 for k in recvcounts):
        raise MPIError(
            ErrorCode.ERR_COUNT,
            f"reduce_scatter needs {n} non-negative counts",
        )
    if op.is_pair_op:
        vals, idxs = x
        vals, idxs = np.asarray(vals), np.asarray(idxs)
        total = sum(recvcounts)
        for nm, a in (("values", vals), ("indices", idxs)):
            if a.shape[0] != n or a.reshape(n, -1).shape[1] != total:
                raise MPIError(
                    ErrorCode.ERR_COUNT,
                    f"reduce_scatter needs {nm} shaped ({n}, {total}), "
                    f"got {a.shape}",
                )
        # the pair allreduce kernel does the reduction; segments are
        # sliced at the driver edge (ragged counts never retrace)
        rv, ri = comm.allreduce((vals.reshape(n, total),
                                 idxs.reshape(n, total)), op)
        rv0, ri0 = np.asarray(rv)[0], np.asarray(ri)[0]
        offs = np.concatenate([[0], np.cumsum(recvcounts)])
        return [
            (jnp.asarray(rv0[offs[i]:offs[i] + recvcounts[i]]),
             jnp.asarray(ri0[offs[i]:offs[i] + recvcounts[i]]))
            for i in range(n)
        ]
    x = np.asarray(x)
    total = sum(recvcounts)
    if x.shape[0] != n or x.reshape(n, -1).shape[1] != total:
        raise MPIError(
            ErrorCode.ERR_COUNT,
            f"reduce_scatter needs x shaped (size, {total}), got {x.shape}",
        )
    x = x.reshape(n, total)
    cmax = max(1, max(recvcounts) if recvcounts else 1)
    dtype = x.dtype
    ident = op.identity_for(dtype) if op.identity is not None else 0
    padded = np.full((n, n, cmax), ident, dtype=dtype)
    offs = np.concatenate([[0], np.cumsum(recvcounts)])
    for r in range(n):
        for j, k in enumerate(recvcounts):
            if k:
                padded[r, j, :k] = x[r, offs[j]:offs[j] + k]

    if kernel == "ring" and op.commutative and op.identity is not None:
        def body(xb):
            return spmd.reduce_scatter_ring(
                xb.reshape(-1), op, AXIS, n
            )
    else:
        def body(xb):
            red = spmd.allreduce_lax(xb, op, AXIS)
            rank = lax.axis_index(AXIS)
            return jnp.take(red, rank, axis=0)

    out = run_sharded(
        comm, (kernel, "reduce_scatter", op, n, cmax, str(dtype)),
        body, jnp.asarray(padded),
    )
    out = np.asarray(out).reshape(n, cmax)
    return [jnp.asarray(out[i, : recvcounts[i]]) for i in range(n)]
