"""btl components: self / ici / dcn / host.

Mapping from the reference's transport zoo (``ompi/mca/btl/``):

  self  loopback (``btl/self``)               -> same-rank device no-op
  ici   intra-slice device fabric (``btl/sm``/``btl/vader`` role:
        the fast, always-there local fabric)  -> direct d2d move the
        runtime routes over the ICI torus
  dcn   inter-slice / inter-host network (``btl/tcp``/``btl/openib``
        role)                                 -> d2d move routed over
        DCN, distinct size constants + ranking
  host  explicit host-memory staging bounce (the CUDA-style staged
        fallback, ``btl/smcuda`` host path)   -> device→host→device

Reachability uses the modex endpoint records (slice_index /
process_index — the business-card fields), exactly how add_procs
decides per-peer BTL eligibility (``ompi/mca/btl/btl.h:810-816``).

Size constants keep the reference's *shape* (eager ≪ max_send,
network eager ≪ local eager — btl_tcp_component.c:268-270 64K/128K,
btl_sm_component.c:244-246 4K/32K) rescaled to fabric reality: ICI
moves HBM arrays, so its limits are MiB-scale.
"""

from __future__ import annotations

import itertools
import os
import threading
import uuid

import numpy as np

from .. import obs as _obs
from ..mca import component as mca_component
from ..mca import pvar as _pvar
from ..mca import var as mca_var
from ..native import USER_TAG_BASE
from ..utils.errors import ErrorCode, MPIError
from . import base

#: frame magics: every staged frame self-identifies, so a receiver that
#: timed out mid-transfer (leaving orphan chunks queued/stashed) can
#: resynchronize — unknown or stale frames are discarded, never parsed
#: as a header or delivered to the wrong transfer
_HDR_MAGIC = "SGH1"
_CHUNK_MAGIC = b"SGC1"
#: pipelined staged framing (``wire_pipeline_segsize`` > 0): chunks
#: carry an explicit fragment index so the receiver reassembles into a
#: PREALLOCATED buffer at ``idx * segsize`` (no join copy) and a late
#: or reordered fragment still lands at its own offset
_HDR2_MAGIC = "SGH2"
_CHUNK2_MAGIC = b"SGC2"
_xfer_ids = itertools.count(1)

#: the zero-copy ledger, split honestly: ``strict`` counts bytes that
#: never touched a Python-side copy at all (nativewire vectored
#: writev / shm-ring memcpy / dlpack handoff); ``sliced`` counts bytes
#: that moved as memoryview slices or preallocated-buffer views — one
#: staging copy at the OOB boundary, no whole-array ``tobytes()``.
#: The historical name ``wire_bytes_zero_copy`` (which used to count
#: the sliced discipline) stays as a summing alias, the same way
#: ``hier_inter_msgs`` aliases its sent+recvd split.
_zero_copy_strict = _pvar.counter(
    "wire_bytes_zero_copy_strict",
    "payload bytes moved with no Python-side copy at all: vectored "
    "writev straight from the source buffer, shm-ring transfers into "
    "the preallocated reassembly buffer (the nativewire datapath)",
)
_sliced_bytes = _pvar.counter(
    "wire_bytes_sliced",
    "payload bytes shipped as memoryview slices over the source "
    "buffer or landed in preallocated-buffer views instead of "
    "whole-array copies (one staging copy at the OOB boundary)",
)
_zero_copy_bytes = _pvar.PVARS.register(
    "wire_bytes_zero_copy", _pvar.PvarClass.COUNTER,
    "zero-copy-discipline wire bytes "
    "(alias: wire_bytes_zero_copy_strict + wire_bytes_sliced)",
    getter=lambda: _zero_copy_strict.read() + _sliced_bytes.read(),
)
_frags_inflight = _pvar.highwatermark(
    "wire_frags_inflight",
    "high watermark of pipeline fragments announced but not yet "
    "reassembled for a single staged transfer",
)


def register_pipeline_vars() -> None:
    """Wire-pipeline cvars live HERE (the transport that reads them)
    so any staged-path user — the wire router, tpu-tune's loopback
    sweep, a bare DcnBtl — sees them registered; runtime/wire.py
    re-exports through its own register_vars."""
    mca_var.register(
        "wire_pipeline_segsize", "size", 1 << 20,
        "Bytes per in-flight wire fragment for cross-process payloads "
        "(the ob1 RNDV pipeline's fragment size): payloads cross as "
        "zero-copy memoryview slices reassembled into a preallocated "
        "receive buffer; 0 restores the legacy single-pass tobytes() "
        "framing",
    )
    mca_var.register(
        "wire_pipeline_depth", "int", 4,
        "Fragments enqueued per destination per round-robin turn when "
        "one exchange posts transfers to several peers (the sliding "
        "in-flight window of coll_send_all striping)",
    )


register_pipeline_vars()  # idempotent; read on every staged send


def _check_user_tag(tag: int) -> None:
    if tag < USER_TAG_BASE:
        raise MPIError(
            ErrorCode.ERR_TAG,
            f"transport payload tags start at {USER_TAG_BASE} (below "
            "is the coordinator/pubsub control plane — a staged frame "
            "there would be consumed as a control frame)",
        )


def _pack_dtype_shape(buf, dtype, shape) -> None:
    """THE array-metadata wire format (dtype string, comma-joined
    shape) — single definition, so staged/shm headers and the
    plan-time :class:`FrameTemplate` can never desynchronize."""
    buf.pack_string(str(dtype))
    buf.pack_string(",".join(str(d) for d in shape))


def _pack_array_header(buf, arr: np.ndarray, *extra_front) -> None:
    """Array-metadata wire format shared by the staged (DCN) and shm
    transports: [*extra_front,] dtype, comma-joined shape."""
    for f in extra_front:
        buf.pack_string(f)
    _pack_dtype_shape(buf, arr.dtype, arr.shape)


def _unpack_array_header(buf):
    """Returns (dtype, shape) from the shared wire format."""
    dtype = np.dtype(buf.unpack_string())
    shape_s = buf.unpack_string()
    shape = tuple(int(d) for d in shape_s.split(",")) if shape_s else ()
    return dtype, shape


def _int64_rec(v: int) -> bytes:
    """One single-value DSS int64 record — byte-identical to
    ``DssBuffer().pack_int64(v).tobytes()`` (native/dss.cc put_header:
    1-byte type tag DSS_INT64, u32 LE count, LE values) without a
    native buffer allocation per call. The live per-send header fields
    (transfer id, CRC) compose through this."""
    return b"\x01\x01\x00\x00\x00" + \
        int(v).to_bytes(8, "little", signed=True)


class FrameTemplate:
    """Plan-time precomposed SGH2/SGC2 framing for ONE fixed
    ``(shape, dtype, segsize)`` transfer slot — the frozen-plan send
    path of :mod:`coll.plan`.

    Everything a header needs that does not depend on the send
    instant is packed ONCE here: the magic/dtype/shape/chunk-count
    records as raw DSS byte strings (DSS records are self-delimiting,
    so concatenated record strings unpack exactly like one
    sequentially-packed buffer) and the per-fragment slice offsets.
    A steady-state send then composes ``pre + xfer + mid + crc`` from
    four byte strings and slices the source memoryview at the stored
    offsets — no per-message dtype/shape stringification, no repeated
    DSS packing, no cvar reads. The transfer id and payload CRC are
    genuinely per-send (receiver resync and end-to-end integrity) and
    stay live. The wire format is BYTE-IDENTICAL to
    :meth:`DcnBtl.staged_frames`'s, so receivers need no changes and
    bitwise parity with the interpreted path is structural."""

    __slots__ = ("shape", "dtype", "nbytes", "nchunks", "chunk",
                 "offsets", "pre", "mid", "idx_tails")

    def __init__(self, shape, dtype, segsize: int) -> None:
        from ..native import DssBuffer

        self.shape = tuple(int(d) for d in shape)
        self.dtype = np.dtype(dtype)
        size = int(np.prod(self.shape, dtype=np.int64)) if self.shape \
            else 1
        self.nbytes = size * self.dtype.itemsize
        self.chunk = max(1, int(segsize))
        self.nchunks = max(1, -(-self.nbytes // self.chunk))
        self.offsets = tuple(i * self.chunk for i in range(self.nchunks))
        self.pre = DssBuffer().pack_string(_HDR2_MAGIC).tobytes()
        mid = DssBuffer()
        _pack_dtype_shape(mid, self.dtype, self.shape)
        mid.pack_int64([self.nchunks, self.chunk])
        self.mid = mid.tobytes()
        self.idx_tails = tuple(int(i).to_bytes(8, "big")
                               for i in range(self.nchunks))

    def matches(self, arr: np.ndarray) -> bool:
        return arr.shape == self.shape and arr.dtype == self.dtype

    def header(self, xfer: int, crc: int) -> bytes:
        return b"".join((self.pre, _int64_rec(xfer),
                         self.mid, _int64_rec(crc)))

    def sg_lists(self, mv, xfer: int, crc: int):
        """Yield each wire frame of one transfer as a scatter-gather
        PART LIST instead of joined bytes: the header frame, then
        ``[magic+xfer, idx_tail, source_slice]`` per fragment. The
        nativewire datapath hands these lists to ``writev``/the shm
        ring, so the fragment payload goes from the source buffer to
        the wire without ever being joined into a Python bytes —
        ``b"".join``-ing each list reproduces the staged frames
        byte-identically (the identity the tests pin)."""
        yield [self.header(xfer, crc)]
        xb = _CHUNK2_MAGIC + int(xfer).to_bytes(8, "big")
        chunk = self.chunk
        for off, tail in zip(self.offsets, self.idx_tails):
            yield [xb, tail, mv[off:off + chunk]]


def plan_frame_template(shape, dtype, segsize: int) -> FrameTemplate:
    """Build the frozen framing for one planned transfer slot (see
    :class:`FrameTemplate`)."""
    return FrameTemplate(shape, dtype, segsize)


#: interpreted-path template cache: ``staged_frames`` used to re-pack
#: the constant header records (magic, dtype, shape, chunking) through
#: a fresh native DssBuffer on EVERY transfer; steady-state transfers
#: repeat a handful of (shape, dtype, segsize) slots, so the frozen
#: template is cached and only the per-send fields (xfer id, CRC) are
#: composed live. Bounded: an adversarial shape churn clears it rather
#: than growing without limit.
_TEMPLATE_CACHE: dict = {}
_TEMPLATE_CACHE_MAX = 512
_template_lock = threading.Lock()


def _template_for(shape, dtype, segsize: int) -> FrameTemplate:
    key = (tuple(shape), str(dtype), int(segsize))
    with _template_lock:
        tpl = _TEMPLATE_CACHE.get(key)
        if tpl is None:
            if len(_TEMPLATE_CACHE) >= _TEMPLATE_CACHE_MAX:
                _TEMPLATE_CACHE.clear()
            tpl = _TEMPLATE_CACHE[key] = FrameTemplate(
                shape, dtype, segsize)
        return tpl


_stash_guard = threading.Lock()


def _ep_stash(oob_ep):
    """The endpoint's frame stash + its lock, created once. Multiple
    threads poll stashed_recv on one endpoint concurrently (the window
    service, the nbc worker's coll_recv, the pml drain): iteration and
    setdefault on the dict must not race."""
    with _stash_guard:
        stash = getattr(oob_ep, "_dcn_stash", None)
        if stash is None:
            stash = oob_ep._dcn_stash = {}
            oob_ep._dcn_stash_lock = threading.Lock()
        return stash, oob_ep._dcn_stash_lock


def stashed_recv(oob_ep, want_src, tag: int, deadline: float):
    """Next (src, payload) for ``tag``, matched by source: frames from
    other senders interleaved on the same tag are stashed on the
    endpoint (the OOB recv filters by tag only) and served to their own
    consumer later — two concurrent transfers on one tag must not
    corrupt each other. ``want_src=None`` takes the oldest stashed
    frame from any source, else the next live frame from ``want_src``.

    Shared by every consumer that multiplexes one OOB endpoint and tag
    across multiple senders (the staged DCN path and the shm handoff).
    """
    import time as _time

    stash, lock = _ep_stash(oob_ep)
    with lock:
        if want_src is None:
            for (s, t), q in stash.items():
                if t == tag and q:
                    return s, q.pop(0)
        else:
            q = stash.get((want_src, tag))
            if q:
                return want_src, q.pop(0)
    while True:
        left = max(1, int((deadline - _time.monotonic()) * 1000))
        src, _, raw = oob_ep.recv(tag=tag, timeout_ms=left)
        if want_src is None or src == want_src:
            return src, raw
        with lock:
            stash.setdefault((src, tag), []).append(raw)


class SelfBtl(base.BtlModule):
    """Loopback: src == dst. Arrays are immutable; a self-send needs no
    copy at all (the reference's btl/self memcpys because its buffers
    are mutable — ours provably cannot alias a future write)."""

    NAME = "self"
    EAGER_LIMIT = 1 << 62
    MAX_SEND_SIZE = 1 << 62
    LATENCY = 0
    BANDWIDTH = 10 ** 9
    EXCLUSIVITY = 64 * 1024  # btl/self owns loopback outright

    def reachable(self, src_ep, dst_ep) -> bool:
        return src_ep.rank == dst_ep.rank

    def move_segment(self, data, dst_device):
        import jax

        if getattr(data, "device", None) == dst_device:
            return data
        return jax.device_put(data, dst_device)


class IciBtl(base.BtlModule):
    """Intra-slice device-to-device over the ICI torus.

    ``jax.device_put`` between two accelerators in one slice compiles
    to a direct device copy the runtime routes over ICI — no host
    bounce. On the CPU simulator mesh the same call is an in-process
    buffer handoff; the component still selects, so CI exercises the
    ICI decision logic clusterlessly (SURVEY §4 simulator strategy).
    """

    NAME = "ici"
    EAGER_LIMIT = 1 * 1024 * 1024
    MAX_SEND_SIZE = 64 * 1024 * 1024
    LATENCY = 1
    BANDWIDTH = 45_000  # ~45 GB/s/link ICI-scale ranking input
    EXCLUSIVITY = 1024

    def reachable(self, src_ep, dst_ep) -> bool:
        # same controller process only: a peer PROCESS's devices are
        # not addressable here even on the same slice — those pairs
        # belong to shm/dcn (under a jax.distributed global runtime the
        # SPMD collective path, not per-pair moves, crosses processes)
        return (
            src_ep.rank != dst_ep.rank
            and src_ep.platform == dst_ep.platform
            and src_ep.slice_index == dst_ep.slice_index
            and src_ep.process_index == dst_ep.process_index
        )

    def move_segment(self, data, dst_device):
        import jax

        return jax.device_put(data, dst_device)


class DcnBtl(base.BtlModule):
    """Inter-slice / inter-host transfers over the data-center network.

    TWO genuinely distinct paths, selected by a capability check:

    * **intra-controller** (the destination device is addressable by
      this process — cross-slice in a single-controller job):
      ``device_put``, which the runtime routes over DCN between
      slices. This is the only case where a direct device move is
      even expressible.
    * **cross-process** (multi-controller: the peer's devices are NOT
      addressable here — ``device_put`` would be a silent lie):
      :meth:`send_staged`/:meth:`recv_staged` — a chunked host-staged
      transfer over the native OOB (the btl/tcp role played
      honestly), with its own chunk/byte accounting, segmented at
      ``max_send_size`` exactly like the reference's pipelined
      protocol (``btl.h:802``). ``move_segment`` on an unaddressable
      device raises ERR_UNREACH loudly instead of claiming the route.
    """

    NAME = "dcn"
    EAGER_LIMIT = 64 * 1024          # tcp eager (btl_tcp_component.c:268)
    MAX_SEND_SIZE = 4 * 1024 * 1024
    LATENCY = 25
    BANDWIDTH = 12_500               # 100 Gb/s-class NIC
    EXCLUSIVITY = 512

    def reachable(self, src_ep, dst_ep) -> bool:
        return src_ep.rank != dst_ep.rank and (
            src_ep.slice_index != dst_ep.slice_index
            or src_ep.process_index != dst_ep.process_index
        )

    @property
    def staged_chunks_pvar(self):
        return self._cached_counter(
            "_staged_chunks_pvar", "btl_dcn_staged_chunks",
            "OOB-staged DCN chunks transferred")

    @property
    def staged_bytes_pvar(self):
        return self._cached_counter(
            "_staged_bytes_pvar", "btl_dcn_staged_bytes",
            "OOB-staged DCN bytes transferred")

    def move_segment(self, data, dst_device):
        import jax

        # the actual multi-controller condition: a peer process's
        # device is never addressable here (device_put would lie)
        if int(getattr(dst_device, "process_index", 0)) != \
                jax.process_index():
            from ..utils.errors import ErrorCode, MPIError

            raise MPIError(
                ErrorCode.ERR_UNREACH,
                f"device {dst_device} belongs to another process; a "
                "multi-controller DCN transfer must go through "
                "DcnBtl.send_staged/recv_staged over the OOB "
                "(device_put across controllers is not a real route)",
            )
        return jax.device_put(data, dst_device)

    # -- cross-process staged path (the honest multi-controller route) ----
    _recv_from = staticmethod(stashed_recv)  # kept as the historical name

    #: (generation, value) stamp for the resolved segsize — the cvar
    #: used to be re-read through the registry lock on EVERY staged
    #: send; now a stale write-generation is the only thing that
    #: triggers a re-resolve (one attr read + int compare per send)
    _segsize_cache = (-1, 0)

    def pipeline_segsize(self) -> int:
        """Effective pipelined-fragment size: the ``wire_pipeline_segsize``
        cvar clamped to this btl's max frame size; 0 = the legacy
        monolithic ``tobytes()`` framing (exact pre-pipeline path).
        Resolved once per registry write generation, not per message."""
        gen, val = self._segsize_cache
        now = mca_var.VARS.generation
        if gen == now:
            return val
        # gen captured BEFORE the value read: a concurrent cvar write
        # that lands between the two bumps the generation past `now`,
        # so the possibly-stale value cached here can never be served
        # once the writer is done (stamping the generation read AFTER
        # would mask that write until an unrelated one)
        seg = int(mca_var.get("wire_pipeline_segsize", 0) or 0)
        if seg <= 0:
            seg = 0
        else:
            seg = min(seg, max(1, self.max_send_size))
        self._segsize_cache = (now, seg)
        return seg

    def staged_frames(self, data, *, segsize: int):
        """Yield the wire frames of ONE pipelined staged transfer:
        header first, then idx-stamped fragments whose payloads are
        memoryview slices over the source buffer (no whole-array
        ``tobytes()`` materialization). The caller owns the actual
        ``oob_ep.send`` calls, so frames from several transfers bound
        for DIFFERENT peers can be striped round-robin (the sliding
        in-flight window the wire router's ``coll_send_all`` drives).

        Sender-side pvar accounting lives HERE — the single place that
        knows frames — so ``send_staged`` and the router's striping
        path can never drift: chunks count as they are yielded, bytes
        count once when the stream completes."""
        import zlib

        arr = np.ascontiguousarray(np.asarray(data))
        # uint8 reinterpret instead of memoryview(arr): extension
        # dtypes (bfloat16) don't implement the buffer protocol
        mv = memoryview(arr.reshape(-1).view(np.uint8)) if arr.size \
            else memoryview(b"")
        # constant header records come from the cached frozen template
        # (same framing code the planned path runs — byte-identity is
        # structural); only xfer id and CRC are composed per send
        tpl = _template_for(arr.shape, arr.dtype, segsize)
        xfer = next(_xfer_ids)
        # end-to-end payload CRC (the opal_datatype_checksum role):
        # one read pass over the source view, no copy
        yield tpl.header(xfer, zlib.crc32(mv))
        xb = _CHUNK2_MAGIC + int(xfer).to_bytes(8, "big")
        chunk = tpl.chunk
        for off, tail in zip(tpl.offsets, tpl.idx_tails):
            sl = mv[off:off + chunk]
            _sliced_bytes.add(len(sl))
            yield b"".join((xb, tail, sl))
            self.staged_chunks_pvar.add()
        self.staged_bytes_pvar.add(tpl.nbytes)

    def planned_frames(self, data, tpl: FrameTemplate):
        """Yield the wire frames of one staged transfer from a frozen
        :class:`FrameTemplate` — the steady-state send path of a
        compiled schedule plan: precomposed header byte strings plus
        memoryview slices at plan-time offsets. Byte-identical to
        :meth:`staged_frames` for the same array, with the same pvar
        accounting; only the per-send transfer id and payload CRC are
        computed live. A shape/dtype mismatch is a loud plan-integrity
        error, never a silently wrong header."""
        import zlib

        arr = np.ascontiguousarray(np.asarray(data))
        if not tpl.matches(arr):
            raise MPIError(
                ErrorCode.ERR_INTERN,
                f"planned staged transfer: buffer {arr.shape}/"
                f"{arr.dtype} does not match the frozen frame template "
                f"{tpl.shape}/{tpl.dtype} — schedule diverged from its "
                "plan (rebuild the persistent request)",
            )
        mv = memoryview(arr.reshape(-1).view(np.uint8)) if arr.size \
            else memoryview(b"")
        xfer = next(_xfer_ids)
        yield tpl.header(xfer, zlib.crc32(mv))
        xb = _CHUNK2_MAGIC + int(xfer).to_bytes(8, "big")
        chunk = tpl.chunk
        for off, tail in zip(tpl.offsets, tpl.idx_tails):
            sl = mv[off:off + chunk]
            _sliced_bytes.add(len(sl))
            yield b"".join((xb, tail, sl))
            self.staged_chunks_pvar.add()
        self.staged_bytes_pvar.add(tpl.nbytes)

    def send_staged(self, oob_ep, peer_nid: int, tag: int, data) -> int:
        """Stream ``data`` to ``peer_nid`` over the OOB in chunks.
        Returns the number of chunks sent. Every frame carries a
        transfer id so a receiver that abandoned an earlier transfer
        resynchronizes instead of parsing orphan chunks as headers.

        With ``wire_pipeline_segsize`` > 0 the transfer is pipelined:
        segsize-bounded fragments sliced straight off the source
        buffer (:meth:`staged_frames`); with 0 the exact legacy
        monolithic path runs (whole-array ``tobytes()``, max_send_size
        chunks, ordered join on receive)."""
        import time as _time

        from ..native import DssBuffer

        _check_user_tag(tag)
        rec = _obs.enabled  # capture once: flag may flip mid-send
        t0 = _time.perf_counter() if rec else 0.0
        seg = self.pipeline_segsize()
        if seg > 0:
            nframes = 0
            for frame in self.staged_frames(data, segsize=seg):
                oob_ep.send(peer_nid, tag, frame)
                nframes += 1
            if rec and _obs.enabled:
                _obs.record("btl_staged_send", "btl", t0,
                            _time.perf_counter() - t0,
                            nbytes=int(getattr(data, "nbytes", 0)),
                            peer=peer_nid - 1)
            return nframes - 1  # header is not a chunk
        xfer = next(_xfer_ids)
        arr = np.ascontiguousarray(np.asarray(data))
        raw = arr.tobytes()
        chunk = max(1, self.max_send_size)
        nchunks = max(1, -(-len(raw) // chunk))
        hdr = DssBuffer()
        hdr.pack_string(_HDR_MAGIC)
        hdr.pack_int64(xfer)
        _pack_array_header(hdr, arr)
        hdr.pack_int64(nchunks)
        # end-to-end payload CRC (the opal_datatype_checksum role for
        # the cross-process wire): the receiver verifies the
        # reassembled bytes, catching corruption anywhere between the
        # sender's buffer and reassembly
        import zlib

        hdr.pack_int64(zlib.crc32(raw))
        oob_ep.send(peer_nid, tag, hdr.tobytes())
        xb = _CHUNK_MAGIC + int(xfer).to_bytes(8, "big")
        for i in range(nchunks):
            oob_ep.send(peer_nid, tag,
                        xb + raw[i * chunk:(i + 1) * chunk])
            self.staged_chunks_pvar.add()
        self.staged_bytes_pvar.add(len(raw))
        if rec and _obs.enabled:
            _obs.record("btl_staged_send", "btl", t0,
                        _time.perf_counter() - t0,
                        nbytes=len(raw), peer=peer_nid - 1)
        return nchunks

    def recv_staged(self, oob_ep, tag: int, *, src=None,
                    dst_device=None, timeout_ms: int = 30_000,
                    first=None):
        """Reassemble one staged transfer; places the result on
        ``dst_device`` (default: this process's first device). All
        chunk frames are matched to the header's source, so transfers
        from different peers on one tag cannot interleave. The
        receiver accepts BOTH framings regardless of its local cvar:
        legacy ordered chunks are joined; pipelined idx-stamped
        fragments land in a preallocated buffer at their own offsets
        and the result is a ``np.frombuffer`` view over it (no join
        copy). ``first`` is an already-popped ``(src_nid, frame)``
        pair to resume from — the wire router's any-source reaping
        peeks the first frame to pick the readiest peer."""
        import time as _time

        import jax

        from ..native import DssBuffer

        _check_user_tag(tag)
        rec = _obs.enabled  # capture once: flag may flip mid-recv
        t_obs = _time.perf_counter() if rec else 0.0
        deadline = _time.monotonic() + timeout_ms / 1000
        # resync: discard frames until a valid header (orphan chunks
        # from an abandoned transfer must not be parsed as headers)
        while True:
            if first is not None:
                src_got, hraw = first
                first = None
            else:
                src_got, hraw = self._recv_from(oob_ep, src, tag,
                                                deadline)
            try:
                hdr = DssBuffer(hraw)
                magic = hdr.unpack_string()
                if magic not in (_HDR_MAGIC, _HDR2_MAGIC):
                    continue
                (xfer,) = hdr.unpack_int64()
                dtype, shape = _unpack_array_header(hdr)
                if magic == _HDR2_MAGIC:
                    nchunks, chunk = hdr.unpack_int64(2)
                else:
                    (nchunks,) = hdr.unpack_int64()
                    chunk = 0
                (crc,) = hdr.unpack_int64()
            except MPIError:
                continue  # a chunk frame: skip to the next header
            src = src_got
            break
        import zlib

        if magic == _HDR2_MAGIC:
            nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            if nbytes < 0 or any(d < 0 for d in shape):
                raise MPIError(ErrorCode.ERR_TRUNCATE,
                               f"staged transfer {xfer}: malformed "
                               f"shape {shape}")
            buf = bytearray(nbytes)
            bmv = memoryview(buf)
            want = _CHUNK2_MAGIC + int(xfer).to_bytes(8, "big")
            _frags_inflight.set(int(nchunks))
            got = 0
            while got < int(nchunks):
                _, praw = self._recv_from(oob_ep, src, tag, deadline)
                if not praw.startswith(want):
                    continue  # stale frame from an abandoned transfer
                idx = int.from_bytes(praw[12:20], "big")
                payload = memoryview(praw)[20:]
                off = idx * int(chunk)
                if idx >= int(nchunks) or off + len(payload) > nbytes:
                    raise MPIError(
                        ErrorCode.ERR_TRUNCATE,
                        f"staged transfer {xfer}: fragment {idx} "
                        f"overruns the {nbytes}-byte buffer",
                    )
                bmv[off:off + len(payload)] = payload
                got += 1
                self.staged_chunks_pvar.add()
            if zlib.crc32(bmv) != int(crc):
                raise MPIError(
                    ErrorCode.ERR_TRUNCATE,
                    f"staged transfer {xfer} failed its payload CRC — "
                    "wire corruption or interleaved frames",
                )
            _sliced_bytes.add(nbytes)
            arr = np.frombuffer(buf, dtype=dtype).reshape(shape)
        else:
            want = _CHUNK_MAGIC + int(xfer).to_bytes(8, "big")
            parts = []
            while len(parts) < int(nchunks):
                _, praw = self._recv_from(oob_ep, src, tag, deadline)
                if not praw.startswith(want):
                    continue  # stale chunk from an abandoned transfer
                parts.append(praw[len(want):])
                self.staged_chunks_pvar.add()
            raw = b"".join(parts)
            if zlib.crc32(raw) != int(crc):
                raise MPIError(
                    ErrorCode.ERR_TRUNCATE,
                    f"staged transfer {xfer} failed its payload CRC — "
                    "wire corruption or interleaved frames",
                )
            arr = np.frombuffer(raw, dtype=dtype).reshape(shape)
        self.staged_bytes_pvar.add(arr.nbytes)
        if rec and _obs.enabled:
            _obs.record("btl_staged_recv", "btl", t_obs,
                        _time.perf_counter() - t_obs,
                        nbytes=int(arr.nbytes),
                        peer=(src - 1) if src is not None else -1)
        if dst_device is None:
            dst_device = jax.local_devices()[0]
        return jax.device_put(arr, dst_device)


class ShmBtl(base.BtlModule):
    """Intra-host CROSS-PROCESS device-buffer handoff through POSIX
    shared memory — the btl/vader role (SURVEY §2.4 item 9). The
    payload crosses the process boundary through one mmap'd segment
    (no socket streaming, no per-chunk copies): the sender writes
    device bytes straight into a named segment (one write, no
    intermediate buffer) and posts a control frame (name, dtype,
    shape) over the OOB — the vader "fast box". The receiver maps the
    segment, copies out (jax retains/aliases host buffers handed to
    device_put, so the mapping cannot be unlinked under a live view),
    device_puts, and unlinks — ownership transfers with the frame.
    """

    NAME = "shm"
    EAGER_LIMIT = 32 * 1024
    MAX_SEND_SIZE = 256 * 1024 * 1024
    SUPPORTS_MOVE = False  # out-of-band: send_shm/recv_shm, never the
    #                        BML move lists (which hold movers only) —
    #                        so the latency/bandwidth/exclusivity
    #                        ranking attributes are deliberately left
    #                        at base defaults: selection happens via
    #                        reachable() alone, not move-list ranking

    def reachable(self, src_ep, dst_ep) -> bool:
        # same machine, different controller process: the only pair
        # shape where shm is both possible and needed (same process
        # uses ici/self; cross-host cannot map the segment)
        return (
            src_ep.process_index != dst_ep.process_index
            and bool(getattr(src_ep, "host", ""))
            and getattr(src_ep, "host", "") == getattr(dst_ep, "host", "")
        )

    def move_segment(self, data, dst_device):
        from ..utils.errors import ErrorCode, MPIError

        raise MPIError(
            ErrorCode.ERR_UNREACH,
            "shm is a cross-process transport: use "
            "send_shm/recv_shm with the peer's OOB endpoint",
        )

    @property
    def handoffs_pvar(self):
        return self._cached_counter(
            "_handoffs_pvar", "btl_shm_handoffs",
            "shared-memory segment handoffs")

    @property
    def shm_bytes_pvar(self):
        return self._cached_counter(
            "_shm_bytes_pvar", "btl_shm_bytes",
            "bytes handed off through shm")

    #: default TTL for posted-but-unconsumed segments; per-instance
    #: (set ``module.SEGMENT_TTL_S`` to tune one module without
    #: affecting other jobs' modules in the same process). Generous
    #: (4x the recv default) so a slow-but-live receiver is never
    #: pulled out from under.
    SEGMENT_TTL_S = 120.0

    #: module-level reaper thread: wakes periodically and reaps every
    #: live ShmBtl instance's expired segments, so a sender that STOPS
    #: sending no longer leaks /dev/shm until process exit (reaping
    #: used to happen only on the next send). Instances register in a
    #: weak set — pending segments are per-instance state, so two jobs'
    #: modules in one process never reap each other's segments early.
    _reaper_lock = threading.Lock()
    _reaper_thread = None
    _instances = None  # weakref.WeakSet, created with the reaper

    def __init__(self) -> None:
        import weakref

        #: segments posted but (maybe) never consumed: (name, deadline).
        #: A receiver that times out or dies never learns the name, so
        #: expired segments are reaped (on the next send and by the
        #: timer thread) — without this a retry loop leaks /dev/shm
        #: until the host runs out.
        self._pending_segments: list = []
        self._pending_lock = threading.Lock()
        ShmBtl._register_for_reaping(self)
        # a GC'd module must not take its pending records to the grave
        # (per-comm modules die with their communicator; a one-shot
        # `ShmBtl().send_shm(...)` dies immediately): at collection the
        # records move — deadlines intact — to a class-level orphan
        # list the timer thread keeps reaping. NOT unlinked eagerly:
        # ownership already passed to the receiver, who may be about
        # to map the segment; the TTL grace still applies.
        weakref.finalize(
            self, ShmBtl._adopt_orphans,
            self._pending_segments, self._pending_lock,
        )

    #: (name, deadline) records inherited from GC'd modules; reaped by
    #: the timer thread on the normal TTL schedule
    _orphaned: list = []

    @classmethod
    def _adopt_orphans(cls, pending: list, lock) -> None:
        with lock:
            records = list(pending)
            pending.clear()
        with cls._reaper_lock:
            cls._orphaned.extend(records)

    @classmethod
    def _reap_orphan_list(cls) -> None:
        import time as _time

        from multiprocessing import shared_memory

        now = _time.monotonic()
        with cls._reaper_lock:
            expired = [nd for nd in cls._orphaned if now >= nd[1]]
            cls._orphaned[:] = [nd for nd in cls._orphaned if now < nd[1]]
        for name, _deadline in expired:
            try:
                seg = shared_memory.SharedMemory(name=name)
                seg.close()
                seg.unlink()
            except FileNotFoundError:
                pass

    @classmethod
    def _register_for_reaping(cls, instance) -> None:
        import weakref

        with cls._reaper_lock:
            if cls._instances is None:
                cls._instances = weakref.WeakSet()
            cls._instances.add(instance)
            if cls._reaper_thread is None:
                t = threading.Thread(
                    target=cls._reaper_loop, daemon=True,
                    name="shm-segment-reaper",
                )
                cls._reaper_thread = t
                t.start()

    @classmethod
    def _reaper_loop(cls) -> None:
        import time as _time

        while True:
            _time.sleep(5.0)
            with cls._reaper_lock:
                live = list(cls._instances) if cls._instances else []
            for mod in live:
                try:
                    mod._reap_orphaned_segments()
                except Exception:
                    pass  # a reap failure must never kill the timer
            try:
                cls._reap_orphan_list()
            except Exception:
                pass

    def _reap_orphaned_segments(self) -> None:
        import time as _time

        from multiprocessing import shared_memory

        now = _time.monotonic()
        with self._pending_lock:  # concurrent senders append in here
            expired = [nd for nd in self._pending_segments
                       if now >= nd[1]]
            self._pending_segments[:] = [
                nd for nd in self._pending_segments if now < nd[1]
            ]
        for name, _deadline in expired:
            try:  # consumed segments are already unlinked: ignore
                seg = shared_memory.SharedMemory(name=name)
                seg.close()
                seg.unlink()
            except FileNotFoundError:
                pass

    def send_shm(self, oob_ep, peer_nid: int, tag: int, data) -> str:
        """Write ``data`` into a fresh shm segment and post the
        control frame; returns the segment name. Ownership of the
        segment passes to the receiver (it unlinks); segments whose
        receiver never consumed the frame are reaped after
        SEGMENT_TTL_S on a later send."""
        import time as _time

        from multiprocessing import shared_memory

        from ..native import DssBuffer

        _check_user_tag(tag)
        rec = _obs.enabled  # capture once: flag may flip mid-handoff
        t_obs = _time.perf_counter() if rec else 0.0
        self._reap_orphaned_segments()
        arr = np.ascontiguousarray(np.asarray(data))
        # name carries the creator pid so tpu-clean can reap segments
        # whose owner died without unlinking (orte-clean's leftover-
        # session duty); uuid tail avoids same-pid collisions
        seg = shared_memory.SharedMemory(
            create=True, size=max(1, arr.nbytes),
            name=f"ompitpu-{os.getpid()}-{uuid.uuid4().hex[:12]}",
        )
        try:
            # single copy: write straight into the mapping (tobytes()
            # would materialize a second full-size host buffer)
            if arr.size:
                np.frombuffer(seg.buf, dtype=arr.dtype,
                              count=arr.size)[:] = arr.ravel()
            frame = DssBuffer()
            frame.pack_string(seg.name)
            _pack_array_header(frame, arr)
            oob_ep.send(peer_nid, tag, frame.tobytes())
        except BaseException:
            seg.close()
            seg.unlink()
            raise
        self.handoffs_pvar.add()
        self.shm_bytes_pvar.add(arr.nbytes)
        name = seg.name
        seg.close()  # receiver owns the segment now
        # ownership transferred: drop OUR resource_tracker registration
        # or the tracker warns at exit about every segment the receiver
        # unlinked (and would double-unlink ones it didn't). The
        # receiver's attach registers in ITS tracker; our TTL reap
        # re-attaches (re-registering) before unlinking — every path
        # stays tracker-consistent. Cost: a segment orphaned by our
        # death inside the TTL window outlives us in /dev/shm.
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister(f"/{name}", "shared_memory")
        except Exception:
            pass  # tracker API is CPython-internal; never fail a send
        with self._pending_lock:
            self._pending_segments.append(
                (name, _time.monotonic() + self.SEGMENT_TTL_S)
            )
        if rec and _obs.enabled:
            _obs.record("btl_shm_send", "btl", t_obs,
                        _time.perf_counter() - t_obs,
                        nbytes=int(arr.nbytes), peer=peer_nid - 1)
        return name

    def recv_shm(self, oob_ep, tag: int, *, src=None, dst_device=None,
                 timeout_ms: int = 30_000, first=None):
        """Map the announced segment, device_put out of it (the single
        copy), unlink. ``src`` filters control frames by sender node id
        (frames from other senders on the same tag are stashed for
        their own consumer — same discipline as the staged path).
        ``first`` is an already-popped ``(src_nid, frame)`` pair to
        resume from (the wire router's any-source reaping)."""
        import time as _time

        from multiprocessing import shared_memory

        import jax

        from ..native import DssBuffer

        _check_user_tag(tag)
        rec = _obs.enabled  # capture once: flag may flip mid-handoff
        t_obs = _time.perf_counter() if rec else 0.0
        deadline = _time.monotonic() + timeout_ms / 1000
        if first is not None:
            _, raw = first
        else:
            _, raw = stashed_recv(oob_ep, src, tag, deadline)
        frame = DssBuffer(raw)
        name = frame.unpack_string()
        dtype, shape = _unpack_array_header(frame)
        try:
            seg = shared_memory.SharedMemory(name=name)
        except FileNotFoundError:
            from ..utils.errors import ErrorCode as _EC, MPIError as _ME

            raise _ME(
                _EC.ERR_OTHER,
                f"shm segment '{name}' no longer exists (reaped after "
                f"TTL or sender died) — the handoff frame is stale",
            )
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if any(d < 0 for d in shape) or nbytes < 0 or nbytes > seg.size:
            # malformed/hostile control frame: do NOT unlink — the
            # segment stays for the sender's TTL reaper, and the error
            # is an MPI truncation, not a raw numpy ValueError
            seg.close()
            raise MPIError(
                ErrorCode.ERR_TRUNCATE,
                f"shm control frame claims {nbytes} bytes but segment "
                f"'{name}' holds only {seg.size} — frame rejected, "
                "segment left for the sender's TTL reaper",
            )
        try:
            view = np.frombuffer(seg.buf[:nbytes],
                                 dtype=dtype).reshape(shape)
            if dst_device is None:
                dst_device = jax.local_devices()[0]
            # copy OUT of the mapping before unmapping: jax retains a
            # reference to host buffers passed to device_put (and on
            # CPU may alias them zero-copy), so handing it the mapped
            # pages directly would make unlink a use-after-free. The
            # receive is therefore segment -> host array -> device:
            # one host memcpy more than the send side's single write,
            # still no per-chunk socket streaming
            staged = np.array(view)
            del view
            out = jax.device_put(staged, dst_device)
        finally:
            seg.close()
            seg.unlink()
        self.handoffs_pvar.add()
        self.shm_bytes_pvar.add(nbytes)
        if rec and _obs.enabled:
            _obs.record("btl_shm_recv", "btl", t_obs,
                        _time.perf_counter() - t_obs, nbytes=int(nbytes),
                        peer=(src - 1) if src is not None else -1)
        return out


class HostBtl(base.BtlModule):
    """Explicit host-staged bounce: device → host numpy → device.

    The universal fallback (reaches every pair), and the measurement
    path for "how much does host staging cost" — the anti-pattern the
    north star forbids on the hot path, kept selectable for debugging
    exactly like forcing ``--mca btl tcp,self`` onto a verbs cluster.
    """

    NAME = "host"
    EAGER_LIMIT = 4 * 1024           # sm eager (btl_sm_component.c:244)
    MAX_SEND_SIZE = 32 * 1024 * 1024
    LATENCY = 100
    BANDWIDTH = 5_000
    EXCLUSIVITY = 0

    def reachable(self, src_ep, dst_ep) -> bool:
        return True

    def move_segment(self, data, dst_device):
        import jax

        staged = np.asarray(data)  # explicit device→host fetch
        return jax.device_put(staged, dst_device)


class _BtlComponent(mca_component.Component):
    """Shared component shell: one module class each."""

    MODULE_CLS = None

    def register_vars(self) -> None:
        base.register_module_vars(self.MODULE_CLS)

    def query(self, ctx=None):
        return (self.priority, self.MODULE_CLS())


class SelfComponent(_BtlComponent):
    NAME = "self"
    PRIORITY = 80
    MODULE_CLS = SelfBtl


class IciComponent(_BtlComponent):
    NAME = "ici"
    PRIORITY = 60
    MODULE_CLS = IciBtl


class ShmComponent(_BtlComponent):
    NAME = "shm"
    PRIORITY = 50
    MODULE_CLS = ShmBtl


class DcnComponent(_BtlComponent):
    NAME = "dcn"
    PRIORITY = 40
    MODULE_CLS = DcnBtl


class HostComponent(_BtlComponent):
    NAME = "host"
    PRIORITY = 10
    MODULE_CLS = HostBtl


base.BTL_FRAMEWORK.register(SelfComponent())
base.BTL_FRAMEWORK.register(IciComponent())
base.BTL_FRAMEWORK.register(ShmComponent())
base.BTL_FRAMEWORK.register(DcnComponent())
base.BTL_FRAMEWORK.register(HostComponent())
