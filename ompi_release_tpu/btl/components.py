"""btl components: self / ici / dcn / host.

Mapping from the reference's transport zoo (``ompi/mca/btl/``):

  self  loopback (``btl/self``)               -> same-rank device no-op
  ici   intra-slice device fabric (``btl/sm``/``btl/vader`` role:
        the fast, always-there local fabric)  -> direct d2d move the
        runtime routes over the ICI torus
  dcn   inter-slice / inter-host network (``btl/tcp``/``btl/openib``
        role)                                 -> d2d move routed over
        DCN, distinct size constants + ranking
  host  explicit host-memory staging bounce (the CUDA-style staged
        fallback, ``btl/smcuda`` host path)   -> device→host→device

Reachability uses the modex endpoint records (slice_index /
process_index — the business-card fields), exactly how add_procs
decides per-peer BTL eligibility (``ompi/mca/btl/btl.h:810-816``).

Size constants keep the reference's *shape* (eager ≪ max_send,
network eager ≪ local eager — btl_tcp_component.c:268-270 64K/128K,
btl_sm_component.c:244-246 4K/32K) rescaled to fabric reality: ICI
moves HBM arrays, so its limits are MiB-scale.
"""

from __future__ import annotations

import numpy as np

from ..mca import component as mca_component
from . import base


class SelfBtl(base.BtlModule):
    """Loopback: src == dst. Arrays are immutable; a self-send needs no
    copy at all (the reference's btl/self memcpys because its buffers
    are mutable — ours provably cannot alias a future write)."""

    NAME = "self"
    EAGER_LIMIT = 1 << 62
    MAX_SEND_SIZE = 1 << 62
    LATENCY = 0
    BANDWIDTH = 10 ** 9
    EXCLUSIVITY = 64 * 1024  # btl/self owns loopback outright

    def reachable(self, src_ep, dst_ep) -> bool:
        return src_ep.rank == dst_ep.rank

    def move_segment(self, data, dst_device):
        import jax

        if getattr(data, "device", None) == dst_device:
            return data
        return jax.device_put(data, dst_device)


class IciBtl(base.BtlModule):
    """Intra-slice device-to-device over the ICI torus.

    ``jax.device_put`` between two accelerators in one slice compiles
    to a direct device copy the runtime routes over ICI — no host
    bounce. On the CPU simulator mesh the same call is an in-process
    buffer handoff; the component still selects, so CI exercises the
    ICI decision logic clusterlessly (SURVEY §4 simulator strategy).
    """

    NAME = "ici"
    EAGER_LIMIT = 1 * 1024 * 1024
    MAX_SEND_SIZE = 64 * 1024 * 1024
    LATENCY = 1
    BANDWIDTH = 45_000  # ~45 GB/s/link ICI-scale ranking input
    EXCLUSIVITY = 1024

    def reachable(self, src_ep, dst_ep) -> bool:
        return (
            src_ep.rank != dst_ep.rank
            and src_ep.platform == dst_ep.platform
            and src_ep.slice_index == dst_ep.slice_index
        )

    def move_segment(self, data, dst_device):
        import jax

        return jax.device_put(data, dst_device)


class DcnBtl(base.BtlModule):
    """Inter-slice / inter-host transfers over the data-center network.

    Same entry point (the runtime routes device_put over DCN when the
    peers are in different slices/processes) but its own component so
    the size constants, ranking, and byte accounting are DCN's —
    mirroring how btl/tcp and btl/sm coexist with different protocol
    switch points (btl_tcp_component.c:268 vs btl_sm_component.c:244).
    """

    NAME = "dcn"
    EAGER_LIMIT = 64 * 1024          # tcp eager (btl_tcp_component.c:268)
    MAX_SEND_SIZE = 4 * 1024 * 1024
    LATENCY = 25
    BANDWIDTH = 12_500               # 100 Gb/s-class NIC
    EXCLUSIVITY = 512

    def reachable(self, src_ep, dst_ep) -> bool:
        return src_ep.rank != dst_ep.rank and (
            src_ep.slice_index != dst_ep.slice_index
            or src_ep.process_index != dst_ep.process_index
        )

    def move_segment(self, data, dst_device):
        import jax

        return jax.device_put(data, dst_device)


class HostBtl(base.BtlModule):
    """Explicit host-staged bounce: device → host numpy → device.

    The universal fallback (reaches every pair), and the measurement
    path for "how much does host staging cost" — the anti-pattern the
    north star forbids on the hot path, kept selectable for debugging
    exactly like forcing ``--mca btl tcp,self`` onto a verbs cluster.
    """

    NAME = "host"
    EAGER_LIMIT = 4 * 1024           # sm eager (btl_sm_component.c:244)
    MAX_SEND_SIZE = 32 * 1024 * 1024
    LATENCY = 100
    BANDWIDTH = 5_000
    EXCLUSIVITY = 0

    def reachable(self, src_ep, dst_ep) -> bool:
        return True

    def move_segment(self, data, dst_device):
        import jax

        staged = np.asarray(data)  # explicit device→host fetch
        return jax.device_put(staged, dst_device)


class _BtlComponent(mca_component.Component):
    """Shared component shell: one module class each."""

    MODULE_CLS = None

    def register_vars(self) -> None:
        base.register_module_vars(self.MODULE_CLS)

    def query(self, ctx=None):
        return (self.priority, self.MODULE_CLS())


class SelfComponent(_BtlComponent):
    NAME = "self"
    PRIORITY = 80
    MODULE_CLS = SelfBtl


class IciComponent(_BtlComponent):
    NAME = "ici"
    PRIORITY = 60
    MODULE_CLS = IciBtl


class DcnComponent(_BtlComponent):
    NAME = "dcn"
    PRIORITY = 40
    MODULE_CLS = DcnBtl


class HostComponent(_BtlComponent):
    NAME = "host"
    PRIORITY = 10
    MODULE_CLS = HostBtl


base.BTL_FRAMEWORK.register(SelfComponent())
base.BTL_FRAMEWORK.register(IciComponent())
base.BTL_FRAMEWORK.register(DcnComponent())
base.BTL_FRAMEWORK.register(HostComponent())
