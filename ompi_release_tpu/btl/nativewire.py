"""btl/nativewire — the zero-copy native datapath (``btl/sm`` +
``btl/tcp`` writev roles, played by ``native/``).

One component, two transports, selected per peer from the modex
business cards exactly like :meth:`WireRouter._btl_for`:

* **co-hosted peers** ride a shared-memory SPSC byte ring
  (``native/btl_shm.cc``): the sender's ``writev`` gathers the
  precomposed SGH2 fragment parts straight into the mapped ring, the
  receiver's ``read_frag`` memcpys each fragment payload directly into
  the preallocated reassembly buffer — zero Python-side copies on the
  whole byte path.
* **cross-host peers** ride vectored socket IO over the existing OOB
  mesh (``native/btl_tcp.cc``): ``wire_sendv`` writev's the frame
  header plus scatter-gather parts in one syscall (byte-identical on
  the wire to ``ep.send(dst, tag, b"".join(parts))``), and
  ``wire_recv_frag`` lands queued SGC2 payloads straight into the
  reassembly buffer.

The SGH2 framing is BYTE-IDENTICAL to the portable staged path
(:class:`~.components.FrameTemplate` is the single framing authority;
``b"".join`` of each scatter-gather list reproduces the staged frame
bit for bit), and header frames ALWAYS ride the portable OOB send —
so sentinel SIG1 piggybacks, any-source header peeks, QoS lane
striping and tpu-doctor flow ids are untouched. Only fragment
payloads leave Python.

Graceful degradation is structural: the component withdraws from MCA
selection (``query`` -> None) when ``libompitpu_native.so`` lacks the
``wire_*``/``shmring_*`` symbols, when ``btl_nativewire_enable``/
``OMPITPU_NATIVEWIRE=0`` turns it off, or — per peer — when the
peer's modex card does not advertise the capability. Every fallback
lands on the portable staged-frames path, which this module can also
SPEAK (legacy SGH1, portable SGH2) because it subclasses
:class:`~.components.DcnBtl`.
"""

from __future__ import annotations

import atexit
import os
import threading
import time as _time
import uuid
import weakref
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from .. import obs as _obs
from ..obs import watchdog as _watchdog
from ..mca import component as mca_component
from ..mca import pvar as _pvar
from ..mca import var as mca_var
from ..utils.errors import ErrorCode, MPIError
from . import base
from . import components as _c
from .components import (
    _CHUNK2_MAGIC, _HDR2_MAGIC, _check_user_tag, _frags_inflight,
    _template_for, _unpack_array_header, _zero_copy_strict, DcnBtl,
    stashed_recv,
)

#: modex business-card key: ``"token:slots:ring_bytes"`` — the
#: receiver-side ring geometry plus a per-process token namespacing
#: its /dev/shm ring names (a restarted replacement process gets a
#: fresh token, so stale rings can never be re-attached)
CARD_KEY = "nativewire"

_RING_SLOTS_DEFAULT = 4
_RING_BYTES_DEFAULT = 8 * 1024 * 1024
_EVENT_SLOTS_DEFAULT = 1 << 16   # 2 MiB of 32-byte records
_SEND_TIMEOUT_MS = 30_000
#: exit-time grace for tx rings holding bytes no consumer mapped yet —
#: covers a receiver still inside interpreter/jax startup, not a hang
_DRAIN_TIMEOUT_MS = 10_000

#: native-datapath ledger: bytes/frames that crossed through the
#: native wire, and the honesty witness for the zero-copy claim —
#: every host-side materialization the fast path was FORCED into
#: (dlpack refused, non-contiguous source, ring cross-tag restash)
#: counts, so ``wire_native_copies_per_mib`` near 0 is evidence, not
#: advertising.
_native_bytes = _pvar.counter(
    "wire_native_bytes",
    "payload bytes moved by the nativewire datapath (shm-ring writev "
    "+ vectored socket writev + native fragment reassembly)",
)
_native_frames = _pvar.counter(
    "wire_native_frames",
    "SGC2 fragment frames moved by the nativewire datapath",
)
_fallback_copies = _pvar.counter(
    "wire_native_fallback_copies",
    "host-side byte materializations the native path was forced "
    "into: dlpack handoff refused (device array, exotic dtype), "
    "non-contiguous source compaction, ring cross-tag restash",
)
_copies_per_mib = _pvar.PVARS.register(
    "wire_native_copies_per_mib", _pvar.PvarClass.LEVEL,
    "forced host copies per MiB of native wire traffic (the zero-copy "
    "witness: ~0 when the byte path truly bypasses Python)",
    getter=lambda: (_fallback_copies.read()
                    / max(1.0, _native_bytes.read() / float(1 << 20))),
)

# ---------------------------------------------------------------------------
# C-side telemetry fold: the counters block lives IN the ring headers
# (native/btl_shm.cc RingHdr slack) and the tcp endpoint struct
# (native/oob_endpoint.h) — relaxed single-writer u64s the transports
# bump on every writev/read_frag, always on. Python never touches them
# on the byte path; these getter pvars fold the live blocks on READ
# (tpu_info snapshot, sampler tick), so the fleet metrics plane sees
# native stalls without re-adding a Python emit site to the datapath.
# ---------------------------------------------------------------------------

_tele_lock = threading.Lock()
#: live producer-side rings: their w_* counters are THIS process's
#: work (the peer's r_* half belongs to the peer's own fold)
_live_tx: set = set()
#: live consumer-side rings: the r_* half is ours
_live_rx: set = set()
#: endpoints that carried native frames (tcp-leg wire_stats source);
#: weak — an endpoint's lifetime belongs to the OOB layer
_seen_eps: "weakref.WeakSet" = weakref.WeakSet()
#: [stalls, stall_ns] folded out of rings at retire time, so closing
#: a ring never makes the process counters go backwards
_retired = [0, 0]
#: monotonic floor for the fold (a GC'd endpoint drops its share;
#: counters still must never decrease between two reads)
_mono = [0, 0]


def _track_ring(ring, tx: bool) -> None:
    if ring is None:
        return
    with _tele_lock:
        (_live_tx if tx else _live_rx).add(ring)


def _retire_ring(ring, tx: bool) -> None:
    """Fold a closing ring's final stall totals into the retired base
    and drop it from the live set (stats() reads shared memory — it
    must run BEFORE close unmaps)."""
    if ring is None:
        return
    try:
        st = ring.stats()
    except Exception:
        st = None
    with _tele_lock:
        (_live_tx if tx else _live_rx).discard(ring)
        if st is not None:
            pre = "w_" if tx else "r_"
            _retired[0] += int(st.get(pre + "stalls", 0))
            _retired[1] += int(st.get(pre + "stall_ns", 0))


def _track_ep(ep) -> None:
    try:
        _seen_eps.add(ep)
    except TypeError:
        pass  # non-weakrefable test double: no tcp stats to fold


def _stall_fold() -> Tuple[int, int]:
    """(stalls, stall_ns) this process has spent blocked on the native
    datapath: full-ring waits on tx rings, empty-ring waits on rx
    rings, queue-cv waits on tcp endpoints, plus the retired base."""
    with _tele_lock:
        rings = ([(r, "w_") for r in _live_tx]
                 + [(r, "r_") for r in _live_rx])
        eps = list(_seen_eps)
        stalls, ns = _retired[0], _retired[1]
    for ring, pre in rings:
        try:
            st = ring.stats()
        except Exception:
            continue
        stalls += int(st.get(pre + "stalls", 0))
        ns += int(st.get(pre + "stall_ns", 0))
    for ep in eps:
        try:
            ws = ep.wire_stats()
        except Exception:
            continue
        stalls += int(ws.get("rx_stalls", 0))
        ns += int(ws.get("rx_stall_ns", 0))
    with _tele_lock:
        _mono[0] = stalls = max(_mono[0], stalls)
        _mono[1] = ns = max(_mono[1], ns)
    return stalls, ns


def _hwm_fold() -> float:
    """Worst occupancy high-water fraction across live rings (0.0 with
    no rings): how close the busiest ring ever came to backpressure."""
    with _tele_lock:
        rings = list(_live_tx) + list(_live_rx)
    frac = 0.0
    for ring in rings:
        try:
            cap = float(ring.capacity)
            if cap > 0:
                frac = max(frac, float(ring.stats().get("hwm", 0)) / cap)
        except Exception:
            continue
    return min(1.0, frac)


_ring_stalls_pvar = _pvar.PVARS.register(
    "wire_native_ring_stalls", _pvar.PvarClass.COUNTER,
    "times a native-datapath call sat blocked (full tx ring, empty rx "
    "ring, empty tcp frame queue) — folded on read from the C-side "
    "counter blocks, zero Python on the byte path",
    getter=lambda: _stall_fold()[0],
)
_stall_seconds_pvar = _pvar.PVARS.register(
    "wire_native_stall_seconds", _pvar.PvarClass.TIMER,
    "cumulative seconds the native datapath spent blocked waiting on "
    "a peer (the time complement of wire_native_ring_stalls)",
    getter=lambda: _stall_fold()[1] / 1e9,
)
_hwm_frac_pvar = _pvar.PVARS.register(
    "wire_native_ring_hwm_frac", _pvar.PvarClass.LEVEL,
    "worst shm-ring occupancy high-water mark as a fraction of ring "
    "capacity (1.0 = some ring completely filled; sustained highs "
    "mean the consumer is the bottleneck or rings are undersized)",
    getter=_hwm_fold,
)


def register_nativewire_vars() -> None:
    """The component's own cvars (its standard ``btl_nativewire_*``
    size/ranking vars come from :func:`base.register_module_vars`)."""
    mca_var.register(
        "btl_nativewire_enable", "bool", True,
        "Use the native zero-copy datapath (shm rings + vectored "
        "socket IO) for staged wire transfers when the native library "
        "provides it; off = the portable staged-frames path "
        "(OMPITPU_NATIVEWIRE=0 is the env spelling)",
    )
    mca_var.register(
        "btl_nativewire_ring_bytes", "size", _RING_BYTES_DEFAULT,
        "Capacity of each receive-side shared-memory ring (one ring "
        "per co-hosted sender per slot); fragments larger than a ring "
        "fall back to the vectored-socket loopback automatically",
    )
    mca_var.register(
        "btl_nativewire_ring_slots", "int", _RING_SLOTS_DEFAULT,
        "Shared-memory rings per co-hosted sender: wire channels hash "
        "across slots so independent lanes do not share one FIFO",
    )
    mca_var.register(
        "btl_nativewire_events", "bool", False,
        "Mmap one per-process native event ring (ompitpu-nativeev-v1) "
        "and have the C transports append a 32-byte record per SGC2 "
        "fragment (t_ns, tag, xfer, bytes, wait_ns; drop-oldest wrap); "
        "tpu-doctor expands dumps into wire-layer spans with paired "
        "flow ids. Off (default) = zero event-path work — the ring "
        "counter blocks stay on either way",
    )
    mca_var.register(
        "btl_nativewire_event_slots", "int", _EVENT_SLOTS_DEFAULT,
        "Record capacity of the native event ring (32 bytes each; a "
        "full ring overwrites its oldest records)",
    )


register_nativewire_vars()  # idempotent; read at modex + module bind


def nativewire_ready() -> bool:
    """Local capability: native symbols present AND not disabled.
    Never raises — a probe failure is just 'not available'."""
    if os.environ.get("OMPITPU_NATIVEWIRE", "1").strip().lower() in (
            "0", "false", "no", "off"):
        return False
    if not mca_var.get("btl_nativewire_enable", True):
        return False
    try:
        from ..native import wire_symbols_available

        return bool(wire_symbols_available())
    except Exception:
        return False


_token_lock = threading.Lock()
_token: Optional[str] = None


def _local_token() -> str:
    global _token
    with _token_lock:
        if _token is None:
            _token = f"{os.getpid():x}-{uuid.uuid4().hex[:8]}"
        return _token


_ev_lock = threading.Lock()
_ev_ring = None
_ev_tried = False


def _event_ring():
    """Lazily create + install the per-process native event ring when
    ``btl_nativewire_events`` turns it on (once per process, however
    many modules bind). The shm name is unlinked immediately after
    create — only this process's own mapping matters (records decode
    in-process at dump time), so nothing can leak in /dev/shm. The
    ring is handed to ``obs.nativeev`` so finalize dumps and watchdog
    postmortems read it without importing this module."""
    global _ev_ring, _ev_tried
    with _ev_lock:
        if _ev_tried:
            return _ev_ring
        _ev_tried = True
        if not mca_var.get("btl_nativewire_events", False):
            return None
        try:
            from ..native import NativeEventRing

            slots = int(mca_var.get("btl_nativewire_event_slots",
                                    _EVENT_SLOTS_DEFAULT)
                        or _EVENT_SLOTS_DEFAULT)
            name = f"/onwev-{_local_token()}"
            ring = NativeEventRing.create(name, max(64, slots))
            if ring is None:
                # leftover name from a crashed earlier run (the token
                # makes a LIVE collision impossible): clear and retry
                NativeEventRing.unlink(name)
                ring = NativeEventRing.create(name, max(64, slots))
            if ring is None:
                return None  # symbols absent or shm refused: stay off
            NativeEventRing.unlink(name)
            ring.install()
            from ..obs import nativeev as _nativeev

            _nativeev.set_ring(ring)
            _ev_ring = ring
            atexit.register(_uninstall_event_ring)
        except Exception:
            _ev_ring = None
        return _ev_ring


def _uninstall_event_ring() -> None:
    """Exit hook: detach the C emit sink before interpreter teardown
    starts unmapping things under it. The mapping itself stays (the
    obs finalize dump may still be reading records)."""
    with _ev_lock:
        ring = _ev_ring
    if ring is not None:
        try:
            ring.uninstall()
        except Exception:
            pass


def _reset_event_state_for_tests() -> None:
    global _ev_ring, _ev_tried
    from ..obs import nativeev as _nativeev

    with _ev_lock:
        if _ev_ring is not None:
            try:
                if _nativeev.get_ring() is _ev_ring:
                    _nativeev.set_ring(None)
                _ev_ring.uninstall()
                _ev_ring.close()
            except Exception:
                pass
        _ev_ring = None
        _ev_tried = False


def modex_entry() -> Dict[str, str]:
    """This process's business-card advertisement (empty when the
    capability is absent — peers key their per-peer fallback on the
    key's presence, the add_procs reachability discipline)."""
    if not nativewire_ready():
        return {}
    slots = int(mca_var.get("btl_nativewire_ring_slots",
                            _RING_SLOTS_DEFAULT) or _RING_SLOTS_DEFAULT)
    ring = int(mca_var.get("btl_nativewire_ring_bytes",
                           _RING_BYTES_DEFAULT) or _RING_BYTES_DEFAULT)
    return {CARD_KEY: f"{_local_token()}:{max(1, slots)}:{ring}"}


def _parse_card(entry) -> Optional[Tuple[str, int, int]]:
    try:
        token, slots, ring = str(entry).split(":")
        return token, max(1, int(slots)), max(1 << 16, int(ring))
    except Exception:
        return None  # malformed advertisement = not capable


def module_for(cards, my_pidx: int) -> Optional["NativeWireBtl"]:
    """The wire router's transport instance: None when the native
    datapath cannot run here (portable paths take over wholesale)."""
    if not nativewire_ready():
        return None
    try:
        mod = NativeWireBtl()
        mod.bind(cards, int(my_pidx))
        return mod
    except Exception:
        return None


def _ring_name(token: str, src_pidx: int, slot: int) -> str:
    return f"/onw-{token}-{src_pidx}-{slot}"


def _ring_wait_info(ring, peer_pidx: int, direction: str) -> dict:
    """Watchdog payload for a blocked ring wait, resolved at DUMP
    time: which ring (the /onw name carries the owner token), which
    peer pid sits on the other end, which direction stalled, and the
    ring's live occupancy — everything a postmortem reader needs to
    tell 'consumer wedged' from 'producer never wrote'."""
    info = {"ring": getattr(ring, "name", "?"),
            "dir": direction, "peer_pidx": int(peer_pidx)}
    try:
        info["peer_pid"] = int(ring.consumer_pid() if direction == "send"
                               else ring.producer_pid())
        pending, cap = int(ring.pending()), int(ring.capacity)
        info["pending"] = pending
        info["capacity"] = cap
        info["occupancy"] = round(pending / max(1, cap), 4)
    except Exception:
        pass  # ring unmapped under us: name + direction still help
    return info


def _slot_of(tag: int, slots: int) -> int:
    # wire p2p tags differ per lane only above bit 17 — fold the high
    # bits down so independent lanes hash to different rings instead
    # of re-coupling head-of-line behind one FIFO
    t = int(tag)
    return ((t >> 17) ^ (t >> 7) ^ t) % max(1, int(slots))


def _host_array(data) -> Tuple[np.ndarray, bool]:
    """Contiguous host ndarray over ``data``'s bytes + a did-we-copy
    verdict. dlpack first: a CPU-backed device array hands its buffer
    over without materializing; only when the producer refuses (real
    device memory, exotic dtype) does the portable ``np.asarray``
    staging copy run — and it is COUNTED."""
    copied = False
    if isinstance(data, np.ndarray):
        arr = data
    else:
        try:
            arr = np.from_dlpack(data)
        except Exception:
            arr = np.asarray(data)
            copied = True
    out = np.ascontiguousarray(arr)
    if out is not arr and not np.may_share_memory(out, arr):
        copied = True
    return out, copied


def _retry_send(fn, what: str):
    """The wire router's first-contact backoff, minus its FT lookups
    (this module has no router handle): a confirmed process failure
    is never retried — ULFM owns that verdict."""
    last = None
    for attempt in range(5):
        try:
            return fn()
        except MPIError as e:
            if e.code == ErrorCode.ERR_PROC_FAILED:
                raise
            last = e
            _time.sleep(0.05 * (attempt + 1))
    raise MPIError(ErrorCode.ERR_UNREACH,
                   f"{what} failed after retries: {last}")


class NativeWireBtl(DcnBtl):
    """The native datapath module. Subclassing :class:`DcnBtl` is the
    point: every ``send_staged``/``recv_staged`` call site in the wire
    router works unchanged, and the portable framings (legacy SGH1,
    interpreted SGH2) remain speakable for per-peer fallback."""

    NAME = "nativewire"
    EAGER_LIMIT = 64 * 1024
    MAX_SEND_SIZE = 4 * 1024 * 1024
    LATENCY = 20                    # beats dcn: no per-frame Python join
    BANDWIDTH = 50_000
    EXCLUSIVITY = 0
    #: wire transport only — never a device-segment mover, so BML move
    #: lists (device routing) are untouched by this component
    SUPPORTS_MOVE = False

    def __init__(self) -> None:
        super().__init__()
        self.cards = []
        self.my_pidx = -1
        #: per-peer parse cache: pidx -> (raw card entry, parsed) —
        #: validated against the LIVE card string on every lookup,
        #: because respawn recovery refreshes the modex cards in place
        #: and a replacement process advertises a FRESH ring token
        self._caps: Dict[int, tuple] = {}
        #: (peer_pidx, peer_token, slot) -> (ring-or-None, lock)
        self._tx: Dict[Tuple[int, str, int], tuple] = {}
        #: (src_pidx, src_token, slot) -> (ring, lock, cross-tag
        #: stash) — the src token in the key makes a respawned
        #: sender's rings fresh attaches, never stale mappings
        self._rx: Dict[Tuple[int, str, int], tuple] = {}
        self._ring_guard = threading.Lock()
        atexit.register(self._shutdown_rings)

    def bind(self, cards, my_pidx: int) -> None:
        self.cards = cards
        self.my_pidx = int(my_pidx)
        self._caps = {}
        _event_ring()  # cvar-gated; no-op (and cheap) when off

    def _cap(self, pidx: int) -> Optional[Tuple[str, int, int]]:
        """LIVE capability of ``pidx`` from the shared cards list."""
        try:
            card = self.cards[pidx]
        except Exception:
            return None
        entry = card.get(CARD_KEY) if isinstance(card, dict) else None
        if entry is None:
            return None
        cached = self._caps.get(pidx)
        if cached is not None and cached[0] == entry:
            return cached[1]
        parsed = _parse_card(entry)
        self._caps[pidx] = (entry, parsed)
        return parsed

    # -- per-peer eligibility (the add_procs verdict) ---------------------
    def peer_capable(self, peer_pidx: int) -> bool:
        """Both-ended capability: the peer advertised the native
        datapath AND this process advertised it (ring mode needs the
        receiver's geometry from OUR card on the peer's side)."""
        return (peer_pidx != self.my_pidx
                and self._cap(peer_pidx) is not None
                and self._cap(self.my_pidx) is not None)

    def _same_host(self, peer_pidx: int) -> bool:
        try:
            mine = self.cards[self.my_pidx].get("host")
            return bool(mine) and mine == self.cards[peer_pidx].get("host")
        except Exception:
            return False

    # -- ring lifecycle ----------------------------------------------------
    def _tx_ring(self, peer_pidx: int, slot: int):
        """Producer-side ring for (me -> peer, slot), created lazily
        with the RECEIVER's advertised geometry. A create failure is a
        permanent per-ring fallback to the vectored socket path (the
        entry caches None), never an error."""
        token, _slots, ring_bytes = self._cap(peer_pidx)
        key = (peer_pidx, token, slot)
        with self._ring_guard:
            ent = self._tx.get(key)
            if ent is None:
                from ..native import ShmRing

                name = _ring_name(token, self.my_pidx, slot)
                ring = ShmRing.create(name, ring_bytes, os.getpid())
                if ring is None:
                    # leftover name from a crashed earlier run: the
                    # token makes collisions with a LIVE ring impossible
                    ShmRing.unlink(name)
                    ring = ShmRing.create(name, ring_bytes, os.getpid())
                _track_ring(ring, tx=True)
                ent = self._tx[key] = (ring, threading.Lock())
            return ent

    def _rx_ring(self, src_pidx: int, slot: int, deadline: float):
        """Consumer-side attach for (src -> me, slot), retried until
        the producer's lazy create lands; the name is unlinked right
        after attach (the mapping lives on) so /dev/shm stays clean.
        A producer that died before creating surfaces as the typed
        ERR_PROC_FAILED — pid liveness is authoritative on one host."""
        src_cap = self._cap(src_pidx)
        key = (src_pidx, src_cap[0] if src_cap else "", slot)
        with self._ring_guard:
            ent = self._rx.get(key)
        if ent is not None:
            return ent
        from ..native import ShmRing

        token = self._cap(self.my_pidx)[0]
        name = _ring_name(token, src_pidx, slot)
        peer_pid = 0
        try:
            peer_pid = int(self.cards[src_pidx].get("pid", 0) or 0)
        except Exception:
            pass
        tok = None
        if _watchdog.enabled:
            tok = _watchdog.arm(
                "nw_ring_attach", peer=src_pidx,
                info=lambda n=name, p=peer_pid, s=src_pidx: {
                    "ring": n, "dir": "attach", "peer_pidx": int(s),
                    "peer_pid": int(p)})
        try:
            while True:
                ring = ShmRing.attach(name, os.getpid())
                if ring is not None:
                    ShmRing.unlink(name)
                    with self._ring_guard:
                        ent = self._rx.get(key)
                        if ent is None:
                            _track_ring(ring, tx=False)
                            ent = self._rx[key] = (ring,
                                                   threading.Lock(), {})
                        else:
                            ring.close()  # benign double-attach race
                    return ent
                if peer_pid:
                    try:
                        os.kill(peer_pid, 0)
                    except ProcessLookupError:
                        raise MPIError(
                            ErrorCode.ERR_PROC_FAILED,
                            f"shm ring from process {src_pidx} never "
                            f"appeared and its producer (pid "
                            f"{peer_pid}) is gone — peer died "
                            "mid-transfer",
                        )
                    except PermissionError:
                        pass  # alive under another uid
                if _time.monotonic() >= deadline:
                    raise MPIError(
                        ErrorCode.ERR_PENDING,
                        f"timed out waiting for process {src_pidx}'s "
                        f"shm ring {name}",
                    )
                _time.sleep(0.0005)
        finally:
            if tok is not None:
                _watchdog.disarm(tok)

    def plan_endpoints(self, tag: int, send_peers, recv_srcs):
        """Per-peer native handles for a frozen-plan executor
        (coll/native_exec): ``{pidx: (tx, rx)}`` where tx is the
        producer-side ``(ring, lock)`` toward the peer (None =
        cross-host or ring creation failed → the executor uses the
        vectored-socket leg, exactly like the interpreted path) and
        rx is the consumer-side ``(ring, lock, cross-tag stash)``
        entry for frames FROM the peer (None = cross-host). The
        executor holds both locks for the whole fire — the rings are
        SPSC, so concurrent Python senders/receivers must stay out
        precisely as long as C owns the cursors."""
        out = {}
        for p in sorted(set(send_peers) | set(recv_srcs)):
            tx = rx = None
            if self._same_host(p):
                if p in send_peers:
                    ent = self._tx_ring(
                        p, _slot_of(tag, self._cap(p)[1]))
                    if ent[0] is not None:
                        tx = ent
                if p in recv_srcs:
                    slot = _slot_of(tag, self._cap(self.my_pidx)[1])
                    rx = self._rx_ring(p, slot,
                                       _time.monotonic() + 5.0)
            out[p] = (tx, rx)
        return out

    def _shutdown_rings(self) -> None:
        from ..native import ShmRing

        with self._ring_guard:
            tx, rx = self._tx, self._rx
            self._tx, self._rx = {}, {}
        # A ring still holding bytes that NO consumer has mapped yet is
        # in-flight data the socket path would have parked in kernel
        # buffers: unlinking now would lose a completed send to a
        # receiver that merely hasn't reached its recv.  Give such
        # rings a bounded grace window to be attached (the attach
        # stamps consumer_pid into the shared header and the mapping
        # outlives our unlink); drained or consumed rings close with
        # zero wait.
        deadline = _time.monotonic() + _DRAIN_TIMEOUT_MS / 1000
        for (ring, _lk) in tx.values():
            if ring is not None:
                try:
                    while (ring.pending() > 0 and ring.consumer_pid() == 0
                           and _time.monotonic() < deadline):
                        _time.sleep(0.001)
                except Exception:
                    pass
                _retire_ring(ring, tx=True)
                ShmRing.unlink(ring.name)  # no-op if consumer unlinked
                ring.close()
        for ent in rx.values():
            _retire_ring(ent[0], tx=False)
            ent[0].close()

    # -- send side ---------------------------------------------------------
    def _ring_put(self, ring, lk, oob_ep, peer_pidx: int, tag: int,
                  parts) -> None:
        deadline = _time.monotonic() + _SEND_TIMEOUT_MS / 1000
        tok = None
        if _watchdog.enabled:
            # a full-ring wait blocks INSIDE ring.writev (C slices of
            # <=2s); the zero-arg info resolves at dump time, so the
            # postmortem names the ring, its consumer, and the LIVE
            # occupancy at the moment the watchdog fired
            tok = _watchdog.arm(
                "nw_ring_put", peer=peer_pidx,
                info=lambda r=ring, p=peer_pidx: _ring_wait_info(
                    r, p, "send"))
        try:
            with lk:
                while True:
                    left = max(1, int((deadline - _time.monotonic())
                                      * 1000))
                    rc = ring.writev(tag, parts, min(left, 2000))
                    if rc == 0:
                        return
                    if rc == -3:
                        raise MPIError(
                            ErrorCode.ERR_PROC_FAILED,
                            f"shm ring to process {peer_pidx} reports "
                            "its consumer dead — peer died "
                            "mid-transfer",
                        )
                    if rc == -2:
                        # frame can NEVER fit this ring: the vectored
                        # socket loopback carries it, still zero-copy
                        oob_ep.sendv(peer_pidx + 1, tag, parts)
                        return
                    if _time.monotonic() >= deadline:
                        raise MPIError(
                            ErrorCode.ERR_PENDING,
                            f"shm ring to process {peer_pidx} stayed "
                            f"full for {_SEND_TIMEOUT_MS} ms "
                            "(consumer stalled)",
                        )
        finally:
            if tok is not None:
                _watchdog.disarm(tok)

    def frame_stream(self, oob_ep, peer_pidx: int, tag: int, data,
                     tpl=None):
        """Side-effecting generator, one wire frame per ``next()`` —
        the native twin of the router's planned/staged frame streams,
        so QoS striping and the in-flight window discipline apply to
        native transfers unchanged. The header frame rides the
        portable OOB send (sentinels, any-source peeks and flow ids
        depend on seeing it there); fragments ride the ring or the
        vectored socket as scatter-gather part lists."""
        _check_user_tag(tag)
        nid = peer_pidx + 1
        seg = self.pipeline_segsize()
        if not self.peer_capable(peer_pidx) or seg <= 0:
            # portable framing end-to-end (legacy SGH1 when seg==0)
            _retry_send(
                lambda: DcnBtl.send_staged(self, oob_ep, nid, tag, data),
                f"staged transfer to process {peer_pidx}")
            yield
            return
        rec = _obs.enabled  # capture once: flag may flip mid-send
        t0 = _time.perf_counter() if rec else 0.0
        _track_ep(oob_ep)  # tcp-leg counters fold from its C struct
        arr, copied = _host_array(data)
        if copied:
            _fallback_copies.add()
        if tpl is not None and not tpl.matches(arr):
            raise MPIError(
                ErrorCode.ERR_INTERN,
                f"planned staged transfer: buffer {arr.shape}/"
                f"{arr.dtype} does not match the frozen frame template "
                f"{tpl.shape}/{tpl.dtype} — schedule diverged from its "
                "plan (rebuild the persistent request)",
            )
        if tpl is None:
            tpl = _template_for(arr.shape, arr.dtype, seg)
        mv = memoryview(arr.reshape(-1).view(np.uint8)) if arr.size \
            else memoryview(b"")
        xfer = next(_c._xfer_ids)
        frames = tpl.sg_lists(mv, xfer, zlib.crc32(mv))
        header = b"".join(next(frames))
        ring = lk = None
        if self._same_host(peer_pidx):
            # ring exists BEFORE the header leaves: a receiver that
            # holds the header can always attach without waiting
            ring, lk = self._tx_ring(
                peer_pidx, _slot_of(tag, self._cap(peer_pidx)[1]))
        _retry_send(lambda: oob_ep.send(nid, tag, header),
                    f"native header to process {peer_pidx}")
        yield
        for parts in frames:
            plen = len(parts[-1])
            if ring is not None:
                self._ring_put(ring, lk, oob_ep, peer_pidx, tag, parts)
            else:
                _retry_send(
                    lambda p=parts: oob_ep.sendv(nid, tag, p),
                    f"native fragment to process {peer_pidx}")
            _zero_copy_strict.add(plen)
            _native_bytes.add(plen)
            _native_frames.add()
            self.staged_chunks_pvar.add()
            yield
        self.staged_bytes_pvar.add(tpl.nbytes)
        if rec and _obs.enabled:
            _obs.record("btl_nw_send", "btl", t0,
                        _time.perf_counter() - t0,
                        nbytes=int(tpl.nbytes), peer=peer_pidx)

    def send_staged(self, oob_ep, peer_nid: int, tag: int, data) -> int:
        n = 0
        for _ in self.frame_stream(oob_ep, peer_nid - 1, tag, data):
            n += 1
        return max(0, n - 1)  # header is not a chunk

    # -- receive side ------------------------------------------------------
    @staticmethod
    def _pop_stashed(oob_ep, src_nid: int, tag: int):
        from .components import _ep_stash

        stash, lock = _ep_stash(oob_ep)
        with lock:
            q = stash.get((src_nid, tag))
            if q:
                return q.pop(0)
        return None

    def recv_staged(self, oob_ep, tag: int, *, src=None,
                    dst_device=None, timeout_ms: int = 30_000,
                    first=None):
        """Native reassembly: the header is popped/parsed exactly like
        the portable path (shared stash, shared resync discipline);
        SGH2 fragments from a capable co-hosted sender then come out
        of the shm ring, from a capable cross-host sender out of the
        native frame queue — both memcpy'd straight into the
        preallocated buffer. Everything else (legacy SGH1, a sender
        that never advertised the capability) resumes the portable
        reassembly with the already-popped header."""
        import jax

        from ..native import DssBuffer

        _check_user_tag(tag)
        rec = _obs.enabled  # capture once: flag may flip mid-recv
        t_obs = _time.perf_counter() if rec else 0.0
        deadline = _time.monotonic() + timeout_ms / 1000
        while True:
            if first is not None:
                src_got, hraw = first
                first = None
            else:
                src_got, hraw = stashed_recv(oob_ep, src, tag, deadline)
            try:
                hdr = DssBuffer(hraw)
                magic = hdr.unpack_string()
                if magic != _HDR2_MAGIC:
                    if magic == _c._HDR_MAGIC:
                        break  # legacy framing: portable reassembly
                    continue  # orphan chunk: resync to the next header
                (xfer,) = hdr.unpack_int64()
                dtype, shape = _unpack_array_header(hdr)
                nchunks, chunk = hdr.unpack_int64(2)
                (crc,) = hdr.unpack_int64()
            except MPIError:
                continue  # a chunk frame: skip to the next header
            break
        src = src_got
        src_pidx = src - 1
        left_ms = max(1, int((deadline - _time.monotonic()) * 1000))
        if magic != _HDR2_MAGIC or not self.peer_capable(src_pidx):
            return DcnBtl.recv_staged(
                self, oob_ep, tag, src=src, dst_device=dst_device,
                timeout_ms=left_ms, first=(src, hraw))
        _track_ep(oob_ep)  # tcp-leg counters fold from its C struct
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if nbytes < 0 or any(d < 0 for d in shape):
            raise MPIError(ErrorCode.ERR_TRUNCATE,
                           f"staged transfer {xfer}: malformed "
                           f"shape {shape}")
        buf = bytearray(nbytes)
        bmv = memoryview(buf)
        want = _CHUNK2_MAGIC + int(xfer).to_bytes(8, "big")
        _frags_inflight.set(int(nchunks))
        nchunks, chunk = int(nchunks), int(chunk)
        ring_ent = None
        if self._same_host(src_pidx):
            slot = _slot_of(tag, self._cap(self.my_pidx)[1])
            ring_ent = self._rx_ring(src_pidx, slot, deadline)

        def place(praw) -> bool:
            """One already-materialized frame (stash/cross-tag restash
            path): the portable placement + stale-drop discipline."""
            if not praw.startswith(want):
                return False  # stale frame from an abandoned transfer
            idx = int.from_bytes(praw[12:20], "big")
            payload = memoryview(praw)[20:]
            off = idx * chunk
            if idx >= nchunks or off + len(payload) > nbytes:
                raise MPIError(
                    ErrorCode.ERR_TRUNCATE,
                    f"staged transfer {xfer}: fragment {idx} overruns "
                    f"the {nbytes}-byte buffer",
                )
            bmv[off:off + len(payload)] = payload
            return True

        got = 0
        tok = None
        if ring_ent is not None and _watchdog.enabled:
            # the empty-ring wait blocks inside ring.read_frag (C
            # slices of <=200ms): name the ring, its producer, and the
            # live occupancy in any stall postmortem
            tok = _watchdog.arm(
                "nw_ring_recv", peer=src_pidx,
                info=lambda r=ring_ent[0], p=src_pidx: _ring_wait_info(
                    r, p, "recv"))
        try:
            while got < nchunks:
                praw = self._pop_stashed(oob_ep, src, tag)
                if praw is not None:
                    if place(praw):
                        got += 1
                        self.staged_chunks_pvar.add()
                    continue
                left_ms = int((deadline - _time.monotonic()) * 1000)
                if left_ms <= 0:
                    raise MPIError(
                        ErrorCode.ERR_PENDING,
                        f"native staged transfer {xfer} from process "
                        f"{src_pidx}: timed out with {got}/{nchunks} "
                        "fragments",
                    )
                step = min(left_ms, 200)
                if ring_ent is not None:
                    ring, rlk, rstash = ring_ent
                    restash = None
                    with rlk:
                        q = rstash.get(tag)
                        praw = q.pop(0) if q else None
                        if praw is None:
                            rc = ring.read_frag(tag, xfer, nchunks,
                                                chunk, buf, step)
                            if rc == -5:
                                restash = self._pop_other_locked(ring)
                    if praw is not None:
                        if place(praw):
                            got += 1
                            self.staged_chunks_pvar.add()
                        continue
                    if restash is not None:
                        rlen, rtag, raw2 = restash
                        with rlk:
                            rstash.setdefault(rtag, []).append(raw2)
                        _fallback_copies.add()  # the one restash copy
                        continue
                    if rc >= 0:
                        got += 1
                        self.staged_chunks_pvar.add()
                        continue
                    if rc in (-1, -4, -5):
                        continue  # slice timeout / stale / raced
                    if rc == -3:
                        raise MPIError(
                            ErrorCode.ERR_PROC_FAILED,
                            f"shm ring from process {src_pidx} reports "
                            f"its producer dead with {got}/{nchunks} "
                            "fragments landed — peer died mid-transfer",
                        )
                    raise MPIError(
                        ErrorCode.ERR_TRUNCATE,
                        f"staged transfer {xfer}: malformed ring "
                        f"record (rc {rc})",
                    )
                else:
                    rc = oob_ep.recv_frag(src, tag, xfer, nchunks,
                                          chunk, buf, step)
                    if rc >= 0:
                        got += 1
                        self.staged_chunks_pvar.add()
                        continue
                    if rc == -1:
                        continue  # slice timeout: re-check deadline
                    if rc == -4:
                        # the queue head for (src, tag) is not ours:
                        # pop it through the shared stash machinery and
                        # apply the portable stale-drop filter
                        try:
                            _, raw2 = stashed_recv(
                                oob_ep, src, tag,
                                _time.monotonic() + 0.05)
                        except MPIError:
                            continue
                        if place(raw2):
                            got += 1
                            self.staged_chunks_pvar.add()
                        continue
                    raise MPIError(
                        ErrorCode.ERR_TRUNCATE,
                        f"staged transfer {xfer}: fragment overruns "
                        f"the {nbytes}-byte buffer (native rc {rc})",
                    )
        finally:
            if tok is not None:
                _watchdog.disarm(tok)
        if zlib.crc32(bmv) != int(crc):
            raise MPIError(
                ErrorCode.ERR_TRUNCATE,
                f"staged transfer {xfer} failed its payload CRC — "
                "wire corruption or interleaved frames",
            )
        _zero_copy_strict.add(nbytes)
        _native_bytes.add(nbytes)
        arr = np.frombuffer(buf, dtype=dtype).reshape(shape)
        self.staged_bytes_pvar.add(arr.nbytes)
        if rec and _obs.enabled:
            _obs.record("btl_nw_recv", "btl", t_obs,
                        _time.perf_counter() - t_obs,
                        nbytes=int(arr.nbytes), peer=src_pidx)
        if dst_device is None:
            dst_device = jax.local_devices()[0]
        return jax.device_put(arr, dst_device)

    @staticmethod
    def _pop_other_locked(ring):
        """Pop the ring head (known to belong to another tag) while
        the caller holds the ring lock; returns (len, tag, bytes) or
        None when the head raced away / cannot be materialized."""
        size = 1 << 16
        while True:
            tmp = bytearray(size)
            rc, rtag = ring.read_into(tmp, 10)
            if rc == -2:
                if size >= ring.capacity:
                    return None
                size = min(size * 8, ring.capacity)
                continue
            if rc < 0:  # -1 raced-empty / -3 dead: main loop handles
                return None
            return rc, rtag, bytes(memoryview(tmp)[:rc])


class NativeWireComponent(mca_component.Component):
    """MCA shell: withdraws (``query`` -> None) whenever the local
    capability is absent, so BML selection and the fallback contract
    are decided by the standard component machinery."""

    NAME = "nativewire"
    PRIORITY = 45  # between shm (50) and dcn (40): preferred wire path

    def register_vars(self) -> None:
        base.register_module_vars(NativeWireBtl)
        register_nativewire_vars()

    def query(self, ctx=None):
        if not nativewire_ready():
            return None
        return (self.priority, NativeWireBtl())


base.BTL_FRAMEWORK.register(NativeWireComponent())


def _native_rings() -> dict:
    """Watchdog-postmortem contributor: every live native ring's
    identity, endpoints, occupancy, and counter block — whatever rank
    is stalled, the postmortem shows which ring sat full/empty and
    which pid was supposed to drain/fill it."""
    with _tele_lock:
        tx = list(_live_tx)
        rx = list(_live_rx)
        retired = (_retired[0], _retired[1])

    def row(ring) -> dict:
        try:
            return {"name": ring.name, "capacity": int(ring.capacity),
                    "pending": int(ring.pending()),
                    "producer_pid": int(ring.producer_pid()),
                    "consumer_pid": int(ring.consumer_pid()),
                    "stats": ring.stats()}
        except Exception:
            return {"name": getattr(ring, "name", "?")}

    return {"tx": [row(r) for r in tx], "rx": [row(r) for r in rx],
            "retired_stalls": retired[0],
            "retired_stall_ns": retired[1]}


_watchdog.add_contributor("native_rings", _native_rings)
