"""btl — pluggable device-transfer layer (ompi/mca/btl + bml analogue)."""

from .base import BTL_FRAMEWORK, BmlEndpoint, BmlR2, BtlModule
from . import components as _components  # noqa: F401  (self-register)
from . import nativewire as _nativewire  # noqa: F401  (self-register)

__all__ = ["BTL_FRAMEWORK", "BmlEndpoint", "BmlR2", "BtlModule"]
