"""MCA-style typed configuration variable system.

TPU-native re-design of the reference's MCA variable system
(``opal/mca/base/mca_base_var.c``, 2064 LoC): every framework/component
registers typed, documented variables into one global registry; values are
resolved with the same precedence order the reference uses
(``mca_base_var.c`` source enum): explicit set/CLI override > environment
variable > parameter file > registered default.

Reference parity notes:
  - variable naming follows ``<framework>_<component>_<name>`` (e.g.
    ``coll_tuned_allreduce_algorithm``), like ``mca_base_var_register``.
  - env lookup uses the ``OMPITPU_MCA_<name>`` prefix (reference uses
    ``OMPI_MCA_<name>``, ``opal/mca/base/mca_base_var.c``).
  - param files are ``key = value`` lines (``mca_base_parse_paramfile.c``).
  - enum-valued variables mirror e.g. the allreduce algorithm enum
    (``ompi/mca/coll/tuned/coll_tuned_allreduce.c:46-54``).
"""

from __future__ import annotations

import dataclasses
import enum
import os
import re
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

ENV_PREFIX = "OMPITPU_MCA_"


class VarSource(enum.IntEnum):
    """Where a variable's current value came from (priority order)."""

    DEFAULT = 0
    FILE = 1
    ENV = 2
    OVERRIDE = 3  # CLI --mca or programmatic set_value


class VarScope(enum.IntEnum):
    """Mirror of MCA_BASE_VAR_SCOPE_*: may the value change after init?

    READONLY/CONSTANT forbid *runtime* writes (set_value/apply_cli after
    the variable is registered). Launch-time sources — env, param files,
    and CLI overrides recorded before registration — still apply, same
    as the reference, where READONLY MCA vars are set via OMPI_MCA_* at
    launch but rejected by MPI_T_cvar_write afterwards.
    """

    CONSTANT = 0   # never changes
    READONLY = 1   # fixed once registered/resolved
    LOCAL = 2      # may differ per process
    ALL = 3        # settable any time


class VarLevel(enum.IntEnum):
    """Mirror of MCA_BASE_VAR_INFO_LVL_* (1..9): user → developer detail."""

    USER_BASIC = 1
    USER_DETAIL = 2
    USER_ALL = 3
    TUNER_BASIC = 4
    TUNER_DETAIL = 5
    TUNER_ALL = 6
    DEV_BASIC = 7
    DEV_DETAIL = 8
    DEV_ALL = 9


_SIZE_SUFFIX = {
    "": 1,
    "k": 1 << 10,
    "kb": 1 << 10,
    "m": 1 << 20,
    "mb": 1 << 20,
    "g": 1 << 30,
    "gb": 1 << 30,
}

_TRUE = {"1", "true", "yes", "on", "enabled"}
_FALSE = {"0", "false", "no", "off", "disabled"}


def parse_size(text: str) -> int:
    """Parse ``64K`` / ``1M`` / ``4096`` into bytes."""
    m = re.fullmatch(r"\s*(\d+)\s*([kKmMgG][bB]?)?\s*", str(text))
    if not m:
        raise ValueError(f"cannot parse size value {text!r}")
    return int(m.group(1)) * _SIZE_SUFFIX[(m.group(2) or "").lower()]


def _coerce(vtype: str, value: Any, choices: Optional[Sequence[str]]) -> Any:
    if value is None:
        return None
    if vtype == "int":
        return int(value)
    if vtype == "float":
        return float(value)
    if vtype == "size":
        if isinstance(value, (int, float)):
            return int(value)
        return parse_size(value)
    if vtype == "bool":
        if isinstance(value, bool):
            return value
        s = str(value).strip().lower()
        if s in _TRUE:
            return True
        if s in _FALSE:
            return False
        raise ValueError(f"cannot parse bool value {value!r}")
    if vtype == "str":
        return str(value)
    if vtype == "enum":
        s = str(value)
        assert choices is not None
        if s not in choices:
            raise ValueError(f"value {s!r} not in enum choices {list(choices)}")
        return s
    if vtype == "list":
        if isinstance(value, (list, tuple)):
            return [str(v) for v in value]
        s = str(value).strip()
        return [p for p in (x.strip() for x in s.split(",")) if p]
    raise ValueError(f"unknown variable type {vtype!r}")


@dataclasses.dataclass
class Var:
    """One registered configuration variable."""

    name: str
    vtype: str  # int | float | bool | str | enum | size | list
    default: Any
    help: str = ""
    scope: VarScope = VarScope.ALL
    level: VarLevel = VarLevel.USER_BASIC
    choices: Optional[Sequence[str]] = None
    # resolved state
    value: Any = None
    source: VarSource = VarSource.DEFAULT
    deprecated: bool = False
    synonyms: Sequence[str] = ()

    def describe(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "type": self.vtype,
            "value": self.value,
            "default": self.default,
            "source": self.source.name,
            "scope": self.scope.name,
            "level": int(self.level),
            "help": self.help,
            "choices": list(self.choices) if self.choices else None,
        }


class VarRegistry:
    """Global registry of typed variables (the ``mca_base_var`` table)."""

    def __init__(self) -> None:
        self._vars: Dict[str, Var] = {}
        self._lock = threading.RLock()
        self._file_values: Dict[str, str] = {}
        self._overrides: Dict[str, str] = {}
        self._files_loaded: List[str] = []
        #: monotone write generation: bumped on every successful value
        #: change (set_value/unset/apply_cli/param file/env refresh and
        #: first-time registrations). Hot paths cache resolved values
        #: stamped with this integer — one plain attribute read and an
        #: int compare replaces a per-message lock + dict lookup, and a
        #: stale stamp says exactly when to re-resolve (the "cvar
        #: writes take effect at the next plan" contract).
        self.generation: int = 0

    # -- registration -----------------------------------------------------
    def register(
        self,
        name: str,
        vtype: str,
        default: Any,
        help: str = "",
        *,
        scope: VarScope = VarScope.ALL,
        level: VarLevel = VarLevel.USER_BASIC,
        choices: Optional[Sequence[str]] = None,
        synonyms: Sequence[str] = (),
    ) -> Var:
        """Register a variable and resolve its value immediately.

        Re-registering the same name with the same type is idempotent and
        returns the existing variable (components may be re-opened).
        """
        with self._lock:
            if name in self._vars:
                existing = self._vars[name]
                if existing.vtype != vtype:
                    raise ValueError(
                        f"variable {name!r} re-registered with type "
                        f"{vtype!r} != {existing.vtype!r}"
                    )
                return existing
            if vtype == "enum" and not choices:
                raise ValueError(f"enum variable {name!r} needs choices")
            var = Var(
                name=name,
                vtype=vtype,
                default=_coerce(vtype, default, choices),
                help=help,
                scope=scope,
                level=level,
                choices=tuple(choices) if choices else None,
                synonyms=tuple(synonyms),
            )
            # resolve before publishing: an invalid env/file value must not
            # leave a half-initialized var in the registry
            self._resolve(var)
            self._vars[name] = var
            self.generation += 1  # a NEW var changes get() results
            return var

    # -- value resolution (precedence) ------------------------------------
    def _raw_lookup(self, var: Var) -> tuple:
        names = (var.name, *var.synonyms)
        for n in names:
            if n in self._overrides:
                return self._overrides[n], VarSource.OVERRIDE
        for n in names:
            env = os.environ.get(ENV_PREFIX + n)
            if env is not None:
                return env, VarSource.ENV
        for n in names:
            if n in self._file_values:
                return self._file_values[n], VarSource.FILE
        return var.default, VarSource.DEFAULT

    def _resolve(self, var: Var) -> None:
        raw, source = self._raw_lookup(var)
        try:
            var.value = _coerce(var.vtype, raw, var.choices)
        except ValueError as exc:
            raise ValueError(
                f"invalid value {raw!r} for MCA variable {var.name!r} "
                f"(type {var.vtype}, from {source.name}): {exc}"
            ) from None
        var.source = source

    def _resolve_all(self) -> None:
        for var in self._vars.values():
            self._resolve(var)

    # -- accessors ---------------------------------------------------------
    def get(self, name: str, default: Any = None) -> Any:
        with self._lock:
            var = self._vars.get(name)
            if var is None:
                return default
            return var.value

    def lookup(self, name: str) -> Optional[Var]:
        with self._lock:
            return self._vars.get(name)

    def set_value(self, name: str, value: Any) -> None:
        """Programmatic/CLI override (highest precedence)."""
        with self._lock:
            var = self._vars.get(name)
            if var is not None and var.scope in (
                VarScope.CONSTANT, VarScope.READONLY
            ):
                raise PermissionError(
                    f"variable {name!r} has scope {var.scope.name}"
                )
            had_prev = name in self._overrides
            prev = self._overrides.get(name)
            self._overrides[name] = value
            self.generation += 1
            if var is not None:
                try:
                    self._resolve(var)
                except (ValueError, TypeError):
                    # a REJECTED set must not poison the registry: the
                    # stored override would make every later get() of
                    # this variable raise (observed as cross-test
                    # contamination) — roll back to the prior state.
                    # TypeError included: int([1, 2]) raises it, not
                    # ValueError, and would slip the same poison past
                    # a ValueError-only net
                    if had_prev:
                        self._overrides[name] = prev
                    else:
                        del self._overrides[name]
                    self._resolve(var)
                    raise

    def unset(self, name: str) -> None:
        with self._lock:
            self._overrides.pop(name, None)
            self.generation += 1
            var = self._vars.get(name)
            if var is not None:
                self._resolve(var)

    # -- param files / CLI -------------------------------------------------
    def load_param_file(self, path: str) -> int:
        """Load ``key = value`` lines; later files win over earlier ones."""
        parsed: Dict[str, str] = {}
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.split("#", 1)[0].strip()
                if not line or "=" not in line:
                    continue
                key, _, val = line.partition("=")
                parsed[key.strip()] = val.strip()
        with self._lock:
            self._file_values.update(parsed)
            self._files_loaded.append(path)
            self.generation += 1
            self._resolve_all()
        return len(parsed)

    def apply_cli(self, pairs: Iterable[tuple]) -> None:
        """Apply ``--mca key value`` pairs from a command line.

        READONLY/CONSTANT variables are skipped with a warning instead
        of raising — a bad CLI flag must not abort the whole launch.
        """
        from ..utils import output

        with self._lock:
            for key, val in pairs:
                var = self._vars.get(key)
                if var is not None and var.scope in (
                    VarScope.CONSTANT, VarScope.READONLY
                ):
                    output.stream("mca.var").warn(
                        f"ignoring --mca {key}: scope {var.scope.name}"
                    )
                    continue
                self._overrides[key] = val
            self.generation += 1
            self._resolve_all()

    def refresh_from_env(self) -> None:
        """Re-read environment (tests mutate os.environ)."""
        with self._lock:
            self.generation += 1
            self._resolve_all()

    def describe_all(self, max_level: VarLevel = VarLevel.DEV_ALL) -> List[Dict]:
        with self._lock:
            return [
                v.describe()
                for v in sorted(self._vars.values(), key=lambda v: v.name)
                if int(v.level) <= int(max_level)
            ]

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._vars)

    # -- test support ------------------------------------------------------
    def _reset_for_tests(self) -> None:
        with self._lock:
            self._vars.clear()
            self._file_values.clear()
            self._overrides.clear()
            self._files_loaded.clear()
            self.generation += 1


#: process-global registry — the single config mechanism (SURVEY §5).
VARS = VarRegistry()


def register(name: str, vtype: str, default: Any, help: str = "", **kw) -> Var:
    return VARS.register(name, vtype, default, help, **kw)


def get(name: str, default: Any = None) -> Any:
    return VARS.get(name, default)


def set_value(name: str, value: Any) -> None:
    VARS.set_value(name, value)
