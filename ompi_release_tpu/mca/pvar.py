"""Performance variables (pvars): runtime counters exposed for tools.

Analogue of ``opal/mca/base/mca_base_pvar.c`` + the MPI_T performance
variable interface (``ompi/mpi/tool/``): components register named
counters/timers/levels; tools (``tpu_info``, tracing layer) read and reset
them without recompiling anything.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Any, Callable, Dict, List, Optional


class PvarClass(enum.Enum):
    COUNTER = "counter"        # monotonically increasing
    LEVEL = "level"            # current utilization level
    HIGHWATERMARK = "highwatermark"
    TIMER = "timer"            # accumulated seconds
    STATE = "state"            # discrete state value


class Pvar:
    def __init__(self, name: str, pclass: PvarClass, help: str = "",
                 getter: Optional[Callable[[], Any]] = None) -> None:
        self.name = name
        self.pclass = pclass
        self.help = help
        self._value: float = 0
        self._getter = getter
        self._lock = threading.Lock()

    def add(self, delta: float = 1) -> None:
        with self._lock:
            self._value += delta

    def set(self, value: float) -> None:
        with self._lock:
            if self.pclass is PvarClass.HIGHWATERMARK:
                self._value = max(self._value, value)
            else:
                self._value = value

    def read(self) -> Any:
        if self._getter is not None:
            return self._getter()
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    class _TimerCtx:
        def __init__(self, pvar: "Pvar") -> None:
            self._pvar = pvar

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._pvar.add(time.perf_counter() - self._t0)
            return False

    def timing(self) -> "_TimerCtx":
        assert self.pclass is PvarClass.TIMER
        return Pvar._TimerCtx(self)


class PvarRegistry:
    def __init__(self) -> None:
        self._pvars: Dict[str, Pvar] = {}
        self._lock = threading.Lock()

    def register(self, name: str, pclass: PvarClass = PvarClass.COUNTER,
                 help: str = "", getter: Optional[Callable[[], Any]] = None) -> Pvar:
        with self._lock:
            if name in self._pvars:
                return self._pvars[name]
            pv = Pvar(name, pclass, help, getter)
            self._pvars[name] = pv
            return pv

    def lookup(self, name: str) -> Optional[Pvar]:
        with self._lock:
            return self._pvars.get(name)

    def read_all(self) -> Dict[str, Any]:
        with self._lock:
            return {n: p.read() for n, p in sorted(self._pvars.items())}

    def describe_all(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                {"name": p.name, "class": p.pclass.value, "help": p.help,
                 "value": p.read()}
                for p in sorted(self._pvars.values(), key=lambda p: p.name)
            ]

    def reset_all(self) -> None:
        with self._lock:
            for p in self._pvars.values():
                p.reset()

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._pvars.clear()


PVARS = PvarRegistry()


def counter(name: str, help: str = "") -> Pvar:
    return PVARS.register(name, PvarClass.COUNTER, help)


def timer(name: str, help: str = "") -> Pvar:
    return PVARS.register(name, PvarClass.TIMER, help)


def highwatermark(name: str, help: str = "") -> Pvar:
    return PVARS.register(name, PvarClass.HIGHWATERMARK, help)
