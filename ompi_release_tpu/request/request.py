"""Completion objects — the ``ompi/request`` analogue.

The reference completes requests by spinning the progress engine
(``ompi/request/request.h:370-386`` wait_completion →
``opal_progress()``). Here the data plane is XLA async dispatch: a jax
array IS a future, so "progress" is asking the runtime whether the
result is ready, and wait is ``block_until_ready``. Host-side work
(matching, deferred rendezvous transfers) progresses via explicit
callbacks the owning engine registers on the request.

Generalized requests (``ompi/request/grequest.c``) carry user
query/free/cancel callbacks and are completed by user code.
"""

from __future__ import annotations

import enum
import threading
import time as _time
from typing import Any, Callable, List, Optional, Sequence, Tuple

from .. import obs as _obs
from ..mca import pvar
from ..utils.errors import ErrorCode, MPIError

_req_count = pvar.counter("requests_created", "requests ever created")

#: shared progress hooks (the async progress engine's tick lands here,
#: registered by runtime/progress.py at import): ONE call advances
#: every pending request an engine owns — wire-channel reaps, ready
#: in-process arrays, queued schedule completions — so the multi-
#: request operations below tick once per pass instead of spinning
#: per-request, and a bare wait() drives the engine instead of
#: sleeping blind.
_progress_hooks: List[Callable[[], int]] = []


def register_progress_hook(fn: Callable[[], int]) -> None:
    """Register an engine tick (idempotent by identity). The hook must
    be nonblocking and return how many items progressed."""
    if fn not in _progress_hooks:
        _progress_hooks.append(fn)


def run_progress() -> int:
    """Run every registered engine tick once; returns total items
    progressed. THE shared hook wait_all/test_all/wait_any and
    from_future-backed waits call between completion checks."""
    n = 0
    for fn in list(_progress_hooks):
        n += int(fn() or 0)
    return n


class RequestState(enum.Enum):
    INACTIVE = "inactive"  # persistent request not started
    ACTIVE = "active"
    COMPLETE = "complete"
    CANCELLED = "cancelled"


class Status:
    """MPI_Status analogue."""

    __slots__ = ("source", "tag", "error", "count", "cancelled")

    def __init__(self, source: int = -1, tag: int = -1, error: int = 0,
                 count: int = 0, cancelled: bool = False) -> None:
        self.source = source
        self.tag = tag
        self.error = error
        self.count = count
        self.cancelled = cancelled

    def __repr__(self) -> str:
        return (
            f"Status(source={self.source}, tag={self.tag}, "
            f"count={self.count})"
        )


class Request:
    """A completion handle.

    ``progress_fn`` (optional) is polled by test/wait — the hook where
    the owning engine advances host-side state (e.g. a rendezvous
    transfer waiting for its matching recv). ``ready_fn`` (optional)
    reports whether async device work has finished without blocking;
    ``block_fn`` blocks on it.
    """

    def __init__(self, *, progress_fn: Optional[Callable] = None,
                 ready_fn: Optional[Callable] = None,
                 block_fn: Optional[Callable] = None,
                 persistent_start: Optional[Callable] = None) -> None:
        _req_count.add()
        self.state = (
            RequestState.INACTIVE if persistent_start else RequestState.ACTIVE
        )
        self.status = Status()
        self.value: Any = None  # recv payload once complete
        self._progress_fn = progress_fn
        self._ready_fn = ready_fn
        self._block_fn = block_fn
        self._persistent_start = persistent_start
        self._lock = threading.Lock()
        self._on_complete: List[Callable] = []

    # -- engine side -------------------------------------------------------
    def complete(self, value: Any = None, status: Optional[Status] = None
                 ) -> None:
        with self._lock:
            if self.state is RequestState.COMPLETE:
                return
            self.value = value if value is not None else self.value
            if status is not None:
                self.status = status
            self.state = RequestState.COMPLETE
            callbacks = list(self._on_complete)
        for cb in callbacks:
            cb(self)

    def on_complete(self, cb: Callable) -> None:
        run_now = False
        with self._lock:
            if self.state is RequestState.COMPLETE:
                run_now = True
            else:
                self._on_complete.append(cb)
        if run_now:
            cb(self)

    # -- user side ---------------------------------------------------------
    @property
    def is_complete(self) -> bool:
        return self.state is RequestState.COMPLETE

    def start(self) -> "Request":
        """Restart a persistent request (MPI_Start)."""
        if self._persistent_start is None:
            raise MPIError(ErrorCode.ERR_REQUEST,
                           "start() on a non-persistent request")
        if self.state is RequestState.ACTIVE:
            raise MPIError(ErrorCode.ERR_REQUEST,
                           "start() on an active request")
        self.state = RequestState.ACTIVE
        self.status = Status()
        self._persistent_start(self)
        return self

    def poll(self) -> bool:
        """Nonblocking readiness check used by the progress engine's
        tick: completes the request if its async device work finished.
        Unlike test(), never invokes progress_fn (the engine IS the
        caller — recursing into its own tick would be a no-op)."""
        if self.state is RequestState.COMPLETE:
            return True
        if self.state is not RequestState.ACTIVE:
            return False
        if self._ready_fn is not None and self._ready_fn():
            self.complete()
        return self.state is RequestState.COMPLETE

    def test(self) -> Tuple[bool, Optional[Status]]:
        if self.state is RequestState.INACTIVE:
            return True, None  # MPI: inactive tests as complete/empty
        if self.state is RequestState.COMPLETE:
            return True, self.status
        if self._progress_fn is not None:
            self._progress_fn(self)
        if (self.state is not RequestState.COMPLETE
                and self._ready_fn is not None and self._ready_fn()):
            self.complete()
        return self.is_complete, self.status if self.is_complete else None

    def wait(self) -> Status:
        rec = _obs.enabled  # capture once: flag may flip mid-wait
        t0 = _time.perf_counter() if rec else 0.0
        done, _ = self.test()
        if not done:
            if self._block_fn is not None:
                self._block_fn()
                self.complete()
            else:
                # host-side requests complete via callbacks; spinning
                # means a matching operation was never posted
                raise MPIError(
                    ErrorCode.ERR_PENDING,
                    "wait() would deadlock: request has no device work "
                    "and no completion event (missing matching call?)",
                )
        if rec:  # how long completion blocked the host
            _obs.record("wait", "request", t0, _time.perf_counter() - t0)
        return self.status

    def cancel(self) -> None:
        """MPI_Cancel: the request then COMPLETES with
        status.cancelled=True (MPI requires a subsequent wait/test to
        succeed and report the cancellation)."""
        with self._lock:
            if self.state is not RequestState.ACTIVE:
                return
            self.state = RequestState.COMPLETE
            self.status.cancelled = True
            callbacks = list(self._on_complete)
        for cb in callbacks:
            cb(self)

    @property
    def is_cancelled(self) -> bool:
        return self.status.cancelled

    def free(self) -> None:
        self._on_complete.clear()


def _raise(exc) -> None:
    raise exc


def from_future(fut) -> Request:
    """Wrap a ``concurrent.futures.Future`` as a Request: success
    completes with the future's value; failure surfaces the worker's
    exception at test()/wait() (the libnbc error-on-progress
    contract). Shared by the nonblocking-IO pool
    (``io/file.py:_future_request`` adds its count Status on top).
    A bare wait() DRIVES the shared progress hook between bounded
    future polls — the engine keeps advancing other in-flight work
    (wire reaps, queued schedules) instead of this thread sleeping the
    whole wait out."""
    from concurrent.futures import TimeoutError as _FutTimeout

    completed = threading.Event()

    def block() -> None:
        # poll cadence adapts to whether the engine actually has work:
        # ticks that advance something keep the tight 5 ms cadence;
        # an idle engine backs off to 100 ms so a long IO wait sleeps
        # in fut.result() instead of burning CPU on empty ticks
        delay = 0.005
        while True:
            progressed = run_progress()
            try:
                fut.result(timeout=delay)  # raises worker's exception
                break
            except _FutTimeout:
                # the future may have SETTLED during this poll slice
                # (and on 3.11+ concurrent.futures.TimeoutError IS
                # builtin TimeoutError, so a done future re-raising a
                # worker TimeoutError looks identical to the slice
                # elapsing): loop — result() on a done future returns
                # the value or raises the WORKER's own exception
                # immediately, never the poll timeout
                if fut.done():
                    continue
                delay = 0.005 if progressed else min(delay * 2, 0.1)
        # Future.set_result wakes result() BEFORE running done
        # callbacks: wait until the callback has completed the
        # request, or wait()'s bare complete() would win the race and
        # report value=None for a successful op
        completed.wait()

    def progress(r) -> None:
        run_progress()
        if fut.done() and fut.exception():
            _raise(fut.exception())

    req = Request(progress_fn=progress, block_fn=block)

    def _done(f) -> None:
        if f.exception() is None:
            req.complete(value=f.result())
        completed.set()

    fut.add_done_callback(_done)
    return req


class GeneralizedRequest(Request):
    """MPI_Grequest_start analogue: user code completes it."""

    def __init__(self, query_fn=None, free_fn=None, cancel_fn=None,
                 extra_state=None) -> None:
        super().__init__()
        self._query_fn = query_fn
        self._free_fn = free_fn
        self._cancel_fn = cancel_fn
        self.extra_state = extra_state

    def complete(self, value: Any = None, status: Optional[Status] = None
                 ) -> None:
        if status is None and self._query_fn is not None:
            status = self._query_fn(self.extra_state)
        super().complete(value, status)

    def cancel(self) -> None:
        if self._cancel_fn is not None:
            self._cancel_fn(self.extra_state,
                            self.state is RequestState.COMPLETE)
        super().cancel()

    def free(self) -> None:
        if self._free_fn is not None:
            self._free_fn(self.extra_state)
        super().free()


# ---------------------------------------------------------------------------
# multi-request operations (ompi/request/req_wait.c / req_test.c)
# ---------------------------------------------------------------------------

def test(req: Request) -> Tuple[bool, Optional[Status]]:
    return req.test()


def wait(req: Request) -> Status:
    return req.wait()


def test_all(reqs: Sequence[Request]) -> Tuple[bool, Optional[List[Status]]]:
    # ONE shared tick first: a single engine pass reaps every pending
    # request's progress; then each test() is a cheap completion check
    run_progress()
    if all(r.test()[0] for r in reqs):
        return True, [r.status for r in reqs]
    return False, None


def wait_all(reqs: Sequence[Request]) -> List[Status]:
    # one tick up front may complete many at once (the engine advances
    # ALL pending schedules/arrays in a pass); per-request wait() then
    # drives the engine for whatever is still in flight
    run_progress()
    return [r.wait() for r in reqs]


def test_any(reqs: Sequence[Request]
             ) -> Tuple[Optional[int], Optional[Status]]:
    run_progress()  # one tick covers the whole scan
    for i, r in enumerate(reqs):
        done, st = r.test()
        if done and r.state is not RequestState.INACTIVE:
            return i, st
    return None, None


def wait_any(reqs: Sequence[Request]) -> Tuple[int, Status]:
    if not reqs:
        raise MPIError(ErrorCode.ERR_ARG, "wait_any on empty request list")
    # pass 1: anything already done; pass 2: block on the first request
    # that CAN block (device work); host-side requests with no pending
    # completion event cannot finish on their own in driver mode
    i, st = test_any(reqs)
    if i is not None:
        return i, st
    for j, r in enumerate(reqs):
        if r._block_fn is not None and r.state is RequestState.ACTIVE:
            return j, r.wait()
    raise MPIError(
        ErrorCode.ERR_PENDING,
        "wait_any would deadlock: no request is complete, and none has "
        "device work to block on (missing matching call?)",
    )


def wait_some(reqs: Sequence[Request]) -> Tuple[List[int], List[Status]]:
    if all(r.state is RequestState.INACTIVE for r in reqs):
        return [], []  # MPI_Waitsome: outcount undefined, nothing waits
    idx, sts = [], []
    wait_any(reqs)
    for j, r in enumerate(reqs):
        if r.state is RequestState.INACTIVE:
            continue  # MPI_Waitsome ignores inactive requests
        done, _ = r.test()
        if done:
            idx.append(j)
            sts.append(r.status)
    return idx, sts
