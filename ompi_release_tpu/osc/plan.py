"""Frozen RMA access plans — the one-sided analogue of ``coll/plan``.

``Window._run_epoch_program`` already aggregates an epoch's ops into
one device program, but every close still pays the full Python
orchestration: branch-key derivation, payload staging, pow2 padding,
cache lookups — and the wire window re-serializes every remote batch
header from scratch. Real one-sided workloads (param-server updates,
KV-cache fills, SHMEM counter loops) close the SAME epoch shape over
and over, so this module freezes per-(window, epoch-signature)
**access plans**:

- the signature is the epoch's op sequence as hashable metadata —
  (kind, target, payload shape/dtype, the frozen Op OBJECT, index,
  read-request flag) per op — derived with the same descriptor rules
  ``coll/plan`` uses (``arg_desc``), so a same-named user op can never
  alias a predefined op's program;
- a plan holds ONE fused XLA program for the epoch's local/device
  side: targets, branch kinds, and indices are baked as constants
  into an unrolled program over the window state (no ``lax.scan``
  carry, no ``lax.switch`` dispatch, no per-close staging of code/
  target/index arrays), reusing ``Window._branch_fn`` so planned and
  interpreted closes are BITWISE identical;
- for the remote side, :class:`BatchTemplate` precomposes the wire
  request record (the per-op meta JSON) at freeze time and re-renders
  only the payload arrays, byte-identical to ``_pack_batch`` output —
  ``WinService``, the sentinel, and tpu-doctor are unchanged on the
  wire;
- plans are generation-stamped against the MCA write generation
  exactly like ``SchedulePlan``: any cvar write re-plans at the next
  epoch close. The first close of a new signature runs the
  interpreted program (the capturing run); replay divergence drops
  the plan loudly and re-records at the next close.

Plans live on the window (``win._access_plans`` /
``win._batch_templates``) and are evicted at ``win.free()`` — a dead
window must not pin fused programs. Callers hold the window's
``_op_lock``; device dispatch itself stays under the process-wide
``_dispatch_lock`` (the jaxlib rendezvous rule in ``window.py``).
"""

from __future__ import annotations

import json
import time as _time
from typing import Any, List, Optional, Tuple

import numpy as np

from .. import obs as _obs
from ..coll.plan import arg_desc
from ..mca import pvar
from ..mca import var as mca_var
from ..obs import ledger as _ledger
from ..request.request import Status
from ..utils import output

_log = output.stream("osc")


def register_vars() -> None:
    mca_var.register(
        "osc_compiled", "bool", True,
        "Freeze per-(window, epoch-signature) RMA access plans: a "
        "repeated epoch replays one fused XLA program plus "
        "precomposed wire frames instead of re-interpreting the "
        "pending queue (osc/plan); false keeps every close on the "
        "interpreted scan/switch program",
    )
    mca_var.register(
        "osc_plan_max_ops", "int", 128,
        "Largest epoch (pending-op count) eligible for a frozen "
        "access plan — the fused program is unrolled, so this bounds "
        "XLA compile size; larger epochs stay interpreted",
    )


register_vars()

_plan_hits = pvar.aggregate(
    "osc_plan_cache_hits",
    "plannable RMA closes served by a frozen access plan (1) vs "
    "capturing/re-freezing runs (0) — sum/count = steady-state ratio",
)
_plans_frozen = pvar.counter(
    "osc_plans_frozen", "RMA access plans frozen (one per new "
    "(window, epoch signature))",
)
_plan_programs = pvar.counter(
    "osc_plan_programs",
    "fused epoch programs compiled (first replay of a frozen plan)",
)
_templates_frozen = pvar.counter(
    "osc_batch_templates",
    "plan-time wire batch templates frozen (precomposed remote "
    "request records)",
)
_orch = pvar.timer(
    "osc_orchestration_seconds",
    "host time from epoch-close entry to device-program handoff "
    "(both the interpreted and the planned path feed it — the bench's "
    "steady_rma_* split reads this)",
)

#: generation-cached cvar snapshot: (generation, enabled, max_ops) —
#: steady-state closes cost one attribute read + int compare, never a
#: registry lookup (the WireRouter.tuning() pattern)
_conf: Tuple[int, bool, int] = (-1, True, 128)


def _refresh_conf() -> Tuple[int, bool, int]:
    global _conf
    gen = mca_var.VARS.generation
    if _conf[0] != gen:
        _conf = (
            gen,
            bool(mca_var.get("osc_compiled", True)),
            int(mca_var.get("osc_plan_max_ops", 128) or 0),
        )
    return _conf


def orch_add(seconds: float) -> None:
    """Interpreted-path hook: ``_run_epoch_program`` reports its
    orchestration span here so planned and interpreted closes are
    measured identically."""
    _orch.add(seconds)


# ---------------------------------------------------------------------------
# epoch signatures
# ---------------------------------------------------------------------------

def epoch_signature(todo: List) -> Optional[Tuple]:
    """Hashable signature of one epoch's op sequence, or None when any
    op is unplannable (an unhashable user op). The sequence is ordered
    — ops on overlapping targets must replay in submission order
    (MPI same-origin ordering), so order is part of the identity."""
    sig = []
    for p in todo:
        dd = None
        if p.data is not None:
            dd = arg_desc(p.data)
            if dd is None:
                return None
        cd = None
        if p.compare is not None:
            cd = arg_desc(p.compare)
            if cd is None:
                return None
        od = None
        if p.op is not None:
            od = arg_desc(p.op)
            if od is None:
                return None
        sig.append((
            p.kind, int(p.target), dd, od, cd,
            -1 if p.index is None else int(p.index),
            p.request is not None,
            -1 if p.status_rank is None else int(p.status_rank),
        ))
    return tuple(sig)


# ---------------------------------------------------------------------------
# the fused device-side plan
# ---------------------------------------------------------------------------

class EpochPlan:
    """One frozen access plan: the epoch's op metadata baked into an
    unrolled fused program over the window state. ``steps`` holds per
    op (kind, target, has_data, has_compare, index, op, status_rank,
    has_request) — everything but the payload bytes, which arrive as
    program arguments at replay."""

    __slots__ = ("gen", "sig", "steps", "prog", "nbytes", "lid")

    def __init__(self, gen: int, sig: Tuple, todo: List) -> None:
        self.gen = gen
        self.sig = sig
        self.steps = tuple(
            (p.kind, int(p.target), p.data is not None,
             p.compare is not None,
             -1 if p.index is None else int(p.index), p.op,
             p.status_rank, p.request is not None)
            for p in todo
        )
        self.prog = None  # compiled lazily at first replay
        self.nbytes = sum(
            int(getattr(p.data, "nbytes", 0) or 0)
            + int(getattr(p.compare, "nbytes", 0) or 0)
            for p in todo
        )
        self.lid: Optional[int] = None  # ledger plan id, on first
        #                                 observed fire

    def _build(self, win):
        """Compile the fused program: targets/kinds/indices are Python
        constants, payloads are arguments, each op reuses the SAME
        branch lambda the interpreted ``lax.scan`` program dispatches
        through — so replays are bitwise-identical to captures."""
        import jax
        import jax.numpy as jnp

        from .window import Window

        dtype = win._data.dtype
        block = win.shape
        steps = self.steps
        fns = []
        for (kind, _t, _hd, _hc, index, op, _sr, _hr) in steps:
            bkind = "acc" if kind in ("acc", "get_acc") else kind
            fns.append(Window._branch_fn((bkind, op, index >= 0), op))

        def fused(data, *bufs):
            zeros = jnp.zeros(block, dtype)
            reads = []
            bi = 0
            for fn, (kind, tgt, has_d, has_c, idx, op, _sr, has_r) in zip(
                    fns, steps):
                if has_d:
                    pay = jnp.broadcast_to(
                        jnp.asarray(bufs[bi]).astype(dtype), block)
                    bi += 1
                else:
                    pay = zeros
                if has_c:
                    cmp = jnp.broadcast_to(
                        jnp.asarray(bufs[bi]).astype(dtype), block)
                    bi += 1
                else:
                    cmp = zeros
                new, read = fn(data[tgt], pay, cmp, max(idx, 0))
                data = data.at[tgt].set(new)
                if has_r:
                    reads.append(read)
            return data, (jnp.stack(reads) if reads else None)

        _plan_programs.add()
        return jax.jit(fused)

    def replay(self, win, todo: List, t0: float) -> None:
        """Fire the fused program for one epoch close and complete its
        read requests. Caller holds ``win._op_lock``; raises on any
        divergence (the caller drops the plan)."""
        import jax.numpy as jnp

        from .window import _dispatch_lock, _epoch_dispatches

        prog = self.prog
        if prog is None:
            prog = self.prog = self._build(win)
        args = []
        for p in todo:
            if p.data is not None:
                args.append(p.data)
            if p.compare is not None:
                args.append(p.compare)
        _orch.add(_time.perf_counter() - t0)
        with _dispatch_lock:
            _epoch_dispatches.add()
            new_data, reads = prog(win._data, *args)
        # read completion mirrors the interpreted path: ONE host copy
        # outside _dispatch_lock (per-shard fetches, not a program —
        # the rendezvous-deadlock rule in window.py)
        reads_np = None
        ri = 0
        for p in todo:
            if p.request is not None:
                if reads_np is None:
                    reads_np = np.asarray(reads)
                value = reads_np[ri]
                ri += 1
                if p.index is not None:
                    value = value.reshape(-1)[p.index]
                src = (p.target if p.status_rank is None
                       else p.status_rank)
                p.request.complete(value=jnp.asarray(value),
                                   status=Status(source=src))
        win._data = new_data


def close_epoch(win, todo: List, t0: float) -> bool:
    """Close one epoch through the access-plan cache. True = a frozen
    plan replayed (requests completed, ``win._data`` rebound); False =
    the caller must run the interpreted epoch program — either plans
    are off/unplannable, or this close is the capturing run of a
    freshly frozen plan."""
    gen, enabled, max_ops = _refresh_conf()
    if not enabled or not todo or len(todo) > max_ops:
        return False
    sig = epoch_signature(todo)
    if sig is None:
        return False
    plans = win._access_plans
    plan = plans.get(sig)
    if plan is not None and plan.gen == gen:
        try:
            plan.replay(win, todo, t0)
        except Exception as e:
            # divergence: drop the plan LOUDLY and re-record at the
            # next close; this close falls back to the interpreted
            # program (replay is functional — state was not touched)
            plans.pop(sig, None)
            _log.verbose(
                1, f"dropping diverged RMA access plan on {win.name}: "
                   f"{type(e).__name__}: {e}; re-recording")
            return False
        _plan_hits.observe(1)
        if _obs.enabled:
            t1 = _time.perf_counter()
            lid = plan.lid
            if lid is None:
                lid = plan.lid = _ledger.register_rma_plan(
                    win.comm.cid, f"epoch[{len(todo)}]", plan.nbytes,
                    sig)
            _ledger.record_fire(_ledger.KIND_RMA, lid, win.comm.cid,
                                t0, t1)
            _obs.record("rma_epoch_replay", "osc", t0, t1 - t0,
                        nbytes=plan.nbytes, comm_id=win.comm.cid)
        return True
    # first sight (or stale generation): freeze now, capture via the
    # interpreted program this close
    plans[sig] = EpochPlan(gen, sig, todo)
    _plans_frozen.add()
    _plan_hits.observe(0)
    return False


# ---------------------------------------------------------------------------
# plan-time wire frames (the remote side)
# ---------------------------------------------------------------------------

class BatchTemplate:
    """Precomposed wire frame for one remote-batch signature: the
    per-op request records (the meta JSON ``_pack_batch`` builds per
    call) are composed ONCE at freeze time; :meth:`render` re-packs
    only the payload arrays through the same deterministic writer, so
    the frame is byte-identical to ``_pack_batch`` output —
    ``WinService``, the wire sentinel, and tpu-doctor flows are
    unchanged on the wire."""

    __slots__ = ("gen", "meta_arr", "picks")

    def __init__(self, gen: int, todo: List) -> None:
        from .wire_win import _batch_meta

        self.gen = gen
        self.meta_arr = np.frombuffer(
            json.dumps(_batch_meta(todo)).encode(), dtype=np.uint8
        ).copy()
        self.picks = tuple(
            (i, p.data is not None, p.compare is not None)
            for i, p in enumerate(todo)
        )

    def render(self, todo: List) -> np.ndarray:
        from .wire_win import _savez_bytes

        arrays = {}
        for i, has_d, has_c in self.picks:
            p = todo[i]
            if has_d:
                arrays[f"d{i}"] = np.asarray(p.data)
            if has_c:
                arrays[f"c{i}"] = np.asarray(p.compare)
        arrays["meta"] = self.meta_arr
        return np.frombuffer(_savez_bytes(arrays), dtype=np.uint8).copy()


def batch_payload(win, todo: List) -> np.ndarray:
    """Serialize one remote batch: replay the signature's frozen
    :class:`BatchTemplate` in steady state, else pack interpreted and
    freeze. Output bytes are identical either way."""
    from .wire_win import _pack_batch

    gen, enabled, max_ops = _refresh_conf()
    if not enabled or len(todo) > max_ops:
        return _pack_batch(todo)
    sig = epoch_signature(todo)
    if sig is None:
        return _pack_batch(todo)
    tpls = win._batch_templates
    tpl = tpls.get(sig)
    if tpl is not None and tpl.gen == gen:
        _plan_hits.observe(1)
        return tpl.render(todo)
    # the interpreted pack runs first: it owns the predefined-op
    # validation, so an unshippable batch raises before any freeze
    payload = _pack_batch(todo)
    tpls[sig] = BatchTemplate(gen, todo)
    _templates_frozen.add()
    _plan_hits.observe(0)
    return payload


# ---------------------------------------------------------------------------
# operator surface
# ---------------------------------------------------------------------------

def cache_stats() -> dict:
    """Operator-visible plan-cache counters (obs --selftest leg).
    Plans live per-window, so totals are the monotone freeze/compile
    counters, not a live cache census."""
    st = _plan_hits.read()
    return {
        "epoch_plans": int(_plans_frozen.read()),
        "batch_templates": int(_templates_frozen.read()),
        "programs": int(_plan_programs.read()),
        "fires": int(st["count"]),
        "hits": int(st["sum"]),
    }


def _reset_for_tests() -> None:
    global _conf
    _conf = (-1, True, 128)
