"""One-sided communication (RMA) — the ``ompi/mca/osc`` analogue."""

from .window import (  # noqa: F401
    Window, win_create, win_allocate, win_allocate_shared,
    LOCK_EXCLUSIVE, LOCK_SHARED,
)
