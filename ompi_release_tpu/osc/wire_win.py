"""Cross-process one-sided communication — home-process-applies RMA.

The reference's osc framework moves RMA data between ANY two ranks
over the BTLs (``ompi/mca/osc/rdma/osc_rdma_data_move.c``: active and
passive target movement; ``osc/pt2pt`` ships ops as active messages
when no RDMA path exists). Under the unified ``tpurun`` world each
controller process owns only its LOCAL members' window slices, so an
RMA op whose target lives in another process is SHIPPED to that
process (the target's *home*) at synchronization time:

- epoch close partitions the pending queue by target owner; local ops
  run as the normal compiled epoch program over the local submesh,
  remote ops serialize into one batch per owner process;
- the owner's *window service thread* applies an incoming batch into
  its local slices — the same ``lax.scan``/``lax.switch`` epoch
  program — and replies with the pre-op values (get/get_accumulate/
  fetch_and_op/compare_and_swap reads) plus a completion ack, which
  gives ``flush`` its remote-completion meaning;
- passive target is real: the lock state for a target rank lives at
  the target's OWNER process (service-side lock table with waiter
  queues), so origins in different processes contending for an
  exclusive lock serialize without the target's application code ever
  being involved — the osc/rdma passive-target model.

Serialization is ``np.savez``/``np.load(allow_pickle=False)`` over the
wire's payload transports (shm handoff on one host, chunked DCN
staging across hosts) with a ``DssBuffer`` envelope — no pickle, no
eval. Only predefined reduction ops may cross a process boundary
(MPI itself restricts MPI_Accumulate to predefined ops).
"""

from __future__ import annotations

import io
import itertools
import json
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs as _obs
from ..mca import pvar
from ..mca import var as mca_var
from ..native import DssBuffer
from ..obs import watchdog as _watchdog
from ..ops.op import PREDEFINED_OPS
from ..request.request import Status
from ..utils import output
from ..utils.errors import ErrorCode, MPIError
from .window import (LOCK_EXCLUSIVE, LOCK_SHARED, Window, _EpochKind,
                     _PendingOp)

_log = output.stream("osc")


def register_vars() -> None:
    mca_var.register(
        "osc_request_timeout_ms", "int", 120_000,
        "Bound in milliseconds on window-service request/reply waits "
        "(batches, lock grants — a grant may legitimately be deferred "
        "behind another holder, hence the generous default). The "
        "effective bound also honors wire_coll_timeout_ms when that "
        "is set higher",
    )
    mca_var.register(
        "osc_abandon_timeout_ms", "int", 10_000,
        "Bound in milliseconds on the best-effort lock-abandon notice "
        "after a timed-out acquire (the home may be unreachable)",
    )
    mca_var.register(
        "osc_pscw_timeout_s", "float", 0.0,
        "Bound in seconds on PSCW start()/wait() notice waits; 0 = "
        "unbounded (MPI's rule — the partner may compute arbitrarily "
        "long before complete()); set it to turn a hung partner into "
        "a diagnosable error",
    )


register_vars()


class OscTuning:
    """One immutable snapshot of the window service's hot-path cvars
    (the ``WireRouter.tuning()`` pattern): per-request registry
    lookups and hard-coded blocking-wait deadlines become attribute
    reads off the current snapshot, re-resolved only when the MCA
    write generation moves — RMA steady state never touches the
    registry."""

    __slots__ = ("gen", "request_timeout_ms", "abandon_timeout_ms",
                 "pscw_timeout_s")

    def __init__(self) -> None:
        self.gen = mca_var.VARS.generation
        req = int(mca_var.get("osc_request_timeout_ms", 120_000)
                  or 120_000)
        wire = int(mca_var.get("wire_coll_timeout_ms", 60_000)
                   or 60_000)
        # an operator-raised collective wait bound must not be
        # undercut by the RMA default: a deferred lock grant can wait
        # behind a holder for as long as any collective may block
        self.request_timeout_ms = max(req, wire)
        self.abandon_timeout_ms = int(
            mca_var.get("osc_abandon_timeout_ms", 10_000) or 10_000)
        self.pscw_timeout_s = float(
            mca_var.get("osc_pscw_timeout_s", 0) or 0)


_win_requests = pvar.counter(
    "osc_wire_requests",
    "cross-process window service requests (batch/lock/abandon)",
)

#: live window services (one per runtime) for the flight recorder's
#: lock-table contributor — weak so a torn-down runtime's service
#: never pins memory or shows up in dumps
_services: "weakref.WeakSet" = weakref.WeakSet()


def _lock_tables_snapshot() -> List[Dict]:
    """Dump contributor: every live service's passive-target lock
    table + outstanding reply slots (who holds what, who waits).
    Lock acquisition is BOUNDED: the recorder dumps because something
    is hung, possibly a thread wedged inside these very critical
    sections — blocking here would hang the flight recorder itself
    (and, via _dump_lock, every later dump)."""
    out = []
    for svc in list(_services):
        entry: Dict = {"pidx": svc.my_pidx}
        if svc._state_lock.acquire(timeout=0.5):
            try:
                entry["locks"] = [
                    {"cid": k[0], "win_seq": k[1], "target": k[2],
                     "mode": st.mode, "holders": sorted(st.holders),
                     "waiters": [{"origin": w[0], "type": w[1],
                                  "local": w[2] is not None}
                                 for w in st.waiters]}
                    for k, st in svc._locks.items()
                ]
            finally:
                svc._state_lock.release()
        else:
            entry["locks"] = "unavailable: state lock held (a thread " \
                             "is wedged inside the lock table)"
        if svc._reply_guard.acquire(timeout=0.5):
            try:
                entry["outstanding_requests"] = len(svc._reply_slots)
            finally:
                svc._reply_guard.release()
        else:
            entry["outstanding_requests"] = "unavailable: reply guard held"
        out.append(entry)
    return out


_watchdog.add_contributor("window_locks", _lock_tables_snapshot)

#: window-service envelopes (any-source); payloads ride the three
#: sibling channels so an any-source envelope pop can never swallow
#: another sender's payload frame
WIRE_WIN_SERVICE = 5 << 20
WIRE_WIN_DATA = 6 << 20
WIRE_WIN_REPLY = 7 << 20
WIRE_WIN_RDATA = 8 << 20

_WIN_MAGIC = "WWIN"

KIND_BATCH = 1    # arg1 = release_target comm rank (or -1)
KIND_LOCK = 2     # arg1 = target, arg2 = lock type
KIND_ABANDON = 3  # arg1 = target: forget this origin's lock interest
KIND_POST = 4     # one-way: src process posted an exposure epoch
KIND_COMPLETE = 5  # one-way: src process completed its access epoch
KIND_ERROR = 99   # home-side failure applying a request


def _savez_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    """Deterministic npz writer: ``np.savez`` stamps every zip member
    with the wall-clock mtime, so two packs of identical ops differ in
    the member headers. Plan-time frame templates (osc/plan) must
    render byte-identical output to the interpreted pack, so the zip
    is written here with a fixed DOS-epoch timestamp — ``np.load``
    reads it unchanged (same .npy members, same STORED layout)."""
    import zipfile

    bio = io.BytesIO()
    with zipfile.ZipFile(bio, "w", zipfile.ZIP_STORED) as zf:
        for name, val in arrays.items():
            zi = zipfile.ZipInfo(name + ".npy",
                                 date_time=(1980, 1, 1, 0, 0, 0))
            with zf.open(zi, "w", force_zip64=True) as fid:
                np.lib.format.write_array(fid, np.asanyarray(val),
                                          allow_pickle=False)
    return bio.getvalue()


def _batch_meta(todo: List[_PendingOp]) -> List[Dict]:
    """Per-op request records (the wire header metadata). Shared by
    the per-call pack below and osc/plan's frozen ``BatchTemplate`` so
    the two can never drift. The predefined check is by IDENTITY, not
    name: a user op that merely shares a predefined op's name must be
    refused, or the home would silently apply the predefined one."""
    meta = []
    for p in todo:
        if p.op is not None and PREDEFINED_OPS.get(p.op.name) is not p.op:
            raise MPIError(
                ErrorCode.ERR_OP,
                f"cross-process RMA requires a predefined op, got "
                f"'{p.op.name}' (MPI_Accumulate's own rule)",
            )
        meta.append({
            "k": p.kind,
            "t": int(p.target),
            "o": p.op.name if p.op is not None else "",
            "i": -1 if p.index is None else int(p.index),
            "r": p.request is not None,
        })
    return meta


def _pack_batch(todo: List[_PendingOp]) -> np.ndarray:
    """Serialize a pending-op batch to one uint8 array (npz form)."""
    meta = _batch_meta(todo)
    arrays: Dict[str, np.ndarray] = {}
    for i, p in enumerate(todo):
        if p.data is not None:
            arrays[f"d{i}"] = np.asarray(p.data)
        if p.compare is not None:
            arrays[f"c{i}"] = np.asarray(p.compare)
    arrays["meta"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8
    ).copy()
    return np.frombuffer(_savez_bytes(arrays), dtype=np.uint8).copy()


def _unpack_batch(raw) -> List[_PendingOp]:
    """Inverse of :func:`_pack_batch`; requests are fresh local ones
    for ops that want a read back."""
    from ..request.request import Request

    z = np.load(io.BytesIO(np.asarray(raw, dtype=np.uint8).tobytes()),
                allow_pickle=False)
    meta = json.loads(bytes(z["meta"]).decode())
    todo = []
    for i, m in enumerate(meta):
        todo.append(_PendingOp(
            m["k"], m["t"],
            data=(jnp.asarray(z[f"d{i}"]) if f"d{i}" in z else None),
            op=(PREDEFINED_OPS[m["o"]] if m["o"] else None),
            request=(Request() if m["r"] else None),
            compare=(jnp.asarray(z[f"c{i}"]) if f"c{i}" in z else None),
            index=(None if m["i"] < 0 else m["i"]),
        ))
    return todo


def _pack_reads(values: List[np.ndarray]) -> np.ndarray:
    return np.frombuffer(
        _savez_bytes({f"r{i}": np.asarray(v)
                      for i, v in enumerate(values)}),
        dtype=np.uint8).copy()


def _unpack_reads(raw, n: int) -> List[np.ndarray]:
    z = np.load(io.BytesIO(np.asarray(raw, dtype=np.uint8).tobytes()),
                allow_pickle=False)
    return [z[f"r{i}"] for i in range(n)]


class _LockState:
    __slots__ = ("mode", "holders", "waiters")

    def __init__(self) -> None:
        self.mode: Optional[int] = None
        self.holders: set = set()  # origin process indices
        self.waiters: deque = deque()  # (origin, type, event|None)


class WinService:
    """Per-runtime window service: applies incoming RMA batches into
    home windows and arbitrates passive-target locks."""

    def __init__(self, runtime) -> None:
        self.rt = runtime
        self.router = runtime.wire
        self.ep = runtime.wire.ep
        self.my_pidx = int(runtime.bootstrap["process_index"])
        self.windows: Dict[Tuple[int, int], "WireWindow"] = {}
        self._locks: Dict[Tuple[int, int, int], _LockState] = {}
        self._state_lock = threading.Lock()
        # PSCW notice sets per window key: which processes have posted
        # an exposure epoch / completed an access epoch (consumed by
        # start()/wait() respectively)
        self._posts: Dict[Tuple[int, int], set] = {}
        self._completes: Dict[Tuple[int, int], set] = {}
        self._pscw_cv = threading.Condition(self._state_lock)
        #: token-demultiplexed replies: every outstanding request
        #: registers a slot keyed by its token; ONE thread at a time
        #: pumps the shared WIRE_WIN_REPLY channel (``_pump_lock``) and
        #: routes each reply — and its RDATA payload — to its slot, so
        #: any number of threads can have requests in flight and a
        #: deferred grant for one can never block another's reply
        self._reply_slots: Dict[int, dict] = {}
        self._reply_guard = threading.Lock()
        self._pump_lock = threading.Lock()
        #: per-request token echoed in replies: after a timeout, a
        #: LATE reply must not be mistaken for the retry's (same cid/
        #: seq/kind) — tokens make staleness decidable
        self._token = itertools.count(1)
        self._tuning = OscTuning()
        self._stop = threading.Event()
        _services.add(self)  # flight-recorder lock-table visibility
        self._thread = threading.Thread(
            target=self._serve, daemon=True, name="win-service"
        )
        self._thread.start()

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def ensure(cls, runtime) -> "WinService":
        svc = getattr(runtime, "_win_service", None)
        if svc is None:
            svc = runtime._win_service = cls(runtime)
        return svc

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)

    # -- tuning snapshot ---------------------------------------------------
    def tuning(self) -> OscTuning:
        """Current tuning snapshot: one generation compare on the hot
        path; any cvar write re-resolves at the next call."""
        t = self._tuning
        if t.gen != mca_var.VARS.generation:
            t = self._tuning = OscTuning()
        return t

    def refresh_tuning(self) -> OscTuning:
        self._tuning = OscTuning()
        return self._tuning

    def register(self, win: "WireWindow") -> None:
        with self._state_lock:
            self.windows[(win.comm.cid, win.win_seq)] = win

    def unregister(self, win: "WireWindow") -> None:
        key = (win.comm.cid, win.win_seq)
        with self._state_lock:
            self.windows.pop(key, None)
            # win_seq is monotone per comm, so a freed window's notice
            # and lock entries can never be consumed again — drop them
            # (late frames for the key are refused by _window())
            self._posts.pop(key, None)
            self._completes.pop(key, None)
            for lk in [k for k in self._locks if k[:2] == key]:
                del self._locks[lk]

    def _window(self, cid: int, seq: int) -> "WireWindow":
        with self._state_lock:
            w = self.windows.get((cid, seq))
        if w is None:
            raise MPIError(
                ErrorCode.ERR_WIN,
                f"window service: no window (cid={cid}, seq={seq}) — "
                "window creation order diverged across processes?",
            )
        return w

    # -- service loop ------------------------------------------------------
    def _serve(self) -> None:
        from ..btl.components import stashed_recv

        while not self._stop.is_set():
            try:
                src_nid, raw = stashed_recv(
                    self.ep, None, WIRE_WIN_SERVICE,
                    time.monotonic() + 0.2,
                )
            except MPIError:
                continue
            except Exception:
                if self._stop.is_set():
                    return
                raise
            try:
                self._handle(src_nid - 1, raw)
            except Exception as e:
                # NOTHING may kill the service: a malformed frame, a
                # corrupt npz, or a user error surfacing as a jax/numpy
                # exception (bad payload shape) would otherwise silently
                # disable all cross-process RMA for this process
                _log.verbose(1, f"win service: dropping frame from "
                                f"process {src_nid - 1}: "
                                f"{type(e).__name__}: {e}")

    def _handle(self, src_pidx: int, raw: bytes) -> None:
        env = DssBuffer(raw)
        if env.unpack_string() != _WIN_MAGIC:
            _log.verbose(1, "win service: non-window frame dropped")
            return
        cid, seq, kind, arg1, arg2, token = env.unpack_int64(6)
        token = int(token)
        if kind == KIND_BATCH:
            # payload must be consumed even if applying fails, and the
            # origin must get SOME reply or it stalls for the full
            # request timeout — failures reply KIND_ERROR (loud at the
            # origin, service stays alive)
            rec = _obs.enabled  # capture once: flag may flip mid-apply
            t0 = time.perf_counter() if rec else 0.0
            payload = self.router._recv_payload(WIRE_WIN_DATA, src_pidx)
            try:
                win = self._window(int(cid), int(seq))
                todo = _unpack_batch(payload)
                reads = win._apply_home_batch(todo)
                if int(arg1) >= 0:
                    self.release(win, int(arg1), src_pidx)
            except Exception as e:
                _log.verbose(1, f"win service: batch from process "
                                f"{src_pidx} failed: {e}")
                self._reply(src_pidx, int(cid), int(seq), KIND_ERROR, [],
                            token)
                return
            if rec and _obs.enabled:
                # consumer side of the origin's (origin pidx, token)
                # flow: both values rode the request envelope
                _obs.record("win_apply", "osc", t0,
                            time.perf_counter() - t0,
                            nbytes=int(getattr(payload, "nbytes", 0)),
                            peer=src_pidx, comm_id=int(cid),
                            flow=_obs.flow_id("win", src_pidx, token),
                            flow_side="t")
            self._reply(src_pidx, int(cid), int(seq), KIND_BATCH, reads,
                        token)
        elif kind == KIND_LOCK:
            win = self._window(int(cid), int(seq))
            granted = self.acquire(win, int(arg1), src_pidx, int(arg2),
                                   event=None, token=token)
            if granted:
                self._reply(src_pidx, int(cid), int(seq), KIND_LOCK, [],
                            token)
            # else: deferred — release() sends the grant later
        elif kind == KIND_ABANDON:
            win = self._window(int(cid), int(seq))
            self.abandon(win, int(arg1), src_pidx)
            self._reply(src_pidx, int(cid), int(seq), KIND_ABANDON, [],
                        token)
        elif kind == KIND_POST:
            self.pscw_record(self._posts, (int(cid), int(seq)), src_pidx)
        elif kind == KIND_COMPLETE:
            self.pscw_record(self._completes, (int(cid), int(seq)),
                             src_pidx)
        else:
            _log.verbose(1, f"win service: unknown kind {kind}")

    def _reply(self, dst_pidx: int, cid: int, seq: int, kind: int,
               reads: List[np.ndarray], token: int = 0) -> None:
        env = DssBuffer()
        env.pack_string(_WIN_MAGIC)
        env.pack_int64([cid, seq, kind, len(reads), token])
        self.router._retry(
            lambda: self.ep.send(self.router._nid(dst_pidx),
                                 WIRE_WIN_REPLY, env.tobytes()),
            f"window reply to process {dst_pidx}",
        )
        if reads:
            self.router._send_payload(dst_pidx, WIRE_WIN_RDATA,
                                      _pack_reads(reads))

    # -- origin-side request/reply -----------------------------------------
    def _send_lock(self, owner_pidx: int) -> threading.Lock:
        """Per-OWNER outbound framing lock (the router's lazily-created
        registry): a request envelope and its payload must land
        back-to-back on the owner's service FIFO, but the lock is held
        only for the SEND — never across the reply wait (the old
        process-wide ``outbound`` lock held through deferred
        lock-grant waits deadlocked a second thread's unlock for up to
        120 s)."""
        return self.router._chan_lock("win_send", owner_pidx)

    def _pump_replies(self, deadline: float) -> None:
        """Pop ONE reply (and its RDATA payload, if any) off the shared
        reply channel and route it to its token's slot. Caller holds
        ``_pump_lock``. Replies whose requester already timed out and
        deregistered are drained and dropped — their RDATA must be
        consumed here or the NEXT read-carrying reply would unpack the
        wrong arrays."""
        from ..btl.components import stashed_recv

        try:
            src_nid, raw = stashed_recv(self.ep, None, WIRE_WIN_REPLY,
                                        deadline)
        except MPIError as e:
            if e.code is ErrorCode.ERR_PENDING:
                return  # nothing within the slice; caller re-checks
            raise  # endpoint closed / link dead: surface it NOW, not
            #        as a misleading 120 s reply timeout
        renv = DssBuffer(raw)
        if renv.unpack_string() != _WIN_MAGIC:
            raise MPIError(ErrorCode.ERR_INTERN,
                           "corrupt window reply envelope")
        rcid, rseq, rkind, n_reads, rtoken = renv.unpack_int64(5)
        reads: List[np.ndarray] = []
        if int(n_reads) and int(rkind) != KIND_ERROR:
            # the owner's service thread sends a reply's RDATA directly
            # behind its envelope, so consuming it HERE (src-matched)
            # keeps the per-owner payload stream aligned no matter
            # which thread's reply this is
            rdata = self.router._recv_payload(WIRE_WIN_RDATA,
                                              src_nid - 1)
            reads = _unpack_reads(rdata, int(n_reads))
        with self._reply_guard:
            slot = self._reply_slots.get(int(rtoken))
            if slot is None:
                _log.verbose(
                    1, f"discarding stale window reply (cid={rcid}, "
                       f"seq={rseq}, kind={rkind}, token={rtoken})")
                return
            slot["cid"], slot["seq"] = int(rcid), int(rseq)
            slot["kind"] = int(rkind)
            slot["reads"] = reads
            slot["ev"].set()

    def request(self, win: "WireWindow", owner_pidx: int, kind: int,
                arg1: int, arg2: int,
                payload: Optional[np.ndarray] = None,
                timeout_ms: Optional[int] = None) -> List[np.ndarray]:
        """Send one request to ``owner_pidx`` and await its reply
        (lock grants may be deferred behind another holder, hence the
        generous default bound — ``osc_request_timeout_ms``, read off
        the tuning snapshot, never the registry). Returns the read
        arrays.

        Concurrency: the reply channel is demultiplexed by token, so
        any number of threads may have requests outstanding — while a
        thread waits for a deferred lock grant, the thread whose
        unlock PRODUCES that grant proceeds through its own
        request/reply unimpeded (the ADVICE r5 two-thread deadlock)."""
        if timeout_ms is None:
            timeout_ms = self.tuning().request_timeout_ms
        token = next(self._token)
        _win_requests.add()
        rec = _obs.enabled  # capture once: flag may flip mid-request
        t0 = time.perf_counter() if rec else 0.0
        wd_tok = None
        if _watchdog.enabled:
            wd_tok = _watchdog.arm(
                f"win_request_kind{kind}", comm_id=win.comm.cid,
                peer=owner_pidx,
                info={"win_seq": win.win_seq, "token": token,
                      "arg1": arg1, "arg2": arg2},
            )
        slot = {"ev": threading.Event(), "reads": None, "kind": None,
                "cid": -1, "seq": -1}
        with self._reply_guard:
            self._reply_slots[token] = slot
        try:
            env = DssBuffer()
            env.pack_string(_WIN_MAGIC)
            env.pack_int64([win.comm.cid, win.win_seq, kind, arg1, arg2,
                            token])
            with self._send_lock(owner_pidx):
                self.router._retry(
                    lambda: self.ep.send(self.router._nid(owner_pidx),
                                         WIRE_WIN_SERVICE, env.tobytes()),
                    f"window request to process {owner_pidx}",
                )
                if payload is not None:
                    self.router._send_payload(owner_pidx, WIRE_WIN_DATA,
                                              payload)
            if rec and _obs.enabled:
                # producer side: the home's win_apply span derives the
                # same (origin pidx, token) id from the envelope
                _obs.record(
                    "win_request", "osc", t0, time.perf_counter() - t0,
                    nbytes=int(getattr(payload, "nbytes", 0) or 0),
                    peer=owner_pidx, comm_id=win.comm.cid,
                    flow=_obs.flow_id("win", self.my_pidx, token),
                    flow_side="s")
            deadline = time.monotonic() + timeout_ms / 1000
            while not slot["ev"].is_set():
                # one thread at a time pumps the shared channel; the
                # others park on their event (woken the instant the
                # pump routes their reply) — whoever holds the pump
                # routes EVERY arriving reply to its waiter
                if self._pump_lock.acquire(blocking=False):
                    try:
                        if slot["ev"].is_set():
                            break
                        self._pump_replies(time.monotonic() + 0.2)
                    finally:
                        self._pump_lock.release()
                else:
                    slot["ev"].wait(timeout=0.02)
                if slot["ev"].is_set():
                    break
                if time.monotonic() >= deadline:
                    raise MPIError(
                        ErrorCode.ERR_PENDING,
                        f"window request (kind {kind}) to process "
                        f"{owner_pidx} got no reply within "
                        f"{timeout_ms / 1000:.0f}s",
                    )
        finally:
            if wd_tok is not None:
                _watchdog.disarm(wd_tok)
            with self._reply_guard:
                self._reply_slots.pop(token, None)
        if slot["kind"] == KIND_ERROR:
            raise MPIError(
                ErrorCode.ERR_RMA_SYNC,
                f"window request (kind {kind}) failed at its "
                f"home process {owner_pidx} — bad payload "
                "shape/dtype for the target window?",
            )
        if (slot["cid"], slot["seq"], slot["kind"]) != (
                win.comm.cid, win.win_seq, kind):
            raise MPIError(
                ErrorCode.ERR_INTERN,
                f"window reply token {token} carries "
                f"(cid={slot['cid']}, seq={slot['seq']}, "
                f"kind={slot['kind']}), expected (cid={win.comm.cid}, "
                f"seq={win.win_seq}, kind={kind})",
            )
        return slot["reads"] or []

    # -- PSCW notices (one-way; no reply awaited) --------------------------
    def notify(self, dst_pidx: int, win: "WireWindow", kind: int) -> None:
        env = DssBuffer()
        env.pack_string(_WIN_MAGIC)
        env.pack_int64([win.comm.cid, win.win_seq, kind, 0, 0, 0])
        self.router._retry(
            lambda: self.ep.send(self.router._nid(dst_pidx),
                                 WIRE_WIN_SERVICE, env.tobytes()),
            f"window notice (kind {kind}) to process {dst_pidx}",
        )

    def pscw_record(self, table: Dict, key: Tuple[int, int],
                    pidx: int) -> None:
        with self._pscw_cv:
            table.setdefault(key, set()).add(pidx)
            self._pscw_cv.notify_all()

    def pscw_check(self, table: Dict, key: Tuple[int, int],
                   procs) -> bool:
        """Non-consuming peek: have all of ``procs`` recorded their
        notice? (MPI_Win_test's question.)"""
        with self._pscw_cv:
            return set(procs) <= table.get(key, set())

    def pscw_await(self, table: Dict, key: Tuple[int, int],
                   procs, what: str) -> None:
        """Block until every process in ``procs`` has recorded its
        notice, then CONSUME those notices (the next epoch must wait
        for its own). MPI requires wait() to block as long as it
        takes (the partner may compute arbitrarily long before
        complete()), so the default is unbounded; operators can bound
        it with ``--mca osc_pscw_timeout_s N`` to turn a hung partner
        into a diagnosable error."""
        want = set(procs)
        if not want:  # MPI_GROUP_EMPTY epochs are legal no-ops
            return
        timeout_s = self.tuning().pscw_timeout_s
        deadline = (time.monotonic() + timeout_s) if timeout_s else None
        wd_tok = None
        if _watchdog.enabled:
            wd_tok = _watchdog.arm(
                f"pscw_{what}", comm_id=key[0],
                info=lambda: {"awaiting_procs": sorted(
                    want - table.get(key, set()))},
            )
        try:
            with self._pscw_cv:
                while not want <= table.get(key, set()):
                    if deadline is not None:
                        left = deadline - time.monotonic()
                        if left <= 0:
                            raise MPIError(
                                ErrorCode.ERR_RMA_SYNC,
                                f"PSCW {what} timed out awaiting "
                                f"processes "
                                f"{sorted(want - table.get(key, set()))}",
                            )
                    self._pscw_cv.wait(timeout=1.0)
                table[key] -= want
        finally:
            if wd_tok is not None:
                _watchdog.disarm(wd_tok)

    # -- home-side lock table ----------------------------------------------
    def _lock_key(self, win: "WireWindow", target: int
                  ) -> Tuple[int, int, int]:
        return (win.comm.cid, win.win_seq, target)

    def acquire(self, win: "WireWindow", target: int, origin: int,
                lock_type: int, event: Optional[threading.Event],
                token: int = 0) -> bool:
        """Try to acquire ``target``'s lock for ``origin``. Returns
        True when granted now; otherwise queues the waiter (remote
        origins get their grant reply — echoing ``token`` — from
        :meth:`release`; local ones wait on ``event``)."""
        with self._state_lock:
            st = self._locks.setdefault(self._lock_key(win, target),
                                        _LockState())
            grantable = (
                not st.holders
                or (st.mode == LOCK_SHARED and lock_type == LOCK_SHARED
                    and not st.waiters)  # don't starve a queued EXCL
            )
            if grantable:
                st.mode = lock_type
                st.holders.add(origin)
                return True
            st.waiters.append((origin, lock_type, event, token))
            return False

    def release(self, win: "WireWindow", target: int, origin: int) -> None:
        grants: List[Tuple[int, int]] = []  # (remote origin, token)
        with self._state_lock:
            st = self._locks.get(self._lock_key(win, target))
            if st is None or origin not in st.holders:
                raise MPIError(
                    ErrorCode.ERR_RMA_SYNC,
                    f"unlock of target {target} not held by process "
                    f"{origin}",
                )
            st.holders.discard(origin)
            if not st.holders:
                st.mode = None
                while st.waiters:
                    o, t, ev, tok = st.waiters[0]
                    if st.mode is None:
                        st.mode = t
                    elif not (st.mode == LOCK_SHARED
                              and t == LOCK_SHARED):
                        break
                    st.waiters.popleft()
                    st.holders.add(o)
                    if ev is not None:
                        # LOCAL grant: set the event INSIDE the lock so
                        # a timed-out acquire_blocking can atomically
                        # distinguish "granted" from "still waiting"
                        ev.set()
                    else:
                        grants.append((o, tok))
                    if t == LOCK_EXCLUSIVE:
                        break
        for origin_p, tok in grants:
            self._reply(origin_p, win.comm.cid, win.win_seq,
                        KIND_LOCK, [], tok)

    def abandon(self, win: "WireWindow", target: int, origin: int) -> None:
        """Forget ``origin``'s interest in ``target``'s lock: drop its
        waiter entry, or release a grant it never saw (the origin timed
        out; without this the ghost holder wedges the lock forever)."""
        with self._state_lock:
            st = self._locks.get(self._lock_key(win, target))
            if st is None:
                return
            st.waiters = deque(w for w in st.waiters if w[0] != origin)
            ghost = origin in st.holders
        if ghost:
            self.release(win, target, origin)

    def acquire_blocking(self, win: "WireWindow", target: int,
                         lock_type: int,
                         timeout_s: Optional[float] = None) -> None:
        """Local-origin acquire against the home table (the target is
        owned by THIS process, but remote origins contend through the
        same table). The default wait bound is the snapshot's request
        timeout — local and remote contenders give up on the same
        clock."""
        if timeout_s is None:
            timeout_s = self.tuning().request_timeout_ms / 1000.0
        ev = threading.Event()
        if self.acquire(win, target, self.my_pidx, lock_type, event=ev):
            return
        wd_tok = None
        if _watchdog.enabled:
            wd_tok = _watchdog.arm(
                "win_lock_wait", comm_id=win.comm.cid, peer=target,
                info={"win_seq": win.win_seq, "lock_type": lock_type},
            )
        try:
            granted = ev.wait(timeout=timeout_s)
        finally:
            if wd_tok is not None:
                _watchdog.disarm(wd_tok)
        if granted:
            return
        with self._state_lock:
            if ev.is_set():
                return  # granted in the race window — we hold it
            st = self._locks.get(self._lock_key(win, target))
            if st is not None:
                st.waiters = deque(
                    w for w in st.waiters if w[2] is not ev
                )
        raise MPIError(
            ErrorCode.ERR_RMA_SYNC,
            f"timed out waiting for lock on target {target} "
            f"(held elsewhere for > {timeout_s:.0f}s)",
        )


class WireWindow(Window):
    """A window on a communicator spanning controller processes: this
    process stores one slice per LOCAL member (the hier driver-mode
    convention); RMA to remote targets ships to the target's home at
    synchronization. Creation is collective and synchronizing (like
    MPI_Win_create), so a peer's first batch can never outrun the
    window's existence."""

    def __init__(self, comm, base: jax.Array, name: str = "") -> None:
        rt = comm.runtime
        if getattr(rt, "wire", None) is None:
            raise MPIError(
                ErrorCode.ERR_WIN,
                "spanning-comm window needs the wire router "
                "(runtime_unified_world)",
            )
        from ..runtime.wire import proc_topology

        t = proc_topology(comm)  # the one shared layout derivation
        self.router = t.router
        self.my_pidx = t.my_pidx
        self.owner = t.owner
        self.local_ranks = t.local_ranks
        self.local_n = t.local_n
        if base.shape[0] != self.local_n:
            raise MPIError(
                ErrorCode.ERR_WIN,
                f"spanning-comm window base carries one slice per "
                f"LOCAL member ({self.local_n}), got leading axis "
                f"{base.shape[0]}",
            )
        self._init_state(comm, base, name)  # shared Window field setup
        # collective creation: same per-comm sequence on every process
        self.win_seq = getattr(comm, "_win_seq", 0)
        comm._win_seq = self.win_seq + 1
        self.service = WinService.ensure(rt)
        self.service.register(self)
        comm.barrier()  # MPI_Win_create is collective + synchronizing

    # -- storage indexing --------------------------------------------------
    def _local_pos(self, target: int) -> int:
        return self.local_ranks.index(target)

    def _queue(self, op: _PendingOp):
        """Validate at the CALL SITE what the wire cannot ship: a
        user-defined op bound for a remote home would otherwise raise
        at epoch close, after sibling ops were already dequeued (and a
        piggybacked lock release lost). The check is by op-object
        IDENTITY — a user op that merely shares a predefined name
        would otherwise ship its name and the home would silently
        apply the predefined combiner."""
        if (op.op is not None
                and PREDEFINED_OPS.get(op.op.name) is not op.op
                and self.owner[op.target] != self.my_pidx):
            raise MPIError(
                ErrorCode.ERR_OP,
                f"cross-process RMA requires a predefined op, got "
                f"'{op.op.name}' (MPI_Accumulate's own rule)",
            )
        return super()._queue(op)

    def read(self) -> jax.Array:
        """LOCAL members' slices only (leading axis ``local_n``) — the
        remote slices live in their home processes' HBM."""
        return self._data

    # -- epoch close: split local / per-home batches -----------------------
    def _apply_pending(self, only_target: Optional[int] = None) -> None:
        from .window import _epoch_count

        with self._op_lock:
            if not self._pending:
                return
            _epoch_count.add()
            todo = self._take_pending(only_target)
            if not todo:
                return
            local: List[_PendingOp] = []
            remote: Dict[int, List[_PendingOp]] = {}
            for p in todo:
                own = self.owner[p.target]
                if own == self.my_pidx:
                    local.append(p)
                else:
                    remote.setdefault(own, []).append(p)
            if local:
                remapped = [
                    _PendingOp(p.kind, self._local_pos(p.target),
                               data=p.data, op=p.op, request=p.request,
                               compare=p.compare, index=p.index,
                               status_rank=p.target)
                    for p in local
                ]
                t0 = time.perf_counter()
                from . import plan as _osc_plan

                if not _osc_plan.close_epoch(self, remapped, t0):
                    self._run_epoch_program(remapped, _t0=t0)
        # ship OUTSIDE _op_lock: holding it while awaiting the peer's
        # ack would deadlock two processes fencing into each other
        # (each service thread needs the lock to apply the other's
        # batch)
        for own in sorted(remote):
            self._ship_batch(own, remote[own], release_target=-1)

    def _ship_batch(self, owner_pidx: int, ops: List[_PendingOp],
                    release_target: int) -> None:
        from . import plan as _osc_plan

        # repeated batches render through the signature's frozen
        # frame template (meta composed once at freeze time); bytes
        # are identical to _pack_batch either way
        reads = self.service.request(
            self, owner_pidx, KIND_BATCH, release_target, 0,
            payload=_osc_plan.batch_payload(self, ops),
        )
        want = [p for p in ops if p.request is not None]
        if len(want) != len(reads):
            raise MPIError(
                ErrorCode.ERR_INTERN,
                f"window batch reply carried {len(reads)} reads for "
                f"{len(want)} read-requests",
            )
        for p, v in zip(want, reads):
            p.request.complete(value=jnp.asarray(v),
                               status=Status(source=p.target))

    def _apply_home_batch(self, todo: List[_PendingOp]
                          ) -> List[np.ndarray]:
        """Service-side: apply a peer's batch into the local slices and
        return the read values in op order."""
        for p in todo:
            if self.owner[p.target] != self.my_pidx:
                raise MPIError(
                    ErrorCode.ERR_RANK,
                    f"batch targets rank {p.target}, owned by process "
                    f"{self.owner[p.target]}, not {self.my_pidx}",
                )
            p.target = self._local_pos(p.target)
        t0 = time.perf_counter()
        from . import plan as _osc_plan

        with self._op_lock:
            # incoming batches ride the same access-plan cache: a
            # peer's steady-state epoch replays one fused program here
            if not _osc_plan.close_epoch(self, todo, t0):
                self._run_epoch_program(todo, _t0=t0)
        return [np.asarray(p.request.value) for p in todo
                if p.request is not None]

    # -- passive target over the home lock table ---------------------------
    def lock(self, target: int, lock_type: int = LOCK_EXCLUSIVE) -> None:
        self._require(_EpochKind.NONE, _EpochKind.LOCK)
        if target in self._locked:
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           f"target {target} already locked")
        self._acquire(target, lock_type)
        self._locked[target] = lock_type
        self._epoch = _EpochKind.LOCK

    def _acquire(self, target: int, lock_type: int) -> None:
        own = self.owner[target]
        if own == self.my_pidx:
            self.service.acquire_blocking(self, target, lock_type)
            return
        try:
            self.service.request(self, own, KIND_LOCK, target, lock_type)
        except MPIError:
            # timed out awaiting the grant: tell the home to forget us
            # (drops our waiter entry, or releases a grant we never
            # saw) so the lock cannot wedge on a ghost holder
            try:
                self.service.request(
                    self, own, KIND_ABANDON, target, 0,
                    timeout_ms=self.service.tuning().abandon_timeout_ms)
            except MPIError:
                pass  # home unreachable; nothing more to clean
            raise

    def lock_all(self) -> None:
        """Shared lock on every target (remote ones at their homes)."""
        self._require(_EpochKind.NONE)
        for t in range(self.comm.size):
            self._acquire(t, LOCK_SHARED)
            self._locked[t] = LOCK_SHARED
        self._epoch = _EpochKind.LOCK

    def _release_one(self, target: int) -> None:
        own = self.owner[target]
        if own == self.my_pidx:
            self._apply_pending(only_target=target)
            self.service.release(self, target, self.my_pidx)
        else:
            with self._op_lock:
                ops = self._take_pending(only_target=target)
            remote = [p for p in ops if self.owner[p.target] != self.my_pidx]
            assert len(remote) == len(ops)  # only_target => one owner
            self._ship_batch(own, remote, release_target=target)

    def unlock(self, target: int) -> None:
        self._require(_EpochKind.LOCK)
        if target not in self._locked:
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           f"target {target} not locked")
        self._release_one(target)
        del self._locked[target]
        if not self._locked:
            self._epoch = _EpochKind.NONE

    def unlock_all(self) -> None:
        self._require(_EpochKind.LOCK)
        for t in sorted(self._locked):
            self._release_one(t)
        self._locked.clear()
        self._epoch = _EpochKind.NONE

    # -- PSCW (generalized active target) across processes -----------------
    # post -> a one-way notice to every accessor process; start blocks
    # for its targets' notices; complete ships+acks the batches THEN
    # notifies each target (service frames from one src are processed
    # in order, so a COMPLETE can never pass its own epoch's data);
    # wait blocks for every accessor process's COMPLETE. This is
    # osc/rdma's PSCW state machine at process granularity (one
    # controller acts as all its local ranks).

    def _procs_of_group(self, group) -> List[int]:
        return sorted({self.router.owner_of(r)
                       for r in group.world_ranks})

    def _key(self) -> Tuple[int, int]:
        return (self.comm.cid, self.win_seq)

    def post(self, group) -> None:
        # PSCW is legal in either order (post-then-start or
        # start-then-post on a process that is both target and
        # origin), so an open PSCW access epoch does not forbid
        # opening the exposure side
        self._require(_EpochKind.NONE, _EpochKind.PSCW)
        if self._group_exposed is not None:
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           "post() with an exposure epoch already open")
        self._group_exposed = group
        self._epoch = _EpochKind.PSCW
        for p in self._procs_of_group(group):
            if p == self.my_pidx:
                self.service.pscw_record(self.service._posts,
                                         self._key(), self.my_pidx)
            else:
                self.service.notify(p, self, KIND_POST)

    def start(self, group) -> None:
        self._require(_EpochKind.NONE, _EpochKind.PSCW)
        targets = self._procs_of_group(group)
        self.service.pscw_await(self.service._posts, self._key(),
                                targets, "start")
        self._start_procs = targets
        self._epoch = _EpochKind.PSCW

    def complete(self) -> None:
        self._require(_EpochKind.PSCW)
        self._apply_pending()  # ships + acks every remote batch first
        for p in getattr(self, "_start_procs", []):
            if p == self.my_pidx:
                self.service.pscw_record(self.service._completes,
                                         self._key(), self.my_pidx)
            else:
                self.service.notify(p, self, KIND_COMPLETE)
        self._start_procs = []
        # keep the epoch open while the exposure side is: a fence()
        # slipped between complete() and wait() must still raise
        self._epoch = (_EpochKind.NONE if self._group_exposed is None
                       else _EpochKind.PSCW)

    def wait(self) -> None:
        if self._group_exposed is None:
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           "wait() without a matching post()")
        accessors = self._procs_of_group(self._group_exposed)
        self.service.pscw_await(self.service._completes, self._key(),
                                accessors, "wait")
        if self._epoch is _EpochKind.PSCW:
            self._apply_pending()
            self._epoch = _EpochKind.NONE
        self._group_exposed = None

    def test(self) -> bool:
        """MPI_Win_test: True (and the exposure closes, like wait)
        exactly when every accessor process's COMPLETE has arrived —
        a non-consuming peek otherwise."""
        if self._group_exposed is None:
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           "test() without a matching post()")
        accessors = self._procs_of_group(self._group_exposed)
        if not self.service.pscw_check(self.service._completes,
                                       self._key(), accessors):
            return False
        self.wait()  # consumes the notices; will not block
        return True

    def free(self) -> None:
        super().free()
        # mirror-image of the creation barrier: peers may still have
        # in-flight release batches bound for this home — unregistering
        # before they land would drop them (no reply -> the peer stalls
        # its full request timeout mid-free)
        self.comm.barrier()
        self.service.unregister(self)

    def shared_query(self, rank: int):
        raise MPIError(
            ErrorCode.ERR_RMA_SHARED,
            "shared windows cannot span controller processes "
            "(device buffers are per-process); use a "
            "split_type_shared communicator",
        )
