"""MPI_Win windows over device buffers.

The reference's osc framework (``ompi/mca/osc/osc.h:205-338``: put/get/
accumulate/CAS/fetch-op + fence/PSCW/lock epochs, ``osc/rdma`` data
movement) recast for a single-controller device mesh:

- A window is a device-resident array with a leading rank axis — slice
  i lives in rank i's HBM (NamedSharding over the comm's sub-mesh), the
  MPI_Win_allocate memory model.
- RMA calls during an epoch queue; closing the epoch (fence, unlock,
  complete, flush) applies them in submission order as ONE jitted
  sharded program per epoch — the MPI completion rule ("RMA completes
  at synchronization") is the natural XLA execution model, and the
  epoch batch is the osc/rdma "aggregate and issue at sync" strategy.
- get/get_accumulate/fetch_and_op/compare_and_swap return Requests
  whose values materialize at epoch close.

Epoch rules enforced (``ompi/win/win.c`` access-epoch checks): RMA
outside any epoch raises; fence/lock/PSCW cannot be mixed.
"""

from __future__ import annotations

import enum
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..mca import pvar
from ..ops.op import Op, REPLACE, SUM
from ..request.request import Request, Status
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("osc")

_epoch_count = pvar.counter("osc_epochs", "RMA epochs closed")
_rma_ops = pvar.counter("osc_rma_ops", "RMA operations issued")

LOCK_EXCLUSIVE = 1
LOCK_SHARED = 2


class _EpochKind(enum.Enum):
    NONE = "none"
    FENCE = "fence"
    LOCK = "lock"
    PSCW = "pscw"


class _PendingOp:
    __slots__ = ("kind", "target", "data", "op", "request", "compare")

    def __init__(self, kind, target, data=None, op=None, request=None,
                 compare=None) -> None:
        self.kind = kind
        self.target = target
        self.data = data
        self.op = op
        self.request = request
        self.compare = compare


class Window:
    def __init__(self, comm, base: jax.Array, name: str = "") -> None:
        if base.shape[0] != comm.size:
            raise MPIError(
                ErrorCode.ERR_WIN,
                f"window base leading axis {base.shape[0]} != comm size "
                f"{comm.size}",
            )
        self.comm = comm
        self.name = name or f"win{id(self):x}"
        self._shard = NamedSharding(comm.submesh, P("rank"))
        self._data = jax.device_put(jnp.asarray(base), self._shard)
        self._epoch = _EpochKind.NONE
        self._locked: Dict[int, int] = {}  # target -> lock type
        self._pending: List[_PendingOp] = []
        self._group_exposed = None  # PSCW exposure group
        self._freed = False

    # -- queries -----------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape[1:])

    @property
    def dtype(self):
        return self._data.dtype

    def read(self) -> jax.Array:
        """Local loads of the whole window (valid outside access epochs
        or after a flush; driver mode sees every rank's slice)."""
        return self._data

    # -- epoch state machine ----------------------------------------------
    def _require(self, *kinds: _EpochKind) -> None:
        if self._freed:
            raise MPIError(ErrorCode.ERR_WIN, f"{self.name} freed")
        if self._epoch not in kinds:
            raise MPIError(
                ErrorCode.ERR_RMA_SYNC,
                f"operation requires epoch {[k.value for k in kinds]}, "
                f"window is in '{self._epoch.value}'",
            )

    def fence(self) -> None:
        """Open/continue a fence epoch; applies queued ops (MPI fence
        both closes the previous access epoch and opens the next)."""
        self._require(_EpochKind.NONE, _EpochKind.FENCE)
        self._apply_pending()
        self._epoch = _EpochKind.FENCE
        self.comm.barrier()

    def fence_end(self) -> None:
        """Final fence (MPI_MODE_NOSUCCEED): close the epoch."""
        self._require(_EpochKind.FENCE)
        self._apply_pending()
        self._epoch = _EpochKind.NONE
        self.comm.barrier()

    def lock(self, target: int, lock_type: int = LOCK_EXCLUSIVE) -> None:
        self._require(_EpochKind.NONE, _EpochKind.LOCK)
        if target in self._locked:
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           f"target {target} already locked")
        self._locked[target] = lock_type
        self._epoch = _EpochKind.LOCK

    def lock_all(self) -> None:
        self._require(_EpochKind.NONE)
        for t in range(self.comm.size):
            self._locked[t] = LOCK_SHARED
        self._epoch = _EpochKind.LOCK

    def unlock(self, target: int) -> None:
        self._require(_EpochKind.LOCK)
        if target not in self._locked:
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           f"target {target} not locked")
        self._apply_pending(only_target=target)
        del self._locked[target]
        if not self._locked:
            self._epoch = _EpochKind.NONE

    def unlock_all(self) -> None:
        self._require(_EpochKind.LOCK)
        self._apply_pending()
        self._locked.clear()
        self._epoch = _EpochKind.NONE

    def flush(self, target: int) -> None:
        """Complete pending ops to one target inside a passive epoch."""
        self._require(_EpochKind.LOCK)
        self._apply_pending(only_target=target)

    def flush_all(self) -> None:
        self._require(_EpochKind.LOCK)
        self._apply_pending()

    # PSCW (generalized active target)
    def post(self, group) -> None:
        """Exposure epoch: this window's slices may be targeted by the
        ranks of ``group`` (driver mode keeps one state machine)."""
        self._require(_EpochKind.NONE)
        self._group_exposed = group
        self._epoch = _EpochKind.PSCW

    def start(self, group) -> None:
        self._require(_EpochKind.NONE, _EpochKind.PSCW)
        self._epoch = _EpochKind.PSCW

    def complete(self) -> None:
        """Close the access side of a PSCW epoch (MPI_Win_complete)."""
        self._require(_EpochKind.PSCW)
        self._apply_pending()
        self._epoch = _EpochKind.NONE

    def wait(self) -> None:
        """Close the exposure side (MPI_Win_wait). The single driver
        state machine conflates access/exposure, so wait() after the
        origin's complete() must succeed — it applies anything still
        pending and clears the exposure group. A bare start() access
        epoch has no exposure to wait on and is rejected."""
        if self._group_exposed is None:
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           "wait() without a matching post()")
        if self._epoch is _EpochKind.PSCW:
            self._apply_pending()
            self._epoch = _EpochKind.NONE
        self._group_exposed = None

    def free(self) -> None:
        if self._pending:
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           "free with unsynchronized RMA operations")
        self._freed = True

    # -- RMA operations ----------------------------------------------------
    def _queue(self, op: _PendingOp) -> Optional[Request]:
        self._require(_EpochKind.FENCE, _EpochKind.LOCK, _EpochKind.PSCW)
        if (self._epoch is _EpochKind.LOCK
                and op.target not in self._locked):
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           f"target {op.target} not locked")
        if not 0 <= op.target < self.comm.size:
            raise MPIError(ErrorCode.ERR_RANK,
                           f"RMA target {op.target} out of range")
        _rma_ops.add()
        self._pending.append(op)
        return op.request

    def put(self, data, target: int) -> None:
        self._queue(_PendingOp("put", target, jnp.asarray(data), REPLACE))

    def get(self, target: int) -> Request:
        req = Request()
        self._queue(_PendingOp("get", target, request=req))
        return req

    def accumulate(self, data, target: int, op: Op = SUM) -> None:
        self._queue(_PendingOp("acc", target, jnp.asarray(data), op))

    def get_accumulate(self, data, target: int, op: Op = SUM) -> Request:
        req = Request()
        self._queue(
            _PendingOp("get_acc", target, jnp.asarray(data), op, req)
        )
        return req

    def fetch_and_op(self, value, target: int, op: Op = SUM) -> Request:
        return self.get_accumulate(value, target, op)

    def compare_and_swap(self, value, compare, target: int) -> Request:
        req = Request()
        self._queue(
            _PendingOp("cas", target, jnp.asarray(value), None, req,
                       compare=jnp.asarray(compare))
        )
        return req

    # -- application -------------------------------------------------------
    def _apply_pending(self, only_target: Optional[int] = None) -> None:
        """Apply queued ops in submission order (MPI same-origin
        ordering); driver mode's single queue is globally ordered."""
        if not self._pending:
            return
        _epoch_count.add()
        if only_target is None:
            todo, self._pending = self._pending, []
        else:
            todo = [p for p in self._pending if p.target == only_target]
            self._pending = [
                p for p in self._pending if p.target != only_target
            ]
        data = self._data
        for p in todo:
            if p.kind == "put":
                data = data.at[p.target].set(p.data.astype(data.dtype))
            elif p.kind == "get":
                p.request.complete(value=data[p.target],
                                   status=Status(source=p.target))
            elif p.kind in ("acc", "get_acc"):
                cur = data[p.target]
                if p.kind == "get_acc":
                    p.request.complete(value=cur,
                                       status=Status(source=p.target))
                new = p.op(cur, p.data.astype(data.dtype))
                data = data.at[p.target].set(new)
            elif p.kind == "cas":
                cur = data[p.target]
                p.request.complete(value=cur,
                                   status=Status(source=p.target))
                new = jnp.where(cur == p.compare.astype(data.dtype),
                                p.data.astype(data.dtype), cur)
                data = data.at[p.target].set(new)
        self._data = data


def win_create(comm, base, name: str = "") -> Window:
    """MPI_Win_create: wrap existing per-rank buffers (leading rank
    axis)."""
    return Window(comm, jnp.asarray(base), name)


def win_allocate(comm, shape: Tuple[int, ...], dtype=jnp.float32,
                 name: str = "") -> Window:
    """MPI_Win_allocate: fresh zeroed window, one ``shape`` block per
    rank."""
    return Window(
        comm, jnp.zeros((comm.size,) + tuple(shape), dtype), name
    )
